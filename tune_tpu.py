"""On-chip tuning sweep: run when the TPU relay is reachable.

Complements bench.py (the driver's fixed-format benchmark) with the sweeps
needed to CHOOSE the production constants (VERDICT r3 item 2 — drive p99
under the 20 ms budget with measured numbers):

1. Pallas flash-attention block sizes (block_q x block_k) at seq 64/128/512
   vs plain XLA attention — picks ops/attention.py defaults.
2. score_fused bucket-size sweep (64..1024): per-bucket device latency and
   txn/s so BATCH_BUCKETS reflects the chip's actual knee.
3. Per-branch device timings at the chosen bucket — where the p99 goes.

Usage:  python tune_tpu.py            # exits 3 immediately if no TPU
Output: one JSON line per sweep point on stdout (greppable), summary last.

Timing discipline: block_until_ready before ANY device->host pull (the
axon tunnel permanently degrades to sync mode after the first transfer —
see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np


def _probe() -> bool:
    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=150)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and "cpu" not in proc.stdout

def _emit(**kv) -> None:
    print(json.dumps(kv), flush=True)


def _time_blocked(fn, iters: int) -> dict:
    """Shared discipline (utils/timing.py): varied inputs, no d2h pulls."""
    from realtime_fraud_detection_tpu.utils.timing import time_blocked

    ms = np.asarray(time_blocked(fn, iters)) * 1e3
    return {"p50_ms": round(float(np.percentile(ms, 50)), 3),
            "p99_ms": round(float(np.percentile(ms, 99)), 3)}


def _throughput(fn, batch: int, iters: int) -> float:
    """Shared discipline (utils/timing.py): varied inputs, no d2h pulls."""
    from realtime_fraud_detection_tpu.utils.timing import (
        throughput_pipelined,
    )

    return throughput_pipelined(fn, batch, iters)


def main() -> int:
    if not _probe():
        print("no TPU reachable; not running the sweep", file=sys.stderr)
        return 3
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
    from realtime_fraud_detection_tpu.models.bert import (
        BertConfig,
        bert_predict,
    )
    from realtime_fraud_detection_tpu.ops.attention import (
        attention_reference,
        flash_attention,
    )
    from realtime_fraud_detection_tpu.scoring import (
        MODEL_NAMES,
        ScorerConfig,
        init_scoring_models,
        make_example_batch,
        score_fused,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    dev = jax.devices()[0]
    # --quant: sweep the QUANTIZED fused program (weight-only int8 BERT +
    # GEMM-form tree kernels — the rtfd quant-drill gated configuration)
    # instead of f32, so one relay window captures both sweeps in two
    # invocations. Calibration pulls the f32 weights host-side ONCE, here
    # at startup, before any timed section.
    quant = "--quant" in sys.argv
    # --kernels: sweep the fused program with the Pallas kernel plane on
    # (fused dequant-matmul + fused score-and-blend epilogue + flash
    # attention — the rtfd kernel-drill gated configuration), so one
    # relay window captures kernel-on numbers next to the f32 / --quant
    # sweeps (ROADMAP consolidated-capture item).
    # --mega: additionally sweep the persistent megakernel (one Pallas
    # program scoring the whole packed microbatch — the rtfd kernel-drill
    # --mega gated configuration) against the per-site fused chain, and
    # emit a mega_verdict line (the attn_verdict pattern) saying whether
    # the one-program path wins at the buckets whose VMEM plan admits it.
    # Implies --kernels.
    mega = "--mega" in sys.argv
    kernels = "--kernels" in sys.argv or mega
    _emit(stage="start", device=str(dev), quantized=quant, kernels=kernels,
          mega=mega)
    rng = np.random.default_rng(0)

    # 1 ------------------------------------------------- pallas block sweep
    # This sweep is the flash-attention DEFAULT driver: the attn_verdict
    # line below says whether flash beats plain XLA at the production
    # sequence length, which is what justifies KernelSettings.full()
    # flipping attention to "flash" (ops/attention.py block defaults).
    attn_best: dict = {}
    for seq in (64, 128, 512):
        b, h, d = 64, 12, 64
        k, v = (jnp.asarray(rng.standard_normal((b, h, seq, d)),
                            jnp.float32) for _ in range(2))
        qs = [jnp.asarray(rng.standard_normal((b, h, seq, d)), jnp.float32)
              for _ in range(8)]
        mask = jnp.ones((b, seq), bool)
        ref = jax.jit(lambda q, k, v, m: attention_reference(q, k, v, m))
        base = _time_blocked(lambda i: ref(qs[i % 8], k, v, mask), 30)
        _emit(stage="attn", seq=seq, impl="xla", **base)
        attn_best[seq] = {"xla_p50_ms": base["p50_ms"], "flash": None}
        for bq in (64, 128, 256):
            for bk in (64, 128, 256):
                if seq % bq or seq % bk:
                    continue
                try:
                    t = _time_blocked(
                        lambda i: flash_attention(qs[i % 8], k, v, mask,
                                                  block_q=bq, block_k=bk), 30)
                except Exception as e:  # noqa: BLE001
                    _emit(stage="attn", seq=seq, impl="pallas", block_q=bq,
                          block_k=bk, error=str(e)[:120])
                    continue
                _emit(stage="attn", seq=seq, impl="pallas", block_q=bq,
                      block_k=bk, **t)
                fl = attn_best[seq]["flash"]
                if fl is None or t["p50_ms"] < fl["p50_ms"]:
                    attn_best[seq]["flash"] = {"block_q": bq, "block_k": bk,
                                               "p50_ms": t["p50_ms"]}
    for seq, rec in attn_best.items():
        fl = rec["flash"]
        _emit(stage="attn_verdict", seq=seq,
              flash_wins=bool(fl and fl["p50_ms"] < rec["xla_p50_ms"]),
              best_flash=fl, xla_p50_ms=rec["xla_p50_ms"],
              drives="KernelSettings.full() attention default")

    # 2 ---------------------------------------------------- bucket sweep
    bert_config = BertConfig()
    sc = ScorerConfig(text_len=64)
    # stamp the exact text-encoder architecture this sweep measures, so a
    # sweep line is never combined with quality numbers from a different
    # model by assumption (VERDICT Weak #5; bench.py records the same)
    _emit(stage="text_encoder", num_layers=bert_config.num_layers,
          hidden_size=bert_config.hidden_size,
          intermediate_size=bert_config.intermediate_size,
          num_heads=bert_config.num_heads,
          vocab_size=bert_config.vocab_size, text_len=sc.text_len)
    models = init_scoring_models(
        jax.random.PRNGKey(0), bert_config=bert_config,
        feature_dim=sc.feature_dim, node_dim=sc.node_dim)
    kernel = "gather"
    if quant:
        from realtime_fraud_detection_tpu.models.quant import (
            quantize_bert_params,
        )

        models = models.replace(
            bert=quantize_bert_params(jax.device_get(models.bert)))
        kernel = "gemm"
    # --mesh: sweep the GSPMD-SHARDED fused program — batch over ``data``,
    # BERT params STORED over ``model`` and re-gathered at the use seam
    # (scoring/mesh_executor.py semantics, the rtfd mesh-drill gated
    # path) — so one relay window captures mesh numbers next to the f32
    # and --quant sweeps (ROADMAP consolidated-capture item).
    mesh = None
    if "--mesh" in sys.argv and len(jax.devices()) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from realtime_fraud_detection_tpu.core.mesh import (
            MeshConfig,
            build_mesh,
        )
        from realtime_fraud_detection_tpu.parallel.layouts import (
            batch_shardings,
            branch_serving_specs,
            tree_specs_to_shardings,
        )

        model_axis = 2 if len(jax.devices()) % 2 == 0 else 1
        mesh = build_mesh(MeshConfig(model=model_axis))
        _emit(stage="mesh", data_axis=int(mesh.shape["data"]),
              model_axis=model_axis, shard_branches=["bert_text"])
        models = jax.device_put(models, tree_specs_to_shardings(
            mesh, branch_serving_specs(models, model_axis,
                                       ("bert_text",))))
        _rep = NamedSharding(mesh, P())

    def _put(x):
        """Stage a host array: sharded over the mesh data axis under
        --mesh, plain default-device put otherwise."""
        if mesh is None:
            return jax.device_put(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(x, NamedSharding(
            mesh, P("data", *([None] * (np.ndim(x) - 1)))))

    # kernel-plane statics (rtfd kernel-drill gated): flash attention +
    # fused dequant-matmul (engages on the int8 params under --quant) +
    # fused epilogue, compiled for real on the chip (interpret=False)
    kern = (dict(use_pallas=True, dequant_kernel="pallas",
                 epilogue_kernel="pallas") if kernels else {})
    if mesh is None:
        models = jax.device_put(models)
        fused = jax.jit(lambda m, b, p, v: score_fused(
            m, b, p, v, bert_config=bert_config, with_model_preds=False,
            tree_kernel=kernel, iforest_kernel=kernel, **kern))
    else:
        fused = jax.jit(lambda m, b, p, v: score_fused(
            m.replace(bert=jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(x, _rep),
                m.bert)),
            b, p, v, bert_config=bert_config, with_model_preds=False,
            tree_kernel=kernel, iforest_kernel=kernel, **kern))
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    valid = jnp.ones((len(MODEL_NAMES),), bool)
    for bucket in (64, 128, 256, 512, 1024):
        host_batch = make_example_batch(
            bucket, sc, rng=np.random.default_rng(bucket))
        # variants built from the HOST copy (a np.asarray on the device
        # copy would be a d2h pull — the tunnel sync-mode trap)
        feats = [_put(host_batch.features + np.float32(j))
                 for j in range(8)]
        batch = (jax.device_put(host_batch) if mesh is None
                 else jax.device_put(host_batch,
                                     batch_shardings(mesh, host_batch)))
        t = _time_blocked(
            lambda i: fused(models, batch.replace(features=feats[i % 8]),
                            params, valid), 40)
        tput = _throughput(
            lambda i: fused(models, batch.replace(features=feats[i % 8]),
                            params, valid), bucket, 40)
        _emit(stage="bucket", bucket=bucket, txn_per_s=round(tput, 1),
              ms_per_batch_pipelined=round(1e3 * bucket / tput, 3), **t)

    # 2b --------------------------------------- megakernel sweep (--mega)
    # Persistent megakernel vs the fused per-site chain, compiled for real
    # on the chip, at every bucket whose VMEM plan admits the one-program
    # path. An unsupported plan (full-size BERT params exceed the
    # persistent grid's VMEM budget) is emitted honestly — that IS the
    # verdict for this architecture, not an error.
    if mega and mesh is None:
        from realtime_fraud_detection_tpu.ops import (
            fused_megakernel,
            mega_launch_accounting,
            mega_plan,
        )

        mv = tuple(True for _ in MODEL_NAMES)
        mega_won, mega_ran = [], []
        for bucket in (64, 128, 256):
            host_batch = make_example_batch(
                bucket, sc, rng=np.random.default_rng(1000 + bucket))
            plan = mega_plan(models, bert_config, b=bucket,
                             text_len=sc.text_len, seq_len=sc.seq_len,
                             feature_dim=sc.feature_dim, has_two_hop=False)
            acct = mega_launch_accounting(bucket, len(MODEL_NAMES),
                                          mega_valid=mv)
            if not plan["supported"]:
                _emit(stage="mega", bucket=bucket, supported=False,
                      param_bytes=plan["param_bytes"],
                      act_row_bytes=plan["act_row_bytes"])
                continue
            batch = jax.device_put(host_batch)
            feats = [_put(host_batch.features + np.float32(j))
                     for j in range(8)]
            chain_t = _time_blocked(
                lambda i: fused(models, batch.replace(features=feats[i % 8]),
                                params, valid), 30)
            try:
                mega_t = _time_blocked(
                    lambda i: fused_megakernel(
                        models, batch.replace(features=feats[i % 8]),
                        params, mega_valid=mv, bert_config=bert_config,
                        block=plan["block"]), 30)
            except Exception as e:  # noqa: BLE001
                _emit(stage="mega", bucket=bucket, supported=True,
                      block=plan["block"], error=str(e)[:120])
                continue
            _emit(stage="mega", bucket=bucket, supported=True,
                  block=plan["block"], chain_p50_ms=chain_t["p50_ms"],
                  mega_p50_ms=mega_t["p50_ms"],
                  launches_chain=acct["launches_per_batch_chain"],
                  launches_mega=acct["launches_per_batch_mega"],
                  hbm_bytes_eliminated=acct["intermediate_bytes_eliminated"])
            mega_ran.append(bucket)
            if mega_t["p50_ms"] < chain_t["p50_ms"]:
                mega_won.append(bucket)
        _emit(stage="mega_verdict",
              mega_wins=bool(mega_ran) and mega_won == mega_ran,
              buckets_ran=mega_ran, buckets_won=mega_won,
              reason=(None if mega_ran else "no_clean_mega_measurement"),
              drives="KernelSettings.mega() megakernel default")

    # 3 ------------------------------------------------ per-branch split
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.lstm import lstm_logits
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_predict

    host_batch = make_example_batch(256, sc, rng=np.random.default_rng(1))
    feats = [_put(host_batch.features + np.float32(j))
             for j in range(8)]
    hists = [_put(host_batch.history + np.float32(j))
             for j in range(8)]
    toks = [_put(((host_batch.token_ids + j)
                  % bert_config.vocab_size).astype(np.int32))
            for j in range(8)]
    if mesh is None:
        batch = jax.device_put(host_batch)
    else:
        from realtime_fraud_detection_tpu.parallel.layouts import (
            batch_shardings,
        )

        batch = jax.device_put(host_batch,
                               batch_shardings(mesh, host_batch))
    jtree = jax.jit(lambda f: tree_ensemble_predict(models.trees, f,
                                                    kernel=kernel))
    jifo = jax.jit(lambda f: iforest_predict(models.iforest, f,
                                             kernel=kernel))
    jlstm = jax.jit(lambda h: jax.nn.sigmoid(lstm_logits(
        models.lstm, h, batch.history_len)))
    jbert = jax.jit(lambda t: bert_predict(
        models.bert, t, batch.token_mask, bert_config))
    branches = {
        "trees": (lambda i: jtree(feats[i % 8])),
        "iforest": (lambda i: jifo(feats[i % 8])),
        "lstm": (lambda i: jlstm(hists[i % 8])),
        "bert": (lambda i: jbert(toks[i % 8])),
    }
    for name, fn in branches.items():
        t = _time_blocked(fn, 30)
        tput = _throughput(fn, 256, 30)
        _emit(stage="branch", branch=name, batch=256,
              ms_per_batch_pipelined=round(256e3 / tput, 3), **t)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Partition-parallel worker plane (cluster/): hash ring + router units,
partitioned stores, handoff fleet, chaos WorkerKill, sync_cluster mirror,
FraudScorer store injection, and the `rtfd shard-drill --fast` tier-1
smoke."""

import dataclasses
import json

import numpy as np
import pytest

from realtime_fraud_detection_tpu.cluster import (
    HandoffStore,
    HashRing,
    PartitionNotOwned,
    PartitionState,
    PartitionedStore,
    ShardRouter,
    WorkerFleet,
    partition_for_key,
)
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker


# ---------------------------------------------------------------------------
# hash ring + router (ISSUE 10 satellite: direct unit tests)
# ---------------------------------------------------------------------------


class TestPartitionForKey:
    def test_matches_transport_partitioner(self):
        """The affinity contract: key→partition is the SAME hash the
        broker uses, so consuming a partition == owning its users."""
        broker = InMemoryBroker()
        n = broker.partitions(T.TRANSACTIONS)
        for i in range(500):
            key = f"user_{i:08x}"
            assert (partition_for_key(key, n)
                    == broker.select_partition(T.TRANSACTIONS, key))

    def test_rejects_bad_partition_count(self):
        with pytest.raises(ValueError):
            partition_for_key("u", 0)


class TestHashRing:
    def test_deterministic_placement(self):
        """Placement is a pure function of (members, virtual_nodes): two
        independently built rings agree on every partition."""
        a = HashRing(["w0", "w1", "w2", "w3"])
        b = HashRing(["w3", "w1", "w0", "w2"])    # insertion order differs
        assert a.assignment(64) == b.assignment(64)

    def test_assignment_exhaustive_and_disjoint(self):
        ring = HashRing([f"w{i}" for i in range(4)])
        assign = ring.assignment(12)
        flat = sorted(p for parts in assign.values() for p in parts)
        assert flat == list(range(12))

    def test_leave_moves_only_leavers_partitions(self):
        """The consistent-hashing property modulo assignment lacks:
        removing a member relocates exactly its own partitions."""
        ring = HashRing([f"w{i}" for i in range(4)])
        before = ring.assignment(48)
        ring.remove("w2")
        after = ring.assignment(48)
        for m in ("w0", "w1", "w3"):
            assert set(before[m]) <= set(after[m])
        moved = {p for m in ("w0", "w1", "w3")
                 for p in set(after[m]) - set(before[m])}
        assert moved == set(before["w2"])

    def test_join_movement_bounded(self):
        """Expected movement when a worker joins N-1 → N is K/N; assert a
        2x slack over many keys (far below the ~K(N-1)/N a modulo
        assignment reshuffles)."""
        k = 10_000
        ring = HashRing([f"w{i}" for i in range(4)])
        before = {i: ring.owner_of_partition(i) for i in range(k)}
        ring.add("w4")
        moved = sum(1 for i in range(k)
                    if ring.owner_of_partition(i) != before[i])
        assert 0 < moved <= 2 * k / 5

    def test_route_key_through_transport_hash(self):
        ring = HashRing(["w0", "w1"])
        for key in ("alice", "bob", "user_00000007"):
            assert ring.route_key(key, 12) == ring.owner_of_partition(
                partition_for_key(key, 12))

    def test_empty_ring_raises(self):
        with pytest.raises(ValueError):
            HashRing([]).owner_of_partition(0)


class TestShardRouter:
    def test_route_agrees_with_assignment(self):
        router = ShardRouter(12, ["w0", "w1", "w2", "w3"])
        owner_of = {p: m for m, parts in router.assignment().items()
                    for p in parts}
        for i in range(200):
            uid = f"user_{i:08x}"
            assert router.route(uid) == owner_of[router.partition_of(uid)]

    def test_membership_change_accounts_movement(self):
        router = ShardRouter(12, ["w0", "w1", "w2", "w3"])
        before = router.assignment()
        moved = router.set_membership(["w0", "w1", "w3"])
        assert moved == len(before["w2"]) > 0
        assert router.moved_keys_total == moved
        assert router.rebalances == 1
        # survivors kept everything they had
        after = router.assignment()
        for m in ("w0", "w1", "w3"):
            assert set(before[m]) <= set(after[m])

    def test_snapshot_shape(self):
        router = ShardRouter(4, ["w0"], addresses={"w0": "http://a:1"})
        snap = router.snapshot()
        assert snap["members"] == ["w0"]
        assert snap["assignment"]["w0"] == [0, 1, 2, 3]
        assert router.address_of("w0") == "http://a:1"


# ---------------------------------------------------------------------------
# partitioned store
# ---------------------------------------------------------------------------


def _store(n_partitions=4, owned=None):
    store = PartitionedStore(n_partitions, seq_len=3, feature_dim=2)
    for p in (range(n_partitions) if owned is None else owned):
        store.acquire(p)
    return store


class TestPartitionedStore:
    def test_facades_route_by_user_key(self):
        store = _store()
        uid = "user_42"
        p = store.partition_for(uid)
        store.velocity.update(uid, 10.0, 1.0)
        assert store.state(p).velocity.get(uid, "5min", 1.0)["count"] == 1
        store.profiles.put_user(uid, {"txn_count": 1})
        assert store.state(p).profiles.get_user(uid) == {"txn_count": 1}
        store.txn_cache.cache_transaction(
            {"transaction_id": "t1", "user_id": uid}, now=1.0)
        assert store.txn_cache.get_transaction("t1", now=1.0)["user_id"] \
            == uid
        assert store.state(p).txn_cache.get_transaction(
            "t1", now=1.0) is not None

    def test_unowned_partition_raises_loudly(self):
        store = _store(owned=[0])
        victim = next(f"u{i}" for i in range(100)
                      if store.partition_for(f"u{i}") != 0)
        with pytest.raises(PartitionNotOwned):
            store.velocity.update(victim, 1.0, 0.0)
        with pytest.raises(PartitionNotOwned):
            store.profiles.get_user(victim)

    def test_merchants_replicated_not_partitioned(self):
        store = _store(owned=[0])
        store.profiles.seed(merchants={"m1": {"name": "shop"}})
        assert store.profiles.get_merchant("m1") == {"name": "shop"}

    def test_history_batch_routing_preserves_semantics(self):
        """A batch with in-batch duplicate users gathers exactly what a
        single unpartitioned store would (per-user rows all live in one
        partition; regrouping must not reorder them)."""
        from realtime_fraud_detection_tpu.state.history import (
            UserHistoryStore,
        )

        store = _store()
        oracle = UserHistoryStore(3, 2)
        uids = ["a", "b", "a", "c", "b", "a"]
        feats = np.arange(12, dtype=np.float32).reshape(6, 2)
        got, got_len = store.history.append_and_gather(uids, feats)
        want, want_len = oracle.append_and_gather(uids, feats)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(got_len, want_len)

    def test_snapshot_restore_digest_identical(self):
        store = _store(owned=[1])
        st = store.state(1)
        uid = next(f"u{i}" for i in range(100)
                   if store.partition_for(f"u{i}") == 1)
        store.velocity.update(uid, 5.0, 2.0)
        store.profiles.put_user(uid, {"txn_count": 3})
        store.history.append_batch([uid], np.ones((1, 2), np.float32))
        store.txn_cache.cache_transaction(
            {"transaction_id": "t9", "user_id": uid,
             "fraud_score": 0.25}, now=2.0)
        blob = st.snapshot_bytes()
        restored = PartitionState.restore_bytes(blob)
        assert restored.digest(now=3.0) == st.digest(now=3.0)
        # the snapshot is a VALUE copy: mutating the live state after the
        # snapshot must not leak into the restored one
        store.velocity.update(uid, 7.0, 2.5)
        assert PartitionState.restore_bytes(blob).digest(now=3.0) \
            == restored.digest(now=3.0)
        assert st.digest(now=3.0) != restored.digest(now=3.0)

    def test_release_and_reacquire(self):
        store = _store(owned=[0, 1])
        st = store.release(1)
        assert store.owned() == [0]
        store.acquire(1, st)
        assert store.owned() == [0, 1]
        with pytest.raises(ValueError):
            store.acquire(0)                      # already owned


# ---------------------------------------------------------------------------
# partition-scoped consumer (stream/transport.py)
# ---------------------------------------------------------------------------


class TestPartitionScopedConsumer:
    def test_polls_only_assigned_partitions(self):
        broker = InMemoryBroker()
        for p in range(4):
            broker.append("t", p % broker.partitions("t"), {"p": p})
        c = broker.consumer(["t"], "g", partitions={"t": [0, 1]})
        got = {r.partition for r in c.poll(100)}
        assert got <= {0, 1}

    def test_set_assignment_sticky_for_retained_partitions(self):
        """Cooperative-sticky: a retained partition keeps its in-memory
        position (no re-poll of in-flight records); an acquired one
        starts from committed."""
        broker = InMemoryBroker()
        for i in range(6):
            broker.append("t", 0, {"i": i})
            broker.append("t", 1, {"i": i})
        c = broker.consumer(["t"], "g", partitions={"t": [0]})
        assert len(c.poll(100)) == 6              # position (t,0) -> 6
        c.set_assignment({"t": [0, 1]})
        got = c.poll(100)
        assert {r.partition for r in got} == {1}  # p0 NOT re-polled
        assert len(got) == 6

    def test_set_assignment_drops_released(self):
        broker = InMemoryBroker()
        broker.append("t", 0, {"x": 1})
        c = broker.consumer(["t"], "g", partitions={"t": [0, 1]})
        c.set_assignment({"t": [1]})
        assert c.assigned_partitions()["t"] == [1]
        assert c.poll(100) == []


# ---------------------------------------------------------------------------
# chaos WorkerKill injector (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


class TestWorkerKillInjector:
    def test_worker_kill_on_chaos_plan(self):
        from realtime_fraud_detection_tpu.chaos import (
            ChaosPlan,
            FaultWindow,
            WorkerKill,
        )

        kills = []

        class StubFleet:
            def kill_worker(self, wid, now=None):
                kills.append((wid, now))

        plan = ChaosPlan([FaultWindow("worker_kill", "cluster", 1.0, 1.1)])
        inj = WorkerKill(StubFleet(), "w2")
        plan.bind("worker_kill", inj)
        plan.poll(0.5)
        assert kills == []
        plan.poll(1.05)
        assert kills == [("w2", 1.05)]
        plan.poll(2.0)                            # one-shot: no re-kill
        assert kills == [("w2", 1.05)] and inj.killed == 1


# ---------------------------------------------------------------------------
# fleet handoff (small-scale unit; the drill is the full acceptance)
# ---------------------------------------------------------------------------


class TestFleetHandoff:
    def test_kill_moves_only_dead_partitions_and_replays(self):
        from realtime_fraud_detection_tpu.cluster.drill import (
            ShardDrillConfig,
            _build_schedule,
            _run_fleet,
        )

        cfg = dataclasses.replace(
            ShardDrillConfig.fast(), num_users=2_000, n_txns=1_024,
            replay_check=False)
        out = _run_fleet(cfg, _build_schedule(cfg), cfg.n_workers,
                         kill=True)
        assert out["kill_target"] is not None
        dead = set(out["pre_kill_assignment"][out["kill_target"]])
        assert set(out["moved_partitions"]) == dead and dead
        assert out["fleet"]["replayed_total"] >= 1
        assert out["committed"] == out["tx_ends"]
        assert out["affinity_violations"] == 0

    def test_handoff_store_roundtrip(self):
        h = HandoffStore()
        assert h.get(3) is None
        h.put(3, 17, b"blob")
        assert h.get(3) == (17, b"blob")
        assert h.offsets() == {3: 17}
        assert h.snapshots_taken == 1


# ---------------------------------------------------------------------------
# sync_cluster Prometheus mirror (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


def _cluster_snapshot(handoffs=2, moved=5):
    return {
        "generation": 2,
        "workers_alive": 3,
        "workers": {"w0": {"partitions_owned": 5},
                    "w1": {"partitions_owned": 4},
                    "w3": {"partitions_owned": 3}},
        "handoffs_total": handoffs,
        "last_replay_depth": 41,
        "router": {"moved_keys_total": moved, "rebalances": 1},
    }


class TestSyncCluster:
    def _cluster_lines(self, collector):
        return "\n".join(
            line for line in
            collector.render_prometheus().splitlines()
            if "cluster_" in line)

    def test_stream_vs_serving_render_identical(self):
        """The render-identical pin every plane's mirror has: two
        collectors (the stream job's and the serving app's) syncing the
        same snapshot expose byte-identical cluster_* series."""
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        a, b = MetricsCollector(), MetricsCollector()
        snap = _cluster_snapshot()
        a.sync_cluster(snap)
        b.sync_cluster(snap)
        assert self._cluster_lines(a) == self._cluster_lines(b)
        assert 'cluster_partitions_owned{worker="w1"} 4' \
            in self._cluster_lines(a)

    def test_honest_counter_deltas(self):
        """Re-syncing the same cumulative totals must not double-count;
        a growing total increments by exactly the delta."""
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_cluster(_cluster_snapshot(handoffs=2, moved=5))
        m.sync_cluster(_cluster_snapshot(handoffs=2, moved=5))
        assert m.cluster_handoff.total() == 2
        assert m.cluster_router_moved_keys.total() == 5
        m.sync_cluster(_cluster_snapshot(handoffs=3, moved=9))
        assert m.cluster_handoff.total() == 3
        assert m.cluster_router_moved_keys.total() == 9

    def test_router_only_snapshot(self):
        """The serving app's router-only shape: handoff series untouched,
        membership + movement mirrored."""
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_cluster({"workers_alive": 2,
                        "workers": {"w0": {"partitions_owned": 6}},
                        "router": {"moved_keys_total": 0}})
        assert m.cluster_workers_alive.value() == 2
        assert m.cluster_handoff.total() == 0


# ---------------------------------------------------------------------------
# FraudScorer store injection (scoring/scorer.py stores= seam)
# ---------------------------------------------------------------------------


class TestScorerStoreInjection:
    @pytest.fixture(scope="class")
    def scorers(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )

        sc = ScorerConfig(text_len=16, tokenizer="word")
        plain = FraudScorer(scorer_config=sc)
        store = PartitionedStore(
            12, seq_len=plain.sc.seq_len,
            feature_dim=plain.sc.feature_dim)
        for p in range(12):
            store.acquire(p)
        sharded = FraudScorer(scorer_config=sc, stores=store)
        return plain, sharded, store

    def test_scores_identical_and_state_lands_in_partitions(self, scorers):
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        plain, sharded, store = scorers
        gen = TransactionGenerator(num_users=64, num_merchants=16, seed=3)
        plain.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        sharded.seed_profiles(gen.users.profiles(),
                              gen.merchants.profiles())
        txns = gen.generate_batch(8)
        a = plain.score_batch(txns, now=1.0)
        b = sharded.score_batch(txns, now=1.0)
        assert [r["fraud_score"] for r in a] \
            == [r["fraud_score"] for r in b]
        # write-back landed in the right partitions
        for txn in txns:
            uid = str(txn["user_id"])
            p = store.partition_for(uid)
            assert store.state(p).velocity.get(
                uid, "5min", 1.0).get("count", 0) >= 1
            assert store.txn_cache.get_transaction(
                str(txn["transaction_id"]), now=1.0) is not None

    def test_replay_state_restores_dedupe_and_history(self, scorers):
        _, sharded, store = scorers
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        gen = TransactionGenerator(num_users=64, num_merchants=16, seed=9)
        txns = gen.generate_batch(4)
        sharded.replay_state(txns, now=2.0)
        for txn in txns:
            cached = store.txn_cache.get_transaction(
                str(txn["transaction_id"]), now=2.0)
            assert cached is not None
            assert cached.get("explanation", {}).get("replay_restored") \
                or cached.get("decision") == "REVIEW"

    def test_stores_and_state_client_mutually_exclusive(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )

        with pytest.raises(ValueError):
            FraudScorer(scorer_config=ScorerConfig(text_len=16,
                                                   tokenizer="word"),
                        stores=_store(), state_client=object())

    def test_history_dim_mismatch_refused(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )

        bad = PartitionedStore(4, seq_len=2, feature_dim=3)
        bad.acquire(0)
        with pytest.raises(ValueError, match="history"):
            FraudScorer(scorer_config=ScorerConfig(text_len=16,
                                                   tokenizer="word"),
                        stores=bad)


# ---------------------------------------------------------------------------
# cluster settings validation
# ---------------------------------------------------------------------------


class TestClusterSettings:
    def test_enabled_requires_workers(self):
        from realtime_fraud_detection_tpu.utils.config import (
            ClusterSettings,
        )

        with pytest.raises(ValueError, match="workers"):
            ClusterSettings(enabled=True).validate()
        with pytest.raises(ValueError, match="worker_id"):
            ClusterSettings(enabled=True, worker_id="w9",
                            workers={"w0": "http://a"}).validate()
        ClusterSettings(enabled=True, worker_id="w0",
                        workers={"w0": "http://a"}).validate()

    def test_bounds(self):
        from realtime_fraud_detection_tpu.utils.config import (
            ClusterSettings,
        )

        with pytest.raises(ValueError):
            ClusterSettings(n_partitions=0).validate()
        with pytest.raises(ValueError):
            ClusterSettings(checkpoint_every=0).validate()


# ---------------------------------------------------------------------------
# serving-side router wiring over live HTTP
# ---------------------------------------------------------------------------


class TestServingShardRouting:
    def test_wrong_shard_421_cluster_endpoint_and_series(self):
        """cluster.enabled serving wiring, end to end over HTTP: a
        wrong-shard /predict answers 421 with the owner + address +
        partition BEFORE admission (no scoring — the test stays cheap:
        the 421 path never compiles a bucket), GET /cluster exposes the
        membership/assignment, and /metrics/prometheus renders the
        cluster_* series from the router snapshot."""
        import asyncio
        import http.client
        import threading

        from realtime_fraud_detection_tpu.serving import ServingApp
        from realtime_fraud_detection_tpu.utils.config import Config

        config = Config()
        config.monitoring.prometheus_port = 0
        config.cluster.enabled = True
        config.cluster.worker_id = "w0"
        config.cluster.workers = {
            f"w{i}": f"http://127.0.0.1:{9100 + i}" for i in range(4)}
        app = ServingApp(config, host="127.0.0.1", port=0)

        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)

            async def _start():
                await app.start()
                started.set()

            loop.run_until_complete(_start())
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(timeout=30)
        try:
            def req(method, path, body=None):
                conn = http.client.HTTPConnection("127.0.0.1", app.port,
                                                  timeout=60)
                payload = json.dumps(body) if body is not None else None
                conn.request(method, path, body=payload,
                             headers={"Content-Type": "application/json"}
                             if payload else {})
                resp = conn.getresponse()
                raw = resp.read()
                conn.close()
                if "json" in resp.getheader("Content-Type", ""):
                    return resp.status, json.loads(raw)
                return resp.status, raw.decode()

            ref = ShardRouter(config.cluster.n_partitions, ["w0", "w1",
                                                            "w2", "w3"],
                              virtual_nodes=config.cluster.virtual_nodes)
            uid = next(f"user_{i:06d}" for i in range(10_000)
                       if ref.route(f"user_{i:06d}") != "w0")
            txn = {"transaction_id": "t_wrong_shard", "user_id": uid,
                   "merchant_id": "m1", "amount": 10.0,
                   "timestamp": 1.0}
            status, data = req("POST", "/predict", txn)
            assert status == 421
            assert data["error"] == "wrong_shard"
            assert data["owner"] == ref.route(uid)
            assert data["location"] == config.cluster.workers[data["owner"]]
            assert data["partition"] == ref.partition_of(uid)

            status, data = req("GET", "/cluster")
            assert status == 200 and data["enabled"]
            assert data["worker_id"] == "w0"
            assert data["members"] == ["w0", "w1", "w2", "w3"]

            status, text = req("GET", "/metrics/prometheus")
            assert status == 200
            lines = [ln for ln in text.splitlines()
                     if ln.startswith("cluster_")]
            assert "cluster_workers_alive 4" in lines
            owned = {m: len(p) for m, p in data["assignment"].items()}
            for m, n in owned.items():
                assert f'cluster_partitions_owned{{worker="{m}"}} {n}' \
                    in lines
        finally:
            asyncio.run_coroutine_threadsafe(app.stop(),
                                             loop).result(timeout=10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# drill compact summary + tier-1 CLI smoke
# ---------------------------------------------------------------------------


class TestCompactSummary:
    def test_under_2kb_even_when_bloated(self):
        from realtime_fraud_detection_tpu.cluster.drill import (
            compact_shard_summary,
        )

        summary = {"metric": "shard_drill", "passed": False,
                   "moved_partitions": list(range(400)),
                   "checks": {f"very_long_check_name_{i}" * 4: False
                              for i in range(64)}}
        compact = compact_shard_summary(summary)
        assert len(json.dumps(compact,
                              separators=(",", ":")).encode()) < 2048


def test_shard_drill_fast_smoke(capsys):
    """Tier-1 acceptance: `rtfd shard-drill --fast` runs un-slow-marked on
    every pass. Pins the whole cluster contract: population sharded over
    4 workers, mid-stream worker kill, checkpointed handoff with zero
    lost / double-scored transactions, gap-free offsets, per-key order,
    sharded state digest-equal to the single-worker oracle, router
    agreement with bounded movement, bit-identical second run."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["shard-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["zero_lost"] and checks["zero_double_scored"]
    assert checks["every_txn_scored_once"]
    assert checks["offsets_gap_free"] and checks["per_key_order_preserved"]
    assert checks["state_equals_oracle"] and checks["scores_equal_oracle"]
    assert checks["handoff_replay_exercised"]
    assert checks["router_agrees_with_fleet"]
    assert checks["only_dead_partitions_moved"]
    assert checks["replay_bit_identical"]
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["digest"] and full["lost"] == 0
    assert full["replayed_total"] >= 1
    assert full["n_workers"] >= 4

"""LSTM + GraphSAGE model and trainer tests (small, CPU-fast)."""

import jax
import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.lstm import (
    init_lstm_params,
    lstm_logits,
    lstm_predict,
)
from realtime_fraud_detection_tpu.models.gnn import (
    build_node_features,
    gather_neighbor_features,
    gnn_predict,
    init_gnn_params,
)
from realtime_fraud_detection_tpu.sim import TransactionGenerator
from realtime_fraud_detection_tpu.training.neural import (
    build_graph_dataset,
    build_sequence_dataset,
    train_gnn,
    train_lstm,
)


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


class TestLSTM:
    def test_shapes_and_range(self):
        params = init_lstm_params(jax.random.PRNGKey(0), 8, hidden=16)
        seqs = np.random.default_rng(0).normal(size=(4, 10, 8)).astype(np.float32)
        p = np.asarray(lstm_predict(params, seqs))
        assert p.shape == (4,)
        assert ((p > 0) & (p < 1)).all()

    def test_length_mask_ignores_padding(self):
        params = init_lstm_params(jax.random.PRNGKey(1), 4, hidden=8)
        rng = np.random.default_rng(1)
        tail = rng.normal(size=(1, 3, 4)).astype(np.float32)
        # same 3-step suffix, once bare, once behind 7 steps of garbage padding
        padded = np.concatenate([np.zeros((1, 7, 4), np.float32), tail], axis=1)
        garbage = np.concatenate([rng.normal(size=(1, 7, 4)).astype(np.float32), tail], axis=1)
        lengths = np.array([3], np.int32)
        a = np.asarray(lstm_logits(params, padded, lengths))
        b = np.asarray(lstm_logits(params, garbage, lengths))
        np.testing.assert_allclose(a, b, atol=1e-5)

    def test_learns_sequential_signal(self):
        # label depends on the mean of the LAST step only - sequence model
        # must read it through the scan
        rng = np.random.default_rng(2)
        n, t, f = 3000, 10, 8
        seqs = rng.normal(size=(n, t, f)).astype(np.float32)
        y = (seqs[:, -1, :].mean(axis=1) > 0).astype(np.float32)
        params = init_lstm_params(jax.random.PRNGKey(2), f, hidden=32)
        from realtime_fraud_detection_tpu.training.neural import NeuralTrainer, bce_loss
        from realtime_fraud_detection_tpu.models.lstm import lstm_logits as ll

        def loss_fn(p, inputs, yy):
            return bce_loss(ll(p, inputs[0]), yy)

        params = NeuralTrainer(epochs=8, seed=0).train(params, loss_fn, (seqs,), y)
        auc = _auc(y, np.asarray(lstm_predict(params, seqs)))
        assert auc > 0.9, f"AUC {auc:.3f}"


class TestGNN:
    def test_shapes_and_range(self):
        nd, k, b = 16, 4, 8
        params = init_gnn_params(jax.random.PRNGKey(0), nd, 64, hidden=32)
        rng = np.random.default_rng(0)
        p = np.asarray(gnn_predict(
            params,
            rng.normal(size=(b, 64)).astype(np.float32),
            rng.normal(size=(b, nd)).astype(np.float32),
            rng.normal(size=(b, nd)).astype(np.float32),
            rng.normal(size=(b, k, nd)).astype(np.float32),
            np.ones((b, k), bool),
            rng.normal(size=(b, k, nd)).astype(np.float32),
            np.ones((b, k), bool),
        ))
        assert p.shape == (b,)
        assert ((p > 0) & (p < 1)).all()

    def test_masked_neighbors_ignored(self):
        nd, k = 8, 4
        params = init_gnn_params(jax.random.PRNGKey(1), nd, 16, hidden=16)
        rng = np.random.default_rng(1)
        txn = rng.normal(size=(1, 16)).astype(np.float32)
        uf = rng.normal(size=(1, nd)).astype(np.float32)
        mf = rng.normal(size=(1, nd)).astype(np.float32)
        neigh = rng.normal(size=(1, k, nd)).astype(np.float32)
        mask1 = np.array([[True, True, False, False]])
        # garbage in masked slots must not change the output
        neigh2 = neigh.copy()
        neigh2[0, 2:] = 1e3
        a = np.asarray(gnn_predict(params, txn, uf, mf, neigh, mask1, neigh, mask1))
        b = np.asarray(gnn_predict(params, txn, uf, mf, neigh2, mask1, neigh2, mask1))
        np.testing.assert_allclose(a, b, atol=1e-5)

    def test_node_feature_tables(self):
        gen = TransactionGenerator(num_users=50, num_merchants=20, seed=0)
        u, m = build_node_features(gen.users, gen.merchants)
        assert u.shape == (50, 16) and m.shape == (20, 16)
        assert (m[:, 8] == 1.0).all() and (u[:, 8] == 0.0).all()  # type tag

    def test_safe_gather_with_padding(self):
        table = np.arange(20, dtype=np.float32).reshape(10, 2)
        idx = np.array([[3, -1]], np.int32)
        mask = idx >= 0
        out = gather_neighbor_features(table, idx, mask)
        np.testing.assert_array_equal(out[0, 0], table[3])


class TestEndToEndTraining:
    @pytest.fixture(scope="class")
    def gen(self):
        return TransactionGenerator(num_users=300, num_merchants=100, seed=9)

    def test_sequence_dataset_builder(self, gen):
        seqs, lens, labels = build_sequence_dataset(gen, 2000, seq_len=5)
        assert seqs.shape == (2000, 5, 64)
        assert (lens >= 1).all()  # current txn always appended first
        assert 0.02 < labels.mean() < 0.1

    def test_graph_dataset_builder(self, gen):
        inputs, labels, (ut, mt, graph) = build_graph_dataset(gen, 2000, fanout=8)
        assert inputs[0].shape[0] == 2000
        assert inputs[3].shape == (2000, 8, 16)
        # later transactions must actually see neighbors
        assert inputs[4][-500:].any()

    def test_lstm_trains_on_stream(self, gen):
        params = train_lstm(gen, n_transactions=6000, epochs=4, seed=1)
        seqs, lens, labels = build_sequence_dataset(gen, 2000)
        auc = _auc(labels, np.asarray(lstm_predict(params, seqs, lens)))
        assert auc > 0.75, f"AUC {auc:.3f}"

    def test_gnn_trains_on_stream(self, gen):
        params, ut, mt, graph = train_gnn(gen, n_transactions=6000, epochs=2, seed=1)
        inputs, labels, _ = build_graph_dataset(gen, 2000)
        p = np.asarray(gnn_predict(params, *[np.asarray(a) for a in inputs]))
        auc = _auc(labels, p)
        assert auc > 0.7, f"AUC {auc:.3f}"


class TestNodeDimGuard:
    def test_small_node_dim_rejected(self):
        gen = TransactionGenerator(num_users=10, num_merchants=5, seed=0)
        with pytest.raises(ValueError, match="node_dim"):
            build_node_features(gen.users, gen.merchants, node_dim=8)

"""Transfer packing (core/packing.py) and the packed scoring seam.

The packed path exists because the streaming hot loop on a remote TPU is
bounded by transport round trips (bench r4: ~85 ms null RTT per blocked
call); correctness requirement: byte-exact round trip and score equivalence
with the unpacked ``score_fused`` program.
"""

import jax
import numpy as np
import pytest

from realtime_fraud_detection_tpu.core.packing import (
    PackSpec,
    pack_tree,
    unpack_tree,
)
from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG
from realtime_fraud_detection_tpu.scoring.pipeline import (
    MODEL_NAMES,
    OUT_COLUMNS,
    ScorerConfig,
    init_scoring_models,
    make_example_batch,
    score_fused,
    score_fused_packed,
)
from realtime_fraud_detection_tpu.utils.config import Config


@pytest.fixture(scope="module")
def batch():
    return make_example_batch(8, ScorerConfig(), rng=np.random.default_rng(7))


def test_pack_unpack_round_trip_exact(batch):
    blobs, spec = pack_tree(batch)
    assert set(blobs) == {"f32", "i32", "u8", "bf16"}
    assert blobs["bf16"].shape == (8, 0)  # nothing opted into bf16 transfer
    assert all(b.shape[0] == 8 for b in blobs.values())
    restored = unpack_tree(blobs, spec)
    orig_leaves = jax.tree_util.tree_flatten(batch)[0]
    new_leaves = jax.tree_util.tree_flatten(restored)[0]
    assert len(orig_leaves) == len(new_leaves)
    for a, b in zip(orig_leaves, new_leaves):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_pack_spec_hashable_and_stable(batch):
    _, s1 = pack_tree(batch)
    _, s2 = pack_tree(batch)
    assert isinstance(s1, PackSpec)
    assert s1 == s2 and hash(s1) == hash(s2)


def test_packed_scoring_matches_dict_path(batch):
    models = init_scoring_models(jax.random.PRNGKey(0))
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    valid = np.ones((len(MODEL_NAMES),), bool)

    ref = score_fused(models, batch, params, jax.numpy.asarray(valid),
                      bert_config=TINY_CONFIG)
    blobs, spec = pack_tree(batch)
    mat = np.asarray(score_fused_packed(
        models, blobs["f32"], blobs["i32"], blobs["u8"], spec=spec,
        params=params, model_valid=jax.numpy.asarray(valid),
        bert_config=TINY_CONFIG))

    assert mat.shape == (8, len(OUT_COLUMNS) + len(MODEL_NAMES))
    for j, name in enumerate(OUT_COLUMNS):
        np.testing.assert_allclose(
            mat[:, j], np.asarray(ref[name], np.float32), rtol=1e-5,
            atol=1e-6, err_msg=name)
    np.testing.assert_allclose(
        mat[:, len(OUT_COLUMNS):], np.asarray(ref["model_predictions"]),
        rtol=1e-5, atol=1e-6)


def test_bf16_transfer_scores_close_to_f32():
    """transfer_bf16 halves the big tensors on the wire; scores must stay
    within bf16 resolution of the f32 path."""
    import ml_dtypes

    from realtime_fraud_detection_tpu.scoring.scorer import FraudScorer
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    gen = TransactionGenerator(num_users=64, num_merchants=16, seed=5)
    records = gen.generate_batch(16)

    def scores(bf16: bool):
        scorer = FraudScorer(seed=0)
        scorer.sc.transfer_bf16 = bf16
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        return np.asarray(
            [r["fraud_probability"] for r in scorer.score_batch(records)])

    f32_scores, bf16_scores = scores(False), scores(True)
    np.testing.assert_allclose(bf16_scores, f32_scores, atol=0.02)


def test_bf16_leaves_ride_the_half_width_blob():
    import ml_dtypes

    tree = {
        "big": np.ones((4, 8), np.float32).astype(ml_dtypes.bfloat16),
        "small": np.ones((4, 2), np.float32),
    }
    blobs, spec = pack_tree(tree)
    assert blobs["bf16"].shape == (4, 8)
    assert blobs["bf16"].dtype == np.dtype(ml_dtypes.bfloat16)
    assert blobs["f32"].shape == (4, 2)
    restored = unpack_tree(blobs, spec)
    assert restored["big"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(np.asarray(restored["small"]),
                                  tree["small"])


def test_pack_rejects_ragged_leading_dim():
    tree = {"a": np.zeros((4, 3), np.float32), "b": np.zeros((5,), np.int32)}
    with pytest.raises(ValueError):
        pack_tree(tree)

"""Text branch tests: tokenizer, attention kernel, BERT, analyzer."""

import jax
import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.bert import (
    TINY_CONFIG,
    BertConfig,
    bert_predict,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.text import (
    TextAnalyzer,
    combined_text,
    detect_fraud_patterns,
    get_text_features,
)
from realtime_fraud_detection_tpu.models.tokenizer import (
    CLS_ID,
    PAD_ID,
    SEP_ID,
    FraudTokenizer,
)
from realtime_fraud_detection_tpu.ops.attention import (
    attention_reference,
    flash_attention,
)


class TestTokenizer:
    def test_preprocess_matches_reference(self):
        # bert_text_analyzer.py:228-251: lower, strip specials, collapse ws
        assert FraudTokenizer.preprocess("  QuickPay!! #1  Wire-Transfer ") == \
            "quickpay 1 wire transfer"

    def test_deterministic_and_special_tokens(self):
        tok = FraudTokenizer(max_length=16)
        a = tok.encode("Bitcoin Exchange LLC")
        b = tok.encode("Bitcoin Exchange LLC")
        assert a == b
        assert a[0] == CLS_ID and a[-1] == SEP_ID

    def test_domain_words_stable_oov_hashed(self):
        tok = FraudTokenizer()
        bitcoin = tok.encode("bitcoin")[1]
        assert bitcoin < 2000  # in-vocab id
        weird = tok.encode("zxqvwk")[1]
        assert 2000 <= weird < tok.vocab_size

    def test_batch_padding_and_mask(self):
        tok = FraudTokenizer(max_length=8)
        ids, mask = tok.encode_batch(["one two", ""])
        assert ids.shape == (2, 8)
        assert mask[0].sum() == 4  # CLS one two SEP
        assert mask[1].sum() == 2  # CLS SEP
        assert (ids[0][~mask[0]] == PAD_ID).all()


class TestFlashAttention:
    @pytest.mark.parametrize("s,block", [(128, 128), (256, 128), (64, 32)])
    def test_matches_reference(self, s, block):
        rng = np.random.default_rng(0)
        b, h, d = 2, 3, 32
        q = rng.normal(size=(b, h, s, d)).astype(np.float32)
        k = rng.normal(size=(b, h, s, d)).astype(np.float32)
        v = rng.normal(size=(b, h, s, d)).astype(np.float32)
        mask = rng.random((b, s)) > 0.3
        mask[:, 0] = True
        ours = np.asarray(flash_attention(q, k, v, mask, block_q=block,
                                          block_k=block, interpret=True))
        ref = np.asarray(attention_reference(q, k, v, mask))
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_fully_masked_rows_no_nan(self):
        rng = np.random.default_rng(1)
        q = rng.normal(size=(1, 1, 64, 16)).astype(np.float32)
        k = rng.normal(size=(1, 1, 64, 16)).astype(np.float32)
        v = rng.normal(size=(1, 1, 64, 16)).astype(np.float32)
        mask = np.zeros((1, 64), bool)  # nothing valid
        out = np.asarray(flash_attention(q, k, v, mask, block_q=32,
                                         block_k=32, interpret=True))
        assert np.isfinite(out).all()

    def test_indivisible_seq_rejected(self):
        q = np.zeros((1, 1, 100, 16), np.float32)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, q, q, block_q=64, block_k=64, interpret=True)


class TestBert:
    def test_logits_shape_and_probs(self):
        cfg = TINY_CONFIG
        params = init_bert_params(jax.random.PRNGKey(0), cfg)
        tok = FraudTokenizer(max_length=32)
        ids, mask = tok.encode_batch(["gift card outlet", "corner grocery store"])
        p = np.asarray(bert_predict(params, ids, mask, cfg))
        assert p.shape == (2,)
        assert ((p > 0) & (p < 1)).all()

    def test_padding_invariance(self):
        # same text at max_length 16 vs 32 must give the same probability
        cfg = TINY_CONFIG
        params = init_bert_params(jax.random.PRNGKey(1), cfg)
        short_tok = FraudTokenizer(max_length=16)
        long_tok = FraudTokenizer(max_length=32)
        text = ["wire transfer co"]
        a = np.asarray(bert_predict(params, *short_tok.encode_batch(text), cfg))
        b = np.asarray(bert_predict(params, *long_tok.encode_batch(text), cfg))
        np.testing.assert_allclose(a, b, atol=2e-3)


class TestTextRules:
    def test_keyword_groups(self):
        # bert_text_analyzer.py:309-342
        p = detect_fraud_patterns({"merchant_name": "QuickBitcoin Wallet",
                                   "description": "urgent gift card reload"})
        assert p["crypto_keywords"] and p["urgent_language"] and p["gift_card_keywords"]
        assert not p["known_scam_patterns"]
        p2 = detect_fraud_patterns({"description": "nigerian prince inheritance"})
        assert p2["known_scam_patterns"]

    def test_combined_text_format(self):
        # bert_text_analyzer.py:253-281
        t = combined_text({"merchant_name": "Acme", "category": "retail"})
        assert t == "Merchant: Acme | Category: retail"

    def test_text_features(self):
        # bert_text_analyzer.py:346-399
        f = get_text_features({"merchant_name": "Shop-24x7!", "description": "pay 99"})
        assert f["merchant_name_length"] == 10
        assert f["numbers_in_merchant"] == 3  # 2, 4, 7
        assert f["special_chars_merchant"] == 2  # '-' and '!'
        assert f["merchant_word_count"] == 1
        assert f["total_word_count"] == 3


class TestTextAnalyzer:
    def test_batched_field_risks_and_overall(self):
        analyzer = TextAnalyzer(config=TINY_CONFIG, max_length=32)
        results = analyzer.analyze_transaction_text([
            {"merchant_name": "Casino Royale", "category": "gambling"},
            {"description": "grocery run"},
            {},
        ])
        r0, r1, r2 = results
        assert {"merchant_name_risk", "combined_text_risk", "overall_text_risk"} <= set(r0)
        # weighted overall (weights .4/.3 renormalized)
        expected = (r0["merchant_name_risk"] * 0.4 + r0["combined_text_risk"] * 0.3) / 0.7
        assert r0["overall_text_risk"] == pytest.approx(expected, rel=1e-5)
        assert "description_risk" in r1 and "merchant_name_risk" not in r1
        assert r2 == {"overall_text_risk": 0.0}

    def test_performance_stats(self):
        analyzer = TextAnalyzer(config=TINY_CONFIG, max_length=16)
        analyzer.analyze_transaction_text([{"merchant_name": "x"}])
        stats = analyzer.get_performance_stats()
        assert stats["total_predictions"] == 1
        assert stats["avg_processing_time_ms"] > 0


class TestTextTraining:
    def test_bert_learns_suspicious_names(self):
        from realtime_fraud_detection_tpu.sim import TransactionGenerator
        from realtime_fraud_detection_tpu.training.text import (
            build_text_dataset,
            train_bert,
        )

        gen = TransactionGenerator(num_users=200, num_merchants=100, seed=4)
        params = train_bert(gen, config=TINY_CONFIG, n_transactions=4000,
                            max_length=32, epochs=3, seed=0)
        ids, mask, labels = build_text_dataset(gen, 2000, max_length=32)
        p = np.asarray(bert_predict(params, ids, mask, TINY_CONFIG))
        order = np.argsort(p)
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(1, len(p) + 1)
        pos = labels > 0.5
        n1, n0 = pos.sum(), (~pos).sum()
        auc = (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
        # text alone is a weak signal (only merchant identity correlates);
        # must still be clearly better than chance
        assert auc > 0.6, f"AUC {auc:.3f}"


class TestKeywordVocabCoupling:
    def test_rule_keywords_are_in_vocab(self):
        from realtime_fraud_detection_tpu.models.keywords import (
            ALL_KEYWORD_GROUPS,
        )

        tok = FraudTokenizer()
        for group in ALL_KEYWORD_GROUPS:
            for phrase in group:
                for word in phrase.split():
                    assert word in tok.vocab, f"{word!r} fell out of the vocab"


class TestWordPiece:
    """models/wordpiece.py: the trained-subword analog of the reference's
    distilbert-base-uncased tokenizer (bert_text_analyzer.py:47-66)."""

    def test_trainer_learns_frequent_words_as_whole_pieces(self):
        from realtime_fraud_detection_tpu.models.wordpiece import (
            train_wordpiece_vocab,
        )

        vocab = train_wordpiece_vocab(
            ["crypto exchange wire transfer"] * 50 + ["casino cash out"] * 30,
            vocab_size=200)
        for w in ("crypto", "exchange", "wire", "transfer", "casino"):
            assert w in vocab, f"frequent word {w!r} not a whole piece"

    def test_greedy_longest_match_and_continuations(self):
        from realtime_fraud_detection_tpu.models.wordpiece import (
            WordPieceTokenizer,
        )

        t = WordPieceTokenizer(vocab=["crypto", "pay", "##pay", "c", "##r"],
                               max_length=16)
        pieces = t.decode_pieces(t.encode("cryptopay"))
        assert pieces == ["[CLS]", "crypto", "##pay", "[SEP]"]

    def test_uncoverable_word_becomes_unk_not_crash(self):
        from realtime_fraud_detection_tpu.models.wordpiece import (
            WordPieceTokenizer,
        )

        t = WordPieceTokenizer(vocab=["abc"], max_length=16)
        pieces = t.decode_pieces(t.encode("abc zzz"))
        assert pieces == ["[CLS]", "abc", "[UNK]", "[SEP]"]

    def test_committed_domain_vocab_loads_and_covers_fraud_terms(self):
        from realtime_fraud_detection_tpu.models.wordpiece import (
            WordPieceTokenizer,
        )

        t = WordPieceTokenizer(max_length=32)   # committed vocab file
        assert t.vocab_size > 1500
        # the planted suspicious-merchant tokens (sim/simulator.py) must
        # tokenize to whole pieces — this is the signal the text branch
        # learns from
        for term in ("crypto", "exchange", "gift", "card", "wire",
                     "transfer", "casino"):
            ids = t.encode(term)
            assert len(ids) == 3, f"{term!r} -> {t.decode_pieces(ids)}"

    def test_encode_batch_shapes_and_special_ids(self):
        import numpy as np

        from realtime_fraud_detection_tpu.models.tokenizer import (
            CLS_ID,
            PAD_ID,
            SEP_ID,
        )
        from realtime_fraud_detection_tpu.models.wordpiece import (
            WordPieceTokenizer,
        )

        t = WordPieceTokenizer(max_length=12)
        ids, mask = t.encode_batch(["crypto exchange", ""])
        assert ids.shape == (2, 12) and mask.shape == (2, 12)
        assert ids.dtype == np.int32
        assert ids[0, 0] == CLS_ID
        assert SEP_ID in ids[0]
        assert ids[1, 2] == PAD_ID and not mask[1, 2]

    def test_scorer_uses_wordpiece_by_config(self):
        from realtime_fraud_detection_tpu.models.wordpiece import (
            WordPieceTokenizer,
        )
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        gen = TransactionGenerator(num_users=16, num_merchants=8, seed=1)
        scorer = FraudScorer(
            scorer_config=ScorerConfig(text_len=32, tokenizer="wordpiece"))
        assert isinstance(scorer.tokenizer, WordPieceTokenizer)
        results = scorer.score_batch(gen.generate_batch(4))
        assert len(results) == 4

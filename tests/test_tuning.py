"""Self-tuning host pipeline (tuning/): forecaster, JIT closer, tuner,
plane wiring, close-reason mirror, per-class queue attribution, and the
autotune drill smoke (ISSUE 6)."""

import asyncio
import json

import pytest

from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector
from realtime_fraud_detection_tpu.tuning import (
    ArrivalForecaster,
    ConfigTuner,
    JitBatchController,
    TuningPlane,
)
from realtime_fraud_detection_tpu.utils.config import (
    Config,
    QosSettings,
    TuningSettings,
)


# ---------------------------------------------------------------- settings
class TestTuningSettings:
    def test_defaults_validate(self):
        TuningSettings().validate()
        Config()  # tree-level validation includes tuning

    def test_rejects_deadline_bounds_violating_qos_budget(self):
        qos = QosSettings(enabled=True, budget_ms=20.0,
                          assemble_margin_ms=2.0)
        with pytest.raises(ValueError, match="violates the QoS budget"):
            TuningSettings(enabled=True,
                           deadline_max_ms=18.5).validate(qos=qos)
        # exactly the budget's assembly slice is allowed
        TuningSettings(enabled=True, deadline_max_ms=18.0).validate(qos=qos)
        # a disabled QoS plane imposes no floor
        TuningSettings(enabled=True, deadline_max_ms=500.0).validate(
            qos=QosSettings(enabled=False))
        # and a DISABLED tuning plane imposes no constraint on an
        # otherwise-valid QoS config (a tight budget must not start
        # failing Config construction just because TuningSettings exists)
        TuningSettings(enabled=False, deadline_max_ms=18.5).validate(qos=qos)
        cfg = Config()
        cfg.qos.enabled = True
        cfg.qos.budget_ms = 8.0
        cfg.validate()                           # tuning disabled: fine

    def test_rejects_empty_or_malformed_bucket_sets(self):
        with pytest.raises(ValueError, match="bucket_sets"):
            TuningSettings(bucket_sets=[]).validate()
        for bad in ([[]], [[8, 1]], [[0, 8]], [[8, 8, 32]]):
            with pytest.raises(ValueError):
                TuningSettings(bucket_sets=bad).validate()

    def test_rejects_inverted_deadline_bounds(self):
        with pytest.raises(ValueError, match="deadline"):
            TuningSettings(deadline_min_ms=5.0,
                           deadline_max_ms=1.0).validate()

    def test_config_tree_rejects_tuning_qos_conflict(self):
        cfg = Config()
        cfg.qos.enabled = True
        cfg.qos.budget_ms = 8.0        # assembly slice = 6 < default 10
        cfg.tuning.enabled = True
        with pytest.raises(ValueError, match="violates the QoS budget"):
            cfg.validate()


# -------------------------------------------------------------- forecaster
class TestArrivalForecaster:
    def test_steady_rate_converges(self):
        f = ArrivalForecaster(bucket_s=0.02)
        t = 0.0
        for _ in range(2000):
            f.observe(t)
            t += 0.001                       # 1000 tps
        assert f.rate(t) == pytest.approx(1000.0, rel=0.1)
        assert f.expected_gap_s(t) == pytest.approx(0.001, rel=0.15)

    def test_gap_ewma_reacts_within_a_few_arrivals(self):
        f = ArrivalForecaster(bucket_s=0.02)
        t = 0.0
        for _ in range(200):
            f.observe(t)
            t += 0.001
        # burst: 10x the rate — the gap estimate must follow within ~10
        # arrivals, far faster than a counting bucket
        for _ in range(12):
            f.observe(t)
            t += 0.0001
        assert f.expected_gap_s(t) < 0.0005

    def test_silence_floors_the_gap(self):
        f = ArrivalForecaster(bucket_s=0.02)
        t = 0.0
        for _ in range(500):
            f.observe(t)
            t += 0.0002                      # 5k tps
        # arrivals stop: the observed silence overrides the stale rate
        assert f.expected_gap_s(t + 0.05) >= 0.05
        # and a long silence decays the folded rate itself
        assert f.rate(t + 10.0) == pytest.approx(0.0, abs=1.0)

    def test_deterministic_replay(self):
        def run():
            f = ArrivalForecaster(bucket_s=0.01, alpha=0.6)
            t = 0.0
            out = []
            for i in range(300):
                f.observe(t, n=1 + i % 3)
                t += 0.0007
                out.append(round(f.rate(t), 6))
            return out

        assert run() == run()


# -------------------------------------------------------------- controller
def _fed_controller(rate_tps: float, t_end: float = 1.0,
                    **kw) -> JitBatchController:
    c = JitBatchController(**kw)
    t = t_end - 0.2
    gap = 1.0 / rate_tps
    while t < t_end:
        c.observe(t)
        t += gap
    return c


class TestJitBatchController:
    def test_trough_closes_immediately(self):
        # 100 tps: waiting 10 ms for one more txn can never pay
        c = _fed_controller(100.0)
        d = c.should_close(1, first_ts=1.0, now=1.0001)
        assert d.close and d.reason == "jit"

    def test_high_rate_waits_then_closes_sustainably(self):
        c = _fed_controller(20_000.0, max_wait_ms=10.0)
        # teach the service model a fixed-cost curve
        for b, ms in ((1, 2.0), (32, 2.2), (128, 2.8)):
            c.observe_batch(b, ms / 1e3)
        d = c.should_close(4, first_ts=1.0, now=1.0002)
        assert not d.close                       # undersized: keep filling
        d = c.should_close(120, first_ts=1.0, now=1.006)
        assert d.close                           # sustainable: hand off

    def test_max_wait_bound_closes_deadline(self):
        c = _fed_controller(20_000.0, max_wait_ms=2.0)
        d = c.should_close(4, first_ts=1.0, now=1.0025)
        assert d.close and d.reason == "deadline"

    def test_budget_close_by_caps_headroom(self):
        c = _fed_controller(20_000.0, max_wait_ms=50.0)
        # the QoS budget says hand off by t=1.001 — the controller must
        # close NOW even though its own bound has headroom left
        d = c.should_close(4, first_ts=1.0, now=1.002, close_by=1.001)
        assert d.close and d.reason == "deadline"

    def test_decisions_counted(self):
        c = _fed_controller(100.0)
        c.should_close(1, 1.0, 1.0001)
        assert c.decisions["jit"] == 1
        snap = c.snapshot()
        assert snap["decisions"]["jit"] == 1
        assert snap["buckets"] == [1, 8, 32, 128, 256]


# ------------------------------------------------------------------- tuner
def _tuner(**kw) -> ConfigTuner:
    s = TuningSettings(enabled=True, tune_interval_batches=5,
                       hysteresis_frac=0.05, tuner_cooldown_epochs=0, **kw)
    c = JitBatchController(max_wait_ms=s.deadline_max_ms)
    return ConfigTuner(s, c)


def _feed_epoch(t, now, latency_ms, n=64):
    for _ in range(t.settings.tune_interval_batches):
        t.observe_result(latency_ms, n=4)
        now += 0.01
        t.on_batch(now)
    return now


class TestConfigTuner:
    def test_trial_reverts_on_regression(self):
        t = _tuner()
        now = _feed_epoch(t, 0.0, 5.0)          # baseline epoch
        now = _feed_epoch(t, now, 5.0)          # rolling baseline + trial
        assert t.counters["trials"] == 1
        saved = t._trial["saved"]
        dim = t._trial["dim"]
        now = _feed_epoch(t, now, 9.0)          # trial epoch measured WORSE
        assert t.counters["reverted"] == 1
        assert t._get(dim) == saved             # knob restored

    def test_trial_accepted_on_improvement(self):
        t = _tuner()
        now = _feed_epoch(t, 0.0, 5.0)
        now = _feed_epoch(t, now, 5.0)          # proposes a trial
        assert t.counters["trials"] == 1
        _feed_epoch(t, now, 3.0)                # clearly better
        assert t.counters["accepted"] == 1

    def test_freezes_when_ladder_degrades(self):
        """Satellite: when the QoS ladder sits above rung 0 the tuner
        must freeze — revert any in-flight trial and start none — rather
        than fight the control loop that owns the emergency."""
        t = _tuner()
        now = _feed_epoch(t, 0.0, 5.0)
        now = _feed_epoch(t, now, 5.0)
        assert t._trial is not None
        saved, dim = t._trial["saved"], t._trial["dim"]
        for _ in range(t.settings.tune_interval_batches):
            t.observe_result(5.0, n=4)
            now += 0.01
            t.on_batch(now, ladder_level=1)
        assert t.frozen
        assert t._trial is None
        assert t._get(dim) == saved
        assert t.counters["frozen_epochs"] == 1
        assert t.counters["reverted"] == 1
        # no new trial starts while frozen
        for _ in range(t.settings.tune_interval_batches):
            t.observe_result(5.0, n=4)
            now += 0.01
            t.on_batch(now, ladder_level=1)
        assert t._trial is None and t.frozen
        # calm again: unfreezes and resumes trialing eventually
        now = _feed_epoch(t, now, 5.0)
        assert not t.frozen

    def test_deadline_knob_clamped_to_validated_range(self):
        t = _tuner(deadline_min_ms=1.0, deadline_max_ms=4.0)
        assert 1.0 <= t.controller.max_wait_ms <= 4.0
        for _ in range(20):                     # no proposal may escape
            for dim in ("max_wait",):
                v = t._propose(dim)
                if v is not None:
                    assert 1.0 <= v <= 4.0
                    t._set(dim, v)
        assert 1.0 <= t.controller.max_wait_ms <= 4.0


# ------------------------------------------------------------------- plane
class TestTuningPlane:
    def test_delegates_and_snapshots(self):
        p = TuningPlane(TuningSettings(enabled=True))
        for i in range(50):
            p.observe(1.0 + i * 0.01)
        d = p.should_close(1, 1.499, 1.5)
        assert d.close and d.reason == "jit"     # 100 tps: close at once
        p.on_batch_complete(32, 0.002, 1.5, latencies_ms=[3.0] * 8)
        snap = p.snapshot()
        assert snap["enabled"]
        assert snap["controller"]["decisions"]["jit"] >= 1
        assert "tuner" in snap and "forecast_tps" in snap

    def test_signals_fn_feeds_freeze(self):
        s = TuningSettings(enabled=True, tune_interval_batches=1)
        p = TuningPlane(s)
        p.signals_fn = lambda: (0.0, 2)          # ladder degraded
        p.on_batch_complete(8, 0.001, 1.0, latencies_ms=[2.0])
        assert p.tuner.frozen

    def test_job_inflight_depth_follows_recommendation(self):
        from realtime_fraud_detection_tpu.stream.job import (
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream.transport import (
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.tuning.drill import (
            AutotuneDrillConfig,
            AutotuneDrillScorer,
        )

        plane = TuningPlane(TuningSettings(
            enabled=True, inflight_min=1, inflight_max=6))
        job = StreamJob(InMemoryBroker(),
                        AutotuneDrillScorer(AutotuneDrillConfig()),
                        JobConfig(pipeline_depth=2, autotune=plane))
        assert job.assembler.controller is plane
        plane.tuner.inflight_depth = 5
        assert job._inflight_depth() == 5

    def test_sync_autotune_honest_deltas(self):
        p = TuningPlane(TuningSettings(enabled=True))
        for i in range(20):
            p.observe(1.0 + i * 0.001)
        p.should_close(1, 1.019, 1.02)
        mc = MetricsCollector()
        snap = p.snapshot()
        mc.sync_autotune(snap)
        total = mc.autotune_decisions.total()
        assert total >= 1
        mc.sync_autotune(snap)                   # unchanged → +0
        assert mc.autotune_decisions.total() == total
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_autotune(snap)
        b.sync_autotune(snap)

        def lines(m):
            return [ln for ln in m.render_prometheus().splitlines()
                    if ln.startswith("autotune_")]

        assert lines(a) == lines(b)


# -------------------------------------------------- close-reason mirroring
class TestCloseReasonMirror:
    def _stream_reasons(self):
        from realtime_fraud_detection_tpu.stream import topics as T
        from realtime_fraud_detection_tpu.stream.microbatch import (
            MicrobatchAssembler,
        )
        from realtime_fraud_detection_tpu.stream.transport import (
            InMemoryBroker,
        )

        clock = [0.0]
        broker = InMemoryBroker()
        consumer = broker.consumer([T.TRANSACTIONS], "g")
        asm = MicrobatchAssembler(consumer, max_batch=4, max_delay_ms=5.0,
                                  clock=lambda: clock[0])
        for i in range(4):                       # one full batch
            broker.produce(T.TRANSACTIONS, {"transaction_id": str(i)})
        assert asm.next_batch(block=False)
        assert asm.last_close_reason == "size"
        broker.produce(T.TRANSACTIONS, {"transaction_id": "tail"})
        assert asm.next_batch(block=False) == []
        clock[0] += 0.006                        # deadline passes
        assert asm.next_batch(block=False)
        assert asm.last_close_reason == "deadline"
        broker.produce(T.TRANSACTIONS, {"transaction_id": "tail2"})
        asm.next_batch(block=False)
        assert asm.flush()
        return asm.close_reasons

    def test_stream_assembler_histogram(self):
        reasons = self._stream_reasons()
        assert reasons == {"size": 1, "deadline": 1, "flush": 1}

    def test_serving_batcher_histogram(self):
        from realtime_fraud_detection_tpu.serving.batcher import (
            RequestMicrobatcher,
        )

        async def main():
            b = RequestMicrobatcher(lambda txns: [dict(t) for t in txns],
                                    max_batch=2, deadline_ms=10.0)
            await b.start()
            # two concurrent submits → one size-closed batch
            r = await asyncio.gather(b.submit({"transaction_id": "a"}),
                                     b.submit({"transaction_id": "b"}))
            assert len(r) == 2
            # a lone submit → deadline close
            await b.submit({"transaction_id": "c"})
            await b.stop()
            return dict(b.close_reasons)

        reasons = asyncio.run(main())
        assert reasons.get("size") == 1
        assert reasons.get("deadline") == 1

    def test_controller_batcher_drains_backlog_in_full_batches(self):
        """Regression: after a stall, aged waiters must NOT deadline-
        close at size 1 while a full batch sits in the queue — the JIT
        path drains available requests before consulting the
        controller (poll first, decide second)."""
        from realtime_fraud_detection_tpu.serving.batcher import (
            RequestMicrobatcher,
        )

        async def main():
            sizes = []

            def score(txns):
                sizes.append(len(txns))
                return [dict(t) for t in txns]

            ctrl = JitBatchController(max_wait_ms=0.5)
            b = RequestMicrobatcher(score, max_batch=8, deadline_ms=5.0,
                                    controller=ctrl)
            # a backlog forms while the drain task isn't running (the
            # stalled-pipeline shape), and every waiter ages past the
            # controller's max-wait bound
            futs = [b.submit_nowait({"transaction_id": str(i)})
                    for i in range(16)]
            await asyncio.sleep(0.01)
            await b.start()
            await asyncio.gather(*futs)
            await b.stop()
            return sizes

        sizes = asyncio.run(main())
        assert max(sizes) == 8, sizes        # full batches, not size-1
        assert len(sizes) <= 3

    def test_mirror_identical_between_stream_and_serving(self):
        """Satellite: the SAME close-reason histogram mirrored through
        the stream job's and the serving app's collectors renders
        identical microbatch_close_reason_total series, and re-syncing
        an unchanged histogram adds zero (honest counters)."""
        reasons = self._stream_reasons()
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_microbatch(reasons)
        b.sync_microbatch(reasons)

        def lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if ln.startswith("microbatch_close_reason_total")]

        assert lines(a) == lines(b)
        assert a.microbatch_close_reason.value(reason="size") == 1
        a.sync_microbatch(reasons)               # unchanged → +0
        assert a.microbatch_close_reason.value(reason="size") == 1
        reasons["size"] += 2
        a.sync_microbatch(reasons)
        assert a.microbatch_close_reason.value(reason="size") == 3


# -------------------------------------------- per-class queue attribution
class TestQueueByPriority:
    def _tracer(self, clock):
        from realtime_fraud_detection_tpu.obs.tracing import Tracer
        from realtime_fraud_detection_tpu.utils.config import (
            TracingSettings,
        )

        return Tracer(TracingSettings(enabled=True, slo_bucket_s=0.01,
                                      slo_fast_window_s=1.0,
                                      slo_slow_window_s=2.0),
                      clock=lambda: clock[0])

    def test_per_class_contributions_sum_to_aggregate(self):
        """Regression pin: for every quantile, the per-class queue
        contributions sum exactly to the aggregate queue figure."""
        clock = [0.0]
        tracer = self._tracer(clock)
        for i in range(30):
            # same e2e, mixed classes: both classes land in every tail
            hi = tracer.begin(f"h{i}", t_admit=clock[0], priority="high")
            lo = tracer.begin(f"l{i}", t_admit=clock[0], priority="low")
            clock[0] += 0.004 + 0.0001 * (i % 5)
            tb = tracer.batch([hi, lo], batch_size=2)
            tb.mark("assemble")
            clock[0] += 0.002
            tracer.finish_batch(tb)
        bd = tracer.breakdown()
        for q in ("p50", "p95", "p99"):
            row = bd["quantiles"][q]
            split = row["queue_ms_by_priority"]
            assert set(split) == {"high", "low"}
            total = sum(v["contrib_ms"] for v in split.values())
            assert total == pytest.approx(row["stage_ms"]["queue"],
                                          rel=1e-3)

    def test_split_names_the_waiting_class(self):
        """The operator question the split answers: IS high-value
        traffic the one waiting? Here only low-priority batches wait
        long, so the tail's queue attribution must be all-low."""
        clock = [0.0]
        tracer = self._tracer(clock)
        for i in range(20):
            lo = tracer.begin(f"l{i}", t_admit=clock[0], priority="low")
            clock[0] += 0.009                    # low waits 9 ms
            tb = tracer.batch([lo], batch_size=1)
            tb.mark("assemble")
            clock[0] += 0.002
            tracer.finish_batch(tb)
            hi = tracer.begin(f"h{i}", t_admit=clock[0], priority="high")
            clock[0] += 0.001                    # high waits 1 ms
            tb = tracer.batch([hi], batch_size=1)
            tb.mark("assemble")
            clock[0] += 0.002
            tracer.finish_batch(tb)
        p99 = tracer.breakdown()["quantiles"]["p99"]
        split = p99["queue_ms_by_priority"]
        assert set(split) == {"low"}
        assert split["low"]["contrib_ms"] == pytest.approx(
            p99["stage_ms"]["queue"], rel=1e-3)

    def test_unclassified_bucket_when_no_qos(self):
        clock = [0.0]
        tracer = self._tracer(clock)
        ctx = tracer.begin("u1", t_admit=0.0)
        clock[0] = 0.004
        tb = tracer.batch([ctx], batch_size=1)
        tb.mark("assemble")
        clock[0] = 0.005
        tracer.finish_batch(tb)
        bd = tracer.breakdown()
        assert set(bd["quantiles"]["p99"]["queue_ms_by_priority"]) == \
            {"unclassified"}


# ----------------------------------------------------- off-path identity
class TestOffPathBitIdentical:
    def _replay(self, autotune):
        from realtime_fraud_detection_tpu.stream import topics as T
        from realtime_fraud_detection_tpu.stream.job import (
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream.microbatch import (
            MicrobatchAssembler,
        )
        from realtime_fraud_detection_tpu.stream.transport import (
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.tuning.drill import (
            AutotuneDrillConfig,
            AutotuneDrillScorer,
        )

        clock = [0.0]
        broker = InMemoryBroker()
        scorer = AutotuneDrillScorer(AutotuneDrillConfig())
        job = StreamJob(broker, scorer, JobConfig(
            max_batch=8, max_delay_ms=2.0, emit_features=False,
            emit_enriched=False, autotune=autotune))
        job.assembler = MicrobatchAssembler(
            job.consumer, max_batch=8, max_delay_ms=2.0,
            clock=lambda: clock[0], controller=job.tuning)
        seq = []
        for i in range(40):
            broker.produce(T.TRANSACTIONS,
                           {"transaction_id": f"x{i}", "user_id": "u",
                            "amount": 10.0, "timestamp": str(clock[0])},
                           timestamp=clock[0])
            clock[0] += (0.0003 if i % 7 else 0.004)
            batch = job.assembler.next_batch(block=False)
            if batch:
                seq.append((job.assembler.last_close_reason, len(batch)))
                ctx = job.dispatch_batch(batch, now=clock[0])
                if ctx is not None:
                    job.complete_batch(ctx, now=clock[0])
        tail = job.assembler.flush()
        if tail:
            seq.append((job.assembler.last_close_reason, len(tail)))
        return seq

    def test_autotune_off_is_the_fixed_deadline_path(self):
        """With autotune off (the default), close decisions must be
        bit-identical to the pre-tuning fixed-deadline behavior — the
        assembler takes the controller branch only when one is attached,
        and this sequence pins the off-path decisions exactly."""
        a = self._replay(autotune=None)
        b = self._replay(autotune=None)
        assert a == b
        assert all(r in ("size", "deadline", "flush") for r, _ in a)
        assert any(r == "size" for r, _ in a)
        assert any(r == "deadline" for r, _ in a)
        # the JIT path makes different (jit-reason) decisions — proving
        # the off path really is off, not coincidentally equal
        s = TuningSettings(enabled=True)
        c = self._replay(autotune=TuningPlane(s))
        assert any(r == "jit" for r, _ in c)

    def test_jobconfig_default_attaches_no_controller(self):
        from realtime_fraud_detection_tpu.stream.job import (
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream.transport import (
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.tuning.drill import (
            AutotuneDrillConfig,
            AutotuneDrillScorer,
        )

        job = StreamJob(InMemoryBroker(),
                        AutotuneDrillScorer(AutotuneDrillConfig()),
                        JobConfig())
        assert job.tuning is None
        assert job.assembler.controller is None


# -------------------------------------------------------------- the drill
def test_autotune_drill_fast_smoke(capsys):
    """Satellite: the `rtfd autotune-drill --fast` acceptance path runs
    un-slow-marked on every tier-1 pass — through the CLI entry, pinning
    that the JIT controller beats every static config on admitted p99 at
    equal-or-better throughput with no high-value sheds, inside the QoS
    budget, reproducibly (final stdout line: the compact <2 KB
    verdict)."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["autotune-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    assert compact["checks"]["beats_every_static_p99"]
    assert compact["checks"]["throughput_equal_or_better"]
    assert compact["checks"]["no_high_value_sheds"]
    assert compact["checks"]["reproducible"]
    best = min(compact["static_p99_ms"].values())
    assert compact["controller"]["p99_ms"] < best
    full = json.loads(out[-2])
    assert full["checks"]["qos_budget_respected"]


def test_arrival_process_feeds_the_drill():
    """The drill consumes the first-class simulator arrival process —
    same seed, same timeline."""
    from realtime_fraud_detection_tpu.tuning.drill import (
        AutotuneDrillConfig,
        _arrivals,
    )

    cfg = AutotuneDrillConfig.fast()
    a = _arrivals(cfg)
    b = _arrivals(cfg)
    assert [t for t, _ in a] == [t for t, _ in b]
    assert all(a[i][0] <= a[i + 1][0] for i in range(len(a) - 1))
    amounts = {txn["amount"] for _, txn in a}
    assert amounts == {1000.0, 60.0, 5.0}

"""Serving layer: live HTTP server, microbatching, all §2.7 endpoints."""

import http.client
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from realtime_fraud_detection_tpu.serving import (
    RequestMicrobatcher,
    validate_batch,
    validate_transaction,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator


# ---------------------------------------------------------------------------
# validation (pure)
# ---------------------------------------------------------------------------

class TestValidation:
    def test_valid_transaction_normalizes(self):
        txn, errs = validate_transaction({
            "transaction_id": "t1", "user_id": 7, "merchant_id": "m1",
            "amount": "12.5",
        })
        assert errs == []
        assert txn["amount"] == 12.5
        assert txn["user_id"] == "7"

    def test_missing_required(self):
        _, errs = validate_transaction({"transaction_id": "t1"})
        assert any("user_id" in e for e in errs)
        assert any("amount" in e for e in errs)

    def test_bad_amount(self):
        _, errs = validate_transaction(
            {"transaction_id": "t", "user_id": "u", "merchant_id": "m",
             "amount": "NaN"})
        assert any("amount" in e for e in errs)

    def test_batch_forms_and_limit(self):
        good = {"transaction_id": "t", "user_id": "u", "merchant_id": "m",
                "amount": 1.0}
        txns, errs = validate_batch({"transactions": [good]}, limit=10)
        assert errs == [] and len(txns) == 1
        _, errs = validate_batch([good] * 11, limit=10)
        assert any("exceeds limit" in e for e in errs)
        _, errs = validate_batch({"nope": 1}, limit=10)
        assert errs


# ---------------------------------------------------------------------------
# microbatcher (asyncio, no device)
# ---------------------------------------------------------------------------

class TestRequestMicrobatcher:
    def test_coalesces_concurrent_requests(self):
        import asyncio

        seen_sizes = []

        def fake_score(txns):
            seen_sizes.append(len(txns))
            return [{"transaction_id": t["transaction_id"], "i": i}
                    for i, t in enumerate(txns)]

        async def main():
            b = RequestMicrobatcher(fake_score, max_batch=64, deadline_ms=20)
            await b.start()
            results = await asyncio.gather(
                *[b.submit({"transaction_id": f"t{i}"}) for i in range(16)])
            await b.stop()
            return results

        results = asyncio.run(main())
        assert len(results) == 16
        # all 16 submitted together -> far fewer device calls than requests
        assert len(seen_sizes) <= 4
        assert sum(seen_sizes) == 16
        # each waiter got ITS OWN row back
        assert all(r["transaction_id"] == f"t{i}"
                   for i, r in enumerate(results))

    def test_score_failure_propagates(self):
        import asyncio

        def boom(txns):
            raise RuntimeError("device fell over")

        async def main():
            b = RequestMicrobatcher(boom, max_batch=4, deadline_ms=1)
            await b.start()
            with pytest.raises(RuntimeError, match="device fell over"):
                await b.submit({"transaction_id": "t"})
            await b.stop()

        asyncio.run(main())

    def test_injected_clock_drives_deadline_close(self):
        """Regression pin for the ISSUE 7 clock-discipline fix: the
        batcher's deadline logic reads its injected clock, never bare
        time.monotonic. With a 60 s configured window, a virtual clock
        leaping past the deadline must close the batch in ~zero real
        time — under the old bare-monotonic code this test times out."""
        import asyncio
        import time as _t

        vnow = [100.0]

        def fake_score(txns):
            return [dict(t) for t in txns]

        async def main():
            b = RequestMicrobatcher(fake_score, max_batch=64,
                                    deadline_ms=60_000.0,
                                    clock=lambda: vnow[0])
            await b.start()
            fut0 = b.submit_nowait({"i": 0})
            await asyncio.sleep(0.05)     # drain loop is inside the window
            vnow[0] += 120.0              # virtual clock leaps past it
            fut1 = b.submit_nowait({"i": 1})  # wakes the drain loop
            t0 = _t.monotonic()
            results = await asyncio.wait_for(
                asyncio.gather(fut0, fut1), timeout=10.0)
            real_s = _t.monotonic() - t0
            reasons = dict(b.close_reasons)
            await b.stop()
            return results, real_s, reasons

        results, real_s, reasons = asyncio.run(main())
        assert results == [{"i": 0}, {"i": 1}]
        assert real_s < 5.0               # nowhere near the 60 s window
        assert reasons.get("deadline", 0) >= 1

    def test_submit_racing_stop_does_not_hang(self):
        import asyncio

        def fake_score(txns):
            return [dict(t) for t in txns]

        async def main():
            b = RequestMicrobatcher(fake_score, max_batch=4, deadline_ms=5)
            await b.start()
            # enqueue a submit concurrently with stop: the waiter must
            # resolve either way (flush-behind-sentinel path)
            sub = asyncio.get_running_loop().create_task(b.submit({"i": 1}))
            await asyncio.sleep(0)               # let submit pass _closed
            stop = asyncio.get_running_loop().create_task(b.stop())
            result = await asyncio.wait_for(sub, timeout=5)
            await stop
            return result

        assert asyncio.run(main()) == {"i": 1}

    def test_max_batch_respected(self):
        import asyncio

        sizes = []

        def fake_score(txns):
            sizes.append(len(txns))
            return [dict(t) for t in txns]

        async def main():
            b = RequestMicrobatcher(fake_score, max_batch=8, deadline_ms=50)
            await b.start()
            await asyncio.gather(*[b.submit({"i": i}) for i in range(20)])
            await b.stop()

        asyncio.run(main())
        assert max(sizes) <= 8
        assert sum(sizes) == 20


# ---------------------------------------------------------------------------
# live server (session-scoped: one compile)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def app_server():
    import asyncio

    from realtime_fraud_detection_tpu.serving import ServingApp
    from realtime_fraud_detection_tpu.utils.config import Config

    config = Config()
    config.serving.microbatch_deadline_ms = 10.0
    # each new batch bucket compiles once (~tens of seconds on the CPU test
    # backend); the timeout must cover compilation, not just steady state
    config.serving.prediction_timeout_seconds = 180.0
    # no fixed-port metrics listener in the shared fixture (8081 could
    # collide across test runs); the dedicated-port behavior has its own test
    config.monitoring.prometheus_port = 0
    # tracing plane on: every /predict in this module flows through the
    # flight recorder, so /latency/breakdown, /slo and the trace_* series
    # are exercised against live traffic (the plane must not perturb any
    # other endpoint's behavior — these tests pin that too)
    config.tracing.enabled = True
    app = ServingApp(config, host="127.0.0.1", port=0)
    gen = TransactionGenerator(num_users=128, num_merchants=32)
    app.scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def _start():
            await app.start()
            started.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    yield app, gen
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)


def _request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    payload = json.dumps(body) if body is not None else None
    conn.request(method, path, body=payload,
                 headers={"Content-Type": "application/json"} if payload else {})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    ctype = resp.getheader("Content-Type", "")
    data = json.loads(raw) if "json" in ctype else raw.decode()
    return resp.status, data


def _txn(gen):
    return gen.generate_batch(1)[0]


class TestEndpoints:
    def test_predict_returns_fraud_prediction_schema(self, app_server):
        app, gen = app_server
        status, data = _request(app.port, "POST", "/predict", _txn(gen))
        assert status == 200
        for field in ("transaction_id", "fraud_probability", "fraud_score",
                      "risk_level", "decision", "model_predictions",
                      "confidence", "processing_time_ms", "explanation"):
            assert field in data, field
        assert 0.0 <= data["fraud_probability"] <= 1.0
        assert data["decision"] in ("APPROVE", "APPROVE_WITH_MONITORING",
                                    "REVIEW", "DECLINE")
        assert set(data["model_predictions"]) == {
            "xgboost_primary", "lstm_sequential", "bert_text",
            "graph_neural", "isolation_forest"}

    def test_prediction_cache_serves_idempotent_retry(self, app_server):
        """Reference TTL prediction cache (ensemble_predictor.py:437-471):
        a retried transaction_id serves the stored response without
        re-scoring; /health exposes the cache stats."""
        app, gen = app_server
        txn = _txn(gen)
        _, first = _request(app.port, "POST", "/predict", txn)
        hits_before = app.prediction_cache.hits
        _, retry = _request(app.port, "POST", "/predict", txn)
        assert app.prediction_cache.hits == hits_before + 1
        assert retry["fraud_probability"] == first["fraud_probability"]
        assert retry["transaction_id"] == first["transaction_id"]
        _, health = _request(app.port, "GET", "/health")
        assert health["prediction_cache"]["hits"] >= 1

    def test_admission_control_sheds_load_at_capacity(self, app_server):
        """max_concurrent_predictions (reference config.py:86) is enforced:
        beyond the cap the request gets an immediate 503, and the in-flight
        counter returns to zero so service resumes."""
        app, gen = app_server
        limit_before = app.config.serving.max_concurrent_predictions
        app.config.serving.max_concurrent_predictions = 5
        try:
            # oversize (can NEVER fit): non-retryable 413, not 503
            status, data = _request(app.port, "POST", "/batch-predict",
                                    {"transactions": gen.generate_batch(10)})
            assert status == 413
            assert "split into smaller batches" in json.dumps(data)
            assert app._inflight_txns == 0
            # transient overload (fits when load drains): 503
            app._inflight_txns = 3
            status, data = _request(app.port, "POST", "/batch-predict",
                                    {"transactions": gen.generate_batch(4)})
            assert status == 503
            assert "at capacity" in json.dumps(data)
            assert app._inflight_txns == 3
            app._inflight_txns = 0
            # within the cap: served normally, counter drains
            status, data = _request(app.port, "POST", "/batch-predict",
                                    {"transactions": gen.generate_batch(4)})
            assert status == 200 and data["count"] == 4
            assert app._inflight_txns == 0
        finally:
            app.config.serving.max_concurrent_predictions = limit_before

    def test_dedicated_prometheus_port(self):
        """config.monitoring.prometheus_port runs a second listener serving
        GET /metrics in Prometheus text (reference: metrics on 8081
        separate from the API)."""
        import asyncio
        import socket

        from realtime_fraud_detection_tpu.serving import ServingApp
        from realtime_fraud_detection_tpu.utils.config import Config

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            free_port = s.getsockname()[1]
        config = Config()
        config.monitoring.prometheus_port = free_port
        app = ServingApp(config, host="127.0.0.1", port=0)
        assert app.metrics_http is not None

        async def main():
            await app.start()
            try:
                # _request blocks; run it off-loop so the server can answer
                return await asyncio.to_thread(
                    _request, free_port, "GET", "/metrics")
            finally:
                await app.stop()

        status, text = asyncio.run(main())
        assert status == 200
        assert "rtfd" in str(text) or "predictions" in str(text)

    def test_prediction_cache_unit_ttl_and_eviction(self):
        from realtime_fraud_detection_tpu.serving.cache import PredictionCache

        c = PredictionCache(ttl_seconds=10.0, max_entries=3)
        for i in range(5):
            c.put(f"t{i}", {"i": i}, now=float(i))
        # oldest two evicted by the size bound
        assert c.get("t0", now=5.0) is None
        assert c.get("t1", now=5.0) is None
        assert c.get("t4", now=5.0) == {"i": 4}
        # TTL expiry: inserted at t=4, TTL 10 -> gone just past t=14
        assert c.get("t4", now=13.9) == {"i": 4}
        assert c.get("t4", now=14.1) is None
        assert c.stats()["max_entries"] == 3

    def test_predict_validation_422(self, app_server):
        app, _ = app_server
        status, data = _request(app.port, "POST", "/predict",
                                {"transaction_id": "x"})
        assert status == 422
        assert any("user_id" in e for e in data["detail"])

    def test_concurrent_predicts_microbatch(self, app_server):
        app, gen = app_server
        txns = gen.generate_batch(32)
        batches_before = app.batcher.batches

        with ThreadPoolExecutor(max_workers=32) as ex:
            out = list(ex.map(
                lambda t: _request(app.port, "POST", "/predict", t), txns))
        assert all(s == 200 for s, _ in out)
        ids = {d["transaction_id"] for _, d in out}
        assert len(ids) == 32                    # every caller got its own row
        batches_done = app.batcher.batches - batches_before
        assert batches_done < 32                 # real coalescing happened

    def test_batch_predict(self, app_server):
        app, gen = app_server
        txns = gen.generate_batch(8)
        status, data = _request(app.port, "POST", "/batch-predict",
                                {"transactions": txns})
        assert status == 200
        assert data["count"] == 8
        assert len(data["results"]) == 8

    def test_health(self, app_server):
        app, _ = app_server
        status, data = _request(app.port, "GET", "/health")
        assert status == 200
        assert data["status"] == "healthy"
        assert data["models_loaded"] == 5

    def test_metrics_json_and_prometheus(self, app_server):
        app, gen = app_server
        _request(app.port, "POST", "/predict", _txn(gen))
        status, data = _request(app.port, "GET", "/metrics")
        assert status == 200 and data["total_predictions"] >= 1
        status, text = _request(app.port, "GET", "/metrics/prometheus")
        assert status == 200
        assert "ml_predictions_total" in text
        assert "scoring_microbatch_size_bucket" in text

    def test_model_info(self, app_server):
        app, _ = app_server
        status, data = _request(app.port, "GET", "/model-info")
        assert status == 200
        assert data["num_models"] == 5
        weights = [m["weight"] for m in data["models"].values()]
        assert abs(sum(weights) - 1.0) < 1e-6

    def test_reload_models_reinit(self, app_server):
        app, gen = app_server
        status, data = _request(app.port, "POST", "/reload-models",
                                {"seed": 123})
        assert status == 200 and data["status"] == "reloaded"
        # service still scores after the swap
        status, data = _request(app.port, "POST", "/predict", _txn(gen))
        assert status == 200

    def test_reload_from_checkpoint(self, app_server, tmp_path):
        import jax

        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
        from realtime_fraud_detection_tpu.scoring import init_scoring_models

        app, gen = app_server
        models = init_scoring_models(jax.random.PRNGKey(99))
        CheckpointManager(tmp_path).save(3, params=models)
        status, data = _request(app.port, "POST", "/reload-models",
                                {"checkpoint_dir": str(tmp_path)})
        assert status == 200
        assert data["source"]["step"] == 3
        status, _ = _request(app.port, "POST", "/predict", _txn(gen))
        assert status == 200


    def test_reload_quality_artifact_reblends_live(self, app_server,
                                                   tmp_path):
        """POST /reload-models {"quality_artifact": ...}: a new measured
        blend (enabled set + weights) deploys with zero recompiles — the
        next prediction carries only the artifact's branches."""
        import json as _json

        app, gen = app_server
        artifact = tmp_path / "q.json"
        artifact.write_text(_json.dumps({
            "selected_blend": {
                "branches": ["xgboost_primary", "lstm_sequential"],
                "weights": {"xgboost_primary": 0.4,
                            "lstm_sequential": 0.1},
            }
        }))
        status, data = _request(app.port, "POST", "/reload-models",
                                {"quality_artifact": str(artifact)})
        assert status == 200
        assert data["source"]["quality_artifact"]["weights"] == {
            "xgboost_primary": 0.4, "lstm_sequential": 0.1}
        status, info = _request(app.port, "GET", "/model-info")
        assert status == 200
        enabled = {n for n, m in info["models"].items() if m["enabled"]}
        assert enabled == {"xgboost_primary", "lstm_sequential"}
        status, pred = _request(app.port, "POST", "/predict", _txn(gen))
        assert status == 200
        assert set(pred["model_predictions"]) == enabled

    def test_reload_bad_quality_artifact_422(self, app_server, tmp_path):
        app, _ = app_server
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        status, _ = _request(app.port, "POST", "/reload-models",
                             {"quality_artifact": str(bad)})
        assert status == 422

    def test_reload_missing_checkpoint_404(self, app_server, tmp_path):
        app, _ = app_server
        status, _ = _request(app.port, "POST", "/reload-models",
                             {"checkpoint_dir": str(tmp_path / "nope")})
        assert status == 404


    def test_canary_artifact_requires_enabled_branches(self, app_server,
                                                       tmp_path):
        """POST /experiments from_quality_artifact: a blend using a branch
        disabled in the live deployment is refused with 409 (host-side
        re-weighting cannot resurrect a prediction that was never
        computed), and accepted once the branch set is enabled."""
        import json as _json

        from realtime_fraud_detection_tpu.scoring import MODEL_NAMES

        app, _ = app_server
        artifact = tmp_path / "q.json"
        artifact.write_text(_json.dumps({"selected_blend": {"weights": {
            "xgboost_primary": 0.4, "bert_text": 0.15}}}))
        idx = list(MODEL_NAMES).index("bert_text")
        was = bool(app.scorer.model_valid[idx])
        app.scorer.model_valid[idx] = False
        try:
            status, data = _request(app.port, "POST", "/experiments",
                                    {"name": "canary-disabled",
                                     "from_quality_artifact": str(artifact)})
            assert status == 409
            app.scorer.model_valid[idx] = True
            status, data = _request(app.port, "POST", "/experiments",
                                    {"name": "canary-enabled",
                                     "from_quality_artifact": str(artifact),
                                     "traffic": 0.3})
            assert status == 200 and data["experiment"] == "canary-enabled"
        finally:
            app.scorer.model_valid[idx] = was

    def test_qos_status_and_runtime_configuration(self, app_server):
        """GET /qos reports the plane; POST /qos flips knobs at runtime
        (zero recompiles) and admission starts shedding low-priority
        requests as explicit scores-with-reason."""
        app, gen = app_server
        status, snap = _request(app.port, "GET", "/qos")
        assert status == 200
        assert snap["enabled"] is False
        assert snap["ladder"]["level"] == 0
        assert snap["ladder_levels"] == ["full_ensemble", "no_text_graph",
                                        "trees_iforest", "rules_only"]

        status, _ = _request(app.port, "POST", "/qos", {"nope": 1})
        assert status == 422

        # enable with a starved bucket: low sheds immediately (reserve),
        # high never sheds
        status, data = _request(app.port, "POST", "/qos",
                                {"enabled": True, "admission_rate": 0.001,
                                 "admission_burst": 1.0})
        assert status == 200
        assert data["applied"]["enabled"] is True
        try:
            low = dict(_txn(gen), amount=5.0)
            status, res = _request(app.port, "POST", "/predict", low)
            assert status == 200
            assert res["risk_level"] == "SHED"
            assert res["decision"] == "REVIEW"
            assert res["explanation"]["shed"] is True
            assert res["explanation"]["shed_reason"].startswith("shed:")
            assert res["explanation"]["priority"] == "low"
            assert res["model_predictions"] == {}

            high = dict(_txn(gen), amount=5000.0)
            status, res = _request(app.port, "POST", "/predict", high)
            assert status == 200
            assert res["explanation"].get("shed") is None   # scored
            assert res["model_predictions"]

            status, snap = _request(app.port, "GET", "/qos")
            assert snap["counters"]["shed"] >= 1
            assert snap["counters"]["admitted"] >= 1
            status, text = _request(app.port, "GET", "/metrics/prometheus")
            assert "qos_shed_total" in text
            assert 'priority="low"' in text
        finally:
            status, _ = _request(app.port, "POST", "/qos",
                                 {"enabled": False, "admission_rate": 0.0})
            assert status == 200

    def test_predict_applies_rung_change_to_scorer(self, app_server):
        """ISSUE 7 review fix: _predict pushes a ladder-rung CHANGE into
        the scorer (under the score lock) and skips the lock entirely
        while the rung is steady — the served level must still track the
        plane's effective level through the real HTTP path."""
        app, gen = app_server
        status, _ = _request(app.port, "POST", "/qos",
                             {"enabled": True, "admission_rate": 0.0})
        assert status == 200
        try:
            assert app.scorer.qos_level == 0
            app.qos.slo_engaged = True       # floors the served rung at 1
            high = dict(_txn(gen), amount=5000.0)
            status, _res = _request(app.port, "POST", "/predict", high)
            assert status == 200
            assert app.qos.effective_level() == 1
            assert app.scorer.qos_level == 1
            app.qos.slo_engaged = False      # gate releases: rung recovers
            status, _res = _request(app.port, "POST", "/predict",
                                    dict(_txn(gen), amount=5000.0))
            assert status == 200
            assert app.scorer.qos_level == 0
        finally:
            app.qos.slo_engaged = False
            status, _ = _request(app.port, "POST", "/qos",
                                 {"enabled": False, "admission_rate": 0.0})
            assert status == 200

    def test_reload_bad_checkpoint_leaves_blend_untouched(self, app_server,
                                                          tmp_path):
        """The /reload-models ordering fix: a combined body whose
        checkpoint restore FAILS must leave the quality-artifact blend
        unapplied — a half-applied update (new blend + old params) never
        serves."""
        import json as _json

        app, _ = app_server
        status, before = _request(app.port, "GET", "/model-info")
        assert status == 200
        artifact = tmp_path / "q.json"
        artifact.write_text(_json.dumps({"selected_blend": {"weights": {
            "xgboost_primary": 0.9, "isolation_forest": 0.1}}}))
        status, _ = _request(app.port, "POST", "/reload-models",
                             {"quality_artifact": str(artifact),
                              "checkpoint_dir": str(tmp_path / "missing")})
        assert status == 404                      # restore failed
        status, after = _request(app.port, "GET", "/model-info")
        assert after == before                    # blend untouched
        # a malformed artifact fails the whole reload up front too
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        status, _ = _request(app.port, "POST", "/reload-models",
                             {"quality_artifact": str(bad),
                              "checkpoint_dir": str(tmp_path / "missing")})
        assert status == 422
        status, after = _request(app.port, "GET", "/model-info")
        assert after == before

    def test_drift_endpoint(self, app_server):
        app, _ = app_server
        status, data = _request(app.port, "GET", "/drift")
        assert status == 200
        assert "drifted" in data and "rows_seen" in data

    def test_experiments_create_and_results(self, app_server):
        app, gen = app_server
        spec = {"name": "exp-http", "variants": [
            {"name": "control", "traffic": 0.5},
            {"name": "treatment", "traffic": 0.5,
             "overrides": {"weights": {"bert_text": 0.9}}},
        ]}
        status, data = _request(app.port, "POST", "/experiments", spec)
        assert status == 200
        # experiments are WIRED: traffic through /predict accumulates arm data
        for txn in gen.generate_batch(16):
            s, _ = _request(app.port, "POST", "/predict", txn)
            assert s == 200
        status, data = _request(app.port, "GET", "/experiments?name=exp-http")
        assert status == 200
        assert set(data["variants"]) == {"control", "treatment"}
        total_preds = sum(v["predictions"] for v in data["variants"].values())
        assert total_preds >= 16
        status, _ = _request(app.port, "GET", "/experiments?name=ghost")
        assert status == 404
        app.ab.stop_experiment("exp-http")       # don't leak into other tests

    def test_query_params_percent_decoded(self, app_server):
        app, _ = app_server
        spec = {"name": "my exp", "variants": [{"name": "only",
                                                "traffic": 1.0}]}
        status, _ = _request(app.port, "POST", "/experiments", spec)
        assert status == 200
        status, data = _request(app.port, "GET", "/experiments?name=my%20exp")
        assert status == 200
        assert data["experiment"] == "my exp"
        app.ab.stop_experiment("my exp")

    def test_reload_non_integer_step_422(self, app_server, tmp_path):
        app, _ = app_server
        status, _ = _request(app.port, "POST", "/reload-models",
                             {"checkpoint_dir": str(tmp_path),
                              "step": "three"})
        assert status == 422

    def test_oversized_headers_413(self, app_server):
        app, _ = app_server
        conn = http.client.HTTPConnection("127.0.0.1", app.port, timeout=30)
        conn.request("GET", "/health", headers={"X-Big": "a" * 70_000})
        resp = conn.getresponse()
        assert resp.status == 413
        resp.read()
        conn.close()

    def test_unknown_route_404_and_405(self, app_server):
        app, _ = app_server
        status, _ = _request(app.port, "GET", "/nope")
        assert status == 404
        status, _ = _request(app.port, "GET", "/predict")
        assert status == 405

    def test_bad_json_400(self, app_server):
        app, _ = app_server
        conn = http.client.HTTPConnection("127.0.0.1", app.port, timeout=30)
        conn.request("POST", "/predict", body="{not json",
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 400
        resp.read()
        conn.close()


class TestTracingEndpoints:
    """The tracing plane's serving surface: /latency/breakdown, /slo, and
    the trace_* Prometheus series, against live /predict traffic."""

    def test_latency_breakdown_attributes_live_traffic(self, app_server):
        app, gen = app_server
        for _ in range(3):
            status, _ = _request(app.port, "POST", "/predict", _txn(gen))
            assert status == 200
        status, bd = _request(app.port, "GET", "/latency/breakdown")
        assert status == 200
        assert bd["enabled"] is True
        assert bd["n"] >= 3
        p99 = bd["quantiles"]["p99"]
        assert p99["dominant_stage"] in (
            "queue", "assemble", "pack", "dispatch", "device_wait",
            "finalize")
        # additive decomposition: the stage means explain the tail e2e
        assert sum(p99["stage_ms"].values()) > 0
        assert {"queue", "assemble", "device_wait"} <= set(p99["stage_ms"])
        assert bd["exemplars"] and bd["exemplars"][0]["trace_id"]

    def test_slo_endpoint_reports_burn(self, app_server):
        app, gen = app_server
        _request(app.port, "POST", "/predict", _txn(gen))
        status, slo = _request(app.port, "GET", "/slo")
        assert status == 200
        assert slo["enabled"] is True
        assert slo["objective"]["latency_ms"] == 20.0
        for window in ("fast", "slow"):
            w = slo["windows"][window]
            assert w["observed"] >= 1
            assert w["burn_rate"] >= 0.0
        assert "engaged" in slo["qos_gate"]

    def test_trace_series_on_prometheus_exposition(self, app_server):
        app, gen = app_server
        _request(app.port, "POST", "/predict", _txn(gen))
        status, text = _request(app.port, "GET", "/metrics/prometheus")
        assert status == 200
        assert "trace_stage_ms_bucket" in text
        assert 'trace_completed_total{terminal="scored"}' in text
        assert "trace_slo_burn_rate" in text

    def test_cached_retry_closes_trace_as_cached(self, app_server):
        app, gen = app_server
        txn = _txn(gen)
        _request(app.port, "POST", "/predict", txn)
        before = app.tracer.counters["cached"]
        _request(app.port, "POST", "/predict", txn)   # cache hit
        assert app.tracer.counters["cached"] == before + 1

    def test_error_path_closes_traces_as_error(self, app_server,
                                               monkeypatch):
        """A failing dispatch must still close every open trace with the
        `error` terminal (the stream job records errors; the serving
        plane must agree) — never a silent gap in the recorder."""
        app, gen = app_server
        txn = dict(_txn(gen), transaction_id="trace-err-1")
        trace = app.tracer.batch(
            [app.tracer.begin("trace-err-1")], batch_size=1)

        def boom(*a, **k):
            raise RuntimeError("injected dispatch failure")

        monkeypatch.setattr(app.scorer, "dispatch", boom)
        before = app.tracer.counters["errors"]
        with pytest.raises(RuntimeError):
            app._score_batch_sync([txn], trace)
        assert app.tracer.counters["errors"] == before + 1
        errs = app.tracer.traces(terminal="error")
        assert any(t.txn_id == "trace-err-1" for t in errs)


def test_serving_app_on_shared_state_tier():
    """The compose/k8s topology: a serving replica wired to the shared RESP
    tier (serve --state). A /predict must score AND write its txn-cache +
    velocity state through the wire so the next replica sees it."""
    import asyncio

    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.serving import ServingApp
    from realtime_fraud_detection_tpu.state import MiniRedisServer, RespClient
    from realtime_fraud_detection_tpu.utils.config import Config

    state = MiniRedisServer().start()
    config = Config()
    config.serving.prediction_timeout_seconds = 180.0
    config.monitoring.prometheus_port = 0   # no fixed-port listener in tests
    scorer = FraudScorer(config, scorer_config=ScorerConfig(text_len=32),
                         state_client=RespClient(port=state.port))
    app = ServingApp(config, host="127.0.0.1", port=0, scorer=scorer)
    gen = TransactionGenerator(num_users=32, num_merchants=16, seed=41)
    app.scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def _start():
            await app.start()
            started.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    try:
        txn = gen.generate_batch(1)[0]
        status, data = _request(app.port, "POST", "/predict", txn)
        assert status == 200
        assert 0.0 <= data["fraud_probability"] <= 1.0
        # the shared tier holds this replica's write-back
        c = RespClient(port=state.port)
        keys = [k.decode() for k in c.keys("*")]
        tid = str(txn["transaction_id"])
        assert any(tid in k for k in keys), keys[:10]
        assert any("velocity" in k or "vel" in k for k in keys), keys[:10]
        # a FRESH scorer (second replica) dedupes against the shared cache
        s2 = FraudScorer(config, scorer_config=ScorerConfig(text_len=32),
                         state_client=RespClient(port=state.port))
        assert s2.txn_cache.get_transaction(tid) is not None
        c.close()
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        state.stop()


class TestQuantReload:
    """ISSUE 9 satellite: the quantization-mode arch stamp over HTTP — an
    int8 checkpoint never silently restores into this (f32) server, the
    allow_arch_mismatch override serves the checkpoint's actual form, and
    the quant_* Prometheus series read the live-params truth."""

    def test_cross_mode_reload_409_then_override(self, app_server,
                                                 tmp_path):
        import jax

        from realtime_fraud_detection_tpu.checkpoint import (
            CheckpointManager,
        )
        from realtime_fraud_detection_tpu.models.quant import (
            is_quantized_bert,
            quantize_bert_params,
        )
        from realtime_fraud_detection_tpu.scoring import (
            init_scoring_models,
        )

        app, gen = app_server
        models = init_scoring_models(jax.random.PRNGKey(7))
        models = models.replace(
            bert=quantize_bert_params(jax.device_get(models.bert)))
        CheckpointManager(tmp_path).save(4, params=models)

        status, _ = _request(app.port, "POST", "/reload-models",
                             {"checkpoint_dir": str(tmp_path)})
        assert status == 409                     # refused, not silent
        assert not is_quantized_bert(app.scorer.models.bert)

        status, data = _request(app.port, "POST", "/reload-models",
                                {"checkpoint_dir": str(tmp_path),
                                 "allow_arch_mismatch": True})
        assert status == 200 and data["source"]["step"] == 4
        assert is_quantized_bert(app.scorer.models.bert)
        # the service still scores, and observability reports the served
        # (checkpoint's) form — int8 — not the config's wish
        status, _ = _request(app.port, "POST", "/predict", _txn(gen))
        assert status == 200
        status, text = _request(app.port, "GET", "/metrics/prometheus")
        assert status == 200
        assert 'quant_branch_mode{branch="bert_text",mode="int8"} 1' in text

"""Entity-graph plane tests (ISSUE 14): typed store units, sampler
determinism + cache coherence, fetch deadline/budget/degrade/fencing
paths, sync_graph mirror pins, the PartitionState handoff regression pin
(the graph bundle rides snapshot/restore digest-equal), columnar==serial
with graph sampling enabled, typed-GNN storage specs + checkpoint
graph-mode stamp, and the `rtfd graph-drill --fast` tier-1 smoke."""

import json
import pickle

import numpy as np
import pytest

from realtime_fraud_detection_tpu.graph import (
    GraphFetchClient,
    GraphFetchServer,
    NeighborSampler,
    TypedEntityGraph,
)
from realtime_fraud_detection_tpu.graph.store import merge_neighbor_lists


def _zeros_rows(node_dim):
    return lambda ids: np.zeros((len(ids), node_dim), np.float32)


# ---------------------------------------------------------------------------
# typed store
# ---------------------------------------------------------------------------


class TestTypedEntityGraph:
    def test_recency_ring_bounded_and_distinct(self):
        g = TypedEntityGraph(fanout=3)
        for i in range(6):
            g.add_transaction("u1", f"m{i}", "d1", "ip1")
        rings = g.neighbors("user->merchant", ["u1"])
        assert rings == [["m3", "m4", "m5"]]          # oldest evicted
        # re-observation moves to end, never duplicates
        g.add_transaction("u1", "m4", "d1", "ip1")
        assert g.neighbors("user->merchant", ["u1"]) == [["m3", "m5", "m4"]]

    def test_both_directions_and_empty_ids_skipped(self):
        g = TypedEntityGraph(fanout=4)
        g.add_batch(["u1", "u2"], ["m1", "m1"], ["d1", ""], ["", "ip1"])
        assert g.neighbors("merchant->user", ["m1"]) == [["u1", "u2"]]
        assert g.neighbors("device->user", ["d1"]) == [["u1"]]
        # u1 had no ip, u2 no device
        assert g.neighbors("user->ip", ["u1"]) == [[]]
        assert g.neighbors("user->device", ["u2"]) == [[]]

    def test_unknown_edge_type_raises(self):
        g = TypedEntityGraph()
        with pytest.raises(ValueError, match="unknown edge type"):
            g.neighbors("user->user", ["u1"])

    def test_digest_and_pickle_round_trip(self):
        g = TypedEntityGraph(fanout=4)
        g.add_batch(["u1", "u2"], ["m1", "m2"], ["d1", "d1"],
                    ["ip1", "ip2"])
        d = g.digest()
        assert d == g.digest()                       # stable
        g2 = pickle.loads(pickle.dumps(g))
        assert g2.digest() == d
        g2.add_transaction("u3", "m1", "d1", "ip1")
        assert g2.digest() != d                      # content-sensitive

    def test_dirty_tracking_drains_touched_ids(self):
        g = TypedEntityGraph(fanout=4)
        g.add_transaction("u1", "m1", "d1", "ip1")
        assert g.drain_dirty() == ["d1", "ip1", "m1", "u1"]
        assert g.drain_dirty() == []
        # a no-op re-observation (already most recent) marks nothing
        g.add_transaction("u1", "m1", "d1", "ip1")
        assert g.drain_dirty() == []

    def test_degree_and_stats(self):
        g = TypedEntityGraph(fanout=8)
        g.add_batch(["u1", "u2", "u3"], ["m1"] * 3, ["d1"] * 3,
                    ["ip1", "ip2", "ip3"])
        assert g.degree("device->user", ["d1", "dX"]) == [3, 0]
        st = g.stats()
        assert st["nodes"] == {"user": 3, "device": 1, "merchant": 1,
                               "ip": 3}
        assert st["edges"]["device->user"] == 3

    def test_merge_neighbor_lists_deterministic_dedup(self):
        local = {"d1": ["u1", "u2"]}
        remote = [{"d1": ["u2", "u3"]}, {"d1": ["u4"]}]
        merged = merge_neighbor_lists(local, remote, ["d1", "d9"], 3)
        assert merged["d1"] == ["u2", "u3", "u4"]    # last-3 of dedup
        assert merged["d9"] == []


# ---------------------------------------------------------------------------
# PartitionState / PartitionedStore integration (the handoff pin)
# ---------------------------------------------------------------------------


class TestPartitionGraphBundle:
    def test_handoff_snapshot_restore_digest_equal(self):
        """ISSUE 14 regression pin: the graph bundle rides handoff
        snapshot/restore digest-equal — a restored partition's graph is
        byte-for-byte the snapshotted one."""
        from realtime_fraud_detection_tpu.cluster.partition import (
            PartitionState,
        )

        ps = PartitionState(seq_len=4, feature_dim=4)
        ps.graph.add_batch(["u1", "u2"], ["m1", "m1"], ["d1", "d1"],
                           ["ip1", "ip2"])
        ps.profiles.put_user("u1", {"user_id": "u1", "txn_count": 1})
        d = ps.digest(now=0.0)
        restored = PartitionState.restore_bytes(ps.snapshot_bytes())
        assert restored.digest(now=0.0) == d
        assert restored.graph.neighbors("device->user", ["d1"]) == [
            ["u1", "u2"]]
        # the digest SEES the graph: new edges change it
        restored.graph.add_transaction("u3", "m1", "d1", "ip1")
        assert restored.digest(now=0.0) != d

    def test_pre_graph_blob_restores_with_empty_graph(self):
        from realtime_fraud_detection_tpu.cluster.partition import (
            PartitionState,
        )

        ps = PartitionState()
        legacy = {k: v for k, v in ps.__dict__.items()
                  if k not in ("graph", "graph_fanout")}
        migrated = PartitionState.__new__(PartitionState)
        migrated.__setstate__(legacy)
        assert len(migrated.graph) == 0
        assert migrated.graph.fanout == 16

    def test_facade_routes_by_user_key_and_merges_entity_reads(self):
        from realtime_fraud_detection_tpu.cluster.partition import (
            PartitionedStore,
        )

        store = PartitionedStore(4, graph_fanout=8)
        for p in range(4):
            store.acquire(p)
        store.graph.add_batch(["uA", "uB", "uC"], ["m1"] * 3, ["dX"] * 3,
                              ["ip1"] * 3)
        # every user's edges landed in ITS partition; the entity-keyed
        # read merges the owned shards
        assert sorted(store.graph.neighbors("device->user", ["dX"])[0]) \
            == ["uA", "uB", "uC"]
        per_part = [s.graph.stats()["edges"]["user->device"]
                    for s in store.states().values()]
        assert sum(per_part) == 3
        # ownership epoch moves on acquire/release (sampler wholesale
        # invalidation signal)
        e0 = store.graph.ownership_epoch
        store.release(0)
        assert store.graph.ownership_epoch == e0 + 1


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


def _ring_graph(fanout=8):
    """u1 has device d1 (shared with u2, u3), ip i1, merchant m1."""
    g = TypedEntityGraph(fanout=fanout)
    g.add_batch(["u1", "u2", "u3"], ["m1", "m2", "m2"],
                ["d1", "d1", "d1"], ["i1", "i2", "i3"])
    g.drain_dirty()
    return g


class TestNeighborSampler:
    def test_masks_and_center_exclusion(self):
        g = _ring_graph()
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16), _zeros_rows(16))
        out = s.sample(["u1"], ["m1"])
        # frontier: device d1, ip i1, merchant m1 -> 3 slots
        assert out["user_neigh_mask"][0].sum() == 3
        assert out["user_neigh_feat"].shape == (1, 4, 16)
        assert out["user_neigh2_feat"].shape == (1, 4, 4, 16)
        # d1's 2-hop users exclude the center u1 -> {u2, u3}
        from realtime_fraud_detection_tpu.models.gnn import DEVICE_TAG_SLOT

        dev_slot = int(np.argmax(
            out["user_neigh_feat"][0][:, DEVICE_TAG_SLOT]))
        assert out["user_neigh2_mask"][0, dev_slot].sum() == 2
        # merchant center m1: users [u1]; 2-hop = u1's merchant ring
        # minus m1 -> empty
        assert out["merch_neigh_mask"][0].sum() == 1
        assert out["merch_neigh2_mask"][0].sum() == 0
        # padded rows are zero and masked off
        assert not out["user_neigh_mask"][0, 3]
        assert np.all(out["user_neigh_feat"][0, 3] == 0.0)

    def test_device_rows_carry_degree_and_tag(self):
        from realtime_fraud_detection_tpu.models.gnn import (
            DEVICE_TAG_SLOT,
            IP_TAG_SLOT,
        )

        g = _ring_graph()
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16), _zeros_rows(16))
        out = s.sample(["u1"], ["m1"])
        feat = out["user_neigh_feat"][0]
        dev_rows = feat[:, DEVICE_TAG_SLOT] > 0
        ip_rows = feat[:, IP_TAG_SLOT] > 0
        assert dev_rows.sum() == 1 and ip_rows.sum() == 1
        # degree slot 0: d1 serves 2 non-center users + center = 3 of
        # fanout2=4
        assert feat[dev_rows][0, 0] == pytest.approx(3 / 4)

    def test_deterministic_across_fresh_samplers(self):
        g1, g2 = _ring_graph(), _ring_graph()
        s1 = NeighborSampler(g1, 16, 4, 4, _zeros_rows(16),
                             _zeros_rows(16))
        s2 = NeighborSampler(g2, 16, 4, 4, _zeros_rows(16),
                             _zeros_rows(16))
        a = s1.sample(["u1", "u2"], ["m1", "m2"])
        b = s2.sample(["u1", "u2"], ["m1", "m2"])
        for k in a:
            assert np.array_equal(a[k], b[k]), k

    def test_cache_hits_and_dependency_eviction(self):
        g = _ring_graph()
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16), _zeros_rows(16))
        s.sample(["u1"], ["m1"])
        misses0 = s.misses
        s.sample(["u1"], ["m1"])                      # clean reuse
        assert s.misses == misses0 and s.hits >= 1
        # a new edge through d1 dirties it -> u1's entry (dep d1) evicts
        before = s.sample(["u1"], ["m1"])
        g.add_batch(["u9"], ["m9"], ["d1"], ["i9"])
        s.sync()
        after = s.sample(["u1"], ["m1"])
        assert s.evictions >= 1
        from realtime_fraud_detection_tpu.models.gnn import DEVICE_TAG_SLOT

        slot = int(np.argmax(
            after["user_neigh_feat"][0][:, DEVICE_TAG_SLOT]))
        # u9 joined d1's 2-hop ring
        assert after["user_neigh2_mask"][0, slot].sum() \
            == before["user_neigh2_mask"][0, slot].sum() + 1

    def test_ownership_epoch_clears_wholesale(self):
        class EpochGraph(TypedEntityGraph):
            ownership_epoch = 0

        g = EpochGraph(fanout=4)
        g.add_batch(["u1"], ["m1"], ["d1"], ["i1"])
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16), _zeros_rows(16))
        s.sample(["u1"], ["m1"])
        assert s.stats()["entries"] > 0
        g.ownership_epoch = 1
        s.sync()
        assert s.stats()["entries"] == 0

    def test_age_out_bounds_staleness(self):
        g = _ring_graph()
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16),
                            _zeros_rows(16), max_entry_age=2)
        s.sample(["u1"], ["m1"])                      # 2 entries (u + m)
        s.sync()
        s.sample(["u1"], ["m1"])                      # 1 sync old: hits
        assert s.misses == 2 and s.hits == 2
        s.sync()
        # 2 syncs old: the lazy probe treats both entries as stale and
        # rebuilds them — bounded staleness without a per-sync full scan
        s.sample(["u1"], ["m1"])
        assert s.misses == 4
        assert s.evictions >= 2

    def test_capacity_cap_never_wipes_a_probed_center_mid_batch(self):
        """Review regression pin: the wholesale capacity clear happens
        BEFORE the probes, so a batch mixing cache hits and misses can
        never lose a hit center's entry between probe and scatter."""
        g = _ring_graph()
        s = NeighborSampler(g, 16, 4, 4, _zeros_rows(16),
                            _zeros_rows(16), max_entries=2)
        s.sample(["u1"], ["m1"])                      # fills to the cap
        # at the cap the clear runs BEFORE the probes; every center of
        # this batch rebuilds and the scatter finds all of them (a
        # mid-batch clear would KeyError on a probed hit)
        out = s.sample(["u1", "u2"], ["m1", "m2"])
        assert out["user_neigh_mask"].shape == (2, 4)
        assert s.stats()["entries"] == 4


# ---------------------------------------------------------------------------
# fetch plane
# ---------------------------------------------------------------------------


class TestGraphFetch:
    def _server(self, graph):
        return GraphFetchServer(lambda: graph, worker_id="w0").start()

    def test_round_trip_and_merge(self):
        g = _ring_graph()
        srv = self._server(g)
        try:
            c = GraphFetchClient({"w0": ("127.0.0.1", srv.port)},
                                 deadline_ms=2_000.0, node_budget=64)
            c.begin_batch()
            maps, degraded = c.fetch("device->user", ["d1", "dX"], 8)
            assert not degraded
            assert maps[0]["d1"] == ["u1", "u2", "u3"]
            assert "dX" not in maps[0]                # empties omitted
            assert c.remote_fetch_total == 1
            assert c.fetched_nodes_total == 1
            assert not c.end_batch()
            c.close()
        finally:
            srv.stop()

    def test_budget_truncates_and_counts(self):
        g = _ring_graph()
        srv = self._server(g)
        try:
            c = GraphFetchClient({"w0": ("127.0.0.1", srv.port)},
                                 deadline_ms=2_000.0, node_budget=1)
            c.begin_batch()
            maps, degraded = c.fetch("device->user", ["d1", "dX"], 8)
            assert degraded and c.budget_exhausted_total == 1
            # second fetch in the same batch: budget gone entirely
            maps2, degraded2 = c.fetch("ip->user", ["i1"], 8)
            assert degraded2 and maps2 == []
            assert c.end_batch()
            assert c.degraded_batches_total == 1
            c.close()
        finally:
            srv.stop()

    def test_deadline_degrades_without_stalling(self):
        c = GraphFetchClient({"w0": ("127.0.0.1", 1)},  # never contacted
                             deadline_ms=0.0, node_budget=64)
        c.begin_batch()
        maps, degraded = c.fetch("device->user", ["d1"], 8)
        assert degraded and maps == []
        # several expired fetches in ONE window count ONE deadline batch
        # (graph_fetch_deadline_total must stay <= degraded_batches_total)
        c.fetch("ip->user", ["i1"], 8)
        assert c.end_batch()
        assert c.fetch_deadline_total == 1
        assert c.degraded_batches_total == 1
        assert c.remote_fetch_total == 0

    def test_dead_peer_backoff_gated_no_sleep(self):
        tnow = [0.0]
        c = GraphFetchClient({"w0": ("127.0.0.1", 9)},  # refused port
                             deadline_ms=50.0, node_budget=64,
                             clock=lambda: tnow[0])
        c.begin_batch()
        _, degraded = c.fetch("device->user", ["d1"], 8)
        assert degraded and c.fetch_error_total == 1
        # immediately after: the peer is down, the attempt is SKIPPED
        # (backoff-gated on the injected clock — no sleep, no connect)
        c.begin_batch()
        c.fetch("device->user", ["d1"], 8)
        assert c.fetch_error_total == 2
        assert not c.backoff.slept                 # never slept
        # past the backoff delay the client tries the connect again
        tnow[0] += 10.0
        c.begin_batch()
        c.fetch("device->user", ["d1"], 8)
        assert c.fetch_error_total == 3

    def test_generation_fencing_refused_and_adopted(self):
        g = _ring_graph()
        srv = self._server(g)
        try:
            srv.fence(5)
            c = GraphFetchClient({"w0": ("127.0.0.1", srv.port)},
                                 deadline_ms=2_000.0, node_budget=64)
            c.begin_batch()
            maps, degraded = c.fetch("device->user", ["d1"], 8)
            assert degraded and maps == []
            assert c.stale_generation_total == 1
            assert srv.fenced_requests_total == 1
            c.set_generation(5)                      # rebalance adoption
            c.begin_batch()
            maps, degraded = c.fetch("device->user", ["d1"], 8)
            assert not degraded and maps[0]["d1"]
            c.close()
        finally:
            srv.stop()

    def test_netfault_link_partition_degrades(self):
        from realtime_fraud_detection_tpu.chaos.netfaults import LinkState

        g = _ring_graph()
        srv = self._server(g)
        try:
            link = LinkState("graphfetch", "peers", sleep=lambda _s: None)
            c = GraphFetchClient({"w0": ("127.0.0.1", srv.port)},
                                 deadline_ms=2_000.0, node_budget=64,
                                 link=link)
            link.set_partition("full")
            c.begin_batch()
            _, degraded = c.fetch("device->user", ["d1"], 8)
            assert degraded and link.partitioned_sends == 1
            assert c.end_batch()
            link.clear_partition()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# typed GNN: projection, storage specs, checkpoint stamp
# ---------------------------------------------------------------------------


class TestTypedGnn:
    def test_typed_projection_selects_by_tag(self):
        import jax

        from realtime_fraud_detection_tpu.models.gnn import (
            DEVICE_TAG_SLOT,
            init_gnn_params,
            is_typed_gnn,
            typed_node_projection,
        )

        params = init_gnn_params(jax.random.PRNGKey(0), typed=True)
        assert is_typed_gnn(params)
        feat = np.zeros((2, 16), np.float32)
        feat[0, 0] = 1.0                              # user row (no tag)
        feat[1, 0] = 1.0
        feat[1, DEVICE_TAG_SLOT] = 1.0                # device row
        out = np.asarray(typed_node_projection(params, feat))
        want_u = feat[0] @ np.asarray(params["w_node_user"])
        want_d = feat[1] @ np.asarray(params["w_node_device"])
        np.testing.assert_allclose(out[0], want_u, rtol=1e-6)
        np.testing.assert_allclose(out[1], want_d, rtol=1e-6)

    def test_typed_params_take_storage_sharding(self):
        import jax
        from jax.sharding import PartitionSpec as P

        from realtime_fraud_detection_tpu.models.bert import BertConfig
        from realtime_fraud_detection_tpu.parallel.layouts import (
            branch_serving_specs,
        )
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        models = init_scoring_models(
            jax.random.PRNGKey(0),
            bert_config=BertConfig(vocab_size=256, hidden_size=16,
                                   num_layers=1, num_heads=2,
                                   intermediate_size=32),
            gnn_typed=True)
        specs = branch_serving_specs(models, 2, ["graph_neural"])
        for name in ("w_node_user", "w_node_merchant", "w_node_device",
                     "w_node_ip"):
            # (16, 16) squares shard over the model axis like every
            # other GNN leaf (the leaf_storage_spec rule)
            assert specs.gnn[name] != P(), name

    def test_checkpoint_graph_mode_stamp_and_refusal(self, tmp_path):
        import jax

        from realtime_fraud_detection_tpu.checkpoint import (
            CheckpointManager,
            _derive_graph_mode,
        )
        from realtime_fraud_detection_tpu.models.bert import BertConfig
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        bc = BertConfig(vocab_size=256, hidden_size=16, num_layers=1,
                        num_heads=2, intermediate_size=32)
        typed = init_scoring_models(jax.random.PRNGKey(0), bert_config=bc,
                                    n_trees=4, tree_depth=3,
                                    gnn_typed=True)
        assert _derive_graph_mode(typed) == {"gnn_nodes": "typed"}
        plain = init_scoring_models(jax.random.PRNGKey(0), bert_config=bc,
                                    n_trees=4, tree_depth=3)
        assert _derive_graph_mode(plain) == {"gnn_nodes": "bipartite"}

        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(1, params=typed)
        assert mgr.manifest(1)["graph_mode"] == {"gnn_nodes": "typed"}
        # a typed checkpoint must not silently restore into a scorer
        # assembling bipartite neighbor tensors
        scorer = FraudScorer(models=plain, bert_config=bc,
                             scorer_config=ScorerConfig())
        with pytest.raises(ValueError, match="graph-mode mismatch"):
            mgr.restore_into_scorer(scorer)


# ---------------------------------------------------------------------------
# scorer integration: one seam, finalize-time ingest, columnar == serial
# ---------------------------------------------------------------------------


def _typed_scorer_pair(seed=9):
    import jax

    from realtime_fraud_detection_tpu.models.bert import BertConfig
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        init_scoring_models,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    bc = BertConfig(vocab_size=512, hidden_size=16, num_layers=1,
                    num_heads=2, intermediate_size=32,
                    max_position_embeddings=32)
    sc = ScorerConfig(graph_mode="typed", fanout=4, graph_fanout2=4,
                      text_len=16, token_cache_entries=256)
    models = init_scoring_models(jax.random.PRNGKey(0), bert_config=bc,
                                 n_trees=4, tree_depth=3, gnn_typed=True)
    gen = TransactionGenerator(num_users=120, num_merchants=24, seed=seed)
    scorers = []
    for _ in range(2):
        s = FraudScorer(models=models, scorer_config=sc, bert_config=bc)
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        scorers.append(s)
    return gen, scorers


class TestScorerGraphIntegration:
    def test_finalize_ingests_ring_entities_one_seam(self):
        """ISSUE 14 small fix: FraudRing's shared device_id/ip_address
        flow into per-entity state at the finalize seam — identically
        for both assemble paths (there is only ONE ingest site)."""
        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRingConfig,
        )

        gen, (scorer, _) = _typed_scorer_pair()
        ring = gen.inject_fraud_ring(FraudRingConfig(rate=1.0,
                                                     n_members=6,
                                                     n_devices=2,
                                                     n_ips=2))
        recs = gen.generate_batch(16)
        scorer.score_batch(recs, now=0.0)
        users = scorer.typed_graph.neighbors("device->user",
                                             ring.device_ids)
        assert sum(len(u) for u in users) >= 2        # cohort visible
        snap = scorer.graph_snapshot()
        assert snap["mode"] == "typed"
        assert snap["store"]["edges_added"] > 0

    def test_columnar_equals_serial_with_graph_sampling(self):
        """Acceptance: columnar==serial stays bit-exact with graph
        sampling enabled — every ScoreBatch leaf AND every served
        score."""
        import jax

        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRingConfig,
        )

        gen, (col, ser) = _typed_scorer_pair()
        gen.inject_fraud_ring(FraudRingConfig(rate=0.3))
        for i in range(3):
            recs = gen.generate_batch(16)
            ts = float(i)
            b_col = col.assemble(recs, now=ts)
            b_ser = ser.assemble_serial(recs, now=ts)
            la, ta = jax.tree_util.tree_flatten(b_col)
            lb, tb = jax.tree_util.tree_flatten(b_ser)
            assert ta == tb
            for x, y in zip(la, lb):
                assert np.array_equal(np.asarray(x), np.asarray(y))
            r_col = col.finalize(col.dispatch_assembled(b_col, recs),
                                 now=ts)
            r_ser = ser.finalize(ser.dispatch_assembled(b_ser, recs),
                                 now=ts)
            for a, b in zip(r_col, r_ser):
                assert a["fraud_score"] == b["fraud_score"]

    def test_bipartite_mode_keeps_legacy_packspec(self):
        """The 2-hop fields are absent (not empty) in bipartite mode:
        the packed spec — a static jit arg — is unchanged with the graph
        plane off."""
        from realtime_fraud_detection_tpu.core.packing import pack_tree
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            ScorerConfig,
            make_example_batch,
        )

        batch = make_example_batch(4, ScorerConfig())
        assert batch.user_neigh2_feat is None
        _, spec = pack_tree(batch)
        # 65 leaves exactly as before the graph plane (txn struct + 13)
        assert len(spec.entries) == 65

    def test_host_state_round_trips_typed_graph(self):
        """Review regression pin: a scorer-LOCAL typed graph rides the
        host-state checkpoint (snapshot/restore), and the restored
        scorer's sampler reads the restored store (cache dropped)."""
        from realtime_fraud_detection_tpu.checkpoint import (
            restore_scorer_host_state,
            snapshot_scorer_host_state,
        )

        gen, (a, b) = _typed_scorer_pair()
        recs = gen.generate_batch(16)
        a.score_batch(recs, now=0.0)
        assert len(a.typed_graph) > 0
        state = snapshot_scorer_host_state(a)
        assert state["typed_graph"] is a.typed_graph
        restore_scorer_host_state(b, pickle.loads(pickle.dumps(state)))
        assert b.typed_graph.digest() == a.typed_graph.digest()
        assert b._sampler.graph is b.typed_graph
        # a PARTITION-bundle-backed graph is the handoff path's to carry,
        # never the host-state blob's
        from realtime_fraud_detection_tpu.cluster.partition import (
            PartitionedStore,
        )
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )

        store = PartitionedStore(4)
        for p in range(4):
            store.acquire(p)
        sharded = FraudScorer(
            models=a.models, bert_config=a.bert_config,
            scorer_config=ScorerConfig(graph_mode="typed", fanout=4,
                                       graph_fanout2=4, text_len=16,
                                       token_cache_entries=256),
            stores=store)
        assert snapshot_scorer_host_state(sharded)["typed_graph"] is None

    def test_attach_graph_fetch_requires_typed(self):
        import jax

        from realtime_fraud_detection_tpu.models.bert import BertConfig
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        bc = BertConfig(vocab_size=256, hidden_size=16, num_layers=1,
                        num_heads=2, intermediate_size=32)
        s = FraudScorer(models=init_scoring_models(
            jax.random.PRNGKey(0), bert_config=bc, n_trees=4,
            tree_depth=3), bert_config=bc, scorer_config=ScorerConfig())
        with pytest.raises(ValueError, match="typed"):
            s.attach_graph_fetch(object())


# ---------------------------------------------------------------------------
# sync_graph mirror
# ---------------------------------------------------------------------------


class TestSyncGraph:
    def _snapshot(self, edges_added=5, hits=3, fetches=7):
        return {
            "mode": "typed",
            "store": {"fanout": 8, "generation": 2,
                      "edges_added": edges_added,
                      "nodes": {"user": 4, "device": 2, "merchant": 3,
                                "ip": 2},
                      "edges": {"user->device": 4, "device->user": 4,
                                "user->merchant": 5,
                                "merchant->user": 5,
                                "user->ip": 4, "ip->user": 4}},
            "sampler": {"hits": hits, "misses": 2, "evictions": 1,
                        "entries": 6},
            "fetch": {"remote_fetch_total": fetches,
                      "fetched_nodes_total": 30,
                      "fetch_deadline_total": 1, "fetch_error_total": 2,
                      "budget_exhausted_total": 0,
                      "stale_generation_total": 1,
                      "degraded_batches_total": 3},
        }

    def test_honest_deltas_idempotent(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_graph(self._snapshot())
        m.sync_graph(self._snapshot())                # same totals: no inc
        assert m.graph_edges_added.total() == 5
        assert m.graph_remote_fetch.total() == 7
        m.sync_graph(self._snapshot(edges_added=9, hits=4, fetches=8))
        assert m.graph_edges_added.total() == 9
        assert m.graph_sampler_cache_hits.total() == 4
        assert m.graph_remote_fetch.total() == 8

    def test_stream_and_serving_render_identical(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        def graph_lines(m):
            return sorted(
                line for line in m.render_prometheus().splitlines()
                if "graph_" in line)

        a, b = MetricsCollector(), MetricsCollector()
        for snap in (self._snapshot(),
                     self._snapshot(edges_added=9, hits=4, fetches=8)):
            a.sync_graph(snap)
            b.sync_graph(snap)
        assert graph_lines(a) == graph_lines(b)

    def test_bipartite_snapshot_sets_mode_only(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_graph({"mode": "bipartite"})
        assert m.graph_typed_mode.value() == 0.0
        assert m.graph_edges_added.total() == 0


# ---------------------------------------------------------------------------
# drill smoke (tier-1, un-slow-marked)
# ---------------------------------------------------------------------------


def test_graph_drill_fast_smoke(capsys):
    """Tier-1 acceptance: `rtfd graph-drill --fast` runs un-slow-marked
    on every pass. Pins the whole graph-plane contract: typed graph +
    two-hop sampling feeding the GNN across 2 partition workers,
    ring-phase AUC lift over the trees-only incumbent, cross-partition
    fetches exercised, netfault degrade window with zero lost scores,
    columnar==serial bit-exact, digest-identical fresh second run."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["graph-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["ring_auc_lift"] and checks["healthy_not_regressed"]
    assert checks["ring_straddles_shards"]
    assert checks["remote_fetch_exercised"]
    assert checks["degrade_exercised_in_window"]
    assert checks["no_degrade_before_window"]
    assert checks["zero_lost"] and checks["every_txn_scored_once"]
    assert checks["zero_errors"] and checks["offsets_gap_free"]
    assert checks["columnar_serial_bitexact"]
    assert checks["replay_bit_identical"]
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["auc"]["ring_phase_lift"] >= 0.05
    assert full["remote_fetches"] > 0 and full["lost"] == 0

"""Stream layer tests: transport semantics, microbatching, the full job."""

import time

import numpy as np
import pytest

from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.stream import (
    FaultInjector,
    InMemoryBroker,
    JobConfig,
    MicrobatchAssembler,
    StreamJob,
)
from realtime_fraud_detection_tpu.stream import topics as T


def test_broker_keyed_partition_ordering():
    b = InMemoryBroker()
    for i in range(20):
        b.produce(T.TRANSACTIONS, {"n": i}, key="user_7")
    c = b.consumer([T.TRANSACTIONS], "g1")
    recs = c.poll(100)
    assert [r.value["n"] for r in recs] == list(range(20))
    assert len({r.partition for r in recs}) == 1  # same key -> same partition


def test_keyed_partitioning_is_restart_stable_crc32():
    """key->partition must be crc32 (process-stable), not salted hash():
    a WAL-backed broker replayed in a new process must route old keys to
    the same partitions, and the in-memory + Kafka transports must agree."""
    import zlib

    b = InMemoryBroker()
    n = b.partitions(T.TRANSACTIONS)
    for key in ("user_1", "user_42", "m-997", "", "unicode-é"):
        assert b.select_partition(T.TRANSACTIONS, key) == \
            zlib.crc32(key.encode()) % n


def test_fanout_failure_releases_inflight_ids_no_record_loss():
    """If fan-out raises mid-batch (broker down), the in-flight ids must be
    released and offsets NOT committed, so redelivery rescores the batch
    instead of dropping it as duplicates (ADVICE r2: silent record loss)."""
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=23)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=8))
    records = gen.generate_batch(6)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    batch = job.assembler.next_batch(block=True, timeout_s=1.0)

    # break scoring (so txn-cache write-back never runs) AND fan-out
    real_produce = broker.produce
    real_dispatch = scorer.dispatch
    scorer.dispatch = lambda *a, **k: (_ for _ in ()).throw(RuntimeError())
    broker.produce = lambda *a, **k: (_ for _ in ()).throw(OSError("down"))
    ctx = job.dispatch_batch(batch, now=1000.0)
    with pytest.raises(OSError):
        job.complete_batch(ctx)
    broker.produce = real_produce
    scorer.dispatch = real_dispatch

    assert not job._inflight_ids          # released despite the exception
    assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 6  # no commit

    # crash-restart: a new job in the same group replays from the committed
    # offset and must rescore the batch, not drop it as duplicates
    job2 = StreamJob(broker, scorer, JobConfig(max_batch=8))
    assert job2.run_until_drained(now=1001.0) == 6
    assert job2.counters["duplicates_skipped"] == 0
    assert broker.lag(job2.config.group_id, T.TRANSACTIONS) == 0


def test_consumer_commit_and_replay():
    b = InMemoryBroker()
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    first = c.poll(4)
    assert len(first) == 4
    # crash without commit: a new consumer in the group re-reads everything
    c2 = b.consumer([T.TRANSACTIONS], "g")
    assert len(c2.poll(100)) == 10
    c2.commit()
    # committed: nothing left
    c3 = b.consumer([T.TRANSACTIONS], "g")
    assert c3.poll(100) == []
    assert b.lag("g", T.TRANSACTIONS) == 0


def test_unkeyed_round_robin_spreads():
    b = InMemoryBroker()
    for i in range(24):
        b.produce(T.TRANSACTIONS, {"n": i})
    ends = b.end_offsets(T.TRANSACTIONS)
    assert sum(ends) == 24
    assert max(ends) - min(ends) <= 1  # even spread


def test_fault_injection_at_least_once():
    """Drops delay delivery (position rewinds to the dropped record); every
    record still arrives eventually, and duplicates model redelivery."""
    b = InMemoryBroker()
    for i in range(200):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    f = FaultInjector(drop_prob=0.1, duplicate_prob=0.1, seed=42)
    c = b.consumer([T.TRANSACTIONS], "g", faults=f)
    ns = []
    polls = 0
    while len(set(ns)) < 200 and polls < 1000:
        ns.extend(r.value["n"] for r in c.poll(500))
        polls += 1
    assert set(ns) == set(range(200))  # at-least-once: nothing lost
    assert polls > 1                   # drops actually delayed delivery
    assert len(ns) > 200               # duplicates happened


def test_microbatch_size_trigger():
    b = InMemoryBroker()
    for i in range(300):
        b.produce(T.TRANSACTIONS, {"n": i}, key=str(i))
    a = MicrobatchAssembler(b.consumer([T.TRANSACTIONS], "g"), max_batch=256,
                            max_delay_ms=1e9)
    batch = a.next_batch(block=False)
    assert len(batch) == 256
    rest = a.next_batch(block=False)
    assert rest == []  # 44 pending, deadline infinite, size not reached
    assert len(a.flush()) == 44


def test_microbatch_deadline_trigger():
    b = InMemoryBroker()
    clock = [0.0]
    a = MicrobatchAssembler(
        b.consumer([T.TRANSACTIONS], "g"), max_batch=256, max_delay_ms=5.0,
        clock=lambda: clock[0],
    )
    for i in range(3):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    assert a.next_batch(block=False) == []   # pulls 3, deadline not passed
    clock[0] += 0.006                        # 6 ms later
    batch = a.next_batch(block=False)
    assert len(batch) == 3                   # deadline closed the batch


@pytest.fixture(scope="module")
def job_env():
    gen = TransactionGenerator(num_users=60, num_merchants=25, seed=11)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=32, max_delay_ms=1.0))
    return gen, broker, job


def test_stream_job_end_to_end(job_env):
    gen, broker, job = job_env
    records = gen.generate_batch(50)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    scored = job.run_until_drained(now=1000.0)
    assert scored == 50
    preds = broker.consumer([T.PREDICTIONS], "check").poll(1000)
    assert len(preds) == 50
    enriched = broker.consumer([T.ENRICHED], "check").poll(1000)
    assert len(enriched) == 50
    assert all("fraud_score" in r.value for r in enriched)
    feats = broker.consumer([T.FEATURES], "check").poll(1000)
    assert len(feats) == 50
    assert len(feats[0].value["features"]) == 64
    # offsets are committed after fan-out
    assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0


def test_stream_job_replay_dedupe(job_env):
    """Re-delivering the same records must not double-score (exactly-once
    effect via txn-cache dedupe)."""
    gen, broker, job = job_env
    records = gen.generate_batch(10)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    job.run_until_drained(now=2000.0)
    before = job.counters["scored"]
    # simulate redelivery (e.g. crash before commit): same records again
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    job.run_until_drained(now=2001.0)
    assert job.counters["scored"] == before
    assert job.counters["duplicates_skipped"] == 10
    # cache-hit duplicates re-emit their prediction ONCE each (at-least-
    # once delivery), even when redelivery lands both copies in one poll
    broker.produce_batch(T.TRANSACTIONS, records + records,
                         key_fn=lambda r: str(r["user_id"]))
    job.run_until_drained(now=2002.0)
    assert job.counters["scored"] == before
    preds = broker.consumer([T.PREDICTIONS], "rchk").poll(1000)
    from collections import Counter
    replayed = Counter(p.value["transaction_id"] for p in preds
                       if p.value["explanation"].get("replayed_from_cache"))
    # run 2 re-emitted each id once; run 3's double-copy collapsed to one
    assert set(replayed.values()) == {2}
    assert len(replayed) == 10


def test_enrichment_applies_with_analytics_only(job_env):
    """enable_enrichment must still blend when emit_enriched=False but the
    analytics stage consumes the enriched dicts."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    gen = TransactionGenerator(num_users=15, num_merchants=8, seed=13)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=16, emit_enriched=False, enable_analytics=True,
        enable_enrichment=True))
    records = gen.generate_batch(20)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    assert job.run_until_drained(now=1000.0) == 20
    # nothing on the enriched topic, but analytics saw blended scores
    assert not broker.consumer([T.ENRICHED], "c").poll(100)
    assert job.analytics.stats()["user_velocity"]["watermark"] > 0


def test_pipelined_dispatch_dedupes_in_flight():
    """A duplicate transaction_id in batch N+1 while batch N is still in
    flight (dispatched, not completed) must be skipped — the pipelined
    dedupe checks in-flight ids, not just the txn cache."""
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=17)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=8))
    records = gen.generate_batch(8)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    batch1 = job.assembler.next_batch(block=True, timeout_s=1.0)
    ctx1 = job.dispatch_batch(batch1, now=1000.0)
    # redeliver the same records while ctx1 is in flight
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    batch2 = job.assembler.next_batch(block=True, timeout_s=1.0)
    ctx2 = job.dispatch_batch(batch2, now=1000.5)
    assert job.counters["duplicates_skipped"] == 8
    assert len(job.complete_batch(ctx1)) == 8
    assert job.complete_batch(ctx2) == []
    assert job.counters["scored"] == 8
    # all offsets committed (the empty ctx still commits its snapshot)
    assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0


def test_pipelined_commit_covers_only_dispatched_offsets():
    """Offsets snapshotted at dispatch: completing batch N must not commit
    past records polled for a later, still-uncommitted batch."""
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=19)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=8))
    broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(16),
                         key_fn=lambda r: str(r["user_id"]))
    batch1 = job.assembler.next_batch(block=True, timeout_s=1.0)
    ctx1 = job.dispatch_batch(batch1, now=1000.0)
    batch2 = job.assembler.next_batch(block=True, timeout_s=1.0)
    assert batch2
    job.dispatch_batch(batch2, now=1000.1)  # in flight, never completed
    job.complete_batch(ctx1)
    # only batch1's records are covered by the commit: batch2 replays
    lag = broker.lag(job.config.group_id, T.TRANSACTIONS)
    assert lag == len(batch2)


def test_depth3_crash_between_writeback_and_fanout_loses_nothing():
    """THE depth-3 failure drill: three batches in flight, the oldest
    crashes BETWEEN state write-back (finalize succeeded — records are in
    the txn cache) and fan-out (no prediction produced). The job dies
    (contract: completion failure propagates; later in-flights are
    abandoned). A restarted job must deliver a prediction for EVERY
    record: the cached-but-never-produced ones re-emit from the cache (not
    re-scored, velocity not double-counted), the rest re-score normally."""
    gen = TransactionGenerator(num_users=40, num_merchants=10, seed=31)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer,
                    JobConfig(max_batch=8, pipeline_depth=3))
    broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(24),
                         key_fn=lambda r: str(r["user_id"]))

    ctxs = []
    for i in range(3):
        batch = job.assembler.next_batch(block=True, timeout_s=1.0)
        assert batch
        ctxs.append(job.dispatch_batch(batch, now=1000.0 + i))
    n0 = len(ctxs[0].fresh)
    assert n0 > 0
    assert len(job._inflight_ids) == sum(len(c.fresh) for c in ctxs)

    real_produce = broker.produce
    broker.produce = lambda *a, **k: (_ for _ in ()).throw(OSError("down"))
    with pytest.raises(OSError):
        job.complete_batch(ctxs[0])   # finalize ran -> cache written;
    broker.produce = real_produce     # fan-out failed -> nothing produced

    assert len(job._inflight_ids) == sum(len(c.fresh) for c in ctxs[1:])
    # job crashes here: ctxs[1]/ctxs[2] are abandoned, nothing committed

    job2 = StreamJob(broker, scorer,
                     JobConfig(max_batch=8, pipeline_depth=3))
    rescored = job2.run_until_drained(now=1010.0)
    # batch-1 records are cache hits (scored, state written): re-emitted
    # from cache, not re-scored; everything else re-scores
    assert rescored == 24 - n0
    assert job2.counters["duplicates_skipped"] == n0
    assert broker.lag(job2.config.group_id, T.TRANSACTIONS) == 0
    preds = broker.consumer([T.PREDICTIONS], "chk").poll(1000)
    ids = {p.value["transaction_id"] for p in preds}
    assert len(preds) == 24 and len(ids) == 24   # every record delivered
    replayed = [p for p in preds
                if p.value["explanation"].get("replayed_from_cache")]
    assert len(replayed) == n0


def test_run_for_depth3_drains_and_scores_everything():
    """run_for with depth 3 completes every dispatched batch by return."""
    gen = TransactionGenerator(num_users=30, num_merchants=10, seed=37)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer,
                    JobConfig(max_batch=8, max_delay_ms=1.0,
                              pipeline_depth=3))
    broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(40),
                         key_fn=lambda r: str(r["user_id"]))
    scored = job.run_for(3.0)
    assert scored == 40
    assert not job._inflight_ids
    assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0


def test_topic_contract_mirrors_reference():
    """29 reference topics (27 regular + 2 compacted) with exact names and
    partition counts (create-topics.sh:60-151), plus the framework's one
    extension: the transaction-labels feedback stream."""
    from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS

    assert len(TOPIC_SPECS) == 30
    assert TOPIC_SPECS[-1].name == "transaction-labels"
    by_name = {t.name: t for t in TOPIC_SPECS}
    assert by_name["payment-transactions"].partitions == 12
    assert by_name["user-profiles"].compacted
    assert by_name["merchant-profiles"].compacted
    assert sum(t.compacted for t in TOPIC_SPECS) == 2
    for expected in ("pattern-detection", "geographic-analysis",
                     "audit-logs", "user-sessions", "login-events",
                     "blacklist-updates", "system-alerts", "risk-signals",
                     "network-analysis", "dashboard-updates",
                     "reporting-data", "merchant-transactions",
                     "fraud-metrics", "transaction-metrics"):
        assert expected in by_name, expected


def test_poisoned_record_degrades_alone_not_the_batch():
    """Per-record degradation (TransactionProcessor.java:83-91): one record
    with a malformed amount must get its own REVIEW error result while its
    batch-mates score normally — not drag the whole batch onto the error
    path."""
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=37)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=16))
    records = gen.generate_batch(10)
    records[3] = dict(records[3], amount="not-a-number")
    records[7] = dict(records[7], geolocation="garbage",  # coerced, scores
                      hour_of_day="NaNish")
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    scored = job.run_until_drained(now=1000.0)
    assert scored == 9                       # record 3 diverted, 7 coerced
    assert job.counters["errors"] == 1
    preds = broker.consumer([T.PREDICTIONS], "check").poll(100)
    assert len(preds) == 10                  # nothing silently dropped
    by_id = {r.value["transaction_id"]: r.value for r in preds}
    bad = by_id[str(records[3]["transaction_id"])]
    assert bad["decision"] == "REVIEW" and bad["risk_level"] == "ERROR"
    assert "validation_errors" in bad["explanation"]
    ok = by_id[str(records[7]["transaction_id"])]
    assert ok["risk_level"] != "ERROR"       # coercion, not rejection
    good_scores = [v for k, v in by_id.items()
                   if k != str(records[3]["transaction_id"])]
    assert all(v["risk_level"] != "ERROR" for v in good_scores)
    assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0


def test_qos_overload_drill_ladder_shed_budget():
    """THE overload acceptance drill (ISSUE 1): offered load 2x the
    sustainable rate through the real assembler/job path on a virtual
    clock. Must hold, deterministically, on CPU:

    - the degradation ladder ENGAGES under overload and DISENGAGES with
      hysteresis once the backlog drains (transitions visible in the
      Prometheus exposition),
    - only low-priority records are shed, every shed record carries an
      explicit shed reason on the predictions topic,
    - admitted transactions' p99 stays inside the configured budget.
    """
    from realtime_fraud_detection_tpu.qos import run_overload_drill

    summary, job, plane = run_overload_drill(
        offered_multiplier=2.0, overload_s=1.0, recovery_s=1.0,
        budget_ms=20.0, seed=7, return_state=True)

    # every produced record is accounted for: scored or explicitly shed
    assert summary["scored"] + summary["shed"] == summary["produced"]
    assert summary["shed"] > 0

    # ladder engaged under overload and recovered after the drain
    assert summary["max_ladder_level"] >= 1
    ladder = summary["ladder"]
    assert ladder["transitions_down"] >= 1
    assert ladder["transitions_up"] >= 1
    assert ladder["level"] == 0                  # fully recovered

    # only low-priority records were shed (high never sheds by contract)
    for key in summary["shed_by_priority_reason"]:
        priority, _, reason = key.partition(":")
        assert priority != "high", key
        assert reason.startswith("shed:"), key

    # admitted p99 inside the budget — the whole point of the plane
    assert summary["admitted_latency_ms"]["p99"] <= summary["budget_ms"], \
        summary["admitted_latency_ms"]

    # the shed decisions are ON THE PREDICTIONS TOPIC as scores-with-reason
    preds = job.broker.consumer(
        [job.config.predictions_topic], "qos-check").poll(100_000)
    shed_records = [p.value for p in preds
                    if p.value.get("explanation", {}).get("shed")]
    assert len(shed_records) == summary["shed"]
    for rec in shed_records:
        assert rec["explanation"]["shed_reason"].startswith("shed:")
        assert rec["explanation"]["priority"] != "high"
        assert rec["risk_level"] == "SHED"
        assert rec["decision"] == "REVIEW"
    # scored + shed predictions all arrived: nothing silently dropped
    assert len(preds) == summary["produced"]

    # ladder transitions are observable through the Prometheus exposition
    text = plane.metrics.render_prometheus()
    assert "qos_ladder_level" in text
    down = [ln for ln in text.splitlines()
            if ln.startswith('qos_ladder_transitions_total{direction="down"}')]
    up = [ln for ln in text.splitlines()
          if ln.startswith('qos_ladder_transitions_total{direction="up"}')]
    assert down and int(float(down[0].split()[-1])) >= 1
    assert up and int(float(up[0].split()[-1])) >= 1
    assert "qos_shed_total" in text
    assert "qos_budget_remaining_seconds_bucket" in text


def test_qos_disabled_job_unchanged():
    """JobConfig without qos: no plane, no shed counter movement, results
    identical to the pre-QoS path."""
    gen = TransactionGenerator(num_users=10, num_merchants=5, seed=43)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(max_batch=8))
    assert job.qos is None
    broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(8),
                         key_fn=lambda r: str(r["user_id"]))
    assert job.run_until_drained(now=1000.0) == 8
    assert job.counters["shed"] == 0


def test_job_topics_configurable_default_contract():
    """Topic names flow from JobConfig (reference JobConfig.java topic
    params); defaults are the §2.5 contract. A renamed predictions topic
    receives the results; the contract topic stays silent."""
    gen = TransactionGenerator(num_users=10, num_merchants=5, seed=41)
    broker = InMemoryBroker()
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=8, transactions_topic="shadow-txns",
        predictions_topic="shadow-preds", emit_features=False,
        emit_enriched=False))
    broker.produce_batch("shadow-txns", gen.generate_batch(8),
                         key_fn=lambda r: str(r["user_id"]))
    assert job.run_until_drained(now=1000.0) == 8
    assert len(broker.consumer(["shadow-preds"], "c").poll(100)) == 8
    assert broker.consumer([T.PREDICTIONS], "c2").poll(100) == []

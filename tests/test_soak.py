"""End-to-end soak: simulator -> stream job -> trained scorer -> topics.

The reference has no test suite at all (SURVEY.md §4); its substitute is
dummy-model fallbacks plus a shell health check. This soak closes the loop
the reference never did: traffic with a known injected fraud mix (~5.5%,
simulator.py:106-127) flows through the full pipeline with TRAINED tree
models, and the output scores must actually separate the injected fraud.
"""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.features.extract import extract_features
from realtime_fraud_detection_tpu.scoring import (
    FraudScorer,
    ScorerConfig,
    init_scoring_models,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.stream import (
    InMemoryBroker,
    JobConfig,
    StreamJob,
)
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.training import GBDTTrainer


def _auc(y, score):
    order = np.argsort(score)
    rank = np.empty(len(score), float)
    rank[order] = np.arange(1, len(score) + 1)
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return float(
        (rank[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


@pytest.fixture(scope="module")
def trained_job():
    import jax

    gen = TransactionGenerator(num_users=400, num_merchants=100, seed=21,
                               tps=20.0)
    # train trees on the encoded path (same §2.3 feature contract)
    batch, labels = gen.generate_encoded(6000)
    x = np.asarray(extract_features(batch))
    y = labels["is_fraud"].astype(np.float32)
    trees = GBDTTrainer(n_estimators=40, max_depth=5, seed=2).fit(x, y)

    models = init_scoring_models(jax.random.PRNGKey(0))
    models = models.replace(trees=trees)

    scorer = FraudScorer(models=models,
                         scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(max_batch=128))

    records = gen.generate_batch(1500)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    scored = job.run_until_drained(now=1_000_000.0)
    return records, broker, scored


class TestSoak:
    def test_everything_scored_exactly_once(self, trained_job):
        records, broker, scored = trained_job
        assert scored == 1500
        preds = broker.consumer([T.PREDICTIONS], "soak").poll(10_000)
        assert len(preds) == 1500
        ids = [p.value["transaction_id"] for p in preds]
        assert len(set(ids)) == 1500

    def test_injected_fraud_rate_in_band(self, trained_job):
        """Simulator injects ~5.5% fraud (simulator.py:106-127)."""
        records, _, _ = trained_job
        rate = np.mean([bool(r.get("is_fraud")) for r in records])
        assert 0.02 <= rate <= 0.10, f"fraud mix drifted: {rate:.3f}"

    def test_trained_pipeline_separates_fraud(self, trained_job):
        """E2E AUC: scores coming out of the FULL pipeline (state joins,
        feature extraction, fused ensemble with 4 random branches + trained
        trees at weight 0.40) must rank injected fraud above normals."""
        records, broker, _ = trained_job
        labels = {str(r["transaction_id"]): bool(r.get("is_fraud"))
                  for r in records}
        preds = broker.consumer([T.PREDICTIONS], "soak2").poll(10_000)
        y = np.asarray([labels[p.value["transaction_id"]] for p in preds],
                       float)
        s = np.asarray([p.value["fraud_probability"] for p in preds])
        auc = _auc(y, s)
        assert auc > 0.75, f"end-to-end AUC too low: {auc:.3f}"

    def test_fraud_scores_higher_on_average(self, trained_job):
        records, broker, _ = trained_job
        labels = {str(r["transaction_id"]): bool(r.get("is_fraud"))
                  for r in records}
        preds = broker.consumer([T.PREDICTIONS], "soak3").poll(10_000)
        fraud = [p.value["fraud_probability"] for p in preds
                 if labels[p.value["transaction_id"]]]
        normal = [p.value["fraud_probability"] for p in preds
                  if not labels[p.value["transaction_id"]]]
        assert np.mean(fraud) > np.mean(normal) + 0.02


def test_multiprocess_group_failover_no_record_loss():
    """VERDICT r3 item 6 'done' criterion: two real StreamJob WORKER
    PROCESSES in one consumer group over the Kafka wire protocol; one is
    SIGKILLed mid-stream. The survivor adopts the dead worker's partitions
    from committed offsets: every transaction ends up scored (nothing
    lost), and duplicate predictions are bounded by the dead worker's
    uncommitted tail (at-least-once; a kill landing between fan-out and
    offset commit legitimately replays that window — cross-process
    exactly-once would need the shared state tier or a transactional
    outbox, asserted elsewhere via test_shared_state.py)."""
    import os
    import subprocess
    import sys
    import time

    from realtime_fraud_detection_tpu.stream.kafka import KafkaBroker
    from realtime_fraud_detection_tpu.stream.kafka_fake import FakeKafkaServer

    server = FakeKafkaServer(port=0).start()
    worker_src = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.stream import JobConfig, StreamJob
from realtime_fraud_detection_tpu.stream.kafka import KafkaBroker

port = int(sys.argv[1])
broker = KafkaBroker(bootstrap=f"127.0.0.1:{port}")

class GroupBroker:
    def __getattr__(self, k): return getattr(broker, k)
    def consumer(self, topics, group_id, faults=None):
        return broker.consumer(topics, group_id, group_managed=True)

gen = TransactionGenerator(num_users=40, num_merchants=15, seed=101)
scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
job = StreamJob(GroupBroker(), scorer,
                JobConfig(max_batch=16, max_delay_ms=5.0))
job.consumer.membership.session_timeout_ms = 2000
print("READY", flush=True)
deadline = time.time() + 120
while time.time() < deadline:
    batch = job.assembler.next_batch(block=False)
    if not batch:
        batch = job.assembler.flush()
    if batch:
        job.process_batch(batch, now=1000.0)
        print(f"SCORED {job.counters['scored']}", flush=True)
    else:
        time.sleep(0.05)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(sys.path))
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", worker_src, str(server.port)],
            env=env, stdout=subprocess.PIPE, text=True, bufsize=1)

    w1 = spawn()
    try:
        assert w1.stdout.readline().strip() == "READY"
        w2 = spawn()
        assert w2.stdout.readline().strip() == "READY"

        prod = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}",
                           idempotent=True)
        gen = TransactionGenerator(num_users=40, num_merchants=15, seed=101)
        records = gen.generate_batch(120)
        prod.produce_batch(T.TRANSACTIONS, records,
                           key_fn=lambda r: str(r["user_id"]))

        # let w1 score a couple of batches, then kill it hard. The reads
        # are select-bounded: partition skew can leave w1 with few records,
        # and a blocking readline would stall the test for the worker's
        # whole internal deadline.
        import select

        deadline = time.time() + 30
        scored_lines = 0
        while scored_lines < 2 and time.time() < deadline:
            ready, _, _ = select.select([w1.stdout], [], [], 1.0)
            if not ready:
                continue
            line = w1.stdout.readline()
            if line.startswith("SCORED"):
                scored_lines += 1
            elif not line:
                break
        w1.kill()                     # SIGKILL: no LeaveGroup, no commit
        w1.wait(timeout=10)

        # wait until the predictions topic covers every transaction id
        check = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
        want = {str(r["transaction_id"]) for r in records}
        seen: list = []
        deadline = time.time() + 90
        consumer = check.consumer([T.PREDICTIONS], "verify")
        while time.time() < deadline:
            seen.extend(r.value["transaction_id"] for r in consumer.poll(500))
            if set(seen) >= want:
                break
            time.sleep(0.25)
        w2.kill()
        assert set(seen) >= want, (
            f"lost {len(want - set(seen))} of {len(want)} transactions")
        # duplicates may only come from w1's uncommitted tail (one batch
        # window, max_batch=16 + one in-flight batch), never wholesale
        n_dups = len(seen) - len(set(seen))
        assert n_dups <= 32, (
            f"{n_dups} duplicate predictions — more than the uncommitted "
            "tail can explain; replay fencing is broken")
        prod.close()
        check.close()
    finally:
        for p in (w1, w2):
            if p.poll() is None:
                p.kill()
        server.stop()

"""End-to-end soak: simulator -> stream job -> trained scorer -> topics.

The reference has no test suite at all (SURVEY.md §4); its substitute is
dummy-model fallbacks plus a shell health check. This soak closes the loop
the reference never did: traffic with a known injected fraud mix (~5.5%,
simulator.py:106-127) flows through the full pipeline with TRAINED tree
models, and the output scores must actually separate the injected fraud.
"""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.features.extract import extract_features
from realtime_fraud_detection_tpu.scoring import (
    FraudScorer,
    ScorerConfig,
    init_scoring_models,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.stream import (
    InMemoryBroker,
    JobConfig,
    StreamJob,
)
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.training import GBDTTrainer


def _auc(y, score):
    order = np.argsort(score)
    rank = np.empty(len(score), float)
    rank[order] = np.arange(1, len(score) + 1)
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return float(
        (rank[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


@pytest.fixture(scope="module")
def trained_job():
    import jax

    gen = TransactionGenerator(num_users=400, num_merchants=100, seed=21,
                               tps=20.0)
    # train trees on the encoded path (same §2.3 feature contract)
    batch, labels = gen.generate_encoded(6000)
    x = np.asarray(extract_features(batch))
    y = labels["is_fraud"].astype(np.float32)
    trees = GBDTTrainer(n_estimators=40, max_depth=5, seed=2).fit(x, y)

    models = init_scoring_models(jax.random.PRNGKey(0))
    models = models.replace(trees=trees)

    scorer = FraudScorer(models=models,
                         scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(max_batch=128))

    records = gen.generate_batch(1500)
    broker.produce_batch(T.TRANSACTIONS, records,
                         key_fn=lambda r: str(r["user_id"]))
    scored = job.run_until_drained(now=1_000_000.0)
    return records, broker, scored


class TestSoak:
    def test_everything_scored_exactly_once(self, trained_job):
        records, broker, scored = trained_job
        assert scored == 1500
        preds = broker.consumer([T.PREDICTIONS], "soak").poll(10_000)
        assert len(preds) == 1500
        ids = [p.value["transaction_id"] for p in preds]
        assert len(set(ids)) == 1500

    def test_injected_fraud_rate_in_band(self, trained_job):
        """Simulator injects ~5.5% fraud (simulator.py:106-127)."""
        records, _, _ = trained_job
        rate = np.mean([bool(r.get("is_fraud")) for r in records])
        assert 0.02 <= rate <= 0.10, f"fraud mix drifted: {rate:.3f}"

    def test_trained_pipeline_separates_fraud(self, trained_job):
        """E2E AUC: scores coming out of the FULL pipeline (state joins,
        feature extraction, fused ensemble with 4 random branches + trained
        trees at weight 0.40) must rank injected fraud above normals."""
        records, broker, _ = trained_job
        labels = {str(r["transaction_id"]): bool(r.get("is_fraud"))
                  for r in records}
        preds = broker.consumer([T.PREDICTIONS], "soak2").poll(10_000)
        y = np.asarray([labels[p.value["transaction_id"]] for p in preds],
                       float)
        s = np.asarray([p.value["fraud_probability"] for p in preds])
        auc = _auc(y, s)
        assert auc > 0.75, f"end-to-end AUC too low: {auc:.3f}"

    def test_fraud_scores_higher_on_average(self, trained_job):
        records, broker, _ = trained_job
        labels = {str(r["transaction_id"]): bool(r.get("is_fraud"))
                  for r in records}
        preds = broker.consumer([T.PREDICTIONS], "soak3").poll(10_000)
        fraud = [p.value["fraud_probability"] for p in preds
                 if labels[p.value["transaction_id"]]]
        normal = [p.value["fraud_probability"] for p in preds
                  if not labels[p.value["transaction_id"]]]
        assert np.mean(fraud) > np.mean(normal) + 0.02

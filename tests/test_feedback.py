"""Continuous-learning plane (feedback/): prequential math pinned against
offline references, label-join semantics, buffer/policy/gate units, the
serving endpoints, and the closed-loop drill acceptance criteria."""

import asyncio
import contextlib
import io
import json
import math

import numpy as np
import pytest

from realtime_fraud_detection_tpu.feedback.labels import (
    LabelJoin,
    make_label_events,
)
from realtime_fraud_detection_tpu.feedback.policy import (
    PromotionGate,
    RetrainPolicy,
)
from realtime_fraud_detection_tpu.feedback.prequential import (
    FadingAUC,
    PrequentialEvaluator,
    sliding_auc,
    weighted_auc,
)
from realtime_fraud_detection_tpu.state.labeled import LabeledExampleBuffer


# --------------------------------------------------------------- prequential
class TestPrequentialMath:
    def _event_sequence(self, n=1500, seed=0):
        rng = np.random.default_rng(seed)
        y = (rng.random(n) < 0.25).astype(float)
        # heavy ties: scores quantized to 2 decimals, informative but noisy
        s = np.round(np.clip(0.55 * y + 0.3 * rng.random(n), 0, 1), 2)
        return y, s

    def test_sliding_auc_matches_sklearn_on_same_event_sequence(self):
        sk = pytest.importorskip("sklearn.metrics")
        y, s = self._event_sequence()
        window = 400
        ev = PrequentialEvaluator(window=window, threshold=0.5)
        for yi, si in zip(y, s):
            ev.update(si, bool(yi))
        yw, sw = y[-window:], s[-window:]
        assert abs(ev.auc() - sk.roc_auc_score(yw, sw)) <= 1e-6
        pr = ev.precision_recall()
        flag = sw >= 0.5
        assert abs(pr["precision"]
                   - sk.precision_score(yw, flag)) <= 1e-6
        assert abs(pr["recall"] - sk.recall_score(yw, flag)) <= 1e-6

    def test_sliding_auc_ties_not_credited_in_argsort_order(self):
        # a constant scorer must be exactly 0.5, not 1.0
        y = np.array([0, 1, 0, 1, 1, 0], float)
        s = np.full(6, 0.7)
        assert sliding_auc(y, s) == pytest.approx(0.5)

    def test_fading_auc_matches_numpy_double_sum_reference(self):
        y, s = self._event_sequence(n=600, seed=3)
        gamma = 0.98
        f = FadingAUC(gamma=gamma, threshold=0.5)
        for yi, si in zip(y, s):
            f.update(si, bool(yi))
        n = len(f)
        yw, sw = y[-n:], s[-n:]
        w = gamma ** np.arange(n - 1, -1, -1, dtype=float)
        pos_idx = np.where(yw > 0.5)[0]
        neg_idx = np.where(yw <= 0.5)[0]
        num = 0.0
        for i in pos_idx:           # the O(n^2) definition, verbatim
            num += (w[i] * w[neg_idx] * (
                (sw[i] > sw[neg_idx]) + 0.5 * (sw[i] == sw[neg_idx]))).sum()
        ref = num / (w[pos_idx].sum() * w[neg_idx].sum())
        assert abs(f.auc() - ref) <= 1e-9

    def test_weighted_auc_single_class_is_nan(self):
        assert math.isnan(weighted_auc(np.ones(5), np.arange(5.0),
                                       np.ones(5)))

    def test_calibration_error_reference(self):
        ev = PrequentialEvaluator(window=100, calibration_bins=2)
        # bin [0, .5): scores .2, fraud rate 0; bin [.5, 1]: .8 vs rate 0.5
        for _ in range(2):
            ev.update(0.2, False)
            ev.update(0.8, True)
            ev.update(0.8, False)
        # ece = (2/6)*|.2-0| + (4/6)*|.8-.5|
        assert ev.calibration_error() == pytest.approx(
            (2 / 6) * 0.2 + (4 / 6) * 0.3)

    def test_drop_one_attribution_flags_the_carrying_branch(self):
        rng = np.random.default_rng(1)
        ev = PrequentialEvaluator(window=600)
        for _ in range(600):
            y = rng.random() < 0.3
            good = 0.7 * y + 0.2 * rng.random()
            noise = rng.random()
            served = 0.8 * good + 0.2 * noise
            ev.update(served, bool(y),
                      branch_preds={"good": good, "noise": noise})
        attr = ev.drop_one_attribution({"good": 0.8, "noise": 0.2})
        assert attr["good"] > 0.1          # dropping it hurts a lot
        assert attr["noise"] < 0.05        # dropping noise barely matters


# ---------------------------------------------------------------- label join
class TestLabelJoin:
    def test_match_and_lag(self):
        j = LabelJoin(horizon_s=100, pred_ooo_s=1, label_ooo_s=1)
        assert j.process_prediction("a", 10.0, {"score": 0.9}) == []
        out = j.process_label({"transaction_id": "a", "is_fraud": True,
                               "label_ts": 14.0})
        assert len(out) == 1 and out[0]["is_fraud"] \
            and out[0]["label_lag_s"] == pytest.approx(4.0)
        assert j.stats()["matched"] == 1 and len(j) == 0

    def test_early_label_buffers_until_prediction(self):
        j = LabelJoin(horizon_s=100)
        j.process_label({"transaction_id": "b", "is_fraud": False,
                         "label_ts": 5.0})
        out = j.process_prediction("b", 5.5, {"score": 0.1})
        assert len(out) == 1 and out[0]["is_fraud"] is False

    def test_unlabeled_prediction_expires_counted(self):
        j = LabelJoin(horizon_s=10, pred_ooo_s=0, label_ooo_s=0)
        j.process_prediction("old", 0.0, {"score": 0.5})
        # advance both watermarks past ts + horizon
        j.process_prediction("new", 20.0, {"score": 0.5})
        j.process_label({"transaction_id": "x", "is_fraud": False,
                         "label_ts": 20.0})
        assert j.stats()["expired_unlabeled"] == 1
        # the expired prediction never matches
        assert j.process_label({"transaction_id": "old", "is_fraud": True,
                                "label_ts": 21.0}) == []

    def test_duplicate_label_and_replayed_prediction_dedupe(self):
        j = LabelJoin(horizon_s=100)
        j.process_label({"transaction_id": "c", "is_fraud": True,
                         "label_ts": 1.0})
        j.process_label({"transaction_id": "c", "is_fraud": True,
                         "label_ts": 1.5})
        assert j.stats()["duplicate_labels"] == 1
        j.process_prediction("d", 2.0, {"score": 0.5})
        assert j.process_prediction("d", 2.1, {"score": 0.5}) == []

    def test_pending_capped_even_with_silent_label_stream(self):
        # no label ever arrives -> joint watermark never advances, but the
        # pending table must stay bounded (oldest expire, counted)
        j = LabelJoin(horizon_s=1e9, max_pending=50)
        for i in range(200):
            j.process_prediction(f"p{i}", float(i), {"score": 0.5})
        assert len(j) <= 50
        assert j.stats()["expired_unlabeled"] == 150
        # the survivors are the NEWEST predictions
        assert j.process_label({"transaction_id": "p199", "is_fraud": True,
                                "label_ts": 300.0})

    def test_replay_after_match_never_double_counts(self):
        j = LabelJoin(horizon_s=100)
        j.process_prediction("e", 1.0, {"score": 0.9})
        label = {"transaction_id": "e", "is_fraud": True, "label_ts": 2.0}
        assert len(j.process_label(label)) == 1
        # label redelivered after the match fired: dropped, counted
        assert j.process_label(dict(label)) == []
        assert j.stats()["duplicate_labels"] == 1
        # prediction redelivered after the match fired: no re-buffer, so a
        # further label replay still can't re-match
        assert j.process_prediction("e", 1.0, {"score": 0.9}) == []
        assert j.process_label(dict(label)) == []
        assert j.stats()["matched"] == 1

    def test_make_label_events_chargeback_shape(self):
        rng = np.random.default_rng(0)
        txns = [{"transaction_id": f"t{i}", "is_fraud": i % 2 == 0,
                 "timestamp_ms": 1000.0 * i} for i in range(200)]
        events = make_label_events(txns, rng, delay_scale=1.0)
        assert len(events) == 200
        lags = {e["transaction_id"]: e["label_ts"] - e["event_ts"]
                for e in events}
        fraud_lags = [lags[f"t{i}"] for i in range(0, 200, 2)]
        legit_lags = [lags[f"t{i}"] for i in range(1, 200, 2)]
        # chargebacks (fraud) arrive much later than legit confirmations
        assert np.median(fraud_lags) > 2 * np.median(legit_lags)
        assert all(v > 0 for v in lags.values())
        # sorted by label time (topic order)
        ts = [e["label_ts"] for e in events]
        assert ts == sorted(ts)


# -------------------------------------------------------------------- buffer
def test_labeled_buffer_bounded_and_class_aware():
    buf = LabeledExampleBuffer(capacity=100)
    for i in range(1000):
        buf.append(np.full(4, i, np.float32), i % 20 == 0, 0.5, float(i))
    st = buf.stats()
    assert st["size"] <= 100
    # positives are 5% of the stream but hold their reserved slots
    assert st["positives"] == 20
    arrays = buf.arrays()
    assert arrays["x"].shape[1] == 4
    assert (np.diff(arrays["ts"]) >= 0).all()     # time-ordered
    assert st["evicted"] == 1000 - st["size"]


# -------------------------------------------------------------------- policy
def test_retrain_policy_triggers_on_auc_drop_with_cooldown():
    p = RetrainPolicy(auc_drop=0.1, min_labels=10, cooldown_s=100)
    healthy = {"labeled_total": 50,
               "sliding": {"auc": 0.95}, "fading": {"auc": 0.96}}
    degraded = {"labeled_total": 50,
                "sliding": {"auc": 0.80}, "fading": {"auc": 0.95}}
    assert p.observe(healthy, None, now=0.0) is None
    t = p.observe(degraded, None, now=1.0)
    assert t is not None and t["reason"] == "prequential_auc_drop"
    assert p.observe(degraded, None, now=50.0) is None    # cooldown
    assert p.observe(degraded, None, now=200.0) is not None

    few = {"labeled_total": 5,
           "sliding": {"auc": 0.5}, "fading": {"auc": 0.99}}
    assert RetrainPolicy(min_labels=10).observe(few, None, 0.0) is None


def test_retrain_policy_drift_trigger():
    class Report:
        drifted = True
        max_psi = 0.4
        top_features = [3, 7]

    p = RetrainPolicy(min_labels=0, use_drift=True)
    t = p.observe({"labeled_total": 1, "sliding": {"auc": float("nan")},
                   "fading": {"auc": float("nan")}}, Report(), now=0.0)
    assert t["reason"] == "feature_drift" and t["max_psi"] == 0.4


def test_promotion_gate_non_regression_and_min_positives():
    gate = PromotionGate(min_positives=5, operating_threshold=0.5)
    y = np.array([1] * 20 + [0] * 80, float)
    served = np.clip(0.6 * y + 0.2 * np.random.default_rng(0).random(100),
                     0, 1)
    better = np.clip(served + 0.2 * y, 0, 1)
    worse = np.clip(served - 0.5 * y, 0, 1)
    ok = gate.evaluate({"strategy": "weighted_average", "holdout": {
        "y": y, "as_served": served, "candidate": better}})
    assert ok["passed"] and ok["auc_candidate"] >= ok["auc_as_served"]
    bad = gate.evaluate({"strategy": "weighted_average", "holdout": {
        "y": y, "as_served": served, "candidate": worse}})
    assert not bad["passed"] and bad["reason"] == "auc_regression"
    thin = gate.evaluate({"strategy": "weighted_average", "holdout": {
        "y": y[16:26], "as_served": served[16:26],
        "candidate": better[16:26]}})    # only 4 labeled positives
    assert not thin["passed"] and "insufficient" in thin["reason"]


# ----------------------------------------------------------------- simulator
def test_simulator_drift_injection_is_labeled_and_in_band():
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=200, num_merchants=100, seed=9)
    gen.inject_drift(0.2)
    txns = gen.generate_batch(800)
    drifted = [t for t in txns if t.get("fraud_type") == "drifted_pattern"]
    assert 80 <= len(drifted) <= 260
    for t in drifted[:20]:
        assert t["is_fraud"] is True
        assert t["payment_method"] == "digital_wallet"
        assert t["fraud_score"] < 0.3       # benign-looking prior
    gen.clear_drift()
    assert not [t for t in gen.generate_batch(300)
                if t.get("fraud_type") == "drifted_pattern"]


# ------------------------------------------------------- the closed-loop drill
@pytest.fixture(scope="module")
def drill_run():
    """ONE `rtfd feedback-drill --fast` through the real CLI: the smoke
    test and the stdout-contract test share this run."""
    from realtime_fraud_detection_tpu import cli

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["feedback-drill", "--fast"])
    lines = [ln for ln in buf.getvalue().strip().splitlines() if ln.strip()]
    return rc, lines


def test_feedback_drill_closed_loop(drill_run):
    """The ISSUE 3 acceptance drill: injected drift -> prequential AUC dip
    -> retrain trigger -> promotion only after gate-pass -> AUC recovers;
    the gate-failed control left the serving blend bit-identical."""
    rc, lines = drill_run
    assert rc == 0
    full = json.loads(lines[-2])
    assert full["passed"] is True
    assert full["auc_dipped"] is True
    assert full["baseline_auc"] - full["dip_auc"] >= 0.05
    assert full["retrain_triggered"] is True
    assert full["trigger_reason"] in ("prequential_auc_drop",
                                     "prequential_auc_floor",
                                     "feature_drift")
    # no promotion ever on a gate-fail; rejected candidate = bit-identical
    assert full["gate_control_rejected"] is True
    assert full["blend_unchanged_on_reject"] is True
    assert full["policy"]["gate_fail"] >= 1
    assert full["policy"]["promotions"] == full["policy"]["gate_pass"] == 1
    # promotion only after gate-pass, through the reload recipe
    assert full["promoted"] is True
    assert full["gate"]["passed"] is True
    assert full["gate"]["auc_candidate"] > full["gate"]["auc_as_served"]
    # and live quality recovers under the still-flowing drifted pattern
    assert full["recovered_auc"] >= full["baseline_auc"] - 0.05
    # label-join hygiene: everything matched or explicitly accounted
    lj = full["label_join"]
    assert lj["matched"] > 3000 and lj["orphan_labels"] == 0


def test_feedback_drill_final_line_is_compact_parseable_json(drill_run):
    rc, lines = drill_run
    final = lines[-1]
    assert len(final.encode()) < 2048
    compact = json.loads(final)
    assert compact["metric"] == "feedback_drill"
    assert compact["passed"] is True
    for key in ("baseline_auc", "dip_auc", "recovered_auc",
                "retrain_triggered", "gate_control_rejected",
                "blend_unchanged_on_reject", "promoted"):
        assert key in compact


# ------------------------------------------------------------------- serving
@pytest.fixture(scope="module")
def feedback_app():
    from realtime_fraud_detection_tpu.serving.app import ServingApp
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = Config()
    cfg.feedback.enabled = True
    cfg.feedback.min_labels = 10 ** 9       # endpoints only, never retrain
    return ServingApp(config=cfg)


def test_serving_label_ingest_quality_live_and_prometheus(feedback_app):
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    app = feedback_app
    gen = TransactionGenerator(num_users=50, num_merchants=20, seed=2)
    txns = gen.generate_batch(8)
    results = app._score_batch_sync(txns)
    labels = [{"transaction_id": r["transaction_id"],
               "is_fraud": bool(t.get("is_fraud"))}
              for t, r in zip(txns, results)]
    status, payload = asyncio.run(app._ingest_labels(labels, {}))
    assert status == 200 and payload["matched"] == 8
    status, q = asyncio.run(app._quality_live(None, {}))
    assert status == 200
    assert q["prequential"]["labeled_total"] == 8
    assert q["label_join"]["matched"] == 8
    assert q["buffer"]["size"] == 8
    _, prom = asyncio.run(app._metrics_prometheus(None, {}))
    assert "prequential_auc" in prom
    assert 'feedback_labels_total{outcome="matched"} 8' in prom


def test_serving_label_ingest_validates(feedback_app):
    from realtime_fraud_detection_tpu.serving.httpd import HttpError

    with pytest.raises(HttpError) as ei:
        asyncio.run(feedback_app._ingest_labels([{"is_fraud": True}], {}))
    assert ei.value.status == 422


def test_reload_models_refuses_text_arch_mismatch(feedback_app, tmp_path):
    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.serving.httpd import HttpError

    ck_dir = tmp_path / "ck"
    CheckpointManager(str(ck_dir)).save(
        0, metadata={"text_model": {"hidden_size": 128, "num_layers": 2}})
    art = tmp_path / "quality.json"
    art.write_text(json.dumps({
        "protocol": {"text_model": {"hidden_size": 768, "num_layers": 6}},
        "selected_blend": {"weights": {"xgboost_primary": 1.0}},
    }))
    with pytest.raises(HttpError) as ei:
        asyncio.run(feedback_app._reload_models(
            {"checkpoint_dir": str(ck_dir), "quality_artifact": str(art)},
            {}))
    assert ei.value.status == 409
    assert "architecture mismatch" in str(ei.value.detail)


# ----------------------------------------------- stacked-combiner deployment
def test_artifact_strategy_deploys_and_ab_refuses_stacking(tmp_path):
    from realtime_fraud_detection_tpu.testing import ABTestManager
    from realtime_fraud_detection_tpu.utils.config import Config

    art = tmp_path / "q.json"
    art.write_text(json.dumps({"selected_blend": {
        "weights": {"xgboost_primary": 0.7, "isolation_forest": 0.3},
        "strategy": "stacking"}}))
    cfg = Config()
    cfg.apply_quality_artifact(str(art))
    assert cfg.ensemble.strategy == "stacking"
    assert not cfg.models["bert_text"].enabled
    # the host-side A/B canary cannot emulate stacking — it must refuse
    with pytest.raises(ValueError, match="stacking"):
        ABTestManager().experiment_from_artifact("exp", str(art))
    # and a typo'd strategy never deploys
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"selected_blend": {
        "weights": {"xgboost_primary": 1.0}, "strategy": "stackingg"}}))
    with pytest.raises(ValueError, match="stackingg"):
        Config().apply_quality_artifact(str(bad))


def test_retrainer_trains_neural_branch_from_buffered_history():
    from realtime_fraud_detection_tpu.feedback.policy import Retrainer

    rng = np.random.default_rng(4)
    n, f = 400, 8
    y = (rng.random(n) < 0.2).astype(np.float32)
    x = rng.normal(size=(n, f)).astype(np.float32) + y[:, None]
    arrays = {
        "x": x, "y": y,
        "score": np.clip(0.5 * y + 0.3 * rng.random(n), 0, 1)
                   .astype(np.float32),
        "ts": np.arange(n, dtype=np.float64),
        "history": rng.normal(size=(n, 5, f)).astype(np.float32)
                     + y[:, None, None],
        "history_len": np.full(n, 5, np.int32),
    }
    cand = Retrainer(n_trees=8, depth=3, iforest_trees=16, train_neural=True,
                     neural_hidden=16, neural_epochs=1).retrain(
        arrays, weights={"xgboost_primary": 0.5, "isolation_forest": 0.2,
                         "lstm_sequential": 0.3})
    assert cand["lstm"] is not None
    assert np.isfinite(cand["holdout"]["candidate"]).all()
    assert 0.3 in [round(v, 4) for v in cand["weights"].values()]


def test_blend_fn_stacking_differs_and_runs_device_combine():
    from realtime_fraud_detection_tpu.training.blend_eval import _blend_fn

    rng = np.random.default_rng(0)
    scores = {"xgboost_primary": rng.random(64).astype(np.float32),
              "isolation_forest": rng.random(64).astype(np.float32)}
    w = {"xgboost_primary": 0.8, "isolation_forest": 0.2}
    wa = _blend_fn(w, "weighted_average")(scores)
    st = _blend_fn(w, "stacking")(scores)
    assert wa.shape == st.shape == (64,)
    assert not np.allclose(wa, st)

"""Blend-selection protocol (training/blend_eval.py): the quality evidence
behind the production model_valid/weights setting. Tiny sizes — the full
protocol is the committed QUALITY_r05.json (rtfd quality-eval)."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.bert import BertConfig
from realtime_fraud_detection_tpu.training.blend_eval import (
    BlendEvalConfig,
    _auc,
    _blend_fn,
    run_blend_eval,
)


def _tiny_cfg() -> BlendEvalConfig:
    return BlendEvalConfig(
        num_users=300, num_merchants=100, seed=5, batch_size=128,
        train_batches=10, val_batches=3, test_batches=5,
        n_trees=10, tree_depth=4, iforest_trees=20,
        lstm_epochs=2, text_epochs=1, gnn_epochs=1, text_len=16,
        bert=BertConfig(hidden_size=32, num_layers=1, num_heads=2,
                        intermediate_size=64),
        bootstrap=50,
    )


def test_auc_known_answer():
    y = np.array([0, 0, 1, 1], np.float32)
    assert _auc(y, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    assert _auc(y, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
    assert _auc(y, np.array([0.5, 0.5, 0.5, 0.5])) == pytest.approx(0.5)


def test_blend_fn_matches_manual_weighted_average():
    """Serving parity: _blend_fn must equal the renormalized weighted
    average the device combine computes over the valid branch set."""
    rng = np.random.default_rng(0)
    n = 50
    scores = {"xgboost_primary": rng.random(n).astype(np.float32),
              "lstm_sequential": rng.random(n).astype(np.float32)}
    w = {"xgboost_primary": 0.3, "lstm_sequential": 0.25}
    got = _blend_fn(w)(scores)
    want = (0.3 * scores["xgboost_primary"] + 0.25 * scores["lstm_sequential"]) / 0.55
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_protocol_end_to_end_tiny():
    result = run_blend_eval(_tiny_cfg())
    # structural contract the artifact consumers rely on
    assert set(result["branch_auc"]) == {
        "xgboost_primary", "isolation_forest", "lstm_sequential", "bert_text",
        "graph_neural"}
    # baseline pair is always in the selected blend; admission is gated
    assert {"xgboost_primary", "isolation_forest"} <= set(
        result["selected_blend"]["branches"])
    assert len(result["admission"]) == 3     # every other branch got a trial
    for a in result["admission"]:
        # the gate: an accepted branch must not have regressed on val
        if a["accepted"]:
            assert a["val_auc_with"] >= a["val_auc_before"]
    # trees must carry real signal even at tiny sizes
    assert result["branch_auc"]["xgboost_primary"]["test"] > 0.8
    t = result["test"]
    assert t["blend_auc"] == pytest.approx(
        t["baseline_pair_auc"] + t["delta_auc"], abs=1e-3)
    lo, hi = t["delta_auc_bootstrap_95ci"]
    assert lo <= hi
    ops = result["operating_points"]
    assert 0 <= ops["at_0.5"]["recall"] <= 1


class TestCalibrationFold:
    """training/calibrate.py: the Platt fold must be EXACT — the calibrated
    model's own forward pass produces sigmoid(a*z+b)."""

    def test_platt_fit_recovers_shift(self):
        from realtime_fraud_detection_tpu.training.calibrate import (
            platt_apply,
            platt_fit,
        )

        rng = np.random.default_rng(0)
        z = rng.normal(0, 2, 4000)
        # true generative model: p = sigmoid(0.8 z - 1.2)
        y = (rng.random(4000) < 1 / (1 + np.exp(-(0.8 * z - 1.2)))).astype(
            np.float32)
        a, b = platt_fit(z, y)
        assert a == pytest.approx(0.8, abs=0.15)
        assert b == pytest.approx(-1.2, abs=0.2)
        p = platt_apply(z, a, b)
        assert 0 < p.min() and p.max() < 1

    def test_lstm_fold_exact(self):
        import jax

        from realtime_fraud_detection_tpu.models.lstm import (
            init_lstm_params,
            lstm_logits,
        )
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_lstm_head,
        )

        p = init_lstm_params(jax.random.PRNGKey(0), 8, 16, head_hidden=8)
        x = np.random.default_rng(1).normal(0, 1, (5, 3, 8)).astype(
            np.float32)
        z = np.asarray(lstm_logits(p, x))
        z2 = np.asarray(lstm_logits(calibrate_lstm_head(p, 0.7, -0.4), x))
        np.testing.assert_allclose(z2, 0.7 * z - 0.4, rtol=2e-3, atol=2e-3)

    def test_gnn_fold_exact(self):
        import jax

        from realtime_fraud_detection_tpu.models.gnn import (
            gnn_logits,
            init_gnn_params,
        )
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_gnn_head,
        )

        rng = np.random.default_rng(2)
        p = init_gnn_params(jax.random.PRNGKey(0), 4, 8, 8, head_hidden=8)
        args = (rng.normal(0, 1, (5, 8)).astype(np.float32),
                rng.normal(0, 1, (5, 4)).astype(np.float32),
                rng.normal(0, 1, (5, 4)).astype(np.float32),
                rng.normal(0, 1, (5, 3, 4)).astype(np.float32),
                np.ones((5, 3), bool),
                rng.normal(0, 1, (5, 3, 4)).astype(np.float32),
                np.ones((5, 3), bool))
        z = np.asarray(gnn_logits(p, *args))
        z2 = np.asarray(gnn_logits(calibrate_gnn_head(p, 1.3, 0.25), *args))
        np.testing.assert_allclose(z2, 1.3 * z + 0.25, rtol=2e-3, atol=2e-3)

    def test_bert_fold_exact(self):
        import jax

        from realtime_fraud_detection_tpu.models.bert import (
            BertConfig,
            bert_logits,
            init_bert_params,
        )
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_bert_head,
        )

        cfg = BertConfig(hidden_size=32, num_layers=1, num_heads=2,
                         intermediate_size=64)
        p = init_bert_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 1000, (4, 10)).astype(np.int32)
        mask = np.ones((4, 10), bool)
        lg = np.asarray(bert_logits(p, ids, mask, cfg))
        z = lg[:, 1] - lg[:, 0]
        lg2 = np.asarray(bert_logits(
            calibrate_bert_head(p, 0.6, 0.9), ids, mask, cfg))
        z2 = lg2[:, 1] - lg2[:, 0]
        np.testing.assert_allclose(z2, 0.6 * z + 0.9, rtol=2e-3, atol=2e-3)


class TestDeployMeasuredBlend:
    """Config.apply_quality_artifact: the loop from measurement to serving
    — the artifact's selected_blend becomes the config's model table."""

    def test_applies_selected_blend(self, tmp_path):
        import json

        from realtime_fraud_detection_tpu.utils.config import Config

        artifact = {
            "selected_blend": {
                "branches": ["isolation_forest", "lstm_sequential",
                             "xgboost_primary"],
                "weights": {"isolation_forest": 0.05,
                            "lstm_sequential": 0.0625,
                            "xgboost_primary": 0.4},
            }
        }
        path = tmp_path / "q.json"
        path.write_text(json.dumps(artifact))
        cfg = Config()
        applied = cfg.apply_quality_artifact(str(path))
        assert applied == artifact["selected_blend"]["weights"]
        enabled = cfg.get_enabled_models()
        assert set(enabled) == {"isolation_forest", "lstm_sequential",
                                "xgboost_primary"}
        assert cfg.models["bert_text"].enabled is False
        assert cfg.models["graph_neural"].enabled is False
        # device combine sees the renormalized artifact weights
        norm = cfg.normalized_weights()
        assert norm["xgboost_primary"] == pytest.approx(0.4 / 0.5125)

    def test_rejects_non_artifact_and_unknown_models(self, tmp_path):
        import json

        from realtime_fraud_detection_tpu.utils.config import Config

        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"not": "an artifact"}))
        with pytest.raises(ValueError, match="selected_blend"):
            Config().apply_quality_artifact(str(bad))
        unknown = tmp_path / "unknown.json"
        unknown.write_text(json.dumps(
            {"selected_blend": {"weights": {"mystery_model": 1.0}}}))
        with pytest.raises(ValueError, match="mystery_model"):
            Config().apply_quality_artifact(str(unknown))

    def test_committed_artifact_applies_cleanly(self):
        """The ACTUAL committed QUALITY_r05.json must deploy."""
        from pathlib import Path

        from realtime_fraud_detection_tpu.utils.config import Config

        path = Path(__file__).resolve().parent.parent / "QUALITY_r05.json"
        cfg = Config()
        applied = cfg.apply_quality_artifact(str(path))
        assert len(applied) >= 3          # the earned >=3-branch blend
        assert set(cfg.get_enabled_models()) == set(applied)


def test_protocol_checkpoint_deploys_into_matching_scorer(tmp_path):
    """The full deployment loop: quality protocol -> trained+calibrated
    checkpoint + artifact -> a scorer built to the artifact's arch restores
    it and serves the measured blend."""
    import json

    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.models.bert import BertConfig
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = _tiny_cfg()
    ckpt_dir = tmp_path / "blend-ckpt"
    result = run_blend_eval(cfg, checkpoint_dir=str(ckpt_dir))
    assert result["checkpoint"] == {"dir": str(ckpt_dir), "step": 0}
    artifact = tmp_path / "quality.json"
    artifact.write_text(json.dumps(result))

    # serve side: blend from the artifact, scorer built to its recorded arch
    serve_cfg = Config()
    applied = serve_cfg.apply_quality_artifact(str(artifact))
    proto = result["protocol"]
    scorer = FraudScorer(
        serve_cfg,
        scorer_config=ScorerConfig(text_len=proto["text_len"],
                                   tokenizer=proto["tokenizer"]),
        bert_config=BertConfig(**proto["text_model"]))
    ck = CheckpointManager(str(ckpt_dir)).restore_into_scorer(scorer)
    assert ck.step == 0
    gen = TransactionGenerator(num_users=30, num_merchants=12, seed=8)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    results = scorer.score_batch(gen.generate_batch(8))
    assert len(results) == 8
    for r in results:
        # only the measured blend's branches contribute
        assert set(r["model_predictions"]) == set(applied)
        assert 0.0 <= r["fraud_probability"] <= 1.0

"""Checkpoint/resume: params via orbax, host state, offsets, job recovery."""

import json

import jax
import numpy as np
import pytest

from realtime_fraud_detection_tpu.checkpoint import (
    CheckpointManager,
    restore_scorer_host_state,
    snapshot_scorer_host_state,
)
from realtime_fraud_detection_tpu.scoring import init_scoring_models
from realtime_fraud_detection_tpu.scoring.scorer import FraudScorer
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker


@pytest.fixture
def gen():
    return TransactionGenerator(num_users=64, num_merchants=32)


class TestManager:
    def test_params_round_trip(self, tmp_path):
        models = init_scoring_models(jax.random.PRNGKey(1))
        mgr = CheckpointManager(tmp_path / "ckpt")
        mgr.save(5, params=models, metadata={"tag": "v1"})
        template = init_scoring_models(jax.random.PRNGKey(2))
        ck = mgr.restore(params_template=template)
        assert ck.step == 5
        assert ck.metadata == {"tag": "v1"}
        a = jax.tree.leaves(models)
        b = jax.tree.leaves(ck.params)
        assert all(np.array_equal(x, y) for x, y in zip(a, b))

    def test_latest_and_retention(self, tmp_path):
        mgr = CheckpointManager(tmp_path, keep=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, host_state={"s": s})
        assert mgr.steps() == [3, 4]
        assert mgr.latest_step() == 4
        assert mgr.restore(step=3).host_state == {"s": 3}

    def test_offsets_in_manifest(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, offsets={"payment-transactions:0": 42})
        ck = mgr.restore()
        assert ck.offsets == {"payment-transactions:0": 42}

    def test_torn_save_ignored(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, host_state={"ok": True})
        torn = mgr._step_dir(2)
        torn.mkdir()
        (torn / "host_state.pkl").write_bytes(b"partial")  # no manifest
        assert mgr.latest_step() == 1
        mgr.save(2, host_state={"ok": 2})                  # overwrites torn
        assert mgr.restore().host_state == {"ok": 2}

    def test_restore_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            CheckpointManager(tmp_path).restore()

    def test_params_restore_requires_template(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, params={"w": np.ones((2, 2), np.float32)})
        with pytest.raises(ValueError):
            mgr.restore()

    def test_manifest_is_json(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        d = mgr.save(7, host_state={"x": 1}, metadata={"m": "y"})
        manifest = json.loads((d / "manifest.json").read_text())
        assert manifest["step"] == 7 and manifest["has_host_state"]


class TestScorerHostState:
    def test_snapshot_restore_preserves_dedupe_and_history(self, gen, tmp_path):
        scorer = FraudScorer()
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        records = gen.generate_batch(32)
        scorer.score_batch(records, now=1000.0)
        snap = snapshot_scorer_host_state(scorer)
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, host_state=snap)

        restored = FraudScorer()           # fresh process analog
        restore_scorer_host_state(restored, mgr.restore().host_state)
        # the transaction cache survives: replayed txns are visible
        txn_id = str(records[0]["transaction_id"])
        assert restored.txn_cache.get_transaction(txn_id, now=1000.0) is not None
        # per-user history survives: same users have non-zero history length
        uids = [str(r["user_id"]) for r in records]
        _, hist_len = restored.history.gather(uids)
        assert (hist_len > 0).all()
        # velocity windows survive
        assert restored.velocity.get(uids[0], "5min", now=1000.0)["count"] >= 1


class TestJobRecovery:
    def test_crash_resume_no_double_scoring(self, gen):
        broker = InMemoryBroker()
        for rec in gen.generate_batch(96):
            broker.produce(T.TRANSACTIONS, rec, key=str(rec["user_id"]))

        scorer1 = FraudScorer()
        scorer1.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job1 = StreamJob(broker, scorer1, JobConfig(max_batch=32))
        # process two microbatches (commits after each), then "crash"
        for _ in range(2):
            batch = job1.assembler.next_batch(block=False) or job1.assembler.flush()
            job1.process_batch(batch, now=2000.0)
        scored_before = job1.counters["scored"]
        assert scored_before > 0

        # new process: same broker (Kafka survives crashes), fresh job;
        # committed offsets are the source of truth
        scorer2 = FraudScorer()
        scorer2.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job2 = StreamJob(broker, scorer2, JobConfig(max_batch=32))
        job2.run_until_drained(now=2000.0)
        total = scored_before + job2.counters["scored"]
        assert total == 96                       # nothing lost, nothing doubled
        assert broker.lag("fraud-detection-job", T.TRANSACTIONS) == 0
        n_preds = sum(broker.end_offsets(T.PREDICTIONS))
        assert n_preds == 96

    def test_uncommitted_tail_replay_deduped_via_host_state(self, gen, tmp_path):
        """Crash AFTER scoring but BEFORE commit: the replayed tail must be
        deduplicated by the restored transaction cache (effectively-once)."""
        broker = InMemoryBroker()
        records = gen.generate_batch(32)
        for rec in records:
            broker.produce(T.TRANSACTIONS, rec, key=str(rec["user_id"]))

        scorer1 = FraudScorer()
        scorer1.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job1 = StreamJob(broker, scorer1, JobConfig(max_batch=64))
        batch = job1.assembler.next_batch(block=False) or job1.assembler.flush()
        # score WITHOUT commit: simulate crash between fan-out and commit
        fresh = [r for r in batch]
        scorer1.score_batch([r.value for r in fresh], now=3000.0)
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, host_state=snapshot_scorer_host_state(scorer1))

        scorer2 = FraudScorer()
        scorer2.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        restore_scorer_host_state(scorer2, mgr.restore().host_state)
        job2 = StreamJob(broker, scorer2, JobConfig(max_batch=64))
        job2.run_until_drained(now=3000.0)
        # every replayed txn was already in the restored cache
        assert job2.counters["duplicates_skipped"] == 32
        assert job2.counters["scored"] == 0


def test_checkpoint_offsets_from_group_managed_consumer(tmp_path):
    """The checkpoint manifest must capture a group-managed consumer's
    positions in the same 'topic:partition' form as the static consumer,
    so resume works regardless of the assignment mode."""
    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.stream.kafka import KafkaBroker
    from realtime_fraud_detection_tpu.stream.kafka_fake import FakeKafkaServer
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        b.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(30)],
                        key_fn=lambda v: str(v["n"]))
        c = KafkaGroupConsumer(b, [T.TRANSACTIONS], "g-ckpt",
                               session_timeout_ms=5000,
                               heartbeat_interval_s=0.5)
        recs = c.poll(30)
        assert recs
        c.commit()
        positions = c.positions()
        assert positions and all(":" in k for k in positions)
        mgr = CheckpointManager(tmp_path)
        mgr.save(1, offsets=positions)
        ck = mgr.restore()
        assert ck.offsets == positions
        # a fresh member of the group resumes exactly from those offsets
        c.close()
        c2 = KafkaGroupConsumer(b, [T.TRANSACTIONS], "g-ckpt",
                                session_timeout_ms=5000,
                                heartbeat_interval_s=0.5)
        assert c2.positions() == ck.offsets
        assert c2.poll(100) == []
        c2.close()
    finally:
        b.close()
        server.stop()


def test_restore_reattaches_feature_importances(tmp_path):
    """train -> restore_into_scorer: served explanations keep the
    trainer's gain importances (set_models alone clears them)."""
    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.cli import main
    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    assert main(["train", "--rows", "1500", "--trees", "8",
                 "--users", "200", "--merchants", "40",
                 "--out", str(tmp_path / "ck")]) == 0
    scorer = FraudScorer(seed=1)
    CheckpointManager(str(tmp_path / "ck")).restore_into_scorer(scorer)
    gen = TransactionGenerator(num_users=200, num_merchants=40, seed=9)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    res = scorer.score_batch(gen.generate_batch(4))
    top = res[0]["explanation"].get("top_feature_importances")
    assert top and len(top) <= 10
    assert all(v > 0 for v in top.values())

"""Chaos plane: fault scheduling (chaos/faults.py), the deterministic
backoff seam (utils/backoff.py), the coordinated fraud ring
(sim/fraud_patterns.FraudRing), the chaos_* metrics mirror, config
validation, and the `rtfd chaos-drill --fast` tier-1 smoke."""

import json

import numpy as np
import pytest

from realtime_fraud_detection_tpu.chaos import (
    ChaosPlan,
    ConsumerMemberKill,
    DeviceReplicaDeath,
    FaultWindow,
    LabelStall,
    SlowDevice,
)
from realtime_fraud_detection_tpu.utils.backoff import DeterministicBackoff


# ---------------------------------------------------------------------------
# fault windows + plan scheduling
# ---------------------------------------------------------------------------

class TestFaultWindow:
    def test_validate_rejects_empty_names_and_bad_interval(self):
        with pytest.raises(ValueError, match="name and a kind"):
            FaultWindow("", "broker", 0.0, 1.0).validate()
        with pytest.raises(ValueError, match="t_end > t_start"):
            FaultWindow("w", "broker", 2.0, 2.0).validate()

    def test_plan_rejects_duplicate_names(self):
        with pytest.raises(ValueError, match="duplicate"):
            ChaosPlan([FaultWindow("a", "k", 0.0, 1.0),
                       FaultWindow("a", "k", 2.0, 3.0)])

    def test_bind_unknown_window_raises(self):
        plan = ChaosPlan([FaultWindow("a", "k", 0.0, 1.0)])
        with pytest.raises(ValueError, match="no fault window"):
            plan.bind("nope", LabelStall())


class _RecInjector:
    def __init__(self):
        self.calls = []

    def begin(self, now):
        self.calls.append(("begin", now))

    def end(self, now):
        self.calls.append(("end", now))


class TestChaosPlan:
    def test_transitions_fire_once_in_order(self):
        plan = ChaosPlan([FaultWindow("a", "k", 1.0, 2.0),
                          FaultWindow("b", "k", 1.5, 3.0)])
        inj = _RecInjector()
        plan.bind("a", inj)
        assert plan.poll(0.5) == []
        trans = plan.poll(1.6)
        assert [(e, w.name) for e, w in trans] == [("begin", "a"),
                                                  ("begin", "b")]
        assert inj.calls == [("begin", 1.6)]
        # re-polling the same instant fires nothing twice
        assert plan.poll(1.6) == []
        trans = plan.poll(2.5)
        assert [(e, w.name) for e, w in trans] == [("end", "a")]
        assert inj.calls[-1] == ("end", 2.5)
        assert plan.active(2.5) == ["b"]
        assert plan.is_active("b", 2.5) and not plan.is_active("a", 2.5)

    def test_fully_past_window_fires_begin_then_end(self):
        """A clock leap over a whole window must still run the injector's
        cleanup — begin and end both fire, in order."""
        plan = ChaosPlan([FaultWindow("a", "k", 1.0, 2.0)])
        inj = _RecInjector()
        plan.bind("a", inj)
        trans = plan.poll(10.0)
        assert [(e, w.name) for e, w in trans] == [("begin", "a"),
                                                  ("end", "a")]
        assert [c[0] for c in inj.calls] == ["begin", "end"]

    def test_note_recovered_first_observation_wins(self):
        plan = ChaosPlan([FaultWindow("a", "k", 1.0, 2.0)])
        plan.poll(5.0)
        plan.note_recovered("a", 3.5)
        plan.note_recovered("a", 9.0)          # idempotent: first wins
        plan.note_recovered("missing", 9.0)    # unknown window: no-op
        assert plan.recovery_s == {"a": 1.5}
        snap = plan.snapshot(5.0)
        assert snap["recovery_s"] == {"a": 1.5}
        w = snap["windows"][0]
        assert w["begun"] and w["ended"] and not w["active"]
        assert [e["event"] for e in snap["events"]] == ["begin", "end"]


class _StubPool:
    def __init__(self):
        self.calls = []

    def inject_fault(self, idx, n):
        self.calls.append(("fault", idx, n))

    def inject_slow(self, idx, delay_s, n):
        self.calls.append(("slow", idx, delay_s, n))

    def revive(self, idx):
        self.calls.append(("revive", idx))


class TestInjectors:
    def test_device_replica_death_arms_and_revives(self):
        pool = _StubPool()
        inj = DeviceReplicaDeath(pool, 2, n_faults=3)
        inj.begin(1.0)
        inj.end(2.0)
        assert pool.calls == [("fault", 2, 3), ("revive", 2)]

    def test_slow_device_is_one_shot(self):
        pool = _StubPool()
        inj = SlowDevice(pool, 1, 0.04, n=2)
        inj.begin(1.0)
        inj.end(2.0)                            # no revive: never unhealthy
        assert pool.calls == [("slow", 1, 0.04, 2)]

    def test_label_stall_gates(self):
        stall = LabelStall()
        assert not stall.active
        stall.begin(1.0)
        assert stall.active and stall.stalls == 1
        stall.end(2.0)
        assert not stall.active

    def test_consumer_member_kill_fires_once(self):
        class _Srv:
            def __init__(self):
                self.killed = []

            def kill_member(self, gid, mid):
                self.killed.append((gid, mid))

        srv = _Srv()
        inj = ConsumerMemberKill(srv, "g", "m-1")
        inj.begin(1.0)
        inj.end(2.0)                            # no resurrection
        assert srv.killed == [("g", "m-1")] and inj.killed == 1


# ---------------------------------------------------------------------------
# deterministic backoff (the satellite replacing the fixed sleeps)
# ---------------------------------------------------------------------------

class TestDeterministicBackoff:
    def test_validation(self):
        with pytest.raises(ValueError, match="base_s > 0"):
            DeterministicBackoff(base_s=0.0)
        with pytest.raises(ValueError, match="base_s > 0"):
            DeterministicBackoff(base_s=0.2, max_s=0.1)
        with pytest.raises(ValueError, match="jitter_frac"):
            DeterministicBackoff(jitter_frac=1.5)

    def test_delay_is_pure_bounded_exponential(self):
        b1 = DeterministicBackoff(base_s=0.05, mult=2.0, max_s=0.4, seed=9)
        b2 = DeterministicBackoff(base_s=0.05, mult=2.0, max_s=0.4, seed=9)
        sched = [b1.delay(k) for k in range(8)]
        # pure: a fresh instance with the same seed replays it exactly
        assert sched == [b2.delay(k) for k in range(8)]
        # bounded: never exceeds max_s; jitter only ever SHRINKS the raw
        # exponential, so the schedule stays within (0, max_s]
        assert all(0.0 < d <= 0.4 for d in sched)
        raw = [min(0.4, 0.05 * 2.0 ** k) for k in range(8)]
        assert all(d <= r for d, r in zip(sched, raw))

    def test_seeds_decorrelate_schedules(self):
        a = DeterministicBackoff(seed=1)
        b = DeterministicBackoff(seed=2)
        assert [a.delay(k) for k in range(4)] != [b.delay(k)
                                                 for k in range(4)]

    def test_no_jitter_is_exact_exponential(self):
        b = DeterministicBackoff(base_s=0.1, mult=2.0, max_s=0.5,
                                 jitter_frac=0.0)
        assert [b.delay(k) for k in range(4)] == [0.1, 0.2, 0.4, 0.5]

    def test_sleep_seam_records_and_applies(self):
        applied = []
        b = DeterministicBackoff(base_s=0.05, max_s=0.2, seed=3,
                                 sleep=applied.append)
        d0 = b.sleep(0)
        d1 = b.sleep(1)
        assert applied == [d0, d1] == list(b.slept)
        assert d0 == b.delay(0) and d1 == b.delay(1)
        # the ledger is bounded (these live in long-lived transports)
        assert b.slept.maxlen is not None


# ---------------------------------------------------------------------------
# coordinated fraud ring
# ---------------------------------------------------------------------------

class TestFraudRing:
    def test_config_validation(self):
        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRingConfig,
        )

        with pytest.raises(ValueError, match="rate"):
            FraudRingConfig(rate=1.5).validate()
        with pytest.raises(ValueError, match=">= 1"):
            FraudRingConfig(n_devices=0).validate()

    def test_ring_is_deterministic_and_shares_entities(self):
        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRingConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        cfg = FraudRingConfig(n_members=8, n_merchants=3, n_devices=2,
                              n_ips=2, rate=1.0)
        outs = []
        for _ in range(2):
            gen = TransactionGenerator(num_users=200, num_merchants=50,
                                       seed=17)
            ring = gen.inject_fraud_ring(cfg)
            txns = gen.generate_batch(64)
            outs.append((list(ring.member_ids), ring.device_ids, ring.ips,
                         [t["transaction_id"] for t in txns],
                         [t.get("device_id") for t in txns]))
            # rate=1.0: every transaction is ring traffic through the
            # SHARED entity sets — the structure the graph branch consumes
            assert ring.applied == 64
            assert {t["user_id"] for t in txns} <= {str(u)
                                                    for u in ring.member_ids}
            assert {t["device_id"] for t in txns} <= set(ring.device_ids)
            assert {t["ip_address"] for t in txns} <= set(ring.ips)
            assert {t["merchant_id"] for t in txns} \
                <= {str(m) for m in ring.merchant_ids}
            assert all(t["is_fraud"] and t["fraud_type"] == "fraud_ring"
                       for t in txns)
            # camouflage: the incumbent's leaky prior stays benign
            assert all(t["fraud_score"] < 0.3 for t in txns)
        # identical seed => identical membership AND identical traffic
        assert outs[0] == outs[1]

    def test_clear_ring_stops_application(self):
        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRingConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        gen = TransactionGenerator(num_users=100, num_merchants=30, seed=5)
        ring = gen.inject_fraud_ring(FraudRingConfig(rate=1.0))
        gen.generate_batch(8)
        assert ring.applied == 8
        gen.clear_fraud_ring()
        gen.generate_batch(8)
        assert ring.applied == 8


# ---------------------------------------------------------------------------
# config + metrics mirror
# ---------------------------------------------------------------------------

class TestChaosSettings:
    def test_validation(self):
        from realtime_fraud_detection_tpu.utils.config import ChaosSettings

        ChaosSettings().validate()
        with pytest.raises(ValueError, match="broker_outage_s"):
            ChaosSettings(broker_outage_s=0.0).validate()
        with pytest.raises(ValueError, match="multipliers"):
            ChaosSettings(flash_crowd_mult=0.5).validate()
        with pytest.raises(ValueError, match="ring_rate"):
            ChaosSettings(ring_rate=0.0).validate()
        with pytest.raises(ValueError, match="entity kind"):
            ChaosSettings(ring_devices=0).validate()
        with pytest.raises(ValueError, match="replica_faults"):
            ChaosSettings(replica_faults=0).validate()

    def test_config_carries_chaos_block(self):
        from realtime_fraud_detection_tpu.utils.config import Config

        cfg = Config()
        assert cfg.chaos.enabled is False
        cfg.validate()

    def test_settings_overlay_reshapes_drill_config(self, tmp_path):
        """chaos.* is LIVE config: the overlay maps every timeline knob
        onto the drill config, and the CLI path loads it via --config."""
        import json

        from realtime_fraud_detection_tpu.chaos.drill import (
            ChaosDrillConfig,
            apply_chaos_settings,
        )
        from realtime_fraud_detection_tpu.utils.config import (
            ChaosSettings,
            Config,
        )

        s = ChaosSettings(seed=99, broker_outage_s=2.5, label_stall_s=1.0,
                          flash_crowd_mult=3.0, flash_burst_mult=1.2,
                          ring_rate=0.2, ring_members=10, ring_merchants=2,
                          ring_devices=3, ring_ips=5, replica_faults=2,
                          slow_device_ms=15.0)
        cfg = apply_chaos_settings(ChaosDrillConfig.fast(), s)
        assert (cfg.seed, cfg.outage_s, cfg.label_stall_s) == (99, 2.5, 1.0)
        assert (cfg.flash_mult, cfg.flash_burst_mult) == (3.0, 1.2)
        assert (cfg.ring_rate, cfg.ring_members, cfg.ring_merchants,
                cfg.ring_devices, cfg.ring_ips) == (0.2, 10, 2, 3, 5)
        assert (cfg.replica_faults, cfg.slow_device_ms) == (2, 15.0)
        # fast-config fields not owned by ChaosSettings are untouched
        assert cfg.n_devices == ChaosDrillConfig.fast().n_devices
        # the file path the CLI uses round-trips
        p = tmp_path / "c.json"
        p.write_text(json.dumps({"chaos": {"seed": 99, "ring_rate": 0.2}}))
        loaded = Config.from_file(str(p)).chaos
        assert loaded.seed == 99 and loaded.ring_rate == 0.2


class TestSyncChaos:
    def test_counter_delta_mirror(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        m = MetricsCollector()
        plan = ChaosPlan([FaultWindow("broker_outage", "broker", 1.0, 2.0)])
        plan.poll(1.5)
        m.sync_chaos(plan.snapshot(1.5))
        m.sync_chaos(plan.snapshot(1.5))        # re-sync: NOT double-counted
        assert m.chaos_fault_windows.value(fault="broker_outage") == 1.0
        assert m.chaos_fault_active.value(fault="broker_outage") == 1.0
        plan.poll(2.5)
        plan.note_recovered("broker_outage", 2.75)
        m.sync_chaos(plan.snapshot(2.5))
        assert m.chaos_fault_windows.value(fault="broker_outage") == 1.0
        assert m.chaos_fault_active.value(fault="broker_outage") == 0.0
        assert m.chaos_recovery_seconds.value(fault="broker_outage") == 0.75
        # the series render on the standard exposition
        text = m.registry.render()
        assert "chaos_fault_windows_total" in text
        assert "chaos_recovery_seconds" in text


# ---------------------------------------------------------------------------
# drill plumbing + tier-1 smoke
# ---------------------------------------------------------------------------

class TestCompactSummary:
    def test_under_2kb_and_parseable(self):
        from realtime_fraud_detection_tpu.chaos.drill import (
            compact_chaos_summary,
        )

        summary = {"metric": "chaos_drill", "passed": True,
                   "checks": {f"check_{i}": True for i in range(20)},
                   "phase_auc": {"healthy": 0.95, "recovery": 0.97},
                   "digest": "a" * 64}
        compact = compact_chaos_summary(summary)
        line = json.dumps(compact, separators=(",", ":"))
        assert len(line.encode()) < 2048
        assert compact["passed"] is True

    def test_oversized_summary_still_fits(self):
        from realtime_fraud_detection_tpu.chaos.drill import (
            compact_chaos_summary,
        )

        summary = {"metric": "chaos_drill", "passed": False,
                   "checks": {f"very_long_check_name_{i}" * 4: False
                              for i in range(64)}}
        compact = compact_chaos_summary(summary)
        assert len(json.dumps(compact,
                              separators=(",", ":")).encode()) < 2048


def test_chaos_drill_fast_smoke(monkeypatch, capsys):
    """Tier-1 acceptance: `rtfd chaos-drill --fast` runs un-slow-marked on
    every pass — through the CLI entry (in-process child mode; the session
    already provides the multi-device host platform). Pins the combined-
    recovery contract: zero high-value sheds, effectively-once across the
    broker outage, ladder + burn recovery, pool retry absorbed, ring AUC
    retrained back, and a bit-identical second run."""
    from realtime_fraud_detection_tpu import cli

    monkeypatch.setenv("_RTFD_CHAOS_DRILL_CHILD", "1")
    rc = cli.main(["chaos-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["zero_high_value_sheds"]
    assert checks["effectively_once"] and checks["offsets_gap_free"]
    assert checks["ladder_recovered"] and checks["burn_recovered"]
    assert checks["pool_retry_absorbed"] and checks["fifo_batch_integrity"]
    assert checks["ring_promoted_via_gate"] and checks["ring_auc_recovered"]
    assert checks["replay_bit_identical"]
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["digest"] and full["high_value_sheds"] == 0
    assert full["phase_auc"]["recovery"] >= full["phase_auc"]["healthy"] - 0.01

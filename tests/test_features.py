"""Golden-vector tests for the 64-feature contract and rule scoring.

Expected values are hand-derived from the cited reference formulas
(FeatureExtractor.java, TransactionProcessor.java) — not from the
implementation under test.
"""

import math

import numpy as np
import pytest

from realtime_fraud_detection_tpu.features import (
    FEATURE_NAMES,
    NUM_FEATURES,
    DECISIONS,
    encode_transactions,
    extract_features,
    feature_index,
    make_decision,
    rule_score,
    risk_level_code,
)
from realtime_fraud_detection_tpu.features.serving import ServingFeatureProcessor

USER = {
    "user_id": "user_a",
    "risk_score": 0.2,
    "account_age_days": 400,
    "kyc_status": "verified",
    "avg_transaction_amount": 50.0,
    "transaction_frequency": 3,
    "device_fingerprints": ["dev1", "dev2"],
    "behavioral_patterns": {
        "preferred_time_start": 8,
        "preferred_time_end": 20,
        "weekend_activity": 0.6,
        "international_transactions": 0.05,
        "online_preference": 0.9,
    },
}
MERCHANT = {
    "merchant_id": "merchant_a",
    "name": "Acme Groceries",
    "category": "grocery",
    "risk_level": "low",
    "avg_transaction_amount": 30.0,
    "fraud_rate": 0.005,
    "is_blacklisted": False,
    "operating_hours": {"start_hour": "8", "end_hour": "22"},
}
TXN = {
    "transaction_id": "t1",
    "user_id": "user_a",
    "merchant_id": "merchant_a",
    "amount": 120.0,
    "currency": "USD",
    "transaction_type": "purchase",
    "payment_method": "credit_card",
    "card_type": "visa",
    "hour_of_day": 14,
    "day_of_week": 3,
    "day_of_month": 15,
    "is_weekend": False,
    "ip_address": "8.8.8.8",
    "device_fingerprint": "dev1",
    "user_agent": "Mozilla/5.0 (X11; Linux x86_64) AppleWebKit",
    "geolocation": {"lat": 40.7, "lon": -74.0},
    "merchant_location": {"lat": 40.8, "lon": -73.9},
    "fraud_score": 0.1,
}


def fv(batch_or_rows, name):
    return np.asarray(batch_or_rows)[:, feature_index(name)]


class TestFeatureContract:
    def test_sixty_four_features(self):
        assert NUM_FEATURES == 64
        assert len(set(FEATURE_NAMES)) == 64

    def test_known_transaction_golden_values(self):
        batch = encode_transactions([TXN], {"user_a": USER}, {"merchant_a": MERCHANT})
        feats = np.asarray(extract_features(batch))
        assert feats.shape == (1, 64)
        row = feats[0]
        get = lambda n: row[feature_index(n)]

        # amount category
        assert get("amount") == pytest.approx(120.0)
        assert get("amount_log") == pytest.approx(math.log(121.0), rel=1e-6)
        assert get("amount_sqrt") == pytest.approx(math.sqrt(120.0), rel=1e-6)
        assert get("is_round_amount") == 1.0  # 120.00 is integral
        assert get("is_round_10") == 1.0
        assert get("is_round_100") == 0.0
        assert get("amount_to_user_avg_ratio") == pytest.approx(120.0 / 50.0)
        assert get("amount_deviation_zscore") == pytest.approx((120 - 50) / 50)
        assert get("is_large_for_user") == 0.0  # ratio 2.4 < 3
        assert get("amount_to_merchant_avg_ratio") == pytest.approx(4.0)
        assert get("is_large_for_merchant") == 1.0  # 120 > 60
        assert get("amount_category") == 2.0  # medium [100, 1000)

        # temporal
        assert get("hour_of_day") == 14.0
        assert get("time_period") == 1.0  # afternoon
        assert get("is_business_hours") == 1.0
        assert get("is_night_time") == 0.0
        assert get("in_user_preferred_time") == 1.0  # 8 <= 14 <= 20

        # geographic: haversine of (40.7,-74.0)-(40.8,-73.9)
        lat1, lon1, lat2, lon2 = map(math.radians, (40.7, -74.0, 40.8, -73.9))
        a = (math.sin((lat2 - lat1) / 2) ** 2
             + math.cos(lat1) * math.cos(lat2) * math.sin((lon2 - lon1) / 2) ** 2)
        expected_km = 6371 * 2 * math.atan2(math.sqrt(a), math.sqrt(1 - a))
        assert get("distance_to_merchant_km") == pytest.approx(expected_km, rel=1e-4)
        assert get("is_high_risk_country") == 0.0
        assert get("user_intl_preference") == pytest.approx(0.05)
        assert get("unexpected_intl_transaction") == 1.0  # 0.05 < 0.1

        # user
        assert get("is_new_account") == 0.0
        assert get("user_risk_score") == pytest.approx(0.2)
        assert get("is_kyc_verified") == 1.0
        assert get("kyc_status") == 0.0  # verified

        # merchant
        assert get("merchant_risk_level") == 0.0  # low
        assert get("is_high_risk_category") == 0.0
        assert get("within_merchant_hours") == 1.0
        assert get("merchant_risk_multiplier") == pytest.approx(1.0)
        assert get("suspicious_merchant_name") == 0.0

        # device / network
        assert get("is_known_device") == 1.0
        assert get("is_new_device") == 0.0
        assert get("is_private_ip") == 0.0
        assert get("ip_risk_score") == pytest.approx(0.3)
        assert get("suspicious_user_agent") == 0.0

        # contextual
        assert get("is_high_risk_payment") == 0.0
        assert get("is_refund") == 0.0

    def test_unknown_profiles_defaults(self):
        batch = encode_transactions([TXN])  # no profile stores
        row = np.asarray(extract_features(batch))[0]
        get = lambda n: row[feature_index(n)]
        # FeatureExtractor.java:244-251 unknown-user defaults
        assert get("account_age_days") == 0.0
        assert get("is_new_account") == 1.0
        assert get("is_very_new_account") == 1.0
        assert get("user_risk_score") == pytest.approx(0.8)
        assert get("is_kyc_verified") == 0.0
        # :288-295 unknown-merchant defaults
        assert get("merchant_fraud_rate") == pytest.approx(0.1)
        assert get("is_blacklisted_merchant") == 0.0
        assert get("is_high_risk_category") == 0.0
        assert get("merchant_risk_multiplier") == pytest.approx(2.0)
        assert get("within_merchant_hours") == 1.0  # no info is not "outside"

    def test_suspicious_merchant_regex(self):
        merch = dict(MERCHANT, name="QuickBitcoin Exchange")
        batch = encode_transactions([TXN], {"user_a": USER}, {"merchant_a": merch})
        assert fv(extract_features(batch), "suspicious_merchant_name")[0] == 1.0

    def test_private_ip_and_bad_agent(self):
        txn = dict(TXN, ip_address="192.168.1.5", user_agent="curl-bot")
        batch = encode_transactions([txn], {"user_a": USER}, {"merchant_a": MERCHANT})
        row = np.asarray(extract_features(batch))[0]
        assert row[feature_index("is_private_ip")] == 1.0
        assert row[feature_index("ip_risk_score")] == pytest.approx(0.1)
        assert row[feature_index("suspicious_user_agent")] == 1.0

    def test_velocity_flags(self):
        vel = {"user_a": {"5min": {"count": 6, "amount": 300.0},
                          "1hour": {"count": 25, "amount": 1200.0},
                          "24hour": {"count": 40, "amount": 2000.0}}}
        batch = encode_transactions([TXN], {"user_a": USER}, {"merchant_a": MERCHANT}, vel)
        row = np.asarray(extract_features(batch))[0]
        assert row[feature_index("velocity_5min_count")] == 6.0
        assert row[feature_index("high_velocity_5min")] == 1.0  # > 5
        assert row[feature_index("high_velocity_1hour")] == 1.0  # > 20
        assert row[feature_index("velocity_24hour_amount")] == 2000.0

    def test_batch_shapes_and_vectorization(self):
        txns = [dict(TXN, amount=float(a)) for a in (5, 50, 500, 5000, 50000)]
        batch = encode_transactions(txns, {"user_a": USER}, {"merchant_a": MERCHANT})
        cats = fv(extract_features(batch), "amount_category")
        np.testing.assert_array_equal(cats, [0, 1, 2, 3, 4])


class TestRuleScore:
    def test_benign_transaction_score(self):
        batch = encode_transactions([TXN], {"user_a": USER}, {"merchant_a": MERCHANT})
        score = float(np.asarray(rule_score(batch))[0])
        # hand-derived: 0.5*0.1 (prior) + 0.2*0.2 (user risk) + 0 (old, verified)
        # + 0 merchant (low risk, rate .005, not blacklisted) + 0 flags
        assert score == pytest.approx(0.05 + 0.04, abs=1e-6)

    def test_risky_transaction_score(self):
        user = dict(USER, risk_score=0.9, account_age_days=5, kyc_status="pending")
        merch = dict(MERCHANT, risk_level="high", fraud_rate=0.15,
                     category="gambling", is_blacklisted=False)
        txn = dict(TXN, fraud_score=0.8, amount=300.0, device_fingerprint="unknown-dev",
                   hour_of_day=3)
        batch = encode_transactions([txn], {"user_a": user}, {"merchant_a": merch})
        score = float(np.asarray(rule_score(batch))[0])
        # 0.5*0.8 + (0.9*0.2 + 0.1 + 0.15) + (0.2 + 0.15*2 + 0.15 gambling)
        # + 0.1 new device + 0.05 unusual hour + 0.1 outside hours (3 < 8)
        expected = 0.4 + 0.43 + 0.65 + 0.25
        assert score == pytest.approx(min(1.0, expected), abs=1e-6)

    def test_unknown_profiles_minimal_defaults(self):
        txn = dict(TXN, fraud_score=0.0, hour_of_day=14)
        batch = encode_transactions([txn])
        score = float(np.asarray(rule_score(batch))[0])
        # minimal user 0.35 + minimal merchant 0.1 (TransactionProcessor.java:489-508)
        assert score == pytest.approx(0.45, abs=1e-6)

    def test_decision_ladder(self):
        scores = np.array([0.2, 0.55, 0.75, 0.95], np.float32)
        blk = np.zeros(4, bool)
        dec, risk = make_decision(scores, blk)
        assert [DECISIONS[d] for d in np.asarray(dec)] == [
            "APPROVE", "APPROVE", "REVIEW", "DECLINE"]
        assert list(np.asarray(risk)) == [1, 2, 3, 4]  # LOW MEDIUM HIGH CRITICAL

    def test_blacklist_override(self):
        dec, risk = make_decision(np.array([0.1], np.float32), np.array([True]))
        assert DECISIONS[int(np.asarray(dec)[0])] == "DECLINE"
        assert int(np.asarray(risk)[0]) == 4

    def test_ensemble_risk_ladder(self):
        probs = np.array([0.1, 0.4, 0.7, 0.85, 0.99], np.float32)
        codes = np.asarray(risk_level_code(probs))
        np.testing.assert_array_equal(codes, [0, 1, 2, 3, 4])


class TestServingProcessor:
    def test_required_feature_missing_raises(self):
        with pytest.raises(ValueError, match="amount"):
            ServingFeatureProcessor().process_features({})

    def test_bounds_and_defaults(self):
        p = ServingFeatureProcessor().process_features(
            {"amount": 100.0, "hour_of_day": 99, "merchant_fraud_rate": -5}
        )
        assert p["hour_of_day"] == 23  # clamped to max
        assert p["merchant_fraud_rate"] == 0.0  # clamped to min
        assert p["country_risk_score"] == 0.5  # default
        assert p["amount_log"] == pytest.approx(math.log1p(100.0))
        assert p["is_business_hours"] in (0.0, 1.0)

    def test_nan_replaced(self):
        p = ServingFeatureProcessor().process_features(
            {"amount": 10.0, "amount_zscore": float("nan")}
        )
        assert p["amount_zscore"] == 0.0

    def test_flink_features_dict_merged(self):
        p = ServingFeatureProcessor().process_features(
            {"amount": 10.0, "features": {"velocity_score": 0.9}}
        )
        assert p["velocity_score"] == pytest.approx(0.9)

    def test_model_matrix_clipped_64(self):
        proc = ServingFeatureProcessor()
        rows = proc.process_batch([{"amount": 1e9}, {"amount": 5.0}])
        mat = proc.to_model_matrix(rows)
        assert mat.shape[1] >= 64
        assert mat.max() <= 10.0 and mat.min() >= -10.0


class TestReviewRegressions:
    def test_no_device_fingerprint_no_penalty(self):
        # TransactionProcessor.java:252-262: rule fires only when the txn
        # carries a fingerprint that is unknown
        txn_nofp = dict(TXN)
        del txn_nofp["device_fingerprint"]
        txn_badfp = dict(TXN, device_fingerprint="stranger-device")
        batch = encode_transactions(
            [txn_nofp, txn_badfp, TXN], {"user_a": USER}, {"merchant_a": MERCHANT}
        )
        scores = np.asarray(rule_score(batch))
        assert scores[1] == pytest.approx(scores[0] + 0.1, abs=1e-6)  # penalty
        assert scores[2] == pytest.approx(scores[0], abs=1e-6)  # known device

    def test_negative_amount_features_finite(self):
        txn = dict(TXN, amount=-20.0, transaction_type="refund")
        batch = encode_transactions([txn], {"user_a": USER}, {"merchant_a": MERCHANT})
        feats = np.asarray(extract_features(batch))
        assert np.isfinite(feats).all()

    def test_fast_path_day_of_month_matches_clock(self):
        from realtime_fraud_detection_tpu.sim import TransactionGenerator

        gen = TransactionGenerator(num_users=10, num_merchants=5, seed=0)
        day0 = gen.clock.day
        batch, _ = gen.generate_encoded(4)
        assert int(np.asarray(batch.day_of_month)[0]) == day0


class TestEnrichment:
    """FeatureEnrichmentProcessor semantics (java :84-150, 122-344)."""

    @staticmethod
    def _features(**overrides):
        from realtime_fraud_detection_tpu.features.extract import (
            NUM_FEATURES,
            feature_index,
        )

        f = np.zeros((1, NUM_FEATURES), np.float32)
        # defaults that zero out the "absence" penalties
        f[0, feature_index("in_user_preferred_time")] = 1.0
        f[0, feature_index("is_kyc_verified")] = 1.0
        f[0, feature_index("within_merchant_hours")] = 1.0
        f[0, feature_index("amount_category")] = 2.0
        for name, v in overrides.items():
            f[0, feature_index(name)] = v
        return f

    def test_zero_risk_features_score_zero(self):
        from realtime_fraud_detection_tpu.features.rules import enrichment_score

        assert float(np.asarray(enrichment_score(self._features()))[0]) == 0.0

    def test_category_weights(self):
        from realtime_fraud_detection_tpu.features.rules import enrichment_score

        # blacklisted merchant alone: 0.8 * 0.2 category weight
        s = enrichment_score(self._features(is_blacklisted_merchant=1.0))
        assert float(np.asarray(s)[0]) == pytest.approx(0.8 * 0.2)
        # high velocity 5min alone: 0.6 * 0.15
        s = enrichment_score(self._features(high_velocity_5min=1.0))
        assert float(np.asarray(s)[0]) == pytest.approx(0.6 * 0.15)
        # very-new account + unverified: (0.4 + 0.3) * 0.25
        s = enrichment_score(self._features(is_very_new_account=1.0,
                                            is_kyc_verified=0.0))
        assert float(np.asarray(s)[0]) == pytest.approx(0.7 * 0.25)

    def test_blend_60_40_and_relevel(self):
        from realtime_fraud_detection_tpu.features.rules import (
            DECISIONS,
            RISK_LEVEL_NAMES,
            blend_enrichment,
        )

        f = self._features(is_blacklisted_merchant=1.0, high_velocity_5min=1.0,
                           is_very_new_account=1.0, is_kyc_verified=0.0,
                           user_risk_score=1.0, merchant_fraud_rate=0.3,
                           is_high_risk_category=1.0, ip_risk_score=1.0,
                           is_new_device=1.0, suspicious_user_agent=1.0,
                           is_night_time=1.0, is_large_for_user=1.0)
        prior = np.asarray([0.9], np.float32)
        blended, dec, risk = blend_enrichment(prior, f)
        b = float(np.asarray(blended)[0])
        assert 0.6 * 0.9 < b <= 1.0
        # enrichment ladder: >=0.6 -> REVIEW/MEDIUM+ (java :341-367)
        assert DECISIONS[int(np.asarray(dec)[0])] in ("REVIEW", "DECLINE")
        assert RISK_LEVEL_NAMES[int(np.asarray(risk)[0])] in (
            "MEDIUM", "HIGH", "CRITICAL")

    def test_job_wires_enrichment(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        gen = TransactionGenerator(num_users=20, num_merchants=10, seed=6)
        broker = InMemoryBroker()
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(broker, scorer,
                        JobConfig(max_batch=32, enable_enrichment=True))
        records = gen.generate_batch(40)
        broker.produce_batch(T.TRANSACTIONS, records,
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 40
        enriched = broker.consumer([T.ENRICHED], "c").poll(1000)
        assert len(enriched) == 40
        for r in enriched:
            assert "ensemble_score" in r.value       # pre-blend score kept
            assert 0.0 <= r.value["fraud_score"] <= 1.0
            assert r.value["decision"] in ("APPROVE", "REVIEW", "DECLINE")


class TestIngestFuzz:
    """Property: NO input shape may crash the sanitize -> encode path.

    The stream ingests arbitrary JSON from the wire; a crash in assembly is
    a whole-batch degradation, so the sanitizer must turn any garbage into
    either a clean reject or an encodable record."""

    @staticmethod
    def _strategies():
        import pytest

        st = pytest.importorskip(
            "hypothesis.strategies",
            reason="hypothesis not installed in this image")

        scalar = st.one_of(
            st.none(), st.booleans(), st.integers(-10**12, 10**12),
            st.floats(allow_nan=True, allow_infinity=True), st.text(max_size=20),
            st.lists(st.integers(), max_size=3),
        )
        geo = st.one_of(
            scalar,
            st.fixed_dictionaries({}, optional={
                "lat": scalar, "lon": scalar}),
        )
        return st.fixed_dictionaries({}, optional={
            "transaction_id": scalar, "user_id": scalar,
            "merchant_id": scalar, "amount": scalar,
            "hour_of_day": scalar, "day_of_week": scalar,
            "day_of_month": scalar, "is_weekend": scalar,
            "geolocation": geo, "merchant_location": geo,
            "payment_method": scalar, "transaction_type": scalar,
            "card_type": scalar, "user_agent": scalar,
            "ip_address": scalar, "device_fingerprint": scalar,
            "description": scalar, "fraud_score": scalar,
            "timestamp": scalar, "unexpected_field": scalar,
        })

    def test_sanitize_then_encode_never_crashes(self):
        import pytest

        hypothesis = pytest.importorskip(
            "hypothesis",
            reason="hypothesis not installed in this image")
        given, settings = hypothesis.given, hypothesis.settings

        from realtime_fraud_detection_tpu.features.schema import (
            encode_transactions,
        )
        from realtime_fraud_detection_tpu.serving.validation import (
            sanitize_for_stream,
        )

        @given(self._strategies())
        @settings(max_examples=300, deadline=None)
        def check(rec):
            txn, errors = sanitize_for_stream(rec)
            if errors:
                return                      # clean reject is a valid outcome
            batch = encode_transactions([txn])
            assert batch.batch_size == 1
            assert float(batch.amount[0]) >= 0.0

        check()

"""Stream join tests (stream/joins.py vs StreamJoiner.java semantics)."""

import pytest

from realtime_fraud_detection_tpu.stream.joins import (
    MultiStreamCorrelator,
    historical_pattern_key,
    pattern_similarity,
    txn_historical_pattern_join,
    txn_merchant_update_join,
    txn_user_behavior_join,
)


def txn(user="u1", merchant="m1", amount=50.0, payment="credit_card",
        category="retail", hour=None, tid="t1"):
    out = {
        "transaction_id": tid, "user_id": user, "merchant_id": merchant,
        "amount": amount, "payment_method": payment,
        "merchant_category": category,
    }
    if hour is not None:
        out["hour_of_day"] = hour
    return out


class TestUserBehaviorJoin:
    def test_joins_within_window_with_risk_factors(self):
        j = txn_user_behavior_join()
        j.process_left(txn(), 100.0)
        j.process_right({"user_id": "u1", "anomalous_login": True,
                         "short_session": False}, 110.0)
        # advance both watermarks past the 5m window end
        j.process_left(txn(user="zz", tid="t2"), 700.0)
        out = j.process_right({"user_id": "zz"}, 700.0)
        assert len(out) == 1
        e = out[0]
        assert e["transaction_id"] == "t1"
        assert e["risk_factors"] == {"recent_login_anomaly": 0.3}
        assert e["user_behavior_context"]["anomalous_login"] is True

    def test_no_join_across_windows_or_users(self):
        j = txn_user_behavior_join()
        j.process_left(txn(), 100.0)
        j.process_right({"user_id": "u2"}, 110.0)        # other user
        j.process_right({"user_id": "u1"}, 400.0)        # next 5m window
        j.process_left(txn(tid="t9"), 2000.0)
        out = j.process_right({"user_id": "x"}, 2000.0)
        assert out == []

    def test_watermark_is_min_of_both_streams(self):
        j = txn_user_behavior_join()
        j.process_left(txn(), 100.0)
        # left side raced ahead; right side still behind -> window must
        # NOT fire yet
        out = j.process_left(txn(tid="t2"), 10_000.0)
        assert out == []
        assert len(j) == 2


class TestMerchantUpdateJoin:
    def test_blacklist_risk_factor(self):
        j = txn_merchant_update_join()
        j.process_left(txn(), 50.0)
        j.process_right({"merchant_id": "m1", "newly_blacklisted": True,
                         "risk_level_increased": True}, 60.0)
        j.process_left(txn(merchant="zz", tid="t2"), 1300.0)
        out = j.process_right({"merchant_id": "zz"}, 1300.0)
        (e,) = out
        assert e["risk_factors"]["merchant_newly_blacklisted"] == 0.8
        assert e["risk_factors"]["merchant_risk_increase"] == 0.4
        assert "merchant_fraud_rate_increase" not in e["risk_factors"]


class TestHistoricalPatternJoin:
    def test_pattern_key_buckets_amount_to_100s(self):
        assert historical_pattern_key("credit_card", "retail", 250.0) == \
            "credit_card:retail:200"
        assert historical_pattern_key(None, None, 0.0) == "unknown:unknown:0"

    def test_similarity_formula(self):
        """StreamJoiner.java:278-301: payment 0.3 + amount 0.4 + time 0.3."""
        t = txn(amount=100.0, hour=10)
        p = {"payment_method": "credit_card", "amount_range": 100.0,
             "hour_of_day": 10}
        assert pattern_similarity(t, p) == pytest.approx(1.0)
        p2 = {"payment_method": "crypto", "amount_range": 200.0,
              "hour_of_day": 22}
        expected = 0.0 + (1 - 100 / 200) * 0.4 + (1 - 12 / 12) * 0.3
        assert pattern_similarity(t, p2) == pytest.approx(expected)

    def test_join_emits_similarity_scaled_risk(self):
        j = txn_historical_pattern_join()
        j.process_left(txn(amount=250.0, hour=3), 100.0)
        j.process_right(
            {"payment_method": "credit_card", "merchant_category": "retail",
             "amount_range": 280.0, "hour_of_day": 3, "fraud_rate": 0.8,
             "recent_pattern": True, "occurrence_count": 500}, 200.0)
        j.process_left(txn(payment="zz", tid="t2"), 8000.0)
        out = j.process_right({"payment_method": "zz", "amount_range": 0.0},
                              8000.0)
        (e,) = out
        rf = e["risk_factors"]
        sim = pattern_similarity(
            txn(amount=250.0, hour=3), e["historical_pattern_context"])
        assert rf["historical_pattern_similarity"] == pytest.approx(sim * 0.8)
        assert rf["recent_high_fraud_pattern"] == 0.4   # recent & rate>0.5
        assert rf["frequent_fraud_pattern"] == 0.3      # >100 occ & rate>0.3

    def test_flush_joins_open_windows(self):
        j = txn_historical_pattern_join()
        j.process_left(txn(amount=100.0), 10.0)
        j.process_right({"payment_method": "credit_card",
                         "merchant_category": "retail",
                         "amount_range": 110.0, "fraud_rate": 0.1}, 20.0)
        assert j.flush()
        assert len(j) == 0


class TestCorrelator:
    def test_emits_on_coinciding_signals(self):
        c = MultiStreamCorrelator(min_signals=2)
        c.on_behavior({"user_id": "u1", "anomalous_login": True}, 100.0)
        c.on_device({"user_id": "u1", "is_new_device": True}, 120.0)
        ev = c.on_transaction(txn(amount=100.0), 150.0)
        assert ev is not None
        assert ev["event_type"] == "COMPLEX_CORRELATION"
        assert set(ev["signals"]) == {"anomalous_behavior", "device_change"}
        assert ev["signal_count"] == 2

    def test_silent_below_threshold_and_outside_horizon(self):
        c = MultiStreamCorrelator(horizon_s=300.0, min_signals=2)
        c.on_behavior({"user_id": "u1", "anomalous_login": True}, 100.0)
        assert c.on_transaction(txn(), 150.0) is None     # 1 signal only
        c.on_device({"user_id": "u1", "is_new_device": True}, 110.0)
        assert c.on_transaction(txn(), 9999.0) is None    # horizon expired

    def test_large_amount_counts_as_signal(self):
        c = MultiStreamCorrelator(min_signals=2)
        c.on_network({"user_id": "u1", "is_vpn": True}, 10.0)
        ev = c.on_transaction(txn(amount=9000.0), 20.0)
        assert ev and set(ev["signals"]) == {"risky_network", "large_amount"}

    def test_sweep_evicts_stale_users(self):
        c = MultiStreamCorrelator(horizon_s=300.0, sweep_interval_events=5)
        for i in range(4):
            c.on_behavior({"user_id": f"old{i}", "anomalous_login": True},
                          100.0)
        # 5th push is far in the future -> triggers the sweep, old users go
        c.on_behavior({"user_id": "fresh"}, 10_000.0)
        assert list(c._behavior) == ["fresh"]

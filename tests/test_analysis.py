"""Invariant guard plane (analysis/): the static checker `rtfd lint` and
the dynamic lock-order watcher.

Three layers:

1. **Seeded-violation corpus** — one minimal bad snippet per rule proves
   every rule actually fires (with the right file/line), plus stale- and
   unknown-pragma cases. No bad code ever exists on disk: the corpus goes
   through ``lint_source``.
2. **Tree enforcement** — the committed tree must be clean. This is the
   tier-1 gate: a new bare wall-clock read in a virtual-clock subsystem,
   a d2h pull in a pre-pull-safe module, a dishonest counter mirror, or
   an unlocked param mutation fails the suite here with the linter's own
   pointed message.
3. **Lockwatch** — unit pins (a deliberately inverted two-lock order must
   be detected as a cycle; a device wait under a held lock must be a
   violation) and the real thing: all six deterministic drills run clean
   under the instrumented locks.
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import realtime_fraud_detection_tpu
from realtime_fraud_detection_tpu.analysis import (
    LockWatcher,
    lint_paths,
    lint_source,
    watch_locks,
)
from realtime_fraud_detection_tpu.analysis.lockwatch import (
    LOCKWATCH_DRILLS,
    WatchedLock,
    run_drill_watched,
)

PKG_ROOT = Path(realtime_fraud_detection_tpu.__file__).parent
REPO_ROOT = PKG_ROOT.parent


def rules_of(findings):
    return sorted({f.rule for f in findings})


def lines_of(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


# ---------------------------------------------------------------------------
# seeded-violation corpus: every rule fires, with the right file/line
# ---------------------------------------------------------------------------

class TestWallClockRule:
    def test_bare_wall_clock_in_scoped_subsystem_fires(self):
        src = ("import time\n"
               "def f():\n"
               "    return time.monotonic()\n")
        findings = lint_source(src, "qos/bad.py")
        assert rules_of(findings) == ["wall-clock"]
        assert lines_of(findings, "wall-clock") == [3]
        assert "qos/" in findings[0].message

    def test_injected_default_reference_is_not_a_call(self):
        src = ("import time\n"
               "def f(clock=time.monotonic):\n"
               "    return clock()\n")
        assert lint_source(src, "tuning/ok.py") == []

    def test_out_of_scope_subsystem_is_exempt(self):
        src = ("import time\n"
               "def f():\n"
               "    return time.perf_counter()\n")
        assert lint_source(src, "utils/whatever.py") == []

    def test_time_alias_and_from_import_are_seen(self):
        src = ("import time as _t\n"
               "from time import monotonic\n"
               "def f():\n"
               "    return _t.time() + monotonic()\n")
        findings = lint_source(src, "stream/bad.py")
        assert lines_of(findings, "wall-clock") == [4, 4]

    def test_datetime_now_is_wall_clock(self):
        src = ("from datetime import datetime\n"
               "def f():\n"
               "    return datetime.now()\n")
        assert lines_of(lint_source(src, "sim/bad.py"), "wall-clock") == [3]


class TestD2hRule:
    SRC = ("import numpy as np\n"
           "import jax\n"
           "def f(x):\n"
           "    a = np.asarray(x)\n"
           "    b = jax.device_get(x)\n"
           "    c = x.item()\n"
           "    d = float(x)\n"
           "    return a, b, c, d\n")

    def test_all_four_pull_shapes_fire_in_scoped_module(self):
        findings = lint_source(self.SRC, "scoring/host_pipeline.py")
        assert rules_of(findings) == ["d2h"]
        assert lines_of(findings, "d2h") == [4, 5, 6, 7]

    def test_unscoped_module_is_exempt(self):
        assert lint_source(self.SRC, "features/anything.py") == []

    def test_quant_calibrator_is_in_scope(self):
        """ISSUE 9: models/quant.py joined D2H_MODULES — its host-side
        calibration sites must carry justified pragmas, and anything
        unexplained reads as a dispatch-path pull."""
        findings = lint_source(self.SRC, "models/quant.py")
        assert rules_of(findings) == ["d2h"]
        assert lines_of(findings, "d2h") == [4, 5, 6, 7]

    def test_scorer_dispatch_scope_is_function_level(self):
        src = ("import numpy as np\n"
               "class FraudScorer:\n"
               "    def dispatch_assembled(self, x):\n"
               "        return np.asarray(x)\n"
               "    def finalize(self, x):\n"
               "        return np.asarray(x)\n")
        findings = lint_source(src, "scoring/scorer.py")
        # dispatch half checked; finalize is the designated pull point
        assert lines_of(findings, "d2h") == [4]

    def test_block_until_ready_is_allowed(self):
        src = ("import jax\n"
               "def f(x):\n"
               "    jax.block_until_ready(x)\n")
        assert lint_source(src, "utils/timing.py") == []


METRICS_SRC = (
    "class MetricsCollector:\n"
    "    def __init__(self, r):\n"
    "        self.foo = r.counter('foo_total', 't')\n"
    "        self.dead = r.counter('dead_total', 't')\n"
    "        self.bad = r.counter('badName', 't')\n"
    "        self.g = r.gauge('oops_total', 't')\n"
    "    def sync_foo(self):\n"
    "        self.foo.inc(1)\n")


class TestMetricsRule:
    def test_name_conventions(self):
        findings = lint_source(METRICS_SRC, "obs/metrics.py")
        msgs = [f.message for f in findings if f.rule == "metrics"]
        assert any("snake_case" in m for m in msgs)          # badName
        assert any("'_total'" in m and "counter" in m
                   for m in msgs)                            # badName no suffix
        assert any("must not claim" in m for m in msgs)      # gauge oops_total

    def test_dead_series_detected(self):
        findings = lint_source(METRICS_SRC, "obs/metrics.py")
        assert any("dead series" in f.message and f.line == 4
                   for f in findings)

    def test_two_planes_writing_one_counter(self):
        plane1 = "def a(m):\n    m.foo.inc(priority='x')\n"
        plane2 = "def b(m):\n    m.foo.inc(priority='y')\n"
        findings = lint_source(plane1, "qos/p1.py", extra={
            "obs/metrics.py": METRICS_SRC, "serving/p2.py": plane2})
        two = [f for f in findings if "two planes" in f.message]
        assert len(two) == 1 and two[0].path == "serving/p2.py"

    def test_raw_cumulative_inc_outside_collector(self):
        plane = ("def a(m, snapshot):\n"
                 "    total = snapshot['scored']\n"
                 "    m.foo.inc(total)\n")
        findings = lint_source(plane, "qos/p1.py",
                               extra={"obs/metrics.py": METRICS_SRC})
        assert any("sync_*" in f.message and f.line == 3 for f in findings)


class TestLockOrderRule:
    def test_unlocked_mutation_entry_fires(self):
        src = ("def rung(scorer):\n"
               "    scorer.set_degradation(None)\n")
        findings = lint_source(src, "qos/x.py")
        assert lines_of(findings, "lock-order") == [2]
        assert "set_degradation" in findings[0].message

    def test_mutation_under_lock_is_clean(self):
        src = ("def rung(scorer, lock):\n"
               "    with lock:\n"
               "        scorer.set_degradation(None)\n")
        assert lint_source(src, "qos/x.py") == []

    def test_lock_kwarg_counts_as_held(self):
        src = ("def promote(scorer, score_lock):\n"
               "    restore_into_scorer(scorer, lock=score_lock)\n")
        assert lint_source(src, "serving/x.py") == []

    def test_caller_holding_lock_covers_callee(self):
        src = ("def inner(scorer):\n"
               "    scorer.set_models(None)\n"
               "def outer(scorer, lock):\n"
               "    with lock:\n"
               "        inner(scorer)\n")
        assert lint_source(src, "scoring/x.py") == []

    def test_blocking_ops_under_lock(self):
        src = ("import time\n"
               "class A:\n"
               "    def f(self):\n"
               "        with self._lock:\n"
               "            time.sleep(0.1)\n"
               "            self._q.get()\n"
               "            self._q.put_nowait(1)\n")
        findings = lint_source(src, "stream/x.py")
        assert lines_of(findings, "lock-order") == [5, 6]  # _nowait is fine


class TestDeterminismRule:
    def test_global_rngs_fire_in_sim_and_drills(self):
        src = ("import random\n"
               "import numpy as np\n"
               "def gen():\n"
               "    random.random()\n"
               "    np.random.rand()\n"
               "    return np.random.default_rng(0)\n")
        for rel in ("sim/bad.py", "qos/bad_drill.py"):
            findings = lint_source(src, rel)
            assert rules_of(findings) == ["determinism"], rel
            assert lines_of(findings, "determinism") == [4, 5]

    def test_non_drill_module_is_exempt(self):
        src = "import random\nx = random.random()\n"
        assert lint_source(src, "training/x.py") == []

    def test_quant_calibrator_is_in_scope(self):
        """ISSUE 9: models/quant.py is under the determinism contract —
        the same f32 weights must always calibrate to the same int8 blobs
        (replica hot-swap + checkpoint round-trips assume it)."""
        src = ("import numpy as np\n"
               "def calibrate(w):\n"
               "    return w + np.random.standard_normal(w.shape)\n")
        findings = lint_source(src, "models/quant.py")
        assert rules_of(findings) == ["determinism"]
        assert lines_of(findings, "determinism") == [3]


class TestPragmaHygiene:
    def test_valid_pragma_suppresses_and_is_not_stale(self):
        src = ("import time\n"
               "def f():\n"
               "    # rtfd-lint: allow[wall-clock] test justification\n"
               "    return time.monotonic()\n")
        assert lint_source(src, "qos/ok.py") == []

    def test_trailing_same_line_pragma(self):
        src = ("import time\n"
               "def f():\n"
               "    return time.time()  # rtfd-lint: allow[wall-clock] why\n")
        assert lint_source(src, "obs/ok.py") == []

    def test_stale_pragma_is_an_error(self):
        src = ("import time\n"
               "# rtfd-lint: allow[wall-clock] nothing underneath anymore\n"
               "X = 1\n")
        findings = lint_source(src, "qos/stale.py")
        assert rules_of(findings) == ["pragma-hygiene"]
        assert findings[0].line == 2
        assert "stale" in findings[0].message

    def test_unknown_rule_name_is_an_error_and_does_not_suppress(self):
        src = ("import time\n"
               "def f():\n"
               "    # rtfd-lint: allow[made-up-rule]\n"
               "    return time.monotonic()\n")
        findings = lint_source(src, "qos/bad.py")
        assert rules_of(findings) == ["pragma-hygiene", "wall-clock"]

    def test_pragma_inside_string_literal_is_ignored(self):
        src = ("MSG = 'annotate with # rtfd-lint: allow[wall-clock] why'\n")
        assert lint_source(src, "qos/strings.py") == []


# ---------------------------------------------------------------------------
# tree enforcement: the tier-1 gate
# ---------------------------------------------------------------------------

class TestCommittedTreeIsClean:
    def test_zero_findings_on_the_package_tree(self):
        findings = lint_paths()
        assert not findings, (
            "rtfd lint found invariant violations — fix them or (only for "
            "a genuinely legitimate site) annotate with "
            "`# rtfd-lint: allow[<rule>] <why>`:\n"
            + "\n".join(str(f) for f in findings))

    def test_cli_json_reports_clean(self):
        proc = subprocess.run(
            [sys.executable, "-m", "realtime_fraud_detection_tpu",
             "lint", "--format", "json"],
            capture_output=True, text=True, timeout=180,
            cwd=str(REPO_ROOT))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        data = json.loads(proc.stdout)
        assert data["clean"] is True and data["count"] == 0
        assert sorted(data["rules"]) == [
            "d2h", "determinism", "lock-order", "metrics",
            "pragma-hygiene", "wall-clock"]

    def test_serving_degradation_lock_regression_pin(self):
        """PR 7 fixed a real finding: the serving plane stepped the QoS
        ladder (a scorer mask mutation) without the score lock while an
        executor thread could be mid-dispatch. The fix flags the rung
        change on the event loop and applies it in _dispatch_batch_sync
        under the lock that thread already holds. Pin both directions:
        the committed code is clean, and hoisting the apply back out of
        the locked section brings the lock-order finding back — the
        linter IS the regression test."""
        app_src = (PKG_ROOT / "serving/app.py").read_text()
        plane_src = (PKG_ROOT / "qos/plane.py").read_text()
        apply_line = "self.qos.apply_degradation(self.scorer)"
        locked = ("with self._score_lock:\n"
                  "                    if self._qos_rung_dirty")
        assert locked in app_src and apply_line in app_src
        extra = {"qos/plane.py": plane_src}
        clean = lint_source(app_src, "serving/app.py", extra=extra)
        assert not [f for f in clean if f.rule == "lock-order"]
        # regression shape: apply hoisted above the locked section
        mutated = app_src.replace(
            locked,
            f"{apply_line}\n"
            "                with self._score_lock:\n"
            "                    if self._qos_rung_dirty")
        dirty = lint_source(mutated, "serving/app.py", extra=extra)
        assert [f for f in dirty if f.rule == "lock-order"
                and "set_degradation" in f.message]


# ---------------------------------------------------------------------------
# lockwatch: unit pins
# ---------------------------------------------------------------------------

class TestLockWatcher:
    def test_inverted_two_lock_order_is_detected_as_cycle(self):
        w = LockWatcher()
        la, lb = w.lock("A"), w.lock("B")

        def ab():
            with la:
                with lb:
                    pass

        def ba():
            with lb:
                with la:
                    pass

        for fn in (ab, ba):           # sequenced: no real deadlock risk
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        rep = w.report()
        assert not rep["ok"]
        assert rep["cycles"], rep["edges"]
        cyc = rep["cycles"][0]
        assert set(cyc) == {"A", "B"}

    def test_consistent_order_is_clean_and_holds_recorded(self):
        w = LockWatcher()
        la, lb = w.lock("A"), w.lock("B")
        with la:
            with lb:
                time.sleep(0.01)
        rep = w.report()
        assert rep["ok"] and rep["cycles"] == []
        assert rep["edges"] == [["A", "B", 1]]
        assert rep["max_hold_ms"]["A"] >= 10.0

    def test_device_wait_under_held_lock_is_a_violation(self):
        w = LockWatcher()
        lock = w.lock("score-lock")
        with watch_locks(w):
            import jax

            with lock:
                jax.block_until_ready(np.zeros(2))
        rep = w.report()
        assert not rep["ok"]
        v = rep["violations"][0]
        assert v["kind"] == "device-wait-under-lock"
        assert v["held"] == ["score-lock"]

    def test_device_wait_without_lock_is_clean(self):
        w = LockWatcher()
        with watch_locks(w):
            import jax

            jax.block_until_ready(np.zeros(2))
        assert w.report()["ok"]

    def test_cond_wait_holding_other_lock_is_a_warning_not_failure(self):
        w = LockWatcher()
        lock, cond = w.lock("L"), w.condition("C")
        with lock:
            with cond:
                cond.wait(timeout=0.01)
        rep = w.report()
        assert rep["ok"]                      # warning, not violation
        assert rep["warnings"][0]["kind"] == "cond-wait-holding-other-lock"
        assert rep["warnings"][0]["held"] == ["L"]

    def test_watch_wraps_package_lock_creation_and_restores(self):
        from realtime_fraud_detection_tpu.obs.metrics import Registry

        with watch_locks() as w:
            r = Registry()                    # created from a package frame
            assert isinstance(r._lock, WatchedLock)
            with r._lock:
                pass
        assert w.acquisitions >= 1
        r2 = Registry()                       # after restore: a real lock
        assert not isinstance(r2._lock, WatchedLock)


# ---------------------------------------------------------------------------
# lockwatch under the real drills (the tier-1 enforcement)
# ---------------------------------------------------------------------------

class TestLockwatchUnderDrills:
    @pytest.mark.parametrize("drill", LOCKWATCH_DRILLS)
    def test_drill_runs_clean_under_instrumented_locks(self, drill):
        rep = run_drill_watched(drill, fast=True)
        assert rep["drill_passed"], drill
        lw = rep["lockwatch"]
        assert lw["ok"], (drill, lw["cycles"], lw["violations"])
        # the watcher actually watched something
        assert lw["acquisitions"] > 0 and lw["locks"]

    @pytest.mark.slow
    def test_lockwatch_cli_all_six_drills(self):
        proc = subprocess.run(
            [sys.executable, "-m", "realtime_fraud_detection_tpu",
             "lint", "--lockwatch", "--fast"],
            capture_output=True, text=True, timeout=1800,
            cwd=str(REPO_ROOT),
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        last = proc.stdout.strip().splitlines()[-1]
        verdict = json.loads(last)
        assert verdict["passed"] is True, verdict
        assert set(verdict["lockwatch"]) == set(LOCKWATCH_DRILLS)


# ---------------------------------------------------------------------------
# bench satellite: the tuner's bucket set reconciles into the sweep
# ---------------------------------------------------------------------------

def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_under_test", REPO_ROOT / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchTunedBucketReconcile:
    def test_autotune_stage_records_tuned_bucket_set(self):
        from realtime_fraud_detection_tpu.core.batching import BATCH_BUCKETS

        bench = _load_bench()
        result = {}
        bench._autotune_stage(result, lambda *a, **k: None)
        at = result["autotune"]
        assert at["passed"] is True
        assert isinstance(at["tuned_bucket_set"], list)
        assert at["tuned_bucket_set"] == sorted(at["tuned_bucket_set"])
        assert at["tuned_bucket_set"]
        assert set(at["tuned_bucket_set"]) <= set(BATCH_BUCKETS)

    def test_compact_summary_carries_both_bucket_truths(self):
        bench = _load_bench()
        op = {"batch": 128, "txn_per_s": 9000.0, "p99_net_of_rtt_ms": 14.0}
        result = {
            "metric": "m", "value": 1.0, "device": "cpu",
            "bucket_sweep": {
                "passing": [64, 128],
                "operating_point": op,
                "tuned_set": [32, 128],
                "tuned_set_passing": [128],
                "operating_point_tuned": op,
                "buckets": {},
            },
        }
        compact = bench._compact_summary(result)
        assert compact["sweep_passing"] == [64, 128]
        assert compact["sweep_tuned"] == {
            "set": [32, 128], "passing": [128], "operating_batch": 128}
        assert len(json.dumps(compact, separators=(",", ":"))) < 2048

    def test_compact_summary_omits_tuned_view_when_absent(self):
        bench = _load_bench()
        compact = bench._compact_summary(
            {"metric": "m", "value": 1.0, "bucket_sweep": {"passing": []}})
        assert compact["sweep_tuned"] is None

"""Ensemble combination math vs the reference semantics."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.ensemble import (
    STACKING,
    VOTING,
    EnsembleParams,
    combine_predictions,
    model_confidence,
)
from realtime_fraud_detection_tpu.features.rules import DECISIONS
from realtime_fraud_detection_tpu.utils.config import Config

MODEL_NAMES = ("xgboost_primary", "lstm_sequential", "bert_text",
               "graph_neural", "isolation_forest")


@pytest.fixture(scope="module")
def params():
    return EnsembleParams.from_config(Config(), MODEL_NAMES)


def _np(x):
    return np.asarray(x)


class TestWeightedAverage:
    def test_matches_hand_computed(self, params):
        preds = np.array([[0.9, 0.8, 0.7, 0.6, 0.5]], np.float32)
        valid = np.ones((1, 5), bool)
        out = combine_predictions(preds, valid, params)
        expected = 0.4 * 0.9 + 0.25 * 0.8 + 0.15 * 0.7 + 0.15 * 0.6 + 0.05 * 0.5
        assert _np(out["fraud_probability"])[0] == pytest.approx(expected, rel=1e-5)

    def test_failed_model_skipped_and_renormalized(self, params):
        preds = np.array([[0.9, 0.8, 0.0, 0.6, 0.5]], np.float32)
        valid = np.array([[True, True, False, True, True]])
        out = combine_predictions(preds, valid, params)
        w = np.array([0.4, 0.25, 0.15, 0.05])
        p = np.array([0.9, 0.8, 0.6, 0.5])
        assert _np(out["fraud_probability"])[0] == pytest.approx(
            (w * p).sum() / w.sum(), rel=1e-5
        )

    def test_all_failed_neutral(self, params):
        preds = np.zeros((1, 5), np.float32)
        valid = np.zeros((1, 5), bool)
        out = combine_predictions(preds, valid, params)
        assert _np(out["fraud_probability"])[0] == pytest.approx(0.5)
        assert _np(out["confidence"])[0] == 0.0


class TestConfidence:
    def test_multipliers(self, params):
        # extreme xgb prediction -> confidence 1.0; neutral -> 0
        preds = np.array([[1.0, 0.5, 0.5, 0.5, 0.5]], np.float32)
        conf = _np(model_confidence(preds, params.confidence_multipliers))
        assert conf[0, 0] == pytest.approx(1.0)
        assert conf[0, 1] == pytest.approx(0.0)
        # iforest multiplier 0.5: p=1.0 -> 2*0.5*0.5 = 0.5
        preds = np.array([[0.5, 0.5, 0.5, 0.5, 1.0]], np.float32)
        conf = _np(model_confidence(preds, params.confidence_multipliers))
        assert conf[0, 4] == pytest.approx(0.5)


class TestStrategies:
    def test_voting(self):
        cfg = Config()
        cfg.ensemble.strategy = "voting"
        params = EnsembleParams.from_config(cfg, MODEL_NAMES)
        assert params.strategy == VOTING
        preds = np.array([[0.9, 0.9, 0.9, 0.2, 0.2]], np.float32)
        out = combine_predictions(preds, np.ones((1, 5), bool), params)
        assert _np(out["fraud_probability"])[0] == pytest.approx(3 / 5)

    def test_stacking_confidence_weighted(self):
        cfg = Config()
        cfg.ensemble.strategy = "stacking"
        params = EnsembleParams.from_config(cfg, MODEL_NAMES)
        assert params.strategy == STACKING
        preds = np.array([[0.9, 0.6, 0.5, 0.5, 0.5]], np.float32)
        out = combine_predictions(preds, np.ones((1, 5), bool), params)
        conf = _np(model_confidence(preds, params.confidence_multipliers))[0]
        expected = (preds[0] * conf).sum() / conf.sum()
        assert _np(out["fraud_probability"])[0] == pytest.approx(expected, rel=1e-5)


class TestDecisionLadder:
    def test_low_confidence_forces_review(self, params):
        # all models mildly positive -> low confidence -> REVIEW
        preds = np.full((1, 5), 0.55, np.float32)
        out = combine_predictions(preds, np.ones((1, 5), bool), params)
        assert float(_np(out["confidence"])[0]) < 0.7
        assert DECISIONS[int(_np(out["decision"])[0])] == "REVIEW"

    def test_decline_at_95(self, params):
        preds = np.full((1, 5), 0.99, np.float32)
        out = combine_predictions(preds, np.ones((1, 5), bool), params)
        assert DECISIONS[int(_np(out["decision"])[0])] == "DECLINE"
        assert int(_np(out["risk_level"])[0]) == 4  # CRITICAL

    def test_monitoring_band(self, params):
        preds = np.full((1, 5), 0.70, np.float32)
        out = combine_predictions(preds, np.ones((1, 5), bool), params)
        # confidence = 2*0.2*mult averaged -> below 0.7 threshold? compute:
        conf = float(_np(out["confidence"])[0])
        d = DECISIONS[int(_np(out["decision"])[0])]
        if conf < 0.7:
            assert d == "REVIEW"
        else:
            assert d == "APPROVE_WITH_MONITORING"

    def test_batch_vectorized(self, params):
        rng = np.random.default_rng(0)
        preds = rng.random((256, 5)).astype(np.float32)
        out = combine_predictions(preds, np.ones((256, 5), bool), params)
        assert out["fraud_probability"].shape == (256,)
        assert out["decision"].shape == (256,)
        assert np.isin(_np(out["decision"]), [0, 1, 2, 3]).all()


def test_decision_ladder_rungs_come_from_config():
    """decline/review/monitor_threshold are config knobs (EnsembleConfig),
    not constants baked into the ladder."""

    from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
    from realtime_fraud_detection_tpu.features.rules import DECISIONS
    from realtime_fraud_detection_tpu.scoring import MODEL_NAMES
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = Config()
    cfg.ensemble.confidence_threshold = 0.0   # isolate the prob rungs
    cfg.ensemble.decline_threshold = 0.5
    cfg.ensemble.review_threshold = 0.4
    cfg.ensemble.monitor_threshold = 0.3
    params = EnsembleParams.from_config(cfg, list(MODEL_NAMES))
    # every branch votes 0.45 with full confidence multipliers: probability
    # 0.45 sits in the custom REVIEW band (>=0.4, <0.5)
    preds = np.full((1, 5), 0.45, np.float32)
    out = combine_predictions(preds, np.ones((1, 5), bool), params)
    assert DECISIONS[int(np.asarray(out["decision"])[0])] == "REVIEW"

    cfg.ensemble.decline_threshold = 0.44   # now the same score DECLINEs
    params2 = EnsembleParams.from_config(cfg, list(MODEL_NAMES))
    out2 = combine_predictions(preds, np.ones((1, 5), bool), params2)
    assert DECISIONS[int(np.asarray(out2["decision"])[0])] == "DECLINE"


def test_scorer_state_ttls_come_from_config():
    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = Config()
    cfg.state.transaction_ttl_s = 123
    cfg.state.user_history_len = 7
    s = FraudScorer(config=cfg)
    assert s.txn_cache.txn_ttl_s == 123
    assert s.txn_cache.user_list_len == 7

"""A/B experimentation: sticky routing, per-arm metrics, significance."""

import pytest

from realtime_fraud_detection_tpu.testing import (
    ABTestManager,
    Variant,
    apply_weight_overrides,
)


def two_arm(mgr, name="exp", split=0.5, salt=""):
    return mgr.create_experiment(name, [
        Variant("control", split, {}),
        Variant("treatment", 1 - split,
                {"weights": {"bert_text": 0.3}}),
    ], salt=salt)


class TestRouting:
    def test_assignment_is_sticky(self):
        mgr = ABTestManager()
        two_arm(mgr)
        first = mgr.assign("exp", "user_42").name
        for _ in range(10):
            assert mgr.assign("exp", "user_42").name == first

    def test_split_approximates_traffic(self):
        mgr = ABTestManager()
        two_arm(mgr, split=0.8)
        n = 5000
        control = sum(
            mgr.assign("exp", f"u{i}").name == "control" for i in range(n))
        assert 0.77 < control / n < 0.83

    def test_salt_reshuffles_assignment(self):
        a, b = ABTestManager(), ABTestManager()
        two_arm(a, salt="s1")
        two_arm(b, salt="s2")
        users = [f"u{i}" for i in range(200)]
        same = sum(a.assign("exp", u).name == b.assign("exp", u).name
                   for u in users)
        assert same < 200                     # at least some users moved

    def test_traffic_must_sum_to_one(self):
        mgr = ABTestManager()
        with pytest.raises(ValueError):
            mgr.create_experiment("bad", [Variant("a", 0.5), Variant("b", 0.4)])

    def test_traffic_must_be_in_unit_range(self):
        mgr = ABTestManager()
        with pytest.raises(ValueError):
            mgr.create_experiment(
                "bad2", [Variant("a", -0.5), Variant("b", 1.5)])

    def test_inactive_experiment_routes_nothing(self):
        mgr = ABTestManager()
        two_arm(mgr)
        mgr.stop_experiment("exp")
        assert mgr.route_config_overrides("exp", "u1") == {}


class TestEvaluation:
    def test_per_variant_metrics(self):
        mgr = ABTestManager()
        two_arm(mgr)
        # control: catches 2 of 4 frauds, 1 false positive on 4 legit
        for flagged, actual in [(True, True), (True, True), (False, True),
                                (False, True), (True, False), (False, False),
                                (False, False), (False, False)]:
            mgr.record_prediction("exp", "control", 0.5, flagged, actual)
        m = mgr.results("exp")["variants"]["control"]
        assert m["labeled"] == 8
        assert m["recall"] == pytest.approx(0.5)
        assert m["precision"] == pytest.approx(2 / 3)

    def test_significance_detects_large_effect(self):
        mgr = ABTestManager()
        two_arm(mgr)
        for _ in range(100):   # control recall 0.5
            mgr.record_prediction("exp", "control", 0.5, True, True)
            mgr.record_prediction("exp", "control", 0.5, False, True)
        for _ in range(190):   # treatment recall 0.95
            mgr.record_prediction("exp", "treatment", 0.5, True, True)
        for _ in range(10):
            mgr.record_prediction("exp", "treatment", 0.5, False, True)
        sig = mgr.results("exp")["significance"]
        assert sig["computed"] and sig["significant"]
        assert sig["effect"] == pytest.approx(0.45)

    def test_significance_requires_labels(self):
        mgr = ABTestManager()
        two_arm(mgr)
        mgr.record_prediction("exp", "control", 0.4, False)
        sig = mgr.results("exp")["significance"]
        assert not sig["computed"]

    def test_apply_weight_overrides_reweights(self):
        preds = {"a": 1.0, "b": 0.0}
        base = {"a": 0.5, "b": 0.5}
        out = apply_weight_overrides(preds, base, {})
        assert out["fraud_probability"] == pytest.approx(0.5)
        # tilt fully onto model a
        out = apply_weight_overrides(preds, base, {"b": 0.0})
        assert out["fraud_probability"] == pytest.approx(1.0)
        out = apply_weight_overrides(preds, base, {"a": 0.25, "b": 0.75})
        assert out["fraud_probability"] == pytest.approx(0.25)

    def test_apply_weight_overrides_outcome_consistency(self):
        """decision/risk_level must match the reweighted probability
        (ensemble_predictor.py:344-369 ladders)."""
        # unknown model names get the default multiplier 0.5 -> confidence
        # = |p-0.5|*2*0.5; p=1.0 on one model gives confidence 0.5 < 0.7
        out = apply_weight_overrides({"a": 1.0}, {"a": 1.0}, {})
        assert out["decision"] == "REVIEW"          # low confidence
        assert out["risk_level"] == "CRITICAL"
        # xgboost_primary's multiplier is 1.0 -> confidence 1.0 at p=1.0
        out = apply_weight_overrides(
            {"xgboost_primary": 1.0}, {"xgboost_primary": 1.0}, {})
        assert out["decision"] == "DECLINE"
        assert out["risk_level"] == "CRITICAL"
        out = apply_weight_overrides(
            {"xgboost_primary": 0.05}, {"xgboost_primary": 1.0}, {})
        assert out["decision"] == "APPROVE"
        assert out["risk_level"] == "VERY_LOW"
        assert out["confidence"] == pytest.approx(0.9)

    def test_apply_weight_overrides_no_live_models(self):
        assert apply_weight_overrides({}, {"a": 1.0}, {}) is None
        assert apply_weight_overrides(
            {"a": 0.8}, {"a": 0.0}, {}) is None

    def test_active_experiments_listing(self):
        mgr = ABTestManager()
        two_arm(mgr, name="e1")
        two_arm(mgr, name="e2")
        mgr.stop_experiment("e1")
        assert mgr.active_experiments() == ["e2"]

    def test_overrides_flow_through_routing(self):
        mgr = ABTestManager()
        two_arm(mgr, split=0.0)               # everyone → treatment
        ov = mgr.route_config_overrides("exp", "anyone")
        assert ov == {"weights": {"bert_text": 0.3}}


class TestExperimentFromArtifact:
    def test_canary_blend_variants(self, tmp_path):
        """experiment_from_artifact: treatment carries the artifact's
        selected weights with excluded branches zeroed (matching the
        artifact's semantics), control carries no overrides."""
        import json

        from realtime_fraud_detection_tpu.scoring import MODEL_NAMES
        from realtime_fraud_detection_tpu.testing.ab import ABTestManager

        artifact = tmp_path / "q.json"
        artifact.write_text(json.dumps({"selected_blend": {"weights": {
            "xgboost_primary": 0.4, "lstm_sequential": 0.1}}}))
        ab = ABTestManager()
        exp = ab.experiment_from_artifact("canary", str(artifact),
                                          traffic=0.25)
        names = {v.name: v for v in exp.variants}
        assert names["control"].traffic == 0.75
        assert not names["control"].overrides
        w = names["artifact"].overrides["weights"]
        assert set(w) == set(MODEL_NAMES)
        assert w["xgboost_primary"] == 0.4 and w["lstm_sequential"] == 0.1
        assert w["bert_text"] == 0.0 and w["graph_neural"] == 0.0
        # sticky routing still works over the two arms
        got = {ab.assign("canary", f"user{i}").name for i in range(200)}
        assert got == {"control", "artifact"}

    def test_rejects_non_artifact(self, tmp_path):
        import pytest as _pytest

        from realtime_fraud_detection_tpu.testing.ab import ABTestManager

        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        with _pytest.raises(ValueError, match="selected_blend"):
            ABTestManager().experiment_from_artifact("x", str(bad))

    def test_rejects_unknown_model_and_non_dict_shapes(self, tmp_path):
        import json

        import pytest as _pytest

        from realtime_fraud_detection_tpu.testing.ab import ABTestManager

        unknown = tmp_path / "unknown.json"
        unknown.write_text(json.dumps(
            {"selected_blend": {"weights": {"mystery": 1.0}}}))
        with _pytest.raises(ValueError, match="mystery"):
            ABTestManager().experiment_from_artifact("x", str(unknown))
        # non-dict shapes must raise ValueError, never AttributeError
        for payload in ("[]", '{"selected_blend": []}',
                        '{"selected_blend": {"weights": []}}'):
            bad = tmp_path / "shape.json"
            bad.write_text(payload)
            with _pytest.raises(ValueError, match="selected_blend"):
                ABTestManager().experiment_from_artifact("y", str(bad))

"""Persistent megakernel (ISSUE 19): one Pallas program scores the whole
packed microbatch — plan predicates (VMEM budget, block divisibility,
min-batch, two-hop exclusion), interpret-mode parity of the fused program
against the verbatim-composition reference on randomized AND
trained/quantized params in f32 and bf16-staged inputs, per-rung static
program cache with zero-retrace memoized statics, the scorer cascade's
honest dispatch/fallback accounting, checkpoint hygiene (megakernel
selection is runtime config, never serialized), device-pool/mesh
composition with a mid-stream hot swap, the kernel_mega_* Prometheus
mirror, and the `rtfd kernel-drill --fast --mega` tier-1 smoke."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from realtime_fraud_detection_tpu.core.mesh import build_mesh
from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG
from realtime_fraud_detection_tpu.models.quant import (
    is_quantized_bert,
    quantize_bert_params,
)
from realtime_fraud_detection_tpu.ops import (
    fused_megakernel,
    mega_launch_accounting,
    mega_plan,
    mega_supported,
    megakernel_reference,
)
from realtime_fraud_detection_tpu.ops.megakernel import (
    MEGA_MIN_BATCH,
    mega_block,
)
from realtime_fraud_detection_tpu.scoring import (
    MODEL_NAMES,
    DevicePool,
    FraudScorer,
    MeshExecutor,
    ScorerConfig,
)
from realtime_fraud_detection_tpu.scoring.pipeline import (
    OUT_COLUMNS,
    init_scoring_models,
    make_example_batch,
    packed_width,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.utils.config import (
    Config,
    KernelSettings,
    QuantSettings,
)

BATCH = 16
DEC, RISK = OUT_COLUMNS.index("decision"), OUT_COLUMNS.index("risk_level")


def _mega_config(mega=True, quant=True) -> Config:
    return Config(
        quant=QuantSettings.full() if quant else QuantSettings(),
        kernels=(KernelSettings.mega() if mega
                 else KernelSettings() if mega is None
                 else KernelSettings.full()))


def _scorer(mega=True, quant=True, seed=0, gen_seed=7, one_device=False):
    gen = TransactionGenerator(num_users=150, num_merchants=40,
                               seed=gen_seed)
    mesh = build_mesh(devices=jax.devices()[:1]) if one_device else None
    s = FraudScorer(_mega_config(mega, quant),
                    scorer_config=ScorerConfig(), mesh=mesh, seed=seed)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, s


def _rows(results):
    return [(r["transaction_id"], r["fraud_probability"], r["confidence"],
             r["decision"], r["risk_level"]) for r in results]


import pytest


@pytest.fixture(scope="module")
def models_f32():
    """Randomized models for direct kernel-level parity (no scorer in the
    loop) — module-scoped: immutable pytrees, built once."""
    return init_scoring_models(jax.random.PRNGKey(3))


@pytest.fixture(scope="module")
def models_q(models_f32):
    return models_f32.replace(
        bert=quantize_bert_params(jax.device_get(models_f32.bert)))


@pytest.fixture(scope="module")
def blend_params():
    return EnsembleParams.from_config(Config(), MODEL_NAMES)


def _batch(b, rng_seed=11):
    return make_example_batch(b, rng=np.random.default_rng(rng_seed))


def _assert_parity(models, batch, params, mv, *, block=None, tol=1e-6):
    ref = np.asarray(megakernel_reference(
        models, batch, params, mega_valid=mv, bert_config=TINY_CONFIG))
    got = np.asarray(fused_megakernel(
        models, batch, params, mega_valid=mv, bert_config=TINY_CONFIG,
        interpret=True, block=block))
    assert got.shape == ref.shape == (
        batch.batch_size, packed_width(len(MODEL_NAMES), epilogue=True))
    assert float(np.abs(got[:, 0] - ref[:, 0]).max()) <= tol
    # the QoS ladder columns are exact small integers — any drift is a flip
    np.testing.assert_array_equal(got[:, DEC], ref[:, DEC])
    np.testing.assert_array_equal(got[:, RISK], ref[:, RISK])
    return ref, got


# ------------------------------------------------------- shape plan honesty
class TestMegaPlan:
    def test_min_batch_and_divisibility(self):
        assert mega_block(MEGA_MIN_BATCH, 1 << 20, 1 << 10) == 8
        assert not mega_supported(1, 1 << 20, 1 << 10)
        assert not mega_supported(MEGA_MIN_BATCH - 1, 1 << 20, 1 << 10)
        # 12 is >= MEGA_MIN_BATCH but divisible by no block candidate
        assert mega_block(12, 1 << 20, 1 << 10) == 0
        assert not mega_supported(12, 1 << 20, 1 << 10)

    def test_two_hop_graph_excluded(self):
        assert mega_supported(32, 1 << 20, 1 << 10)
        assert not mega_supported(32, 1 << 20, 1 << 10, has_two_hop=True)

    def test_vmem_budget_declines_oversized_params(self, models_f32):
        models, sc = models_f32, ScorerConfig()
        # f32 TINY word embeddings alone (~15.6 MB) exceed the VMEM budget
        plan = mega_plan(models, TINY_CONFIG, b=32, text_len=sc.text_len,
                         seq_len=sc.seq_len, feature_dim=sc.feature_dim,
                         has_two_hop=False)
        assert not plan["supported"]
        # full DistilBERT-base dims stay unsupported even quantized — the
        # plan must say so honestly (tune_tpu emits supported=False)
        assert not mega_supported(
            32, 90 * (1 << 20), plan["act_row_bytes"])

    def test_quantized_tiny_supported_with_block(self, models_q):
        models, sc = models_q, ScorerConfig()
        plan = mega_plan(models, TINY_CONFIG, b=32, text_len=sc.text_len,
                         seq_len=sc.seq_len, feature_dim=sc.feature_dim,
                         has_two_hop=False)
        assert plan["supported"] and 32 % plan["block"] == 0

    def test_launch_accounting_collapse(self):
        mv = (True,) * len(MODEL_NAMES)
        acct = mega_launch_accounting(128, len(MODEL_NAMES), mega_valid=mv)
        assert acct["programs_mega"] == 1
        assert acct["launches_per_batch_mega"] == 1
        assert acct["programs_chain"] == len(MODEL_NAMES) + 2
        assert acct["launches_per_batch_chain"] > 1
        assert acct["intermediate_bytes_eliminated"] > 0


# ------------------------------------------------- interpret-mode parity
class TestMegakernelParity:
    def test_randomized_params_parity_f32(self, models_f32, blend_params):
        # f32 TINY exceeds the VMEM plan, so the block rides explicitly —
        # parity of the program itself is dtype-independent
        mv = (True,) * len(MODEL_NAMES)
        _assert_parity(models_f32, _batch(BATCH), blend_params, mv, block=8)

    def test_trained_quantized_params_parity(self, models_q, blend_params):
        sc = ScorerConfig()
        plan = mega_plan(models_q, TINY_CONFIG, b=32, text_len=sc.text_len,
                         seq_len=sc.seq_len, feature_dim=sc.feature_dim,
                         has_two_hop=False)
        assert plan["supported"]
        _assert_parity(models_q, _batch(32), blend_params,
                       (True,) * len(MODEL_NAMES), block=plan["block"])

    def test_bf16_staged_batch_parity(self, models_q, blend_params):
        # the bf16 wire format widens back to f32 before the kernel; the
        # fused program and the verbatim reference must agree on the SAME
        # rounded inputs — bit-level ladder agreement, not "close enough"
        staged = jax.tree.map(
            lambda x: (jnp.asarray(x, jnp.bfloat16).astype(jnp.float32)
                       if hasattr(x, "dtype") and x.dtype == jnp.float32
                       else x), _batch(BATCH))
        _assert_parity(models_q, staged, blend_params,
                       (True,) * len(MODEL_NAMES), block=8)

    def test_qos_rung_statics_parity(self, models_q, blend_params):
        batch = _batch(BATCH)
        for mv in ((True, False, False, True, True),
                   (False,) * len(MODEL_NAMES)):
            ref, got = _assert_parity(models_q, batch, blend_params, mv,
                                      block=8)
            # rules-only rung: probability IS the rule score, bit-exact
            if not any(mv):
                np.testing.assert_array_equal(got[:, 0], ref[:, 0])


# ------------------------------------------------------- scorer cascade
class TestScorerMegaPlane:
    def test_mega_site_modes_and_never_serialized_default(self):
        assert KernelSettings.mega().site_modes()["megakernel"] == "pallas"
        assert KernelSettings.full().site_modes()["megakernel"] == "off"

    def test_end_to_end_matches_kernels_off(self):
        gen_a, off = _scorer(mega=None)
        gen_b, mega = _scorer(mega=True)
        ra = off.score_batch(gen_a.generate_batch(BATCH), now=1000.0)
        rb = mega.score_batch(gen_b.generate_batch(BATCH), now=1000.0)
        assert [r["decision"] for r in ra] == [r["decision"] for r in rb]
        assert [r["risk_level"] for r in ra] == [r["risk_level"] for r in rb]
        pa = np.asarray([r["fraud_probability"] for r in ra])
        pb = np.asarray([r["fraud_probability"] for r in rb])
        assert np.max(np.abs(pa - pb)) < 1e-3
        snap = mega.kernel_snapshot()
        assert snap["dispatch"]["megakernel"] == 1
        assert all(v == 0 for site, v in snap["dispatch"].items()
                   if site != "megakernel")
        assert all(v == 0 for v in snap["fallback"].values())
        assert snap["launches_per_batch"] == 1

    def test_unsupported_bucket_honest_fallback(self):
        # single-device mesh so a 1-record batch stays in bucket 1 (the
        # harness's 8-virtual-device mesh would round it up to 8)
        gen, s = _scorer(mega=True, one_device=True)
        s.score_batch(gen.generate_batch(1), now=1000.0)  # bucket 1 < min
        snap = s.kernel_snapshot()
        assert snap["dispatch"]["megakernel"] == 1
        assert snap["fallback"]["megakernel"] == 1
        # the per-site chain took over — its counting proceeds honestly
        assert snap["dispatch"]["dequant_matmul"] == 1
        acct = mega_launch_accounting(
            1, len(MODEL_NAMES),
            mega_valid=tuple(bool(v) for v in s.effective_model_valid()))
        assert snap["launches_per_batch"] == \
            acct["launches_per_batch_chain"] > 1

    def test_zero_retrace_memoized_statics(self):
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            score_fused_packed,
        )

        gen, s = _scorer(mega=True)
        assert s.kernel_static() is s.kernel_static()
        assert s.quant_static() is s.quant_static()
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        compiled = score_fused_packed._cache_size()
        for _ in range(3):
            s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        assert score_fused_packed._cache_size() == compiled
        # per-rung program cache: each rung is its own static key...
        full = (True,) * len(MODEL_NAMES)
        rung = (True, False, True, True, True)
        assert s.kernel_static(full) is s.kernel_static(full)
        assert s.kernel_static(rung) is not s.kernel_static(full)
        assert s.kernel_static(rung)["mega_valid"] == rung

    def test_ladder_never_churns_cache_when_mega_off(self):
        # with the megakernel off, mega_valid stays None for every rung —
        # stepping the QoS ladder reuses ONE static dict (and program)
        _, s = _scorer(mega=False)
        full = (True,) * len(MODEL_NAMES)
        rung = (True, False, True, True, True)
        assert s.kernel_static(full) is s.kernel_static(rung)
        assert s.kernel_static(full)["mega_valid"] is None


# ------------------------------------------------------- checkpoint hygiene
class TestCheckpointMegaHygiene:
    def test_one_checkpoint_serves_mega_on_and_off(self, tmp_path):
        """Megakernel selection is runtime config: one checkpoint restores
        into a mega-on scorer AND a mega-off scorer, each keeps its own
        (unserialized) kernel selection, and both serve the same
        decisions."""
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        _, src = _scorer(mega=None, seed=0)
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(3, params=src.models)
        manifest = mgr.manifest(3)
        assert not any("kernel" in k or "mega" in k for k in manifest)

        gen_off, off = _scorer(mega=None, seed=9)
        gen_on, on = _scorer(mega=True, seed=9)
        assert mgr.restore_into_scorer(off).step == 3
        assert mgr.restore_into_scorer(on).step == 3
        assert off.kernel_static()["megakernel"] == "off"
        assert on.kernel_static()["megakernel"] == "pallas"
        ra = off.score_batch(gen_off.generate_batch(BATCH), now=1000.0)
        rb = on.score_batch(gen_on.generate_batch(BATCH), now=1000.0)
        assert [r["decision"] for r in ra] == [r["decision"] for r in rb]
        pa = np.asarray([r["fraud_probability"] for r in ra])
        pb = np.asarray([r["fraud_probability"] for r in rb])
        assert np.max(np.abs(pa - pb)) < 1e-3
        assert on.kernel_snapshot()["dispatch"]["megakernel"] == 1


# ------------------------------------------------- pool / mesh composition
class TestPoolMeshMegaComposition:
    @staticmethod
    def _pipelined(scorer, batches, swap_to=None):
        """Depth-2 pipelined drive with an optional mid-stream hot swap
        after the first finalize — the SAME interleaving on both sides so
        state evolution (and the swap point) matches step for step."""
        from collections import deque

        pend, got = deque(), []
        for i, b in enumerate(batches):
            pend.append(scorer.dispatch(b, now=1000.0))
            if len(pend) >= 2:
                got.append(_rows(scorer.finalize(pend.popleft(),
                                                 now=1000.0)))
                if i == 1 and swap_to is not None:
                    scorer.set_models(swap_to)
                    assert is_quantized_bert(scorer.models.bert)
        while pend:
            got.append(_rows(scorer.finalize(pend.popleft(), now=1000.0)))
        return got

    def _fresh_models(self, scorer):
        return init_scoring_models(jax.random.PRNGKey(42),
                                   bert_config=scorer.bert_config,
                                   feature_dim=scorer.sc.feature_dim,
                                   node_dim=scorer.sc.node_dim)

    def test_pool_mega_bit_identical_with_hot_swap(self):
        sides = []
        for use_pool in (False, True):
            gen, s = _scorer(mega=True)
            if use_pool:
                DevicePool(s, inflight_depth=2)
            batches = [gen.generate_batch(BATCH) for _ in range(4)]
            sides.append(self._pipelined(s, batches,
                                         swap_to=self._fresh_models(s)))
            snap = s.kernel_snapshot()
            assert snap["dispatch"]["megakernel"] == 4
            assert all(v == 0 for v in snap["fallback"].values())
            assert snap["launches_per_batch"] == 1
        assert sides[0] == sides[1]

    def test_mesh_mega_pipelined_depth2_with_hot_swap(self):
        gen_a, ref = _scorer(mega=True, one_device=True)
        want = self._pipelined(
            ref, [gen_a.generate_batch(BATCH) for _ in range(4)],
            swap_to=self._fresh_models(ref))

        gen_b, meshed = _scorer(mega=True, one_device=True)
        MeshExecutor(meshed, model_axis=2, inflight_depth=2,
                     shard_branches=("bert_text",))
        got = self._pipelined(
            meshed, [gen_b.generate_batch(BATCH) for _ in range(4)],
            swap_to=self._fresh_models(meshed))
        assert got == want
        snap = meshed.kernel_snapshot()
        assert snap["dispatch"]["megakernel"] == 4
        assert all(v == 0 for site, v in snap["dispatch"].items()
                   if site != "megakernel")
        assert all(v == 0 for v in snap["fallback"].values())


# ----------------------------------------------------------------- metrics
class TestMegaMetrics:
    def test_sync_kernels_mega_counters_and_gauge(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        gen, s = _scorer(mega=True, one_device=True)
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        m = MetricsCollector()
        snap = s.kernel_snapshot()
        m.sync_kernels(snap)
        m.sync_kernels(snap)   # delta mirror: same snapshot never recounts
        assert m.kernel_mega_dispatch.value() == 2.0
        assert m.kernel_mega_fallback.value() == 0.0
        assert m.kernel_launches_per_batch.value() == 1.0
        s.score_batch(gen.generate_batch(1), now=1000.0)  # mega fallback
        m.sync_kernels(s.kernel_snapshot())
        assert m.kernel_mega_dispatch.value() == 3.0
        assert m.kernel_mega_fallback.value() == 1.0
        assert m.kernel_launches_per_batch.value() > 1.0

    def test_stream_and_serving_render_identical(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        gen, s = _scorer(mega=True)
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        snap = s.kernel_snapshot()
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_kernels(snap)
        b.sync_kernels(snap)

        def mega_lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if "mega" in ln or "launches_per_batch" in ln]

        assert mega_lines(a) and mega_lines(a) == mega_lines(b)
        text = a.render_prometheus()
        assert "kernel_mega_dispatch_total 1" in text
        assert "kernel_mega_fallback_total 0" in text
        assert "kernel_launches_per_batch 1" in text


# ----------------------------------------------------------------- CLI
class TestCliMegaFlags:
    def test_parse_mega_flags(self):
        from realtime_fraud_detection_tpu.cli import build_parser

        p = build_parser()
        for cmd in ("run-job", "serve", "bench"):
            assert p.parse_args([cmd, "--mega"]).mega is True
            assert p.parse_args([cmd]).mega is False
        args = p.parse_args(["kernel-drill", "--fast", "--mega"])
        assert args.fast and args.mega


def test_kernel_drill_mega_fast_smoke():
    """Tier-1 acceptance: `rtfd kernel-drill --fast --mega` passes — the
    full kernel-plane gate PLUS the megakernel section: fused-vs-reference
    parity under the bf16 noise bound with zero ladder flips, GEMM-tree
    leaves exact against descend_complete_trees on the served params, the
    megakernel dispatched with every per-site counter subsumed, zero guard
    fallbacks, and launches-per-batch collapsed to one. Same subprocess
    convention as the non-mega smoke (single-device serving env)."""
    import os
    import pathlib
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    proc = subprocess.run(
        [sys.executable, "-m", "realtime_fraud_detection_tpu",
         "kernel-drill", "--fast", "--mega", "--no-replay"],
        capture_output=True, text=True, timeout=600,
        cwd=str(pathlib.Path(__file__).resolve().parents[1]), env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["mega_reference_parity"]
    assert checks["gemm_tree_leaves_exact"]
    assert checks["mega_dispatched"]
    assert checks["per_site_subsumed"]
    assert checks["launches_collapsed_to_one"]
    assert checks["zero_fallbacks"]
    assert checks["zero_decision_flips"]
    assert compact["mega"]["launches_per_batch"] == 1
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["mega"] is True
    assert full["divergence"]["decision_flips"] == 0

"""Windowed analytics tests (stream/windows.py vs WindowProcessor.java
semantics)."""

import math

import pytest

from realtime_fraud_detection_tpu.stream.windows import (
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    WindowedAnalytics,
    amount_bucket,
    amount_cluster_key,
    amount_cluster_windows,
    fraud_pattern_key,
    fraud_pattern_windows,
    geo_cluster_windows,
    geo_grid_key,
    high_frequency_windows,
    merchant_pattern_windows,
    user_session_windows,
    user_velocity_windows,
)


def txn(user="u1", merchant="m1", amount=50.0, fraud=False, score=0.0,
        payment="credit_card", category="retail", lat=37.5, lon=-122.3):
    return {
        "user_id": user, "merchant_id": merchant, "amount": amount,
        "is_fraud": fraud, "fraud_score": score, "payment_method": payment,
        "merchant_category": category,
        "geolocation": {"lat": lat, "lon": lon},
    }


class TestAssigners:
    def test_tumbling(self):
        assert TumblingWindow(300.0).assign(601.0) == [(600.0, 900.0)]

    def test_sliding_covers_event(self):
        wins = SlidingWindow(300.0, 60.0).assign(301.0)
        assert len(wins) == 5                      # size/slide overlapping
        for s, e in wins:
            assert s <= 301.0 < e
            assert e - s == 300.0

    def test_session_is_point_window(self):
        assert SessionWindow(1800.0).assign(10.0) == [(10.0, 1810.0)]


class TestKeySelectors:
    def test_geo_grid(self):
        assert geo_grid_key(txn(lat=37.7, lon=-122.4)) == "geo_37_-123"
        assert geo_grid_key({"geolocation": {}}) == "unknown"
        assert geo_grid_key({}) == "unknown"

    def test_amount_buckets(self):
        # FraudPatternKeySelector.getAmountBucket thresholds
        assert amount_bucket(5) == "micro"
        assert amount_bucket(50) == "small"
        assert amount_bucket(400) == "medium"
        assert amount_bucket(1500) == "large"
        assert amount_bucket(9500) == "very_large"
        assert amount_bucket(20_000) == "extreme"

    def test_fraud_pattern_key(self):
        k = fraud_pattern_key(txn(amount=250.0))
        assert k == "pattern_credit_card_retail_medium"

    def test_amount_cluster_key_log_buckets(self):
        assert amount_cluster_key({"amount": 0.0}) == "zero"
        assert amount_cluster_key({"amount": 9500.0}) == "amount_3_9"
        assert amount_cluster_key({"amount": 42.0}) == "amount_1_4"


class TestUserVelocity:
    def test_aggregate_fields(self):
        op = user_velocity_windows()
        t0 = 1000 * 60.0                           # minute-aligned
        for i in range(6):
            op.process(txn(amount=100.0, merchant=f"m{i}"), t0 + i)
        # watermark far past: all 5 sliding windows close
        results = op.advance_watermark(t0 + 400.0)
        assert results
        r = max(results, key=lambda r: r["transaction_count"])
        assert r["user_id"] == "u1"
        assert r["transaction_count"] == 6
        assert r["total_amount"] == pytest.approx(600.0)
        assert r["unique_merchant_count"] == 6
        assert r["fraud_rate"] == 0.0
        # 6 txns (>5) -> 0.1; amounts 600 < 1000 -> 0; diversity 1.0 -> 0
        assert r["velocity_score"] == pytest.approx(0.1)

    def test_velocity_score_factors(self):
        """WindowProcessor.java:328-351: counts, amounts, fraud rate,
        low merchant diversity."""
        op = user_velocity_windows()
        t0 = 0.0
        for i in range(21):                        # >20 txns, one merchant
            op.process(txn(amount=600.0, fraud=(i < 7)), t0 + i)
        r = max(op.advance_watermark(t0 + 400.0),
                key=lambda r: r["transaction_count"])
        # 0.4 (count>20) + 0.3 (amount>10k) + 7/21*0.4 + 0.2 (diversity<0.2)
        assert r["velocity_score"] == pytest.approx(
            min(1.0, 0.4 + 0.3 + (7 / 21) * 0.4 + 0.2))


class TestMerchantPatterns:
    def test_std_dev_matches_population(self):
        import numpy as np

        op = merchant_pattern_windows()
        amounts = [10.0, 20.0, 30.0, 100.0, 5.0]
        for i, a in enumerate(amounts):
            op.process(txn(amount=a, user=f"u{i}"), 100.0 + i)
        (r,) = op.advance_watermark(100.0 + 3600.0 + 20.0)
        assert r["merchant_id"] == "m1"
        assert r["amount_std_dev"] == pytest.approx(np.std(amounts))
        assert r["unique_user_count"] == 5

    def test_risk_score_low_user_diversity(self):
        op = merchant_pattern_windows()
        for i in range(30):                        # one user hammering
            op.process(txn(user="u1", amount=10.0), 50.0 + i)
        (r,) = op.advance_watermark(7300.0)
        assert r["risk_score"] == pytest.approx(0.3)   # diversity < 0.1

    def test_welford_merge(self):
        """Chan's merge must equal single-pass accumulation."""
        import numpy as np

        from realtime_fraud_detection_tpu.stream.windows import (
            MerchantPatternAggregate,
        )

        agg = MerchantPatternAggregate()
        a, b = agg.create(), agg.create()
        xs = [3.0, 7.0, 1.0, 9.0]
        ys = [100.0, 2.0, 5.0]
        for i, x in enumerate(xs):
            agg.add(a, txn(amount=x), float(i))
        for i, y in enumerate(ys):
            agg.add(b, txn(amount=y), float(i))
        merged = agg.merge(a, b)
        r = agg.result(merged, "m1", (0.0, 3600.0))
        assert r["amount_std_dev"] == pytest.approx(np.std(xs + ys))


class TestSessions:
    def test_session_merges_on_gap(self):
        op = user_session_windows()
        fired = []
        fired += op.process(txn(amount=10.0), 0.0)
        fired += op.process(txn(amount=20.0), 60.0)  # same session (<30m gap)
        # >30m later: new session; watermark passing closes the first
        fired += op.process(txn(amount=30.0), 5000.0)
        assert len(op) == 1
        assert len(fired) == 1
        assert fired[0]["transaction_count"] == 2
        assert fired[0]["session_duration_s"] == pytest.approx(60.0)
        (second,) = op.flush()
        assert second["transaction_count"] == 1

    def test_bridge_event_merges_two_sessions(self):
        from realtime_fraud_detection_tpu.stream.windows import (
            SessionWindow,
            UserSessionAggregate,
            WindowOperator,
        )

        # huge out-of-orderness so out-of-order arrival exercises the merge
        op = WindowOperator(
            "s", lambda t: str(t.get("user_id")), SessionWindow(1800.0),
            UserSessionAggregate(), out_of_orderness_s=1e6)
        op.process(txn(), 0.0)
        op.process(txn(), 3000.0)                  # separate session
        assert len(op) == 2
        op.process(txn(), 1600.0)                  # bridges both (gap 1800)
        assert len(op) == 1
        (r,) = op.flush()
        assert r["transaction_count"] == 3


class TestHighFrequency:
    def test_count_trigger_fires_early(self):
        op = high_frequency_windows(trigger_count=10)
        fired = []
        for i in range(25):
            fired.extend(op.process(txn(), 10.0 + i * 0.1))
        # two early fires at counts 10 and 20, window still open
        assert len(fired) == 2
        assert fired[0]["transaction_count"] == 10
        assert fired[1]["transaction_count"] == 20
        assert fired[0]["alert_type"] == "HIGH_FREQUENCY"
        assert fired[1]["transactions_per_second"] > 1.0


class TestWatermarks:
    def test_late_event_dropped_only_when_all_windows_closed(self):
        op = geo_cluster_windows()                 # tumbling 15m, ooo 10s
        op.process(txn(), 1000.0)
        # event in a closed window (watermark = max_ts - 10)
        op.process(txn(), 5000.0)                  # advances watermark
        fired = op.process(txn(), 100.0 - 900.0)   # far in the past
        assert op.late_dropped == 1
        assert all(r["window_end"] <= op.watermark for r in fired)

    def test_slightly_late_event_still_counts(self):
        op = geo_cluster_windows()
        op.process(txn(), 900.0 + 100.0)           # window (900, 1800)
        op.process(txn(), 900.0 + 105.0)
        op.process(txn(), 900.0 + 98.0)            # behind max_ts, in window
        assert op.late_dropped == 0
        (r,) = op.advance_watermark(3000.0)
        assert r["transaction_count"] == 3


class TestComposite:
    def test_all_seven_operators_fire(self):
        from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker

        broker = InMemoryBroker()
        analytics = WindowedAnalytics(broker)
        t0 = 0.0
        for i in range(200):
            analytics.process(
                txn(user=f"u{i % 5}", merchant=f"m{i % 3}",
                    amount=10.0 + (i % 7) * 300.0), t0 + i * 30.0)
        out = analytics.flush()
        names = set(out)
        assert {"user_velocity", "merchant_patterns", "user_sessions",
                "geo_clusters", "fraud_patterns", "high_frequency",
                "amount_clusters"} <= names | set(analytics.stats())
        # results actually landed on the stream-processing topics
        vel = broker.consumer(["velocity-checks"], "t").poll(10_000)
        assert vel
        stats = analytics.stats()
        assert stats["user_velocity"]["fired"] > 0


class TestJobIntegration:
    def test_stream_job_feeds_analytics(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        gen = TransactionGenerator(num_users=20, num_merchants=10, seed=5,
                                   tps=2.0)
        broker = InMemoryBroker()
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(broker, scorer,
                        JobConfig(max_batch=64, enable_analytics=True))
        records = gen.generate_batch(120)          # 60s of simulated traffic
        broker.produce_batch(T.TRANSACTIONS, records,
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 120
        job.analytics.flush()
        stats = job.analytics.stats()
        assert stats["user_velocity"]["fired"] > 0
        assert stats["merchant_patterns"]["fired"] > 0

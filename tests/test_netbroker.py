"""Transport contract suite: InMemoryBroker vs networked BrokerServer.

The same assertions run against both transports — the contract (keyed
partition ordering, committed offsets, group replay, snapshot commits, lag)
is what StreamJob depends on, so any future backend (Kafka adapter included)
must pass this file unchanged.
"""

import os
import signal
import subprocess
import sys

import pytest

from realtime_fraud_detection_tpu.stream import InMemoryBroker
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.netbroker import (
    BrokerServer,
    HaBrokerClient,
    NetBrokerClient,
)


@pytest.fixture(params=["memory", "net"])
def any_broker(request, tmp_path):
    if request.param == "memory":
        yield InMemoryBroker()
        return
    server = BrokerServer(port=0).start()
    client = NetBrokerClient(port=server.port)
    try:
        yield client
    finally:
        client.close()
        server.stop()


def test_contract_keyed_ordering(any_broker):
    b = any_broker
    for i in range(20):
        b.produce(T.TRANSACTIONS, {"n": i}, key="user_7")
    c = b.consumer([T.TRANSACTIONS], "g1")
    recs = c.poll(100)
    assert [r.value["n"] for r in recs] == list(range(20))
    assert len({r.partition for r in recs}) == 1


def test_contract_commit_replay(any_broker):
    b = any_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(4)) == 4
    # crash without commit: a new consumer in the group re-reads everything
    c2 = b.consumer([T.TRANSACTIONS], "g")
    assert len(c2.poll(100)) == 10
    c2.commit()
    assert b.consumer([T.TRANSACTIONS], "g").poll(100) == []
    assert b.lag("g", T.TRANSACTIONS) == 0


def test_contract_snapshot_commit(any_broker):
    """commit(offsets) covers exactly the snapshot, not later polls."""
    b = any_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    first = c.poll(6)
    snap = c.snapshot_positions()
    second = c.poll(10)
    assert len(first) == 6 and len(second) == 4
    c.commit(snap)
    assert b.lag("g", T.TRANSACTIONS) == 4


def test_contract_produce_batch_and_end_offsets(any_broker):
    b = any_broker
    n = b.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(24)],
                        key_fn=lambda v: str(v["n"] % 5))
    assert n == 24
    assert sum(b.end_offsets(T.TRANSACTIONS)) == 24


def test_netbroker_durability(tmp_path):
    """Kill the server; a fresh server over the same log_dir serves the
    records and committed offsets (the Kafka-log durability analog)."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client = NetBrokerClient(port=server.port)
    client.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(12)],
                         key_fn=lambda v: str(v["n"] % 3))
    c = client.consumer([T.TRANSACTIONS], "g")
    got = c.poll(7)
    # commit exactly what we read so far
    c.commit()
    client.close()
    server.stop()

    server2 = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client2 = NetBrokerClient(port=server2.port)
    try:
        assert sum(client2.end_offsets(T.TRANSACTIONS)) == 12
        c2 = client2.consumer([T.TRANSACTIONS], "g")
        rest = c2.poll(100)
        ids_before = {(r.partition, r.offset) for r in got}
        ids_after = {(r.partition, r.offset) for r in rest}
        assert not ids_before & ids_after          # no double delivery
        assert len(got) + len(rest) == 12          # nothing lost
    finally:
        client2.close()
        server2.stop()


def test_consumer_resumes_from_committed_after_broker_restart(tmp_path):
    """Chaos satellite regression: a NetBrokerClient that reconnects after
    a broker RESTART must re-fetch from the last COMMITTED offset, not its
    in-memory cursor — records polled-but-uncommitted at the moment of the
    outage are re-delivered (and deduped downstream by txn id), never
    silently skipped past by a later commit."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    port = server.port
    waits = []          # injected backoff seam: no wall sleeps in the test
    client = NetBrokerClient(port=port, reconnect_attempts=8,
                             retry_sleep=waits.append)
    try:
        client.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(30)],
                             key_fn=lambda v: str(v["n"]))
        c = client.consumer([T.TRANSACTIONS], "g")
        first = c.poll(10)
        c.commit()                       # committed: the recovery anchor
        mid = c.poll(10)                 # polled but NOT committed
        assert len(first) == len(mid) == 10

        # broker dies and RESTARTS from its WAL on the same address
        server.stop()
        server = BrokerServer(port=port, log_dir=str(log_dir)).start()

        # next poll rides the reconnect: the client rewinds to committed,
        # so the uncommitted middle slice is DELIVERED AGAIN
        rest = []
        deadline = 50
        while len(rest) < 20 and deadline > 0:
            rest.extend(c.poll(100))
            deadline -= 1
        slots_mid = {(r.partition, r.offset) for r in mid}
        slots_rest = {(r.partition, r.offset) for r in rest}
        assert slots_mid <= slots_rest           # re-delivered, not skipped
        assert waits                             # the backoff seam was hit
        # nothing lost and nothing committed re-read: first∪rest covers all
        slots_first = {(r.partition, r.offset) for r in first}
        assert not slots_first & slots_rest
        assert len(slots_first | slots_rest) == 30
        vals = [r.value["n"] for r in first + rest]
        assert set(vals) == set(range(30))
        # committing now accounts for every offset — gap-free
        c.commit()
        ends = client.end_offsets(T.TRANSACTIONS)
        assert [client.committed("g", T.TRANSACTIONS, p)
                for p in range(len(ends))] == ends
    finally:
        client.close()
        server.stop()


def test_every_sharing_consumer_rewinds_after_reconnect(tmp_path):
    """Epoch regression pin: TWO consumers share ONE NetBrokerClient (the
    StreamJob shape — transactions + labels consumers on the job's
    client). After a broker restart, BOTH must rewind to committed — a
    read-and-clear flag would rewind only the first to poll and leave the
    second with a stale cursor over re-delivered records."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    port = server.port
    client = NetBrokerClient(port=port, reconnect_attempts=8,
                             retry_sleep=lambda d: None)
    try:
        client.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(8)],
                             key_fn=lambda v: str(v["n"]))
        client.produce_batch(T.LABELS, [{"m": i} for i in range(8)],
                             key_fn=lambda v: str(v["m"]))
        c_txn = client.consumer([T.TRANSACTIONS], "g-txn")
        c_lbl = client.consumer([T.LABELS], "g-lbl")
        a = c_txn.poll(100)
        b = c_lbl.poll(100)
        assert len(a) == 8 and len(b) == 8     # polled, NOT committed

        server.stop()
        server = BrokerServer(port=port, log_dir=str(log_dir)).start()

        # c_txn polls first and rides the reconnect; c_lbl polls SECOND —
        # the epoch (not a consumed flag) must still rewind it
        a2, b2 = [], []
        for _ in range(10):
            a2.extend(c_txn.poll(100))
            b2.extend(c_lbl.poll(100))
            if len(a2) >= 8 and len(b2) >= 8:
                break
        assert {(r.partition, r.offset) for r in a} \
            == {(r.partition, r.offset) for r in a2}
        assert {(r.partition, r.offset) for r in b} \
            == {(r.partition, r.offset) for r in b2}
    finally:
        client.close()
        server.stop()


def test_stream_job_over_netbroker():
    """The full scoring job runs unchanged against the networked broker."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.stream import JobConfig, StreamJob

    server = BrokerServer(port=0).start()
    client = NetBrokerClient(port=server.port)
    try:
        gen = TransactionGenerator(num_users=30, num_merchants=12, seed=23)
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(client, scorer, JobConfig(max_batch=16,
                                                  max_delay_ms=1.0))
        client.produce_batch(T.TRANSACTIONS, gen.generate_batch(40),
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 40
        preds = client.consumer([T.PREDICTIONS], "check").poll(1000)
        assert len(preds) == 40
        assert client.lag(job.config.group_id, T.TRANSACTIONS) == 0
    finally:
        client.close()
        server.stop()


def test_netbroker_keyed_routing_stable_across_restart(tmp_path):
    """Per-key ordering across a broker restart: records produced for a key
    AFTER the WAL replay must land on the same partition as the key's
    records from before the restart (the crc32-partitioner contract — a
    salted hash() would scatter them and break per-key ordering)."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client = NetBrokerClient(port=server.port)
    keys = [f"user_{i}" for i in range(10)]
    before = {k: client.produce(T.TRANSACTIONS, {"k": k}, key=k).partition
              for k in keys}
    client.close()
    server.stop()

    server2 = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client2 = NetBrokerClient(port=server2.port)
    try:
        after = {k: client2.produce(T.TRANSACTIONS, {"k": k},
                                    key=k).partition
                 for k in keys}
        assert after == before
        # and per-key order is intact end to end
        c = client2.consumer([T.TRANSACTIONS], "g-stable")
        recs = c.poll(1000)
        per_key = {}
        for r in recs:
            per_key.setdefault(r.key, []).append(r.offset)
        for k, offs in per_key.items():
            assert offs == sorted(offs), f"key {k} out of order"
    finally:
        client2.close()
        server2.stop()


# ---------------------------------------------------------------------------
# replication / failover (reference runs RF=3 minISR=2 — create-topics.sh:9-12)
# ---------------------------------------------------------------------------


class TestReplication:
    def test_sync_replication_and_offset_forwarding(self):
        """Every acked produce and every commit is on the replica before the
        client's call returns (min_isr=2 = self + one replica)."""
        replica = BrokerServer(port=0, role="replica").start()
        primary = BrokerServer(port=0, min_isr=2).start()
        primary.add_replica("127.0.0.1", replica.port)
        client = NetBrokerClient(port=primary.port)
        rclient = NetBrokerClient(port=replica.port)
        try:
            for i in range(40):
                client.produce(T.TRANSACTIONS, {"n": i}, key=f"u{i % 7}")
            assert (sum(rclient.end_offsets(T.TRANSACTIONS))
                    == sum(client.end_offsets(T.TRANSACTIONS)) == 40)
            # replica holds identical records at identical offsets
            for p in range(rclient.partitions(T.TRANSACTIONS)):
                prim = client.read(T.TRANSACTIONS, p, 0, 100)
                rep = rclient.read(T.TRANSACTIONS, p, 0, 100)
                assert [(r.offset, r.key, r.value) for r in prim] == \
                       [(r.offset, r.key, r.value) for r in rep]
            # offset commits ride the shipping lane too
            c = client.consumer([T.TRANSACTIONS], "g-rep")
            c.poll(25)
            c.commit()
            for p in range(client.partitions(T.TRANSACTIONS)):
                assert (rclient.committed("g-rep", T.TRANSACTIONS, p)
                        == client.committed("g-rep", T.TRANSACTIONS, p))
        finally:
            client.close()
            rclient.close()
            primary.stop()
            replica.stop()

    def test_replica_is_readonly_until_promoted(self):
        replica = BrokerServer(port=0, role="replica").start()
        rclient = NetBrokerClient(port=replica.port)
        try:
            with pytest.raises(RuntimeError, match="READONLY"):
                rclient.produce(T.TRANSACTIONS, {"n": 1}, key="k")
            with pytest.raises(RuntimeError, match="READONLY"):
                rclient.commit("g", {(T.TRANSACTIONS, 0): 1})
            assert rclient.status()["role"] == "replica"
            rclient.promote()
            assert rclient.status()["role"] == "primary"
            rclient.produce(T.TRANSACTIONS, {"n": 1}, key="k")
            assert sum(rclient.end_offsets(T.TRANSACTIONS)) == 1
        finally:
            rclient.close()
            replica.stop()

    def test_min_isr_gates_the_ack(self):
        """min_isr=2 with no replica: produce FAILS (NotEnoughReplicas)
        rather than pretending durability; attaching a replica heals it;
        losing the replica breaks it again (ISR shrink)."""
        primary = BrokerServer(port=0, min_isr=2).start()
        client = NetBrokerClient(port=primary.port)
        replica = BrokerServer(port=0, role="replica").start()
        try:
            with pytest.raises(RuntimeError, match="NotEnoughReplicas"):
                client.produce(T.TRANSACTIONS, {"n": 0}, key="k")
            primary.add_replica("127.0.0.1", replica.port)
            client.produce(T.TRANSACTIONS, {"n": 1}, key="k")
            assert primary.isr_size() == 2
            replica.stop()
            with pytest.raises(RuntimeError, match="NotEnoughReplicas"):
                client.produce(T.TRANSACTIONS, {"n": 2}, key="k")
            assert primary.isr_size() == 1
        finally:
            client.close()
            primary.stop()

    def test_unacked_records_invisible_until_replicated(self):
        """Read-committed regression (ADVICE r5): a produce that fails
        min-ISR replication must NOT surface to consumers — the record
        sits above the high watermark until a later backlog sync makes it
        min_isr-replicated, at which point it becomes visible (at-least-
        once, never read-uncommitted)."""
        primary = BrokerServer(port=0, min_isr=2).start()
        client = NetBrokerClient(port=primary.port)
        replica = BrokerServer(port=0, role="replica").start()
        try:
            primary.add_replica("127.0.0.1", replica.port)
            client.produce(T.TRANSACTIONS, {"n": "acked"}, key="k")
            consumer = client.consumer([T.TRANSACTIONS], "g-hw")
            assert [r.value["n"] for r in consumer.poll(10)] == ["acked"]
            consumer.commit()

            replica.stop()
            with pytest.raises(RuntimeError, match="NotEnoughReplicas"):
                client.produce(T.TRANSACTIONS, {"n": "unacked"}, key="k")
            # the failed record is on the primary's log but must be
            # invisible: no fetch results, no phantom lag to spin on
            assert consumer.poll(10) == []
            assert client.lag("g-hw", T.TRANSACTIONS) == 0

            # a fresh replica re-syncs the backlog -> the tail is now on
            # min_isr copies and becomes visible (at-least-once)
            replica2 = BrokerServer(port=0, role="replica").start()
            try:
                primary.add_replica("127.0.0.1", replica2.port)
                assert [r.value["n"] for r in consumer.poll(10)] == \
                    ["unacked"]
            finally:
                replica2.stop()
        finally:
            client.close()
            primary.stop()

    def test_replica_reads_follow_primary_watermark(self):
        """A replica that APPLIED a record whose produce still failed
        min-ISR (min_isr=3, one replica short) must not serve it to
        readers — its visible end follows the primary's shipped watermark,
        not its own log end. promote() then commits the tail (the Kafka
        leader-election retroactive commit), making it readable."""
        primary = BrokerServer(port=0, min_isr=3).start()
        replica = BrokerServer(port=0, role="replica").start()
        client = NetBrokerClient(port=primary.port)
        rclient = NetBrokerClient(port=replica.port)
        try:
            primary.add_replica("127.0.0.1", replica.port)
            with pytest.raises(RuntimeError, match="NotEnoughReplicas"):
                client.produce(T.TRANSACTIONS, {"n": "partial"}, key="k")
            # the record IS on the replica's log (it applied the ship) ...
            assert sum(replica.broker.end_offsets(T.TRANSACTIONS)) == 1
            # ... but neither side serves it to a consumer
            assert rclient.consumer([T.TRANSACTIONS], "g-a").poll(10) == []
            assert client.consumer([T.TRANSACTIONS], "g-b").poll(10) == []
            replica.promote()
            assert [r.value["n"] for r in
                    rclient.consumer([T.TRANSACTIONS], "g-c").poll(10)] == \
                ["partial"]
        finally:
            client.close()
            rclient.close()
            primary.stop()
            replica.stop()

    def test_unacked_tail_stays_invisible_across_restart(self, tmp_path):
        """The watermark pin survives a primary restart: the WAL holds the
        replication-failed record (written before replication), so replay
        must re-pin it invisible rather than serve it (code-review r6
        finding — in-memory-only HW re-exposed the tail)."""
        log_dir = str(tmp_path / "wal")
        primary = BrokerServer(port=0, min_isr=2, log_dir=log_dir).start()
        client = NetBrokerClient(port=primary.port)
        replica = BrokerServer(port=0, role="replica").start()
        try:
            primary.add_replica("127.0.0.1", replica.port)
            client.produce(T.TRANSACTIONS, {"n": "acked"}, key="k")
            replica.stop()
            with pytest.raises(RuntimeError, match="NotEnoughReplicas"):
                client.produce(T.TRANSACTIONS, {"n": "unacked"}, key="k")
        finally:
            client.close()
            primary.stop()

        restarted = BrokerServer(port=0, min_isr=2, log_dir=log_dir).start()
        client = NetBrokerClient(port=restarted.port)
        try:
            consumer = client.consumer([T.TRANSACTIONS], "g-restart")
            # the WAL replayed BOTH records, but only the acked one is
            # visible: the pin persisted across the restart
            assert [r.value["n"] for r in consumer.poll(10)] == ["acked"]
            assert client.lag("g-restart", T.TRANSACTIONS) == 1
            # a replica re-sync makes the tail min_isr-replicated again
            replica2 = BrokerServer(port=0, role="replica").start()
            try:
                restarted.add_replica("127.0.0.1", replica2.port)
                assert [r.value["n"] for r in consumer.poll(10)] == \
                    ["unacked"]
            finally:
                replica2.stop()
        finally:
            client.close()
            restarted.stop()

    def test_late_replica_catches_up_backlog(self):
        """add_replica on a primary with history pushes the whole backlog +
        group offsets before admitting the replica to the ISR."""
        primary = BrokerServer(port=0).start()
        client = NetBrokerClient(port=primary.port)
        for i in range(120):
            client.produce(T.TRANSACTIONS, {"n": i}, key=f"u{i % 11}")
        c = client.consumer([T.TRANSACTIONS], "g-late")
        c.poll(60)
        c.commit()

        replica = BrokerServer(port=0, role="replica").start()
        rclient = NetBrokerClient(port=replica.port)
        try:
            primary.add_replica("127.0.0.1", replica.port)
            assert sum(rclient.end_offsets(T.TRANSACTIONS)) == 120
            for p in range(client.partitions(T.TRANSACTIONS)):
                assert (rclient.committed("g-late", T.TRANSACTIONS, p)
                        == client.committed("g-late", T.TRANSACTIONS, p))
            # and it is IN the ISR: the next produce lands on it too
            client.produce(T.TRANSACTIONS, {"n": 120}, key="u0")
            assert sum(rclient.end_offsets(T.TRANSACTIONS)) == 121
        finally:
            client.close()
            rclient.close()
            primary.stop()
            replica.stop()


_PRIMARY_SCRIPT = """
import sys, time
from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer
log_dir, replica_port = sys.argv[1], int(sys.argv[2])
s = BrokerServer(port=0, log_dir=log_dir, min_isr=2).start()
s.add_replica("127.0.0.1", replica_port)
print(s.port, flush=True)
while True:
    time.sleep(1)
"""


class TestKillThePrimary:
    def test_sigkill_primary_no_acked_record_lost(self, tmp_path):
        """The drill the state tier already passes (resp.py), now for the
        data plane: run the primary in a real OS process with min_isr=2,
        SIGKILL it mid-traffic, promote the replica, and prove every acked
        record and committed offset survives on the promoted node."""
        replica = BrokerServer(port=0, role="replica",
                               log_dir=str(tmp_path / "replica-wal")).start()
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        proc = subprocess.Popen(
            [sys.executable, "-c", _PRIMARY_SCRIPT,
             str(tmp_path / "primary-wal"), str(replica.port)],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            line = proc.stdout.readline().strip()
            assert line, "primary subprocess died before reporting its port"
            primary_port = int(line)

            client = HaBrokerClient([("127.0.0.1", primary_port),
                                     ("127.0.0.1", replica.port)])
            acked = []
            for i in range(300):
                client.produce(T.TRANSACTIONS, {"n": i}, key=f"u{i % 13}")
                acked.append(i)   # appended only after the min_isr=2 ack
            c = client.consumer([T.TRANSACTIONS], "g-kill")
            seen_before = len(c.poll(150))
            c.commit()
            committed_before = {
                p: client.committed("g-kill", T.TRANSACTIONS, p)
                for p in range(client.partitions(T.TRANSACTIONS))
            }
            assert seen_before == 150

            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            replica.promote()

            # the SAME client keeps working: rotates to the promoted node
            for i in range(300, 350):
                client.produce(T.TRANSACTIONS, {"n": i}, key=f"u{i % 13}")
                acked.append(i)

            # every acked record is present on the survivor
            survivor = NetBrokerClient(port=replica.port)
            try:
                present = set()
                for p in range(survivor.partitions(T.TRANSACTIONS)):
                    for r in survivor.read(T.TRANSACTIONS, p, 0, 10_000):
                        present.add(r.value["n"])
                missing = [n for n in acked if n not in present]
                assert not missing, f"acked records lost: {missing[:10]}"
                # committed group offsets survived the failover
                for p, off in committed_before.items():
                    assert survivor.committed("g-kill", T.TRANSACTIONS,
                                              p) == off
                # and the group resumes past the committed offsets: together
                # with the pre-kill reads it covers every acked record
                c2 = survivor.consumer([T.TRANSACTIONS], "g-kill")
                rest = c2.poll(10_000)
                assert len(rest) + seen_before >= len(acked)
            finally:
                survivor.close()
            client.close()
        finally:
            if proc.poll() is None:
                proc.kill()
            replica.stop()


class TestReplicationStress:
    def test_concurrent_producers_with_sync_replication(self):
        """4 producer threads + a committing consumer against a min_isr=2
        pair: the replication lane (io_lock-serialized WAL + ship) must
        neither deadlock nor diverge — replica ends with byte-identical
        per-partition logs."""
        import threading

        replica = BrokerServer(port=0, role="replica").start()
        primary = BrokerServer(port=0, min_isr=2).start()
        primary.add_replica("127.0.0.1", replica.port)
        n_threads, per_thread = 4, 150
        errors: list = []

        def produce(tid: int) -> None:
            client = NetBrokerClient(port=primary.port)
            try:
                for i in range(per_thread):
                    client.produce(T.TRANSACTIONS,
                                   {"t": tid, "n": i}, key=f"u{(tid * 7 + i) % 23}")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                client.close()

        consumer_client = NetBrokerClient(port=primary.port)
        stop = threading.Event()

        def consume() -> None:
            c = consumer_client.consumer([T.TRANSACTIONS], "stress-g")
            try:
                while not stop.is_set():
                    if c.poll(200):
                        c.commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=produce, args=(t,))
                   for t in range(n_threads)]
        ct = threading.Thread(target=consume)
        ct.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        ct.join(timeout=30)
        assert not errors, errors[:3]

        pclient = NetBrokerClient(port=primary.port)
        rclient = NetBrokerClient(port=replica.port)
        try:
            total = n_threads * per_thread
            assert sum(pclient.end_offsets(T.TRANSACTIONS)) == total
            assert sum(rclient.end_offsets(T.TRANSACTIONS)) == total
            for p in range(pclient.partitions(T.TRANSACTIONS)):
                prim = pclient.read(T.TRANSACTIONS, p, 0, total)
                rep = rclient.read(T.TRANSACTIONS, p, 0, total)
                assert [(r.offset, r.key, r.value) for r in prim] == \
                       [(r.offset, r.key, r.value) for r in rep]
        finally:
            pclient.close()
            rclient.close()
            consumer_client.close()
            primary.stop()
            replica.stop()


class TestSocketHangHardening:
    """ISSUE 13 satellite: every blocking client read carries a deadline,
    so a hung-not-dead peer can never wedge a worker forever."""

    BROKER_CHILD = (
        "import signal\n"
        "from realtime_fraud_detection_tpu.stream.netbroker import "
        "BrokerServer\n"
        "srv = BrokerServer(port=0).start()\n"
        "print(srv.port, flush=True)\n"
        "signal.pause()\n"
    )

    def test_sigstop_broker_bounded_error_then_resume_on_sigcont(self):
        """SIGSTOP a REAL broker process: the client errors out within
        the deadline x retry budget (recording its DeterministicBackoff
        sleeps on the way), then resumes cleanly on SIGCONT."""
        import time as _time

        proc = subprocess.Popen(
            [sys.executable, "-c", self.BROKER_CHILD],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            port = int(proc.stdout.readline())
            cli = NetBrokerClient(port=port, timeout_s=1.0,
                                  reconnect_attempts=2,
                                  retry_sleep=lambda s: None)
            cli.produce(T.TRANSACTIONS, {"v": 0}, key="k")   # healthy
            os.kill(proc.pid, signal.SIGSTOP)
            t0 = _time.monotonic()
            with pytest.raises((ConnectionError, OSError)):
                # a stopped process still completes TCP handshakes (the
                # kernel backlog accepts), so every retry reconnects
                # "successfully" and then times out on the frame read —
                # the absolute deadline bounds each attempt
                cli.produce(T.TRANSACTIONS, {"v": 1}, key="k")
            elapsed = _time.monotonic() - t0
            # 3 attempts x 1.0 s deadline + slack (backoff sleeps are
            # recorded, not slept)
            assert elapsed < 8.0, f"wedged for {elapsed:.1f}s"
            assert len(cli._backoff.slept) >= 1, \
                "client never entered its DeterministicBackoff"
            os.kill(proc.pid, signal.SIGCONT)
            deadline = _time.monotonic() + 15
            while True:
                try:
                    cli.produce(T.TRANSACTIONS, {"v": 2}, key="k")
                    break
                except (ConnectionError, OSError):
                    if _time.monotonic() > deadline:
                        raise
            cli.close()
        finally:
            proc.kill()
            proc.wait(timeout=10)

    def test_trickling_peer_hits_absolute_deadline(self):
        """A peer that trickles bytes slower than the frame but faster
        than the per-recv timeout used to reset the clock forever; the
        absolute whole-frame deadline bounds it."""
        import socket as _socket
        import threading as _threading
        import time as _time

        srv = _socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        stop = _threading.Event()

        def _trickle():
            conn, _ = srv.accept()
            try:
                conn.recv(65536)                     # swallow the request
                # claim a 1000-byte frame, then trickle 1 byte / 0.25 s —
                # each byte lands well inside a naive per-recv timeout
                conn.sendall((1000).to_bytes(4, "big"))
                while not stop.is_set():
                    try:
                        conn.sendall(b"x")
                    except OSError:
                        return
                    _time.sleep(0.25)
            finally:
                conn.close()

        t = _threading.Thread(target=_trickle, daemon=True)
        t.start()
        try:
            cli = NetBrokerClient(port=port, timeout_s=1.0,
                                  reconnect_attempts=0,
                                  retry_sleep=lambda s: None)
            t0 = _time.monotonic()
            with pytest.raises((ConnectionError, OSError)):
                cli.ping()
            elapsed = _time.monotonic() - t0
            assert elapsed < 4.0, \
                f"trickling peer held the client {elapsed:.1f}s"
            cli.close()
        finally:
            stop.set()
            srv.close()

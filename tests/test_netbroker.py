"""Transport contract suite: InMemoryBroker vs networked BrokerServer.

The same assertions run against both transports — the contract (keyed
partition ordering, committed offsets, group replay, snapshot commits, lag)
is what StreamJob depends on, so any future backend (Kafka adapter included)
must pass this file unchanged.
"""

import pytest

from realtime_fraud_detection_tpu.stream import InMemoryBroker
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.netbroker import (
    BrokerServer,
    NetBrokerClient,
)


@pytest.fixture(params=["memory", "net"])
def any_broker(request, tmp_path):
    if request.param == "memory":
        yield InMemoryBroker()
        return
    server = BrokerServer(port=0).start()
    client = NetBrokerClient(port=server.port)
    try:
        yield client
    finally:
        client.close()
        server.stop()


def test_contract_keyed_ordering(any_broker):
    b = any_broker
    for i in range(20):
        b.produce(T.TRANSACTIONS, {"n": i}, key="user_7")
    c = b.consumer([T.TRANSACTIONS], "g1")
    recs = c.poll(100)
    assert [r.value["n"] for r in recs] == list(range(20))
    assert len({r.partition for r in recs}) == 1


def test_contract_commit_replay(any_broker):
    b = any_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(4)) == 4
    # crash without commit: a new consumer in the group re-reads everything
    c2 = b.consumer([T.TRANSACTIONS], "g")
    assert len(c2.poll(100)) == 10
    c2.commit()
    assert b.consumer([T.TRANSACTIONS], "g").poll(100) == []
    assert b.lag("g", T.TRANSACTIONS) == 0


def test_contract_snapshot_commit(any_broker):
    """commit(offsets) covers exactly the snapshot, not later polls."""
    b = any_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    first = c.poll(6)
    snap = c.snapshot_positions()
    second = c.poll(10)
    assert len(first) == 6 and len(second) == 4
    c.commit(snap)
    assert b.lag("g", T.TRANSACTIONS) == 4


def test_contract_produce_batch_and_end_offsets(any_broker):
    b = any_broker
    n = b.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(24)],
                        key_fn=lambda v: str(v["n"] % 5))
    assert n == 24
    assert sum(b.end_offsets(T.TRANSACTIONS)) == 24


def test_netbroker_durability(tmp_path):
    """Kill the server; a fresh server over the same log_dir serves the
    records and committed offsets (the Kafka-log durability analog)."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client = NetBrokerClient(port=server.port)
    client.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(12)],
                         key_fn=lambda v: str(v["n"] % 3))
    c = client.consumer([T.TRANSACTIONS], "g")
    got = c.poll(7)
    # commit exactly what we read so far
    c.commit()
    client.close()
    server.stop()

    server2 = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client2 = NetBrokerClient(port=server2.port)
    try:
        assert sum(client2.end_offsets(T.TRANSACTIONS)) == 12
        c2 = client2.consumer([T.TRANSACTIONS], "g")
        rest = c2.poll(100)
        ids_before = {(r.partition, r.offset) for r in got}
        ids_after = {(r.partition, r.offset) for r in rest}
        assert not ids_before & ids_after          # no double delivery
        assert len(got) + len(rest) == 12          # nothing lost
    finally:
        client2.close()
        server2.stop()


def test_stream_job_over_netbroker():
    """The full scoring job runs unchanged against the networked broker."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.stream import JobConfig, StreamJob

    server = BrokerServer(port=0).start()
    client = NetBrokerClient(port=server.port)
    try:
        gen = TransactionGenerator(num_users=30, num_merchants=12, seed=23)
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(client, scorer, JobConfig(max_batch=16,
                                                  max_delay_ms=1.0))
        client.produce_batch(T.TRANSACTIONS, gen.generate_batch(40),
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 40
        preds = client.consumer([T.PREDICTIONS], "check").poll(1000)
        assert len(preds) == 40
        assert client.lag(job.config.group_id, T.TRANSACTIONS) == 0
    finally:
        client.close()
        server.stop()


def test_netbroker_keyed_routing_stable_across_restart(tmp_path):
    """Per-key ordering across a broker restart: records produced for a key
    AFTER the WAL replay must land on the same partition as the key's
    records from before the restart (the crc32-partitioner contract — a
    salted hash() would scatter them and break per-key ordering)."""
    log_dir = tmp_path / "wal"
    server = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client = NetBrokerClient(port=server.port)
    keys = [f"user_{i}" for i in range(10)]
    before = {k: client.produce(T.TRANSACTIONS, {"k": k}, key=k).partition
              for k in keys}
    client.close()
    server.stop()

    server2 = BrokerServer(port=0, log_dir=str(log_dir)).start()
    client2 = NetBrokerClient(port=server2.port)
    try:
        after = {k: client2.produce(T.TRANSACTIONS, {"k": k},
                                    key=k).partition
                 for k in keys}
        assert after == before
        # and per-key order is intact end to end
        c = client2.consumer([T.TRANSACTIONS], "g-stable")
        recs = c.poll(1000)
        per_key = {}
        for r in recs:
            per_key.setdefault(r.key, []).append(r.offset)
        for k, offs in per_key.items():
            assert offs == sorted(offs), f"key {k} out of order"
    finally:
        client2.close()
        server2.stop()

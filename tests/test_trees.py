"""GBDT tensorization + trainer tests."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.trees import (
    TreeEnsemble,
    tree_ensemble_predict,
    tree_ensemble_logits,
)
from realtime_fraud_detection_tpu.training.gbdt import GBDTTrainer, _numpy_tree_forward


def _toy_problem(n=4000, f=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float32)
    # nonlinear rule: interactions + threshold
    logit = 2.0 * (x[:, 0] > 0.5) + 1.5 * x[:, 1] * (x[:, 2] > 0) - 1.0
    p = 1 / (1 + np.exp(-logit))
    y = (rng.random(n) < p).astype(np.float32)
    return x, y


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


class TestTensorizedForward:
    def test_single_manual_tree(self):
        # depth-2 tree: root splits on feature 0 @ 0.0; left child on f1 @ 1.0;
        # right child unsplit (inf -> always left, leaves 2,3 duplicated)
        import jax.numpy as jnp

        ens = TreeEnsemble(
            feature=jnp.array([[0, 1, 0]], jnp.int32),
            threshold=jnp.array([[0.0, 1.0, np.inf]], jnp.float32),
            leaf=jnp.array([[10.0, 20.0, 30.0, 30.0]], jnp.float32),
            base_score=jnp.asarray(0.0, jnp.float32),
        )
        x = np.array([
            [-1.0, 0.0],   # left, left -> leaf 0 = 10
            [-1.0, 2.0],   # left, right -> leaf 1 = 20
            [1.0, 99.0],   # right, (inf: left) -> leaf 2 = 30
        ], np.float32)
        np.testing.assert_allclose(np.asarray(tree_ensemble_logits(ens, x)), [10, 20, 30])

    def test_trainer_numpy_and_jax_forward_agree(self):
        x, y = _toy_problem(n=2000)
        ens = GBDTTrainer(n_estimators=10, max_depth=4, seed=1).fit(x, y)
        jax_logits = np.asarray(tree_ensemble_logits(ens, x[:500]))
        np_logits = np.full(500, float(ens.base_score))
        feat, thr, leaf = map(np.asarray, (ens.feature, ens.threshold, ens.leaf))
        for t in range(ens.n_trees):
            np_logits += _numpy_tree_forward(feat[t], thr[t], leaf[t], x[:500])
        np.testing.assert_allclose(jax_logits, np_logits, rtol=1e-4, atol=1e-5)


class TestTrainer:
    def test_learns_toy_problem(self):
        x, y = _toy_problem()
        xtr, ytr, xte, yte = x[:3000], y[:3000], x[3000:], y[3000:]
        ens = GBDTTrainer(n_estimators=50, max_depth=4, seed=2).fit(xtr, ytr)
        auc = _auc(yte, np.asarray(tree_ensemble_predict(ens, xte)))
        # label noise caps Bayes AUC near 0.78 on this problem (sklearn: 0.775)
        assert auc > 0.75, f"AUC {auc:.3f}"

    def test_beats_or_matches_sklearn(self):
        from sklearn.ensemble import GradientBoostingClassifier

        x, y = _toy_problem(seed=3)
        xtr, ytr, xte, yte = x[:3000], y[:3000], x[3000:], y[3000:]
        ours = GBDTTrainer(n_estimators=60, max_depth=4, seed=0).fit(xtr, ytr)
        ours_auc = _auc(yte, np.asarray(tree_ensemble_predict(ours, xte)))
        sk = GradientBoostingClassifier(
            n_estimators=60, max_depth=4, learning_rate=0.1, random_state=0
        ).fit(xtr, ytr)
        sk_auc = _auc(yte, sk.predict_proba(xte)[:, 1])
        assert ours_auc > sk_auc - 0.03, f"ours {ours_auc:.3f} vs sklearn {sk_auc:.3f}"

    def test_probabilities_in_range(self):
        x, y = _toy_problem(n=500)
        ens = GBDTTrainer(n_estimators=5, max_depth=3).fit(x, y)
        p = np.asarray(tree_ensemble_predict(ens, x))
        assert (p > 0).all() and (p < 1).all()

    def test_reference_hyperparams_shape(self):
        # reference config.py:136-142: 100 trees, depth 6
        x, y = _toy_problem(n=800)
        ens = GBDTTrainer(n_estimators=12, max_depth=6).fit(x, y)
        assert ens.feature.shape == (12, 63)
        assert ens.leaf.shape == (12, 64)


class TestFeatureImportances:
    def test_gain_importances_find_the_signal_features(self):
        """The toy rule uses features 0,1,2 only — gain importance must
        concentrate there (the reference's top-10 explanation field,
        ensemble_predictor.py:371-435)."""
        x, y = _toy_problem(n=3000)
        tr = GBDTTrainer(n_estimators=20, max_depth=4, seed=1)
        tr.fit(x, y)
        imp = tr.feature_importances_
        assert imp.shape == (16,)
        assert abs(float(imp.sum()) - 1.0) < 1e-5
        assert (imp >= 0).all()
        assert set(np.argsort(imp)[::-1][:3]) == {0, 1, 2}

    def test_importance_length_must_match_feature_contract(self):
        from realtime_fraud_detection_tpu.features.extract import (
            top_feature_importances,
        )

        with pytest.raises(ValueError, match="feature contract"):
            top_feature_importances(np.ones(16, np.float32))

    def test_scorer_attaches_top10_to_explanations(self):
        from realtime_fraud_detection_tpu.features.extract import FEATURE_NAMES
        from realtime_fraud_detection_tpu.scoring import FraudScorer
        from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

        gen = TransactionGenerator(num_users=32, num_merchants=8, seed=3)
        scorer = FraudScorer(seed=0)
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        recs = gen.generate_batch(4)
        assert "top_feature_importances" not in (
            scorer.score_batch(recs)[0]["explanation"])

        imp = np.zeros(64, np.float32)
        imp[5], imp[0], imp[63] = 0.5, 0.3, 0.2
        scorer.set_feature_importances(imp)
        out = scorer.score_batch(gen.generate_batch(4))[0]
        top = out["explanation"]["top_feature_importances"]
        assert list(top) == [FEATURE_NAMES[5], FEATURE_NAMES[0],
                             FEATURE_NAMES[63]]
        scorer.set_feature_importances(None)
        assert "top_feature_importances" not in (
            scorer.score_batch(gen.generate_batch(4))[0]["explanation"])


class TestGemmKernel:
    """GEMM-form traversal (ISSUE 9, Hummingbird): identical leaves to the
    gather oracle — exact, on every tested ensemble — with logits inside
    the documented summation-order tolerance."""

    def _random_ensemble(self, seed, t=12, depth=6, f=16, unsplit=0.3):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        n_int = 2 ** depth - 1
        feature = jnp.asarray(rng.integers(0, f, (t, n_int)), jnp.int32)
        threshold = jnp.where(
            jnp.asarray(rng.random((t, n_int)) < unsplit), jnp.inf,
            jnp.asarray(rng.standard_normal((t, n_int)), jnp.float32))
        leaf = jnp.asarray(rng.standard_normal((t, 2 ** depth)), jnp.float32)
        return TreeEnsemble(feature=feature, threshold=threshold, leaf=leaf,
                            base_score=jnp.asarray(0.05, jnp.float32))

    def test_leaf_equality_randomized_ensembles(self):
        import jax.numpy as jnp

        from realtime_fraud_detection_tpu.models.trees import (
            descend_complete_trees,
            gemm_leaf_index,
        )

        for seed in range(5):
            ens = self._random_ensemble(seed)
            x = jnp.asarray(
                np.random.default_rng(100 + seed).standard_normal((64, 16)),
                jnp.float32)
            a = descend_complete_trees(ens.feature, ens.threshold, x)
            b = gemm_leaf_index(ens.feature, ens.threshold, x)
            assert bool(jnp.all(a == b)), f"leaf mismatch at seed {seed}"

    def test_leaf_equality_trained_ensemble(self):
        import jax.numpy as jnp

        from realtime_fraud_detection_tpu.models.trees import (
            descend_complete_trees,
            gemm_leaf_index,
        )

        x, y = _toy_problem(n=2000)
        ens = GBDTTrainer(n_estimators=16, max_depth=5, seed=0).fit(x, y)
        xt = jnp.asarray(x[:256])
        a = descend_complete_trees(ens.feature, ens.threshold, xt)
        b = gemm_leaf_index(ens.feature, ens.threshold, xt)
        assert bool(jnp.all(a == b))

    def test_logits_within_tolerance(self):
        import jax.numpy as jnp

        x, y = _toy_problem(n=2000)
        trained = GBDTTrainer(n_estimators=16, max_depth=5, seed=0).fit(x, y)
        for ens, xs in ((trained, x[:256]), (self._random_ensemble(9),
                                             np.random.default_rng(9)
                                             .standard_normal((128, 16)))):
            xt = jnp.asarray(np.asarray(xs, np.float32))
            lg = np.asarray(tree_ensemble_logits(ens, xt, kernel="gather"))
            lm = np.asarray(tree_ensemble_logits(ens, xt, kernel="gemm"))
            # identical leaves, different summation order: float-tolerance
            # closeness only (the documented GEMM contract)
            np.testing.assert_allclose(lg, lm, atol=1e-4)

    def test_predictions_agree_and_unknown_kernel_raises(self):
        import jax.numpy as jnp

        ens = self._random_ensemble(3)
        x = jnp.asarray(np.random.default_rng(3).standard_normal((32, 16)),
                        jnp.float32)
        a = np.asarray(tree_ensemble_predict(ens, x, kernel="gather"))
        b = np.asarray(tree_ensemble_predict(ens, x, kernel="gemm"))
        np.testing.assert_allclose(a, b, atol=1e-5)
        with pytest.raises(ValueError, match="kernel"):
            tree_ensemble_logits(ens, x, kernel="einsum")

"""Core runtime tests: mesh construction, sharding, bucketing, config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from realtime_fraud_detection_tpu.core import (
    BATCH_BUCKETS,
    MeshConfig,
    batch_sharding,
    bucket_for,
    build_mesh,
    pad_to_bucket,
    shard_batch,
    unpad,
)
from realtime_fraud_detection_tpu.utils.config import Config


class TestMesh:
    def test_eight_virtual_devices(self):
        assert jax.device_count() == 8

    def test_default_mesh_uses_all_devices(self, mesh8):
        assert mesh8.shape["data"] == 8
        assert mesh8.shape["model"] == 1
        assert mesh8.shape["seq"] == 1

    def test_model_axis_mesh(self):
        mesh = build_mesh(MeshConfig(model=2))
        assert mesh.shape["data"] == 4
        assert mesh.shape["model"] == 2

    def test_invalid_mesh_shape_raises(self):
        with pytest.raises(ValueError):
            build_mesh(MeshConfig(data=3, model=2))

    def test_sharded_matmul_matches_local(self, mesh8):
        x = np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)
        w = np.random.default_rng(1).normal(size=(32, 8)).astype(np.float32)
        xs = jax.device_put(x, batch_sharding(mesh8, 1))

        @jax.jit
        def f(x, w):
            return x @ w

        out = f(xs, w)
        np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-5)

    def test_shard_batch_tree(self, mesh8):
        tree = {"a": np.ones((8, 4), np.float32), "b": np.zeros((8,), np.int32)}
        sharded = shard_batch(mesh8, tree)
        assert sharded["a"].sharding.spec[0] == "data"
        np.testing.assert_array_equal(np.asarray(sharded["a"]), tree["a"])


class TestBucketing:
    def test_bucket_rounding(self):
        assert bucket_for(1) == 1
        assert bucket_for(2) == 8
        assert bucket_for(8) == 8
        assert bucket_for(33) == 128
        assert bucket_for(256) == 256
        assert bucket_for(300) == 512  # multiples of top bucket

    def test_bucket_invalid(self):
        with pytest.raises(ValueError):
            bucket_for(0)

    def test_pad_and_unpad_roundtrip(self):
        tree = {"x": np.arange(12, dtype=np.float32).reshape(6, 2)}
        padded, mask, size = pad_to_bucket(tree, 6)
        assert size == 8
        assert padded["x"].shape == (8, 2)
        assert mask.sum() == 6
        # padding replicates row 0 (stays in-distribution)
        np.testing.assert_array_equal(padded["x"][6], tree["x"][0])
        restored = unpad(padded, 6)
        np.testing.assert_array_equal(restored["x"], tree["x"])

    def test_buckets_cover_reference_batching_config(self):
        # TF-Serving allowed batch sizes 1..128 (ml-models-deployment.yaml)
        for n in (1, 8, 32, 128):
            assert n in BATCH_BUCKETS


class TestConfig:
    def test_default_model_registry(self):
        cfg = Config()
        assert set(cfg.models) == {
            "xgboost_primary",
            "lstm_sequential",
            "bert_text",
            "graph_neural",
            "isolation_forest",
        }
        # reference config.py weights
        assert cfg.models["xgboost_primary"].weight == 0.40
        assert cfg.models["lstm_sequential"].weight == 0.25
        assert cfg.models["isolation_forest"].weight == 0.05

    def test_normalized_weights_sum_to_one(self):
        cfg = Config()
        assert abs(sum(cfg.normalized_weights().values()) - 1.0) < 1e-9

    def test_disable_model_renormalizes(self):
        cfg = Config()
        cfg.disable_model("bert_text")
        weights = cfg.normalized_weights()
        assert "bert_text" not in weights
        assert abs(sum(weights.values()) - 1.0) < 1e-9

    def test_decision_thresholds(self):
        cfg = Config()
        assert cfg.ensemble.decline_threshold == 0.95
        assert cfg.ensemble.review_threshold == 0.8
        assert cfg.ensemble.monitor_threshold == 0.6
        assert cfg.ensemble.confidence_threshold == 0.7

    def test_from_dict_overlay(self):
        cfg = Config.from_dict(
            {
                "ensemble": {"strategy": "voting"},
                "models": {"bert_text": {"enabled": False}},
                "sim": {"tps": 500},
            }
        )
        assert cfg.ensemble.strategy == "voting"
        assert not cfg.models["bert_text"].enabled
        assert cfg.sim.tps == 500

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("RTFD_ENSEMBLE_STRATEGY", "stacking")
        cfg = Config()
        assert cfg.ensemble.strategy == "stacking"


class TestReviewRegressions:
    """Regressions for code-review findings."""

    def test_bucket_respects_mesh_multiple(self):
        assert bucket_for(1, multiple_of=8) == 8
        assert bucket_for(8, multiple_of=8) == 8
        assert bucket_for(300, multiple_of=8) == 512
        assert bucket_for(257, multiple_of=7) == 518  # 512 -> next mult of 7

    def test_pad_to_bucket_shardable_on_mesh(self, mesh8):
        tree = {"x": np.ones((1, 4), np.float32)}
        padded, mask, size = pad_to_bucket(tree, 1, multiple_of=8)
        assert size == 8
        sharded = shard_batch(mesh8, padded)  # must not raise
        assert sharded["x"].shape == (8, 4)

    def test_unpad_preserves_non_batch_leaves(self):
        tree = {"x": np.ones((6, 2)), "emb": np.arange(10)}
        padded, _, size = pad_to_bucket(tree, 6)
        out = unpad(padded, 6, padded_size=size)
        assert out["x"].shape == (6, 2)
        assert out["emb"].shape == (10,)

    def test_env_beats_file_overlay(self, monkeypatch):
        monkeypatch.setenv("RTFD_FRAUD_THRESHOLD", "0.9")
        cfg = Config.from_dict({"ensemble": {"fraud_threshold": 0.5}})
        assert cfg.ensemble.fraud_threshold == 0.9

    def test_invalid_strategy_rejected_early(self, monkeypatch):
        monkeypatch.setenv("RTFD_ENSEMBLE_STRATEGY", "majority")
        with pytest.raises(ValueError, match="RTFD_ENSEMBLE_STRATEGY"):
            Config()

    def test_serving_matrix_columns_aligned(self):
        from realtime_fraud_detection_tpu.features.serving import ServingFeatureProcessor

        proc = ServingFeatureProcessor()
        rows = proc.process_batch([
            {"amount": 100.0, "user_avg_amount": 50.0,
             "user_transaction_count_1h": 2, "user_transaction_count_24h": 10},
            {"amount": 100.0},
        ])
        assert list(rows[0].keys()) == list(rows[1].keys())


def test_misordered_decision_ladder_is_rejected():
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = Config()
    cfg.ensemble.review_threshold = 0.4
    cfg.ensemble.monitor_threshold = 0.6   # shadows the monitor rung
    import pytest

    with pytest.raises(ValueError, match="decision ladder"):
        cfg.validate()

"""Native (C++) microbatcher tests: correctness + concurrency."""

import json
import shutil
import threading

import pytest

from realtime_fraud_detection_tpu.native import (
    NativeMicrobatchQueue,
    native_available,
    native_build_error,
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="module")
def _native():
    if not native_available():
        pytest.fail(f"native build failed: {native_build_error()}")


def test_push_pop_roundtrip(_native):
    q = NativeMicrobatchQueue(capacity=64, max_batch=8, max_delay_ms=1e9)
    payloads = [json.dumps({"n": i}).encode() for i in range(8)]
    for p in payloads:
        assert q.push(p)
    batch = q.next_batch()
    assert batch == payloads
    assert q.pending() == 0
    q.close()


def test_size_trigger_before_deadline(_native):
    q = NativeMicrobatchQueue(capacity=256, max_batch=4, max_delay_ms=1e9)
    for i in range(10):
        q.push(f"r{i}".encode())
    assert len(q.next_batch()) == 4
    assert len(q.next_batch()) == 4
    assert q.next_batch() == []      # 2 pending, no deadline, not full
    assert q.pending() == 2
    q.close()


def test_deadline_trigger(_native):
    q = NativeMicrobatchQueue(capacity=64, max_batch=256, max_delay_ms=5.0)
    q.push(b"only-one")
    # blocking poll longer than the deadline must flush the partial batch
    batch = q.next_batch(block_ms=100)
    assert batch == [b"only-one"]
    q.close()


def test_backpressure_when_full(_native):
    q = NativeMicrobatchQueue(capacity=4, max_batch=4, max_delay_ms=1e9)
    assert all(q.push(b"x") for _ in range(4))
    assert not q.push(b"overflow")
    assert q.stats()["dropped"] == 1
    q.close()


def test_oversized_payload_raises(_native):
    q = NativeMicrobatchQueue(capacity=4, slot_bytes=16)
    with pytest.raises(ValueError):
        q.push(b"y" * 17)
    q.close()


def test_concurrent_producers_no_loss(_native):
    """8 producer threads, one consumer; every record arrives exactly once."""
    q = NativeMicrobatchQueue(capacity=8192, max_batch=128, max_delay_ms=1.0)
    n_threads, per_thread = 8, 500
    errors = []

    def produce(tid):
        for i in range(per_thread):
            payload = f"{tid}:{i}".encode()
            while not q.push(payload):
                pass  # spin on backpressure

    threads = [threading.Thread(target=produce, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()

    seen = set()
    expected = n_threads * per_thread
    import time
    t_end = time.monotonic() + 30.0
    while len(seen) < expected and time.monotonic() < t_end:
        for p in q.next_batch(block_ms=10):
            key = p.decode()
            if key in seen:
                errors.append(f"duplicate {key}")
            seen.add(key)
    for t in threads:
        t.join()
    assert not errors
    assert len(seen) == expected
    q.close()


def test_tsan_stress(tmp_path):
    """Race-freedom under ThreadSanitizer (SURVEY.md §5.2 requirement)."""
    import subprocess
    from pathlib import Path

    src_dir = Path(__file__).resolve().parent.parent / (
        "realtime_fraud_detection_tpu/native"
    )
    binary = tmp_path / "stress_tsan"
    build = subprocess.run(
        ["g++", "-O1", "-g", "-std=c++17", "-fsanitize=thread", "-pthread",
         str(src_dir / "stress_main.cpp"), "-o", str(binary)],
        capture_output=True, text=True, timeout=120,
    )
    if build.returncode != 0:
        pytest.skip(f"TSAN unavailable: {build.stderr[:200]}")
    run = subprocess.run([str(binary)], capture_output=True, text=True,
                         timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert run.stdout.startswith("OK")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
class TestNativeTreeScorer:
    """C++ tree kernel vs the JAX tensorized traversal (same layout)."""

    @pytest.fixture(scope="class")
    def trained(self):
        import numpy as np

        from realtime_fraud_detection_tpu.training import GBDTTrainer

        rng = np.random.default_rng(0)
        x = rng.standard_normal((2000, 64)).astype(np.float32)
        y = (x[:, 3] + 0.5 * x[:, 17] > 0.7).astype(np.float32)
        ens = GBDTTrainer(n_estimators=20, max_depth=4, seed=1).fit(x, y)
        return ens, x

    def test_matches_jax_kernel(self, trained):
        import numpy as np

        from realtime_fraud_detection_tpu.models.trees import (
            tree_ensemble_logits,
        )
        from realtime_fraud_detection_tpu.native import (
            NativeTreeScorer,
            native_trees_available,
        )

        if not native_trees_available():
            pytest.skip("native build failed")
        ens, x = trained
        scorer = NativeTreeScorer(ens)
        got = scorer.logits(x)
        expect = np.asarray(tree_ensemble_logits(ens, x))
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

    def test_predict_is_sigmoid_and_threaded_matches(self, trained):
        import numpy as np

        from realtime_fraud_detection_tpu.native import (
            NativeTreeScorer,
            native_trees_available,
        )

        if not native_trees_available():
            pytest.skip("native build failed")
        ens, x = trained
        st = NativeTreeScorer(ens, n_threads=1)
        mt = NativeTreeScorer(ens, n_threads=4)
        np.testing.assert_allclose(st.logits(x), mt.logits(x))
        p = st.predict(x[:8])
        np.testing.assert_allclose(
            p, 1.0 / (1.0 + np.exp(-st.logits(x[:8]))), rtol=1e-6)
        assert ((p >= 0) & (p <= 1)).all()

    def test_rejects_too_narrow_input(self, trained):
        import numpy as np

        from realtime_fraud_detection_tpu.native import (
            NativeTreeScorer,
            native_trees_available,
        )

        if not native_trees_available():
            pytest.skip("native build failed")
        ens, _ = trained
        scorer = NativeTreeScorer(ens)
        narrow = np.zeros((4, scorer.min_features - 1), np.float32)
        with pytest.raises(ValueError, match="features"):
            scorer.logits(narrow)


class TestIngressGateway:
    """The native queue's production call site: threaded ingress gateway."""

    def test_concurrent_submitters_exact_delivery(self):
        import threading

        from realtime_fraud_detection_tpu.stream import (
            IngressGateway,
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        broker = InMemoryBroker()
        gw = IngressGateway(broker, T.TRANSACTIONS)
        n_threads, per = 6, 300

        def producer(tid):
            for i in range(per):
                txn = {"transaction_id": f"{tid}:{i}", "user_id": f"u{tid}",
                       "merchant_id": "m", "amount": 1.0}
                while not gw.submit(txn):
                    pass

        threads = [threading.Thread(target=producer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert gw.flush(timeout_s=30)
        gw.close()
        recs = broker.consumer([T.TRANSACTIONS], "check").poll(10_000)
        ids = [r.value["transaction_id"] for r in recs]
        assert len(ids) == n_threads * per
        assert len(set(ids)) == n_threads * per      # exactly once, no dup
        assert gw.dropped == 0
        # per-key (per-submitter-user) FIFO survives the lock-free handoff
        per_user = {}
        for r in recs:
            per_user.setdefault(r.value["user_id"], []).append(
                int(r.value["transaction_id"].split(":")[1]))
        for uid, seq in per_user.items():
            assert seq == sorted(seq), f"{uid} reordered"

    def test_oversized_payload_bypasses_ring(self):
        from realtime_fraud_detection_tpu.stream import (
            IngressGateway,
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        broker = InMemoryBroker()
        gw = IngressGateway(broker, T.TRANSACTIONS)
        txn = {"transaction_id": "big", "user_id": "u", "merchant_id": "m",
               "amount": 1.0, "description": "x" * 20_000}
        assert gw.submit(txn)
        assert gw.flush(timeout_s=10)
        gw.close()
        recs = broker.consumer([T.TRANSACTIONS], "check").poll(10)
        assert recs and recs[0].value["transaction_id"] == "big"

    def test_native_backend_engaged_when_available(self):
        from realtime_fraud_detection_tpu.native import native_available
        from realtime_fraud_detection_tpu.stream import (
            IngressGateway,
            InMemoryBroker,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        gw = IngressGateway(InMemoryBroker(), T.TRANSACTIONS)
        assert gw.native == native_available()
        gw.close()

"""Simulator + fraud pattern library tests."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.features import extract_features, rule_score
from realtime_fraud_detection_tpu.sim import (
    AdvancedFraudPatterns,
    BASIC_FRAUD_MIX,
    TransactionGenerator,
)


@pytest.fixture(scope="module")
def gen():
    return TransactionGenerator(num_users=500, num_merchants=200, seed=7)


class TestGeneratorDicts:
    def test_schema_fields(self, gen):
        txn = gen.generate_batch(1)[0]
        for key in ("transaction_id", "user_id", "merchant_id", "amount",
                    "currency", "payment_method", "timestamp", "geolocation",
                    "is_fraud", "fraud_score", "device_fingerprint"):
            assert key in txn
        assert txn["amount"] >= 1.0

    def test_fraud_rate_near_basic_mix(self):
        g = TransactionGenerator(num_users=500, num_merchants=200, seed=11)
        txns = g.generate_batch(4000)
        rate = sum(t["is_fraud"] for t in txns) / len(txns)
        expected = sum(BASIC_FRAUD_MIX.values())  # 0.055
        assert abs(rate - expected) < 0.02

    def test_deterministic_with_seed(self):
        a = TransactionGenerator(num_users=50, num_merchants=20, seed=3).generate_batch(5)
        b = TransactionGenerator(num_users=50, num_merchants=20, seed=3).generate_batch(5)
        assert [t["amount"] for t in a] == [t["amount"] for t in b]

    def test_dict_batch_encodes_and_scores(self, gen):
        txns = gen.generate_batch(64)
        batch = gen.encode_dicts(txns)
        feats = np.asarray(extract_features(batch))
        scores = np.asarray(rule_score(batch))
        assert feats.shape == (64, 64)
        assert np.isfinite(feats).all()
        assert (scores >= 0).all() and (scores <= 1).all()


class TestGeneratorFastPath:
    def test_encoded_batch_shapes(self, gen):
        batch, labels = gen.generate_encoded(256)
        assert batch.batch_size == 256
        assert labels["is_fraud"].shape == (256,)
        feats = np.asarray(extract_features(batch))
        assert feats.shape == (256, 64)
        assert np.isfinite(feats).all()

    def test_fraud_labels_have_signal(self):
        g = TransactionGenerator(num_users=2000, num_merchants=500, seed=5)
        batch, labels = g.generate_encoded(20000)
        rate = labels["is_fraud"].mean()
        assert 0.03 < rate < 0.08  # ~5.5% mix
        # fraud rows should carry higher prior scores on average
        prior = np.asarray(batch.prior_fraud_score)
        assert prior[labels["is_fraud"]].mean() > prior[~labels["is_fraud"]].mean() + 0.3

    def test_throughput_adequate(self, gen):
        import time
        t0 = time.perf_counter()
        gen.generate_encoded(100_000)
        dt = time.perf_counter() - t0
        # must sustain >> 50k txn/s generation so the bench isn't input-bound
        assert 100_000 / dt > 200_000, f"only {100_000/dt:.0f} txn/s"


class TestFraudPatterns:
    def test_ten_scenarios(self):
        p = AdvancedFraudPatterns(np.random.default_rng(0))
        assert len(p.scenarios) == 10
        total = sum(s.probability for s in p.scenarios.values())
        assert total == pytest.approx(0.12, abs=1e-9)

    def test_money_laundering_structuring(self):
        p = AdvancedFraudPatterns(np.random.default_rng(0))
        txn = {"user_id": "u1", "timestamp": "2026-01-05T10:00:00"}
        out = p.apply_fraud_pattern("money_laundering", dict(txn))
        assert 9000.0 <= out["amount"] <= 9900.0

    def test_velocity_tracking_escalates(self):
        p = AdvancedFraudPatterns(np.random.default_rng(0))
        scores = []
        for i in range(8):
            txn = {"user_id": "u1", "timestamp": f"2026-01-05T10:0{i}:00"}
            out = p.apply_fraud_pattern("velocity_fraud", dict(txn))
            scores.append(out["fraud_score"])
        # after >5 txns in 10 min the score formula kicks in: 0.5 + n*0.1
        assert scores[-1] == pytest.approx(min(0.95, 0.5 + 8 * 0.1))

    def test_account_takeover_moves_location(self):
        p = AdvancedFraudPatterns(np.random.default_rng(0))
        p.record_location("u1", {"lat": 10.0, "lon": 10.0})
        out = p.apply_fraud_pattern(
            "account_takeover",
            {"user_id": "u1", "geolocation": {"lat": 10.0, "lon": 10.0}},
        )
        moved = abs(out["geolocation"]["lat"] - 10.0) + abs(out["geolocation"]["lon"] - 10.0)
        assert moved > 0.0
        assert "device_fingerprint" in out


class TestDiurnalBurstArrivals:
    """Nonstationary offered-load process (sim/arrivals.py): diurnal ramp
    + Poisson bursts, seedable and virtual-clock compatible (ISSUE 6
    satellite)."""

    def _proc(self, seed=7, **kw):
        from realtime_fraud_detection_tpu.sim import (
            DiurnalBurstConfig,
            DiurnalBurstProcess,
        )

        defaults = dict(trough_tps=200.0, peak_tps=2000.0, period_s=4.0,
                        burst_every_s=2.0, burst_offset_s=1.0,
                        burst_duration_s=0.2, burst_mult=4.0)
        defaults.update(kw)
        return DiurnalBurstProcess(DiurnalBurstConfig(**defaults),
                                   seed=seed)

    def test_deterministic_per_seed(self):
        a = self._proc(seed=7).generate(4.0)
        b = self._proc(seed=7).generate(4.0)
        c = self._proc(seed=8).generate(4.0)
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c[: len(a)])

    def test_sorted_within_window_from_t0(self):
        p = self._proc(t0=100.0)
        t = p.generate(4.0)
        assert np.all(np.diff(t) >= 0)
        assert t.min() >= 100.0 and t.max() < 104.0

    def test_diurnal_envelope(self):
        # deterministic intensity: trough at phase 0, peak at phase 0.5
        p = self._proc(burst_duration_s=0.0)
        assert p.rate_at(0.0) == pytest.approx(200.0)
        assert p.rate_at(2.0) == pytest.approx(2000.0)   # period/2
        # and the realized counts follow the envelope
        t = p.generate(4.0)
        trough = np.sum((t >= 0.0) & (t < 0.4))
        peak = np.sum((t >= 1.8) & (t < 2.2))
        assert peak > 3 * max(trough, 1)

    def test_burst_elevates_rate(self):
        p = self._proc()
        # burst window [1.0, 1.2): 4x the diurnal rate at that phase
        in_burst = p.rate_at(1.1)
        just_after = p.rate_at(1.25)
        assert in_burst == pytest.approx(4.0 * just_after, rel=0.15)
        t = p.generate(4.0)
        burst_n = np.sum((t >= 1.0) & (t < 1.2))
        calm_n = np.sum((t >= 0.75) & (t < 0.95))
        assert burst_n > 2 * max(calm_n, 1)

    def test_validation(self):
        from realtime_fraud_detection_tpu.sim import DiurnalBurstConfig

        for bad in (dict(trough_tps=0.0), dict(trough_tps=500.0,
                                               peak_tps=100.0),
                    dict(period_s=0.0), dict(burst_mult=0.5),
                    dict(burst_every_s=0.0)):
            with pytest.raises(ValueError):
                DiurnalBurstConfig(**bad).validate()

    def test_paired_with_generator(self):
        from realtime_fraud_detection_tpu.sim import TransactionGenerator

        p = self._proc()
        pairs = p.paired_with(
            TransactionGenerator(num_users=50, num_merchants=10, seed=3),
            1.0)
        assert pairs
        assert all(isinstance(ts, float) and "transaction_id" in txn
                   for ts, txn in pairs)
        s = p.summary([ts for ts, _ in pairs])
        assert s["n"] == len(pairs) and s["mean_tps"] > 0

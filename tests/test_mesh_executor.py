"""Mesh-sharded branch execution (scoring/mesh_executor.py): serving
storage specs pinned against COMMITTED shardings, executor mechanics
behind the pool seam, bit-equality vs single-device, sync_mesh mirrors,
MeshSettings validation, serving wiring, checkpoint restore into a
mesh-attached scorer, and the `rtfd mesh-drill --fast` CI smoke."""

import asyncio
import json
import threading

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from realtime_fraud_detection_tpu.core.mesh import MODEL_AXIS, build_mesh
from realtime_fraud_detection_tpu.parallel.layouts import (
    SHARDABLE_BRANCHES,
    bert_serving_param_specs,
    branch_serving_specs,
    leaf_storage_spec,
)
from realtime_fraud_detection_tpu.scoring import (
    FraudScorer,
    MeshExecutor,
    ScorerConfig,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.utils.config import (
    MESH_SHARDABLE_BRANCHES,
    Config,
    MeshSettings,
    QuantSettings,
)


def make_scorer(seed=3, model_seed=0, quant=False):
    """Scorer whose OWN mesh is one device, so reference runs are truly
    single-device and an attached executor owns the batch seam."""
    gen = TransactionGenerator(num_users=300, num_merchants=60, seed=seed)
    cfg = Config(quant=QuantSettings.full()) if quant else None
    s = FraudScorer(config=cfg, scorer_config=ScorerConfig(),
                    mesh=build_mesh(devices=jax.devices()[:1]),
                    seed=model_seed)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, s


def rows(results):
    return [(r["transaction_id"], r["fraud_probability"], r["confidence"],
             r["decision"]) for r in results]


# ------------------------------------------------------------ storage specs
class TestServingSpecs:
    """Satellite: param-spec trees pinned against the shardings actually
    COMMITTED by the executor — never just the spec intent."""

    def test_shardable_branches_config_pin(self):
        # utils.config validates shard_branches against its own tuple;
        # layouts maps them onto ScoringModels fields — the two must
        # never drift
        assert sorted(MESH_SHARDABLE_BRANCHES) == sorted(SHARDABLE_BRANCHES)

    def test_leaf_storage_spec_rules(self):
        assert leaf_storage_spec(np.zeros((192, 512)), 2) == P(None,
                                                               MODEL_AXIS)
        assert leaf_storage_spec(np.zeros((512,)), 2) == P(MODEL_AXIS)
        # indivisible everywhere -> replicated, never an uneven shard
        assert leaf_storage_spec(np.zeros((7, 3)), 2) == P()
        assert leaf_storage_spec(np.zeros(()), 2) == P()
        assert leaf_storage_spec(np.zeros((512, 64)), 1) == P()

    def test_bert_specs_match_param_tree(self):
        _, s = make_scorer()
        specs = bert_serving_param_specs(s.models.bert, 2)
        layer = specs["layers"][0]
        assert layer["q"]["w"] == P(None, MODEL_AXIS)      # column
        assert layer["q"]["b"] == P(MODEL_AXIS)
        assert layer["o"]["w"] == P(MODEL_AXIS, None)      # row
        assert layer["o"]["b"] == P()
        assert specs["word_emb"] == P(MODEL_AXIS, None)    # vocab rows
        assert specs["emb_ln"]["scale"] == P()
        assert specs["classifier"]["w"] == P()
        # the spec tree must zip the real param tree leaf-for-leaf
        jax.tree_util.tree_map(lambda a, b: None, s.models.bert, specs,
                               is_leaf=lambda x: isinstance(x, P))

    def test_quantized_bert_specs_match_param_tree(self):
        _, s = make_scorer(quant=True)
        specs = bert_serving_param_specs(s.models.bert, 2)
        layer = specs["layers"][0]
        assert layer["q"]["qw"] == P(None, MODEL_AXIS)
        assert layer["q"]["scale"] == P(MODEL_AXIS)        # out-channel
        assert layer["o"]["qw"] == P(MODEL_AXIS, None)
        assert layer["o"]["scale"] == P()                  # stays whole
        assert specs["word_emb"]["qe"] == P(MODEL_AXIS, None)
        assert specs["word_emb"]["scale"] == P(MODEL_AXIS)  # per-row
        jax.tree_util.tree_map(lambda a, b: None, s.models.bert, specs,
                               is_leaf=lambda x: isinstance(x, P))

    @pytest.mark.parametrize("quant", [False, True])
    def test_committed_shardings_honor_specs(self, quant):
        """The COMMITTED arrays on the executor's mesh carry exactly the
        storage specs — the drill's byte numbers rest on this."""
        _, s = make_scorer(quant=quant)
        ex = MeshExecutor(s, model_axis=2,
                          shard_branches=("bert_text", "lstm_sequential"))
        rep = ex.replicas[0]
        specs = branch_serving_specs(
            s.models, 2, ("bert_text", "lstm_sequential"))

        def check(arr, spec):
            assert arr.sharding.spec == spec, (arr.shape, spec)

        jax.tree_util.tree_map(
            check, rep.models.bert, specs.bert,
            is_leaf=lambda x: isinstance(x, P))
        jax.tree_util.tree_map(
            check, rep.models.lstm, specs.lstm,
            is_leaf=lambda x: isinstance(x, P))
        # un-named branches replicate
        for leaf in jax.tree_util.tree_leaves(rep.models.gnn):
            assert leaf.sharding.spec == P()
        # and the bytes follow: sharded BERT storage halves (<= 60%)
        pb = ex.param_bytes()
        assert (pb["bert_text"]["per_chip"]
                <= 0.6 * pb["bert_text"]["replicated"])
        assert pb["graph_neural"]["per_chip"] == \
            pb["graph_neural"]["replicated"]

    def test_refuses_unshardable_branch(self):
        _, s = make_scorer()
        with pytest.raises(ValueError, match="not shardable"):
            MeshExecutor(s, model_axis=2,
                         shard_branches=("xgboost_primary",))


# --------------------------------------------------------- executor basics
class TestExecutorMechanics:
    def test_batch_multiple_seam(self):
        """A 1-device scorer driving a data-axis-4 executor pads its
        buckets to the EXECUTOR's multiple, not its own mesh's."""
        gen, s = make_scorer()
        ex = MeshExecutor(s, model_axis=2, shard_branches=("bert_text",))
        assert ex.data_axis == 4
        assert ex.batch_multiple == 4
        pending = s.dispatch(gen.generate_batch(5), now=1000.0)
        assert pending.out.shape[0] % 4 == 0
        out = s.finalize(pending, now=1000.0)
        assert len(out) == 5

    def test_device_split_validation(self):
        _, s = make_scorer()
        with pytest.raises(ValueError, match="equal"):
            MeshExecutor(s, replicas=3)          # 8 % 3 != 0
        with pytest.raises(ValueError, match="model_axis"):
            MeshExecutor(s, model_axis=3)        # 8 % 3 != 0
        with pytest.raises(ValueError, match="not both"):
            MeshExecutor(s, mesh=build_mesh(), replicas=2)

    def test_round_robin_and_slots(self):
        gen, s = make_scorer()
        ex = MeshExecutor(s, model_axis=2, replicas=2, inflight_depth=2,
                          shard_branches=())
        assert len(ex) == 2
        assert ex.total_slots() == 4
        pend = [s.dispatch(gen.generate_batch(4), now=1000.0)
                for _ in range(4)]
        assert list(ex.assignment_log) == [0, 1, 0, 1]
        assert [p.pool_token.replica_idx for p in pend] == [0, 1, 0, 1]
        for p in pend:
            s.finalize(p, now=1000.0)
        st = ex.stats()
        assert st["dispatched"] == 4 and st["completed"] == 4
        assert st["kind"] == "mesh"

    def test_degradation_masks_flow_through(self):
        gen, s = make_scorer()
        MeshExecutor(s, model_axis=2, shard_branches=("bert_text",))
        s.set_degradation(np.asarray([True, False, False, False, True]),
                          level=2)
        res = s.score_batch(gen.generate_batch(4), now=1000.0)
        for r in res:
            assert set(r["model_predictions"]) == {"xgboost_primary",
                                                   "isolation_forest"}


# --------------------------------------------------------- bit equality
class TestBitEquality:
    """Targeted equality pins (the drill covers the full placement x
    quant x rung matrix; these keep the contract enforced in-process)."""

    @pytest.mark.parametrize("quant", [False, True])
    def test_mesh_equals_single_device(self, quant):
        gen_a, ref = make_scorer(quant=quant)
        batches = [gen_a.generate_batch(16) for _ in range(3)]
        want = [rows(ref.score_batch(b, now=1000.0)) for b in batches]

        gen_b, meshed = make_scorer(quant=quant)
        MeshExecutor(meshed, model_axis=2,
                     shard_branches=("bert_text", "graph_neural",
                                     "lstm_sequential"))
        got = [rows(meshed.score_batch(gen_b.generate_batch(16),
                                       now=1000.0))
               for _ in range(3)]
        assert got == want

    def test_mesh_equals_single_device_under_rung(self):
        gen_a, ref = make_scorer()
        gen_b, meshed = make_scorer()
        MeshExecutor(meshed, model_axis=2, shard_branches=("bert_text",))
        mask = np.asarray([True, True, False, False, True])
        ref.set_degradation(mask, level=1)
        meshed.set_degradation(mask, level=1)
        want = rows(ref.score_batch(gen_a.generate_batch(16), now=1000.0))
        got = rows(meshed.score_batch(gen_b.generate_batch(16), now=1000.0))
        assert got == want

    def test_hot_swap_serves_new_params_sharded(self):
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        gen, s = make_scorer()
        ex = MeshExecutor(s, model_axis=2, shard_branches=("bert_text",))
        before = rows(s.score_batch(gen.generate_batch(4), now=1000.0))
        new = init_scoring_models(
            jax.random.PRNGKey(42), bert_config=s.bert_config,
            feature_dim=s.sc.feature_dim, node_dim=s.sc.node_dim)
        s.set_models(new)
        after = rows(s.score_batch(gen.generate_batch(4), now=1000.0))
        assert before != after           # genuinely new params serving
        pb = ex.param_bytes()["bert_text"]
        assert pb["per_chip"] <= 0.6 * pb["replicated"]


# ------------------------------------------------------------- sync_mesh
class TestSyncMesh:
    def _snapshot(self):
        gen, s = make_scorer()
        MeshExecutor(s, model_axis=2, replicas=2,
                     shard_branches=("bert_text",))
        for _ in range(3):
            s.score_batch(gen.generate_batch(4), now=1000.0)
        return s.pool.mesh_snapshot()

    def test_honest_deltas_not_double_counted(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        snap = self._snapshot()
        m = MetricsCollector()
        m.sync_mesh(snap)
        m.sync_mesh(snap)                      # re-sync: no double count
        total = sum(v for _, v in m.mesh_dispatched.by_label())
        assert total == sum(float(v) for v in snap["dispatched"].values())
        assert m.mesh_model_axis.value() == 2.0
        assert m.mesh_replica_count.value() == 2.0
        assert m.mesh_branch_sharded.value(branch="bert_text") == 1.0
        assert m.mesh_branch_sharded.value(branch="xgboost_primary") == 0.0
        assert m.mesh_param_bytes.value(branch="bert_text") > 0

    def test_stream_vs_serving_render_identical(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        snap = self._snapshot()
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_mesh(snap)
        b.sync_mesh(snap)

        def mesh_lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if ln.startswith("mesh_")]

        assert mesh_lines(a) == mesh_lines(b)
        assert any(ln.startswith("mesh_param_bytes_per_chip")
                   for ln in mesh_lines(a))


# ------------------------------------------------------------ settings
class TestMeshSettings:
    def test_defaults_validate(self):
        MeshSettings().validate()
        Config().validate()

    def test_refuses_bad_values(self):
        with pytest.raises(ValueError):
            MeshSettings(replicas=0).validate()
        with pytest.raises(ValueError):
            MeshSettings(inflight_depth=0).validate()
        with pytest.raises(ValueError, match="not shardable"):
            MeshSettings(shard_branches=["isolation_forest"]).validate()
        with pytest.raises(ValueError):
            MeshSettings(model=0).validate()


# ------------------------------------------------------- serving wiring
def test_serving_app_constructs_mesh_executor():
    """config.mesh.enabled routes the serving plane through a
    MeshExecutor behind the same pool seam, and the Prometheus
    exposition carries the mesh_* series."""
    from realtime_fraud_detection_tpu.serving import ServingApp

    config = Config()
    config.mesh.enabled = True
    config.mesh.model = 2
    config.mesh.replicas = 1
    config.mesh.shard_branches = ["bert_text"]
    app = ServingApp(config, host="127.0.0.1", port=0)
    assert isinstance(app.pool, MeshExecutor)
    assert app.pool.model_axis == 2
    status, text = asyncio.run(app._metrics_prometheus(None, None))
    assert status == 200
    assert "mesh_model_axis_size 2" in text
    assert 'mesh_branch_sharded{branch="bert_text"} 1' in text
    # the replicated-pool family stays untouched (no phantom writers)
    assert "device_pool_dispatched_total" in text   # registered, zero
    assert 'device_pool_dispatched_total{' not in text


# ------------------------------------------- checkpoint restore (score lock)
def test_checkpoint_restore_into_mesh_attached_scorer(tmp_path):
    """Satellite: restore_into_scorer under the score lock re-shards the
    restored params per the executor's placement and the mesh serves
    them bit-identically to a single-device scorer restored from the
    same checkpoint."""
    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        init_scoring_models,
    )

    donor = init_scoring_models(jax.random.PRNGKey(77))
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, params=donor)

    gen_a, ref = make_scorer()
    CheckpointManager(str(tmp_path / "ck")).restore_into_scorer(ref)
    want = rows(ref.score_batch(gen_a.generate_batch(16), now=1000.0))

    gen_b, meshed = make_scorer()
    ex = MeshExecutor(meshed, model_axis=2, shard_branches=("bert_text",))
    lock = threading.Lock()
    CheckpointManager(str(tmp_path / "ck")).restore_into_scorer(
        meshed, lock=lock)
    got = rows(meshed.score_batch(gen_b.generate_batch(16), now=1000.0))
    assert got == want
    pb = ex.param_bytes()["bert_text"]
    assert pb["per_chip"] <= 0.6 * pb["replicated"]


# --------------------------------------------------------- drill smoke (CI)
def test_mesh_drill_fast_smoke(monkeypatch, capsys):
    """Acceptance: `rtfd mesh-drill --fast` passes deterministically in
    tier-1 — through the CLI entry (in-process child mode; the session
    already provides the 8-device host platform), replay digest
    included."""
    from realtime_fraud_detection_tpu import cli

    monkeypatch.setenv("_RTFD_MESH_DRILL_CHILD", "1")
    rc = cli.main(["mesh-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])           # final line: compact verdict
    assert compact["passed"] is True
    assert len(out[-1].encode()) < 2048
    checks = compact["checks"]
    assert checks["bit_identical_bert_sharded"]
    assert checks["bit_identical_quant_all_neural_sharded"]
    assert checks["bit_identical_all_ladder_rungs"]
    assert checks["no_mixed_params_batch"]
    assert checks["donation_reaches_compiler"]
    assert checks["replay_bit_identical"]
    for frac in compact["bert_per_chip_frac"].values():
        assert frac <= 0.60
    full = json.loads(out[-2])
    assert full["placements"]["pool_x_mesh"]["per_replica_dispatched"]

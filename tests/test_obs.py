"""Observability plane: metrics registry, drift monitor, logs, span timer,
and the per-transaction tracing plane (flight recorder, critical-path
analyzer, SLO burn rate, Prometheus mirror, overhead guard)."""

import json
import logging

import numpy as np
import pytest

from realtime_fraud_detection_tpu.obs import (
    DriftConfig,
    FeatureDriftMonitor,
    JsonFormatter,
    MetricsCollector,
    Registry,
    SloTracker,
    SpanTimer,
    Tracer,
    log_prediction_result,
)
from realtime_fraud_detection_tpu.utils.config import TracingSettings


def _vclock_tracer(clock, **kw):
    defaults = dict(enabled=True, ring_size=256, slowest_n=4,
                    slo_objective_ms=20.0, slo_fast_window_s=1.0,
                    slo_slow_window_s=4.0, slo_bucket_s=0.05)
    defaults.update(kw)
    return Tracer(TracingSettings(**defaults), clock=lambda: clock[0])


class TestRegistry:
    def test_counter_labels_and_total(self):
        r = Registry()
        c = r.counter("preds_total", "predictions", ("model", "decision"))
        c.inc(model="xgb", decision="APPROVE")
        c.inc(2, model="xgb", decision="DECLINE")
        assert c.value(model="xgb", decision="APPROVE") == 1
        assert c.total() == 3

    def test_counter_rejects_negative(self):
        c = Registry().counter("c", "h")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        g = Registry().gauge("g", "h")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_histogram_buckets_and_quantile(self):
        h = Registry().histogram("h", "lat", buckets=(0.01, 0.1, 1.0))
        for v in [0.005] * 98 + [0.5, 0.5]:
            h.observe(v)
        assert h.count() == 100
        assert h.quantile(0.5) == 0.01
        assert h.quantile(0.99) == pytest.approx(1.0)

    def test_prometheus_text_format(self):
        r = Registry()
        c = r.counter("x_total", "things", ("k",))
        c.inc(k="v")
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        text = r.render()
        assert "# TYPE x_total counter" in text
        assert 'x_total{k="v"} 1' in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 1' in text
        assert "lat_seconds_count 1" in text

    def test_non_finite_observation_dropped(self):
        h = Registry().histogram("h", "lat", buckets=(0.1, 1.0))
        h.observe(float("nan"))
        h.observe(float("inf"))
        h.observe(0.05)
        assert h.count() == 1
        assert h.sum() == pytest.approx(0.05)
        sum_line = [ln for ln in h.render() if "_sum" in ln][0]
        assert "nan" not in sum_line and "inf" not in sum_line.lower()

    def test_quantile_in_overflow_bucket_reports_max(self):
        h = Registry().histogram("h", "lat", buckets=(0.1, 1.0))
        for _ in range(10):
            h.observe(60.0)
        assert h.quantile(0.99) == pytest.approx(60.0)

    def test_label_values_escaped(self):
        c = Registry().counter("c_total", "h", ("k",))
        c.inc(k='say "hi"\nnewline\\slash')
        line = [ln for ln in c.render() if ln.startswith("c_total{")][0]
        assert '\\"hi\\"' in line and "\\n" in line and "\\\\" in line
        assert "\n" not in line

    def test_duplicate_name_rejected(self):
        r = Registry()
        r.counter("dup", "h")
        with pytest.raises(ValueError):
            r.gauge("dup", "h")


class TestMetricsCollector:
    def test_record_and_summary(self):
        t = [0.0]
        m = MetricsCollector(clock=lambda: t[0])
        for i in range(10):
            t[0] = float(i)
            m.record_prediction(
                "APPROVE" if i < 8 else "DECLINE",
                fraud_score=0.1 * i, duration_s=0.004,
                model_predictions={"xgboost_primary": 0.2},
            )
        s = m.summary()
        assert s["total_predictions"] == 10
        assert s["decision_counts"] == {"APPROVE": 8, "DECLINE": 2}
        assert s["throughput_tps_60s"] == pytest.approx(10 / 60.0)
        assert s["latency_ms"]["p99"] <= 5.0 + 1e-9
        assert m.predictions_total.value(
            model="xgboost_primary", decision="APPROVE") == 8

    def test_prometheus_render_includes_domain_metrics(self):
        m = MetricsCollector()
        m.record_prediction("REVIEW", 0.9, 0.002)
        m.record_error("assemble")
        text = m.render_prometheus()
        assert 'ml_predictions_total{decision="REVIEW",model="ensemble"} 1' in text
        assert 'ml_prediction_errors_total{stage="assemble"} 1' in text

    def test_throughput_not_capped_by_latency_window(self):
        t = [0.0]
        m = MetricsCollector(window=100, clock=lambda: t[0])
        for i in range(1000):           # 1000 events in 10 "seconds"
            t[0] = i / 100.0
            m.record_prediction("APPROVE", 0.1, 0.001)
        s = m.summary()
        assert s["throughput_tps_60s"] == pytest.approx(1000 / 60.0)
        assert s["recent_predictions"] == 100   # latency window stays capped

    def test_batch_duration_recorded(self):
        m = MetricsCollector()
        m.record_batch(32, 0.008)
        assert m.batch_duration.count() == 1
        assert m.batch_duration.sum() == pytest.approx(0.008)

    def test_reset_clears_window_not_counters(self):
        m = MetricsCollector()
        m.record_prediction("APPROVE", 0.1, 0.001)
        m.reset()
        s = m.summary()
        assert s["recent_predictions"] == 0
        assert s["throughput_tps_60s"] == 0.0
        assert m.predictions_total.total() > 0


class TestDrift:
    def _warm(self, mon, rng, rows, loc=0.0, scale=1.0):
        mon.update(rng.normal(loc, scale, size=(rows, 8)))

    def test_no_drift_on_same_distribution(self):
        rng = np.random.default_rng(0)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=1000,
                                              window_rows=1000))
        self._warm(mon, rng, 1200)
        assert mon.baseline_frozen
        self._warm(mon, rng, 1000)
        rep = mon.report()
        assert not rep.drifted
        assert rep.max_psi < 0.1

    def test_detects_mean_shift(self):
        rng = np.random.default_rng(1)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=1000,
                                              window_rows=1000))
        self._warm(mon, rng, 1200)
        shifted = rng.normal(0, 1, size=(1000, 8))
        shifted[:, 3] += 3.0                       # feature 3 drifts hard
        mon.update(shifted)
        rep = mon.report()
        assert rep.drifted
        assert 3 in rep.top_features
        assert rep.psi[3] > 0.25
        assert rep.psi[0] < 0.25

    def test_report_before_freeze_is_quiet(self):
        mon = FeatureDriftMonitor(DriftConfig(num_features=4, warmup_rows=100))
        mon.update(np.zeros((10, 4)))
        rep = mon.report()
        assert not rep.drifted and not rep.baseline_frozen

    def test_shape_validation(self):
        mon = FeatureDriftMonitor(DriftConfig(num_features=4))
        with pytest.raises(ValueError):
            mon.update(np.zeros((10, 5)))

    def test_tiny_window_does_not_false_alarm(self):
        rng = np.random.default_rng(2)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=500,
                                              window_rows=500,
                                              min_report_rows=200))
        mon.update(rng.normal(size=(600, 8)))
        mon.update(rng.normal(size=(1, 8)))       # near-empty window
        rep = mon.report()
        assert not rep.drifted and rep.max_psi == 0.0


class TestLogs:
    def test_json_formatter_fields(self):
        rec = logging.LogRecord("t", logging.INFO, __file__, 1, "hello",
                                (), None)
        rec.transaction_id = "tx1"
        out = json.loads(JsonFormatter().format(rec))
        assert out["message"] == "hello"
        assert out["transaction_id"] == "tx1"
        assert out["level"] == "INFO"

    def test_log_prediction_result_structured(self, caplog):
        logger = logging.getLogger("test.pred")
        with caplog.at_level(logging.INFO, logger="test.pred"):
            log_prediction_result(logger, "tx9", 0.87, "REVIEW", 3.2)
        rec = caplog.records[-1]
        assert rec.transaction_id == "tx9"
        assert rec.decision == "REVIEW"
        assert rec.fraud_score == pytest.approx(0.87)


class TestSpanTimer:
    def test_span_stats(self):
        t = [0.0]
        timer = SpanTimer(clock=lambda: t[0])
        for dt in (0.001, 0.002, 0.010):
            with timer.span("assemble"):
                t[0] += dt
        st = timer.stats("assemble")["assemble"]
        assert st["count"] == 3
        assert st["max_ms"] == pytest.approx(10.0)
        assert st["total_s"] == pytest.approx(0.013)
        timer.reset()
        assert timer.stats() == {}

    def test_percentiles_interpolate(self):
        """Satellite: p50/p99 interpolate between order statistics —
        raw index selection made p99 on small n simply the max."""
        timer = SpanTimer()
        for ms in range(1, 101):            # 1..100 ms
            timer.record("s", ms / 1e3)
        st = timer.stats("s")["s"]
        assert st["p50_ms"] == pytest.approx(50.5)       # numpy default
        assert st["p99_ms"] == pytest.approx(99.01)
        assert st["p99_ms"] < st["max_ms"]               # not just the max
        np.testing.assert_allclose(
            [st["p50_ms"], st["p99_ms"]],
            np.percentile(np.arange(1.0, 101.0), [50, 99]))

    def test_small_n_p99_not_max(self):
        timer = SpanTimer()
        for ms in (1.0, 2.0, 100.0):
            timer.record("s", ms / 1e3)
        st = timer.stats("s")["s"]
        assert st["p99_ms"] < 100.0
        assert st["p99_ms"] == pytest.approx(
            np.percentile([1.0, 2.0, 100.0], 99))


class TestTracer:
    def _scored_batch(self, tracer, clock, txn_ids, stage_costs_ms,
                      ingest_lag_s=0.0):
        """Drive one batch through the mark protocol on a virtual clock."""
        ctxs = [tracer.begin(t, ingest_lag_s=ingest_lag_s)
                for t in txn_ids]
        tb = tracer.batch(ctxs, batch_size=len(txn_ids))
        for stage in ("assemble", "pack", "dispatch", "device_wait",
                      "finalize"):
            tb.mark(stage)
            clock[0] += stage_costs_ms.get(stage, 0.0) / 1e3
        tracer.finish_batch(tb)
        return tb

    def test_stages_additive_and_recorded(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        costs = {"assemble": 3.0, "pack": 0.5, "dispatch": 0.5,
                 "device_wait": 5.0, "finalize": 1.0}
        self._scored_batch(tracer, clock, ["a", "b"], costs,
                           ingest_lag_s=0.002)
        traces = tracer.traces(terminal="scored")
        assert len(traces) == 2
        for t in traces:
            # consecutive-mark stages partition e2e exactly
            assert sum(t.stages.values()) == pytest.approx(t.e2e_ms)
            assert t.stages["ingest"] == pytest.approx(2.0)
            for stage, ms in costs.items():
                assert t.stages[stage] == pytest.approx(ms)
        assert tracer.counters["completed"] == 2

    def test_disabled_is_noop(self):
        tracer = Tracer(TracingSettings(enabled=False))
        assert tracer.begin("x") is None
        assert tracer.batch([None]) is None
        tracer.finish_batch(None)                 # must not raise
        tracer.finish_terminal(None, "shed")
        assert tracer.traces() == []

    def test_shed_terminal_recorded(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        tracer.finish_terminal(tracer.begin("s1"), "shed",
                               reason="no_tokens")
        traces = tracer.traces(terminal="shed")
        assert len(traces) == 1
        assert traces[0].meta["reason"] == "no_tokens"
        assert tracer.counters["shed"] == 1
        # shed traces never pollute the scored attribution or the SLO
        assert tracer.breakdown()["n"] == 0
        assert tracer.slo.observations_total == 0

    def test_slowest_survive_ring_eviction(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock, ring_size=16, slowest_n=2)
        # one slow outlier, then enough fast traces to evict it from the
        # ring — the exemplar store must still hold it verbatim
        self._scored_batch(tracer, clock, ["slow"],
                           {"device_wait": 500.0})
        for i in range(40):
            self._scored_batch(tracer, clock, [f"f{i}"],
                               {"device_wait": 1.0})
        ring_ids = {t.txn_id for t in tracer.traces()}
        assert "slow" not in ring_ids                 # evicted from ring
        slowest = tracer.slowest()
        assert slowest[0].txn_id == "slow"            # kept verbatim
        assert slowest[0].e2e_ms == pytest.approx(500.0)

    def test_breakdown_names_dominant_stage(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        for i in range(20):
            self._scored_batch(tracer, clock, [f"t{i}"],
                               {"assemble": 1.0, "device_wait": 12.0,
                                "finalize": 0.5})
        bd = tracer.breakdown()
        assert bd["n"] == 20
        for q in ("p50", "p95", "p99"):
            assert bd["quantiles"][q]["dominant_stage"] == "device_wait"
            stage_ms = bd["quantiles"][q]["stage_ms"]
            assert sum(stage_ms.values()) == pytest.approx(
                bd["quantiles"][q]["e2e_ms"], rel=0.05)
        assert bd["exemplars"]

    def test_chrome_export_structure(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        self._scored_batch(tracer, clock, ["c1", "c2"],
                           {"assemble": 2.0, "device_wait": 3.0})
        payload = tracer.export_chrome_trace()
        events = payload["traceEvents"]
        assert len(events) == 2 * 6        # 2 txns x 6 recorded stages
        assert {e["ph"] for e in events} == {"X"}
        names = {e["name"] for e in events}
        assert {"queue", "assemble", "device_wait"} <= names
        args = events[0]["args"]
        assert args["trace_id"] and args["txn_id"]
        json.dumps(payload)                # must be JSON-serializable

    def test_reset_clears_window_not_counters(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        self._scored_batch(tracer, clock, ["r1"], {"assemble": 1.0})
        tracer.reset()
        assert tracer.traces() == []
        assert tracer.counters["completed"] == 1


class TestSloTracker:
    def test_burn_rate_math(self):
        clock = [0.0]
        slo = SloTracker(objective_ms=20.0, objective_frac=0.99,
                         fast_window_s=1.0, slow_window_s=4.0,
                         bucket_s=0.05, clock=lambda: clock[0])
        for i in range(100):
            slo.record(5.0, now=clock[0])         # within objective
        slo.record(50.0, now=clock[0])            # one violation
        # violation frac 1/101 over a 1% budget -> burn ~0.99
        assert slo.burn_rate(1.0, now=clock[0]) == pytest.approx(
            (1 / 101) / 0.01, rel=1e-6)
        snap = slo.snapshot(now=clock[0])
        assert snap["windows"]["fast"]["violations"] == 1
        assert snap["violations_total"] == 1

    def test_window_ages_out(self):
        clock = [0.0]
        slo = SloTracker(objective_ms=20.0, objective_frac=0.99,
                         fast_window_s=1.0, slow_window_s=4.0,
                         bucket_s=0.05, clock=lambda: clock[0])
        for _ in range(50):
            slo.record(100.0, now=clock[0])       # all violations
        assert slo.burn_rate(1.0, now=clock[0]) == pytest.approx(100.0)
        clock[0] += 2.0                           # past the fast window
        assert slo.burn_rate(1.0, now=clock[0]) == 0.0
        # the slow window still sees them
        assert slo.burn_rate(4.0, now=clock[0]) == pytest.approx(100.0)


class TestSyncTracing:
    def _snapshot_with_traffic(self, clock, tracer):
        ctxs = [tracer.begin(f"m{i}") for i in range(4)]
        tb = tracer.batch(ctxs, batch_size=4)
        for stage in ("assemble", "pack", "dispatch", "device_wait",
                      "finalize"):
            tb.mark(stage)
            clock[0] += 0.003
        tracer.finish_batch(tb)
        return tracer.snapshot()

    def test_counter_delta_mirror(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        snap = self._snapshot_with_traffic(clock, tracer)
        mc = MetricsCollector()
        mc.sync_tracing(snap)
        assert mc.trace_completed.value(terminal="scored") == 4
        assert mc.trace_stage_ms.count(stage="assemble") == 4
        assert mc.trace_stage_ms.sum(stage="assemble") == pytest.approx(
            4 * 3.0, rel=0.01)
        # honest deltas: an unchanged snapshot mirrors as +0
        mc.sync_tracing(snap)
        assert mc.trace_completed.value(terminal="scored") == 4
        assert mc.trace_stage_ms.count(stage="assemble") == 4
        # more traffic mirrors only the increment
        snap2 = self._snapshot_with_traffic(clock, tracer)
        mc.sync_tracing(snap2)
        assert mc.trace_completed.value(terminal="scored") == 8
        assert mc.trace_stage_ms.count(stage="assemble") == 8

    def test_identical_series_from_two_collectors(self):
        """Satellite: stream-job and serving mirror the SAME snapshot into
        independent collectors — the rendered trace_* series must match."""
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        snap = self._snapshot_with_traffic(clock, tracer)
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_tracing(snap)
        b.sync_tracing(snap)

        def trace_lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if ln.startswith("trace_")]

        assert trace_lines(a) == trace_lines(b)

    def test_exemplar_rendered_with_trace_id(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock)
        snap = self._snapshot_with_traffic(clock, tracer)
        mc = MetricsCollector()
        mc.sync_tracing(snap)
        text = mc.render_prometheus()
        ex_lines = [ln for ln in text.splitlines()
                    if ln.startswith("# exemplar trace_stage_ms_bucket")]
        assert ex_lines, "exemplar trace_ids must render as comment lines"
        assert 'trace_id="' in ex_lines[0]
        assert "trace_slo_burn_rate" in text
        # classic text format (version=0.0.4): no sample line may carry
        # trailing content — a trailing '#' would fail the WHOLE scrape
        for ln in text.splitlines():
            if ln and not ln.startswith("#"):
                assert "#" not in ln, f"exemplar leaked onto sample: {ln}"

    def test_slo_violation_counter_mirrors(self):
        clock = [0.0]
        tracer = _vclock_tracer(clock, slo_objective_ms=1.0)
        self._snapshot_with_traffic(clock, tracer)   # e2e 15ms > 1ms
        mc = MetricsCollector()
        mc.sync_tracing(tracer.snapshot())
        assert mc.trace_slo_violations.total() == 4
        mc.sync_tracing(tracer.snapshot())
        assert mc.trace_slo_violations.total() == 4


class TestStreamJobTracing:
    """Trace-context propagation through the REAL stream path."""

    def _run_job(self, qos=None, n=96, batch=32):
        from realtime_fraud_detection_tpu.obs.trace_drill import (
            TraceDrillConfig,
            TraceDrillScorer,
        )
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        clock = [0.0]
        tracer = _vclock_tracer(clock, ring_size=1024)
        scorer = TraceDrillScorer(clock, TraceDrillConfig(max_batch=batch))
        broker = InMemoryBroker()
        job = StreamJob(broker, scorer, JobConfig(
            max_batch=batch, emit_features=False, emit_enriched=False,
            qos=qos, tracing=tracer))
        txns = [{"transaction_id": f"j{i}", "user_id": f"u{i % 7}",
                 "merchant_id": "m1", "amount": 5.0 if i % 2 else 900.0,
                 "timestamp": "0.0"}
                for i in range(n)]
        broker.produce_batch(T.TRANSACTIONS, txns,
                             key_fn=lambda r: r["user_id"])
        job.run_until_drained(now=0.0)
        return tracer, job, txns

    def test_every_scored_txn_has_one_trace(self):
        tracer, job, txns = self._run_job()
        scored = tracer.traces(terminal="scored")
        assert len(scored) == len(txns)
        assert {t.txn_id for t in scored} == \
            {t["transaction_id"] for t in txns}
        for t in scored:
            assert {"queue", "assemble", "pack", "dispatch",
                    "device_wait", "finalize"} <= set(t.stages)
            assert t.meta["batch_size"] >= 1
            assert t.meta["close_reason"] in (
                "size", "deadline", "budget", "timeout", "flush")

    def test_shed_txns_carry_terminal_shed_stage(self):
        from realtime_fraud_detection_tpu.qos import QosPlane
        from realtime_fraud_detection_tpu.utils.config import QosSettings

        qos = QosPlane(QosSettings(enabled=True, admission_rate=1.0,
                                   admission_burst=8.0))
        tracer, job, txns = self._run_job(qos=qos)
        assert job.counters["shed"] > 0
        shed = tracer.traces(terminal="shed")
        assert len(shed) == job.counters["shed"]
        for t in shed:
            assert t.terminal == "shed"
            assert t.meta["reason"]
        # shed + scored partition the admitted stream
        assert len(shed) + len(tracer.traces(terminal="scored")) \
            == len(txns)


def test_trace_drill_fast_smoke(capsys):
    """The `rtfd trace-drill --fast` acceptance path runs un-slow-marked
    on every tier-1 pass — through the CLI entry, pinning attribution,
    SLO reaction + recovery, FIFO/shed equality, and the overhead bound
    (final stdout line: the compact <2 KB verdict)."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["trace-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    assert compact["dominant"] == {"slow_assembly": "assemble",
                                   "slow_device": "device_wait"}
    assert compact["burn"]["slow_device_peak"] > compact["burn"]["threshold"]
    full = json.loads(out[-2])
    assert full["checks"]["noop_under_bound"]


def test_tracing_overhead_guard_real_scorer():
    """Tier-1 CI overhead guard: a fixed fake-Kafka workload on the REAL
    scorer, tracing off vs on — the per-txn wall-clock ratio must stay
    under the pinned bound (the plane is admissible on the hot path, not
    just in the virtual drill). Batch 16 reuses the bucket other tier-1
    suites already compiled in-process, so the guard costs seconds."""
    import time

    from realtime_fraud_detection_tpu.obs.tracing import Tracer as _Tracer
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T

    batch, n = 16, 256

    def soak(traced: bool) -> float:
        gen = TransactionGenerator(num_users=500, num_merchants=100,
                                   seed=13)
        broker = InMemoryBroker()
        s = FraudScorer(scorer_config=ScorerConfig())
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        tracer = (_Tracer(TracingSettings(enabled=True))
                  if traced else None)
        job = StreamJob(broker, s, JobConfig(
            max_batch=batch, emit_features=False, tracing=tracer))
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(n),
                             key_fn=lambda r: str(r["user_id"]))
        s.score_batch(gen.generate_batch(batch))     # compile outside
        t0 = time.perf_counter()
        job.run_until_drained(now=1000.0)
        return time.perf_counter() - t0

    # interleaved best-of-2 per arm damps scheduler noise; the bound is
    # deliberately generous (tracing measures ~1.01x) so only a real
    # hot-path regression trips it
    off = min(soak(False), soak(False))
    on = min(soak(True), soak(True))
    assert on / off < 1.5, f"tracing overhead ratio {on / off:.3f} >= 1.5"


# ---------------------------------------------------------------------------
# distributed tracing + fleet aggregation (ISSUE 20)
# ---------------------------------------------------------------------------

class TestCarrier:
    """Cross-process trace carrier: wire roundtrip, transit attribution,
    redirect ledger, and loss accounting (fresh root, never a wedge)."""

    def test_roundtrip_and_sparse_wire_form(self):
        from realtime_fraud_detection_tpu.obs.tracing import (
            make_carrier,
            parse_carrier,
        )

        c = make_carrier("tingress-2a", origin="ingress", produced_ts=12.5,
                         priority="high", hops=2, redirect_s=0.003)
        # survives JSON framing (the broker wire) verbatim
        p = parse_carrier(json.loads(json.dumps(c)))
        assert p["tid"] == "tingress-2a" and p["org"] == "ingress"
        assert p["ts"] == 12.5 and p["rh"] == 2 and p["rs"] == 0.003
        # empty fields never ride the wire — the carrier stays tiny
        assert set(make_carrier("t1")) == {"v", "tid"}

    def test_parse_rejects_garbage(self):
        from realtime_fraud_detection_tpu.obs.tracing import parse_carrier

        for bad in (None, "x", 7, [], {}, {"tid": ""}, {"tid": 3}):
            assert parse_carrier(bad) is None

    def test_adopted_carrier_books_transit_additively(self):
        from realtime_fraud_detection_tpu.obs.tracing import make_carrier

        clock = [0.0]
        tracer = _vclock_tracer(clock)
        # produced at wall 10.0, consumed at wall 10.4; the record's own
        # event-time lag is 0.5 s — ingest must shrink by the transit so
        # the pre-admission segments never double-count one interval
        c = make_carrier("tingress-1", origin="ingress", produced_ts=10.0)
        ctx = tracer.begin("tx1", ingest_lag_s=0.5, carrier=c,
                           now_wall=10.4)
        tb = tracer.batch([ctx])
        tb.mark("device_wait")
        clock[0] += 0.010
        tracer.finish_batch(tb)
        (t,) = tracer.traces(terminal="scored")
        assert t.trace_id == "tingress-1" and t.origin == "ingress"
        assert t.stages["broker_transit"] == pytest.approx(400.0)
        assert t.stages["ingest"] == pytest.approx(100.0)
        assert sum(t.stages.values()) == pytest.approx(t.e2e_ms)
        assert t.to_dict()["origin"] == "ingress"
        assert tracer.counters["carrier_adopted"] == 1
        assert tracer.counters["carrier_lost"] == 0

    def test_redirect_ledger_is_a_stage(self):
        from realtime_fraud_detection_tpu.obs.tracing import make_carrier

        clock = [0.0]
        tracer = _vclock_tracer(clock)
        c = make_carrier("tserving-9", origin="serving", hops=1,
                         redirect_s=0.002)
        ctx = tracer.begin("tx2", carrier=c)
        tracer.finish_terminal(ctx, "shed", reason="no_tokens")
        (t,) = tracer.traces(terminal="shed")
        assert t.stages["redirect_hops"] == pytest.approx(2.0)

    def test_lost_carrier_degrades_to_fresh_local_root(self):
        clock = [0.0]
        tracer = Tracer(TracingSettings(enabled=True, ring_size=64,
                                        origin="w7"),
                        clock=lambda: clock[0])
        # expected-but-missing and present-but-garbled both count as loss
        lost1 = tracer.begin("tx3", expect_carrier=True)
        lost2 = tracer.begin("tx4", carrier={"v": 1})
        for ctx in (lost1, lost2):
            # fresh LOCAL root: minted id carries THIS process's origin
            # prefix, no adopted origin, no transit
            assert ctx.trace_id.startswith("tw7-")
            assert ctx.origin == "" and ctx.broker_transit_s == 0.0
            tracer.finish_terminal(ctx, "shed", reason="test")
        assert tracer.counters["carrier_lost"] == 2
        assert tracer.counters["carrier_adopted"] == 0
        # never a wedge: every started trace reached a terminal
        c = tracer.counters
        assert c["started"] == (c["completed"] + c["shed"] + c["errors"]
                                + c["cached"])


class TestLogTraceCorrelation:
    def test_json_formatter_stamps_active_trace_context(self):
        from realtime_fraud_detection_tpu.obs.tracing import (
            clear_log_context,
            set_log_context,
        )

        rec = logging.LogRecord("t", logging.INFO, __file__, 1, "in-batch",
                                (), None)
        set_log_context("tw2-0000002a", "w2")
        try:
            out = json.loads(JsonFormatter().format(rec))
        finally:
            clear_log_context()
        assert out["trace_id"] == "tw2-0000002a"
        assert out["worker"] == "w2"
        # context cleared -> no stamp (and explicit record fields win)
        rec2 = logging.LogRecord("t", logging.INFO, __file__, 1, "idle",
                                 (), None)
        out2 = json.loads(JsonFormatter().format(rec2))
        assert "trace_id" not in out2 and "worker" not in out2


class TestFleetMetrics:
    def _fm(self):
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            FleetMetrics,
        )

        return FleetMetrics()

    def test_delta_fold_is_exact_and_dedupes_stale_seq(self):
        fm = self._fm()
        assert fm.ingest_delta({"worker": "w0", "seq": 1,
                                "counters": {"scored_total": 3.0,
                                             "shed": 0.0}})
        assert fm.ingest_delta({"worker": "w1", "seq": 1,
                                "counters": {"scored_total": 2.0}})
        assert fm.ingest_delta({"worker": "w0", "seq": 2,
                                "counters": {"scored_total": 4.0,
                                             "shed": 1.0}})
        # replayed/stale event is dropped, not double-counted
        assert not fm.ingest_delta({"worker": "w0", "seq": 2,
                                    "counters": {"scored_total": 99.0}})
        fleet = fm.fleet_counters()
        assert fleet["scored_total"] == 9.0
        assert fleet["shed"] == 1.0
        assert fm.worker_counters()["w0"]["scored_total"] == 7.0
        snap = fm.snapshot()
        assert snap["events_applied"] == 3 and snap["events_stale"] == 1
        assert snap["seq"] == {"w0": 2, "w1": 1}

    def test_render_prometheus_hygiene(self):
        fm = self._fm()
        fm.ingest_cumulative("w0", {"scored_total": 3, "shed": 1})
        fm.ingest_cumulative("w1", {"scored_total": 2})
        fm.set_worker_info("w0", pid="123", version="0.1.0")
        text = fm.render(version="0.1.0")
        lines = text.splitlines()
        # exactly one HELP/TYPE pair per family, HELP immediately
        # followed by TYPE
        helps = [ln.split()[2] for ln in lines if ln.startswith("# HELP")]
        types = [ln.split()[2] for ln in lines if ln.startswith("# TYPE")]
        assert helps == sorted(set(helps))
        assert types == helps
        # counter suffix normalization: never _total_total, and keys
        # without the suffix gain it exactly once
        assert "_total_total" not in text
        assert 'rtfd_worker_shed_total{worker="w0"} 1' in lines
        # the unlabeled fleet sum equals the per-worker sum
        assert "rtfd_fleet_scored_total 5" in lines
        # identity gauges
        assert any(ln.startswith("rtfd_build_info{")
                   and 'version="0.1.0"' in ln and ln.endswith(" 1")
                   for ln in lines)
        assert any(ln.startswith("fleet_worker_info{")
                   and 'pid="123"' in ln and 'worker="w0"' in ln
                   for ln in lines)


def _trace_row(tid, txn, worker_s, t_start, stages, origin="",
               terminal="scored", spans=None):
    e2e = sum(stages.values())
    meta = {"spans": spans} if spans else {}
    row = {"trace_id": tid, "txn_id": txn, "t_start": t_start,
           "e2e_ms": e2e, "stages": dict(stages), "meta": meta,
           "terminal": terminal, "priority": ""}
    if origin:
        row["origin"] = origin
    return row


class TestFleetTraceStore:
    def _store(self, **kw):
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            FleetTraceStore,
        )

        return FleetTraceStore(**kw)

    def test_stitch_stats_crossed_fresh_and_remote(self):
        st = self._store()
        st.ingest("w0", [
            _trace_row("tingress-1", "a", "w0", 1.0,
                       {"ingest": 1.0, "broker_transit": 4.0,
                        "device_wait": 2.0}, origin="ingress"),
            _trace_row("tw0-1", "b", "w0", 1.1, {"device_wait": 2.0}),
        ], pid=41)
        st.ingest("w1", [
            _trace_row("tingress-2", "c", "w1", 1.2,
                       {"ingest": 0.5, "broker_transit": 8.0,
                        "device_wait": 2.0,
                        "remote_fetch": 1.5}, origin="ingress",
                       spans=[{"name": "remote_fetch", "ms": 1.5}]),
        ], pid=42)
        s = st.stitch_stats()
        assert s["total"] == 3
        assert s["crossed_process"] == 2
        assert s["fresh_roots"] == 1
        assert s["with_remote_span"] == 1
        assert s["stitch_rate"] == pytest.approx(2 / 3, abs=1e-3)
        assert s["broker_transit_ms"]["n"] == 2
        assert s["broker_transit_ms"]["max"] == pytest.approx(8.0)

    def test_breakdown_attributes_dominant_worker(self):
        st = self._store()
        # w0 fast, w1 the slow worker: device_wait owns w1's traces and
        # w1 owns the fleet tail
        st.ingest("w0", [
            _trace_row(f"tw0-{i}", f"f{i}", "w0", 1.0 + i * 0.01,
                       {"assemble": 1.0, "device_wait": 2.0})
            for i in range(10)])
        st.ingest("w1", [
            _trace_row(f"tw1-{i}", f"s{i}", "w1", 1.0 + i * 0.01,
                       {"assemble": 1.0, "device_wait": 90.0 + i})
            for i in range(10)])
        bd = st.breakdown()
        assert bd["n"] == 20
        for q in ("p50", "p95", "p99"):
            assert bd["quantiles"][q]["dominant_worker"] == "w1"
            assert bd["quantiles"][q]["dominant_stage"] == "device_wait"
        assert bd["per_worker"]["w1"]["dominant_stage"] == "device_wait"
        assert bd["exemplars"][0]["worker"] == "w1"

    def test_export_draws_flow_arrows_across_the_broker_hop(self):
        st = self._store()
        st.ingest("w0", [
            _trace_row("tingress-1", "a", "w0", 1.0,
                       {"ingest": 1.0, "broker_transit": 4.0,
                        "device_wait": 2.0}, origin="ingress"),
            _trace_row("tw0-1", "b", "w0", 1.1, {"device_wait": 2.0}),
        ], pid=41)
        payload = st.export_chrome_trace()
        ev = payload["traceEvents"]
        track_names = {e["args"]["name"] for e in ev if e["ph"] == "M"}
        assert "worker w0 (pid 41)" in track_names
        assert "ingress ingress" in track_names
        starts = [e for e in ev if e["ph"] == "s"]
        ends = [e for e in ev if e["ph"] == "f"]
        assert len(starts) == len(ends) == 1      # one crossed trace
        assert starts[0]["pid"] != ends[0]["pid"]  # arrow crosses tracks
        # the stitched trace's transit slice draws on the ORIGIN track
        transit = [e for e in ev if e["ph"] == "X"
                   and e["name"] == "broker_transit"]
        assert transit[0]["pid"] == starts[0]["pid"]
        json.dumps(payload)

    def test_merge_chrome_traces_folds_ring_dumps(self):
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            merge_chrome_traces,
        )

        dumps = [
            {"worker": "w0", "pid": 41, "traces": [
                _trace_row("tingress-1", "a", "w0", 1.0,
                           {"ingest": 1.0, "broker_transit": 4.0,
                            "device_wait": 2.0}, origin="ingress")]},
            {"worker": "w1", "pid": 42, "traces": [
                _trace_row("tw1-1", "b", "w1", 1.1,
                           {"device_wait": 2.0})]},
        ]
        merged = merge_chrome_traces(dumps)
        tracks = merged["metadata"]["tracks"]
        assert {"w0", "w1", "ingress"} <= set(tracks)
        assert merged["metadata"]["n_traces"] == 2
        assert any(e["ph"] == "s" for e in merged["traceEvents"])


def test_obs_drill_fast_smoke(capsys):
    """The `rtfd obs-drill --fast --no-replay` acceptance path runs
    un-slow-marked on every tier-1 pass — ≥2 real OS worker processes,
    producer-stamped carriers over the TCP netbroker, the netfault
    carrier-strip window, fleet-metric exactness, and the compact <2 KB
    verdict as the final stdout line. One retry absorbs a wall-clock
    scheduling stall on oversubscribed CI hosts (the drill's overhead
    ratio and p99 attribution are real-time measurements over real OS
    processes — the `_dryrun_multihost` retry discipline); a retried
    pass still proves the plane, a double failure fails the gate."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["obs-drill", "--fast", "--no-replay"])
    if rc != 0:
        capsys.readouterr()                       # drop the failed pass
        rc = cli.main(["obs-drill", "--fast", "--no-replay"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    assert compact["crossed"] > 0
    carriers = compact["carriers"]
    assert carriers["lost_total"] == carriers["stripped"]
    # "carried" counts every record that kept its carrier (redirect
    # records included) — adoption must match it exactly
    assert carriers["adopted_total"] == carriers["carried"]
    full = json.loads(out[-2])
    assert full["checks"]["fleet_counters_exact"]
    assert full["checks"]["no_cross_attachment"]
    assert full["checks"]["broker_transit_nonzero"]

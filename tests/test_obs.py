"""Observability plane: metrics registry, drift monitor, logs, span timer."""

import json
import logging

import numpy as np
import pytest

from realtime_fraud_detection_tpu.obs import (
    DriftConfig,
    FeatureDriftMonitor,
    JsonFormatter,
    MetricsCollector,
    Registry,
    SpanTimer,
    log_prediction_result,
)


class TestRegistry:
    def test_counter_labels_and_total(self):
        r = Registry()
        c = r.counter("preds_total", "predictions", ("model", "decision"))
        c.inc(model="xgb", decision="APPROVE")
        c.inc(2, model="xgb", decision="DECLINE")
        assert c.value(model="xgb", decision="APPROVE") == 1
        assert c.total() == 3

    def test_counter_rejects_negative(self):
        c = Registry().counter("c", "h")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc_dec(self):
        g = Registry().gauge("g", "h")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_histogram_buckets_and_quantile(self):
        h = Registry().histogram("h", "lat", buckets=(0.01, 0.1, 1.0))
        for v in [0.005] * 98 + [0.5, 0.5]:
            h.observe(v)
        assert h.count() == 100
        assert h.quantile(0.5) == 0.01
        assert h.quantile(0.99) == pytest.approx(1.0)

    def test_prometheus_text_format(self):
        r = Registry()
        c = r.counter("x_total", "things", ("k",))
        c.inc(k="v")
        h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        text = r.render()
        assert "# TYPE x_total counter" in text
        assert 'x_total{k="v"} 1' in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 1' in text
        assert "lat_seconds_count 1" in text

    def test_non_finite_observation_dropped(self):
        h = Registry().histogram("h", "lat", buckets=(0.1, 1.0))
        h.observe(float("nan"))
        h.observe(float("inf"))
        h.observe(0.05)
        assert h.count() == 1
        assert h.sum() == pytest.approx(0.05)
        sum_line = [ln for ln in h.render() if "_sum" in ln][0]
        assert "nan" not in sum_line and "inf" not in sum_line.lower()

    def test_quantile_in_overflow_bucket_reports_max(self):
        h = Registry().histogram("h", "lat", buckets=(0.1, 1.0))
        for _ in range(10):
            h.observe(60.0)
        assert h.quantile(0.99) == pytest.approx(60.0)

    def test_label_values_escaped(self):
        c = Registry().counter("c_total", "h", ("k",))
        c.inc(k='say "hi"\nnewline\\slash')
        line = [ln for ln in c.render() if ln.startswith("c_total{")][0]
        assert '\\"hi\\"' in line and "\\n" in line and "\\\\" in line
        assert "\n" not in line

    def test_duplicate_name_rejected(self):
        r = Registry()
        r.counter("dup", "h")
        with pytest.raises(ValueError):
            r.gauge("dup", "h")


class TestMetricsCollector:
    def test_record_and_summary(self):
        t = [0.0]
        m = MetricsCollector(clock=lambda: t[0])
        for i in range(10):
            t[0] = float(i)
            m.record_prediction(
                "APPROVE" if i < 8 else "DECLINE",
                fraud_score=0.1 * i, duration_s=0.004,
                model_predictions={"xgboost_primary": 0.2},
            )
        s = m.summary()
        assert s["total_predictions"] == 10
        assert s["decision_counts"] == {"APPROVE": 8, "DECLINE": 2}
        assert s["throughput_tps_60s"] == pytest.approx(10 / 60.0)
        assert s["latency_ms"]["p99"] <= 5.0 + 1e-9
        assert m.predictions_total.value(
            model="xgboost_primary", decision="APPROVE") == 8

    def test_prometheus_render_includes_domain_metrics(self):
        m = MetricsCollector()
        m.record_prediction("REVIEW", 0.9, 0.002)
        m.record_error("assemble")
        text = m.render_prometheus()
        assert 'ml_predictions_total{decision="REVIEW",model="ensemble"} 1' in text
        assert 'ml_prediction_errors_total{stage="assemble"} 1' in text

    def test_throughput_not_capped_by_latency_window(self):
        t = [0.0]
        m = MetricsCollector(window=100, clock=lambda: t[0])
        for i in range(1000):           # 1000 events in 10 "seconds"
            t[0] = i / 100.0
            m.record_prediction("APPROVE", 0.1, 0.001)
        s = m.summary()
        assert s["throughput_tps_60s"] == pytest.approx(1000 / 60.0)
        assert s["recent_predictions"] == 100   # latency window stays capped

    def test_batch_duration_recorded(self):
        m = MetricsCollector()
        m.record_batch(32, 0.008)
        assert m.batch_duration.count() == 1
        assert m.batch_duration.sum() == pytest.approx(0.008)

    def test_reset_clears_window_not_counters(self):
        m = MetricsCollector()
        m.record_prediction("APPROVE", 0.1, 0.001)
        m.reset()
        s = m.summary()
        assert s["recent_predictions"] == 0
        assert s["throughput_tps_60s"] == 0.0
        assert m.predictions_total.total() > 0


class TestDrift:
    def _warm(self, mon, rng, rows, loc=0.0, scale=1.0):
        mon.update(rng.normal(loc, scale, size=(rows, 8)))

    def test_no_drift_on_same_distribution(self):
        rng = np.random.default_rng(0)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=1000,
                                              window_rows=1000))
        self._warm(mon, rng, 1200)
        assert mon.baseline_frozen
        self._warm(mon, rng, 1000)
        rep = mon.report()
        assert not rep.drifted
        assert rep.max_psi < 0.1

    def test_detects_mean_shift(self):
        rng = np.random.default_rng(1)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=1000,
                                              window_rows=1000))
        self._warm(mon, rng, 1200)
        shifted = rng.normal(0, 1, size=(1000, 8))
        shifted[:, 3] += 3.0                       # feature 3 drifts hard
        mon.update(shifted)
        rep = mon.report()
        assert rep.drifted
        assert 3 in rep.top_features
        assert rep.psi[3] > 0.25
        assert rep.psi[0] < 0.25

    def test_report_before_freeze_is_quiet(self):
        mon = FeatureDriftMonitor(DriftConfig(num_features=4, warmup_rows=100))
        mon.update(np.zeros((10, 4)))
        rep = mon.report()
        assert not rep.drifted and not rep.baseline_frozen

    def test_shape_validation(self):
        mon = FeatureDriftMonitor(DriftConfig(num_features=4))
        with pytest.raises(ValueError):
            mon.update(np.zeros((10, 5)))

    def test_tiny_window_does_not_false_alarm(self):
        rng = np.random.default_rng(2)
        mon = FeatureDriftMonitor(DriftConfig(num_features=8,
                                              warmup_rows=500,
                                              window_rows=500,
                                              min_report_rows=200))
        mon.update(rng.normal(size=(600, 8)))
        mon.update(rng.normal(size=(1, 8)))       # near-empty window
        rep = mon.report()
        assert not rep.drifted and rep.max_psi == 0.0


class TestLogs:
    def test_json_formatter_fields(self):
        rec = logging.LogRecord("t", logging.INFO, __file__, 1, "hello",
                                (), None)
        rec.transaction_id = "tx1"
        out = json.loads(JsonFormatter().format(rec))
        assert out["message"] == "hello"
        assert out["transaction_id"] == "tx1"
        assert out["level"] == "INFO"

    def test_log_prediction_result_structured(self, caplog):
        logger = logging.getLogger("test.pred")
        with caplog.at_level(logging.INFO, logger="test.pred"):
            log_prediction_result(logger, "tx9", 0.87, "REVIEW", 3.2)
        rec = caplog.records[-1]
        assert rec.transaction_id == "tx9"
        assert rec.decision == "REVIEW"
        assert rec.fraud_score == pytest.approx(0.87)


class TestSpanTimer:
    def test_span_stats(self):
        t = [0.0]
        timer = SpanTimer(clock=lambda: t[0])
        for dt in (0.001, 0.002, 0.010):
            with timer.span("assemble"):
                t[0] += dt
        st = timer.stats("assemble")["assemble"]
        assert st["count"] == 3
        assert st["max_ms"] == pytest.approx(10.0)
        assert st["total_s"] == pytest.approx(0.013)
        timer.reset()
        assert timer.stats() == {}

"""Elastic process-cluster plane (ISSUE 12): the network handoff store's
failure modes (torn blob -> previous checkpoint, zombie fencing, server
restart retried), the autoscale controller's deterministic ledger +
ahead-of-ramp property, the sync_autoscale Prometheus mirror pins, the
421-following ingress client over live HTTP, the SIGTERM-vs-SIGKILL
replay-depth regression on a REAL worker subprocess, the tuner's
in-flight-depth freeze under cluster feedback, and the `rtfd
elastic-drill --fast` tier-1 smoke."""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from realtime_fraud_detection_tpu.cluster.autoscale import (
    AutoscaleController,
)
from realtime_fraud_detection_tpu.cluster.handoff import (
    HandoffClient,
    HandoffServer,
)
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.tuning.forecast import ArrivalForecaster


# ---------------------------------------------------------------------------
# handoff server: durability + failure modes
# ---------------------------------------------------------------------------


class TestHandoffStore:
    def test_roundtrip_and_server_restart_scan(self, tmp_path):
        """Blobs survive a handoff-server restart: the committed files
        are rescanned and served, sha-verified."""
        blob_dir = str(tmp_path / "blobs")
        srv = HandoffServer(blob_dir=blob_dir).start()
        port = srv.port
        cli = HandoffClient(port=port)
        cli.epoch = 1
        cli.put(3, 120, b"state-blob-a")
        cli.put(3, 150, b"state-blob-b")
        assert cli.get(3) == (150, b"state-blob-b")
        assert cli.offsets() == {3: 150}
        cli.close()
        srv.stop()

        srv2 = HandoffServer(port=port, blob_dir=blob_dir).start()
        try:
            cli2 = HandoffClient(port=port)
            assert cli2.get(3) == (150, b"state-blob-b")
            assert cli2.stats()["restores_total"] == 1
            cli2.close()
        finally:
            srv2.stop()

    def test_torn_blob_detected_and_previous_served(self, tmp_path):
        """A torn/truncated newest checkpoint fails its sha256 and the
        PREVIOUS checkpoint is served instead — counted, never silently
        used."""
        blob_dir = str(tmp_path / "blobs")
        srv = HandoffServer(blob_dir=blob_dir).start()
        try:
            cli = HandoffClient(port=srv.port)
            cli.put(0, 100, b"good-old-checkpoint")
            cli.put(0, 200, b"torn-new-checkpoint")
            newest = sorted(
                glob.glob(os.path.join(blob_dir, "p0-*.blob")),
                key=lambda p: int(os.path.basename(p).split("-")[1]))[-1]
            assert "200" in os.path.basename(newest)
            with open(newest, "r+b") as f:
                f.truncate(70)            # sha header + a few bytes
            # force the disk path (drop the in-memory copy, like a
            # restarted server would)
            with srv._lock:
                srv._ledger[0] = [(off, ep, sha, None, path)
                                  for off, ep, sha, _, path
                                  in srv._ledger[0]]
            assert cli.get(0) == (100, b"good-old-checkpoint")
            stats = cli.stats()
            assert stats["torn_blobs_total"] == 1
            assert stats["restores_total"] == 1
            cli.close()
        finally:
            srv.stop()

    def test_zombie_writer_fenced_by_epoch(self, tmp_path):
        """A checkpoint put carrying a stale offset-epoch — a zombie
        worker that lost the partition in a rebalance — is refused
        loudly; the current-epoch owner still writes."""
        srv = HandoffServer(blob_dir=str(tmp_path / "b")).start()
        try:
            cli = HandoffClient(port=srv.port)
            cli.epoch = 3
            cli.put(5, 10, b"gen3")
            cli.fence(5, 4)
            with pytest.raises(RuntimeError, match="FencedEpochError"):
                cli.put(5, 12, b"zombie-gen3")
            assert cli.stats()["fenced_rejects_total"] == 1
            cli.epoch = 4
            cli.put(5, 15, b"gen4")
            assert cli.get(5) == (15, b"gen4")
            cli.close()
        finally:
            srv.stop()

    def test_server_restart_mid_restore_retried_with_backoff(self,
                                                             tmp_path):
        """A restore against a restarting handoff server retries the
        SAME address with DeterministicBackoff instead of surfacing a
        worker crash."""
        blob_dir = str(tmp_path / "blobs")
        srv = HandoffServer(blob_dir=blob_dir).start()
        port = srv.port
        slept = []

        def _sleep(d):
            slept.append(d)
            time.sleep(min(d, 0.05))

        cli = HandoffClient(port=port, retry_sleep=_sleep)
        cli.put(7, 42, b"before-restart")
        srv.stop()

        def _restart():
            time.sleep(0.15)
            HandoffServer(port=port, blob_dir=blob_dir).start()

        t = threading.Thread(target=_restart, daemon=True)
        t.start()
        assert cli.get(7) == (42, b"before-restart")
        assert slept, "reconnect must go through the backoff seam"
        t.join()
        cli.close()


# ---------------------------------------------------------------------------
# autoscale controller
# ---------------------------------------------------------------------------


def _ramp_arrivals(seed: int = 7):
    from realtime_fraud_detection_tpu.sim.arrivals import (
        DiurnalBurstConfig,
        DiurnalBurstProcess,
    )

    proc = DiurnalBurstProcess(DiurnalBurstConfig(
        trough_tps=100.0, peak_tps=700.0, period_s=12.0,
        burst_duration_s=0.0), seed=seed)
    return proc, proc.generate(12.0)


class TestAutoscaleController:
    def _controller(self):
        return AutoscaleController(
            per_worker_tps=110.0, min_workers=4, max_workers=8,
            headroom=1.25, lead_s=1.5, decide_interval_s=0.5,
            down_patience=3,
            forecaster=ArrivalForecaster(bucket_s=0.25))

    def test_validation(self):
        with pytest.raises(ValueError):
            AutoscaleController(per_worker_tps=0.0)
        with pytest.raises(ValueError):
            AutoscaleController(per_worker_tps=10, min_workers=5,
                                max_workers=4)
        with pytest.raises(ValueError):
            AutoscaleController(per_worker_tps=10, headroom=0.9)

    def test_ledger_deterministic_and_chunking_independent(self):
        """The decision ledger is a pure function of the arrival
        schedule: idle polls at arbitrary instants between arrivals must
        not change it (boundaries are decided before arrivals beyond
        them are fed)."""
        _, times = _ramp_arrivals()
        a, b = self._controller(), self._controller()
        for t in times:
            a.observe(float(t), 1)
        a.observe(14.0, 0)
        poll = 0.137
        nxt = poll
        for t in times:
            while nxt < t:            # irregular idle polls interleaved
                b.observe(nxt, 0)
                nxt += poll
            b.observe(float(t), 1)
        while nxt < 14.0:
            b.observe(nxt, 0)
            nxt += poll
        b.observe(14.0, 0)
        assert a.snapshot()["decisions"] == b.snapshot()["decisions"]
        assert a.events == b.events and a.events["up"] >= 1

    def test_ahead_of_ramp_and_drain(self):
        """Provisioned capacity (ledger target x per-worker tps) covers
        the true diurnal envelope at every decision boundary — the
        forecast lead + headroom keep the controller ahead of a steep
        ramp — and after the ramp the target drains back to the floor."""
        proc, times = _ramp_arrivals()
        c = self._controller()
        for t in times:
            c.observe(float(t), 1)
        decisions = list(c.decisions)
        target_at = [(0.0, 4)] + [(d["t"], d["target"]) for d in decisions]

        def target(t):
            cur = 4
            for td, tg in target_at:
                if td <= t:
                    cur = tg
            return cur

        for i in range(25):
            t = i * 0.5
            assert target(t) * 110.0 >= proc.rate_at(t) - 1e-6, \
                f"under-provisioned at t={t}"
        ups = [d for d in decisions if d["direction"] == "up"]
        assert ups and ups[-1]["t"] < 6.0        # peak is at period/2
        assert max(d["target"] for d in ups) == 8
        # trailing silence: the rate forecast decays, the fleet drains
        for i in range(1, 30):
            c.observe(12.0 + i * 0.25, 0)
        assert c.target == 4
        assert c.events["down"] >= 1

    def test_down_patience_hysteresis(self):
        c = AutoscaleController(
            per_worker_tps=100.0, min_workers=1, max_workers=8,
            headroom=1.0, lead_s=0.0, decide_interval_s=1.0,
            down_patience=3,
            forecaster=ArrivalForecaster(bucket_s=0.5))
        t = 0.0
        for _ in range(4000):             # ~400 tps for 10s
            c.observe(t, 1)
            t += 0.0025
        assert c.target >= 4
        high = c.target
        # one quiet decision must NOT drain (patience 3)
        c.observe(t + 1.0, 0)
        assert c.target == high
        for i in range(2, 6):
            c.observe(t + i * 1.0, 0)
        assert c.target == 1


# ---------------------------------------------------------------------------
# sync_autoscale Prometheus mirror
# ---------------------------------------------------------------------------


def _autoscale_snapshot(up=2, down=1, ckpts=10, restores=3, torn=1):
    return {
        "target_workers": 6, "forecast_rate": 512.3,
        "events": {"up": up, "down": down},
        "handoff_server": {"checkpoints_total": ckpts,
                           "restores_total": restores,
                           "torn_blobs_total": torn},
    }


class TestSyncAutoscale:
    def _lines(self, m):
        return "\n".join(
            ln for ln in m.render_prometheus().splitlines()
            if ln.startswith(("autoscale_", "handoff_server_")))

    def test_stream_vs_serving_render_identical(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        a, b = MetricsCollector(), MetricsCollector()
        snap = _autoscale_snapshot()
        a.sync_autoscale(snap)
        b.sync_autoscale(snap)
        assert self._lines(a) == self._lines(b)
        assert "autoscale_target_workers 6" in self._lines(a)
        assert 'autoscale_events_total{direction="up"} 2' in self._lines(a)
        assert "handoff_server_torn_blobs_total 1" in self._lines(a)

    def test_honest_counter_deltas(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_autoscale(_autoscale_snapshot())
        m.sync_autoscale(_autoscale_snapshot())       # re-sync: no growth
        assert m.autoscale_events.total() == 3
        assert m.handoff_server_checkpoints.total() == 10
        m.sync_autoscale(_autoscale_snapshot(up=4, ckpts=15))
        assert m.autoscale_events.total() == 5
        assert m.handoff_server_checkpoints.total() == 15

    def test_snapshot_without_handoff_block(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        m = MetricsCollector()
        m.sync_autoscale({"target_workers": 3, "forecast_rate": 9.0,
                          "events": {"up": 0, "down": 0}})
        assert m.autoscale_target_workers.value() == 3
        assert m.handoff_server_checkpoints.total() == 0


# ---------------------------------------------------------------------------
# partition-scoped consumers over the TCP netbroker
# ---------------------------------------------------------------------------


class TestNetbrokerScopedConsumer:
    def test_partition_scoped_consumption_over_tcp(self):
        from realtime_fraud_detection_tpu.stream.netbroker import (
            BrokerServer,
            NetBrokerClient,
        )

        srv = BrokerServer(port=0).start()
        try:
            cli = NetBrokerClient(port=srv.port)
            n_parts = cli.partitions(T.TRANSACTIONS)
            for i in range(200):
                cli.produce(T.TRANSACTIONS, {"i": i}, key=f"user_{i}")
            scoped = cli.consumer([T.TRANSACTIONS], "g-scoped",
                                  partitions={T.TRANSACTIONS: [0, 1]})
            got = []
            while True:
                recs = scoped.poll(64)
                if not recs:
                    break
                got.extend(recs)
            assert got and all(r.partition in (0, 1) for r in got)
            ends = cli.end_offsets(T.TRANSACTIONS)
            assert len(got) == ends[0] + ends[1] < 200
            assert n_parts == len(ends)
            cli.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# ingress client: follows 421s over live HTTP
# ---------------------------------------------------------------------------


class TestShardIngressClient:
    def test_unreachable_fleet_retries_then_raises(self):
        from realtime_fraud_detection_tpu.serving.ingress_client import (
            NoShardAvailableError,
            ShardIngressClient,
        )

        slept = []
        cli = ShardIngressClient(["http://127.0.0.1:1"], retries=3,
                                 timeout_s=0.5,
                                 retry_sleep=slept.append)
        with pytest.raises(NoShardAvailableError):
            cli.predict({"transaction_id": "t1", "user_id": "u1",
                         "merchant_id": "m1", "amount": 1.0})
        assert len(slept) == 3          # the deterministic backoff seam
        assert cli.snapshot()["retried"] == 3

    def test_stale_ring_pingpong_terminates_explicitly(self):
        """ISSUE 13 satellite: when BOTH the learned affinity and the
        serving ring are stale mid-rebalance, two workers can bounce a
        key back and forth forever — the bounded-redirect guard must
        terminate with an explicit error, never loop."""
        from realtime_fraud_detection_tpu.serving.ingress_client import (
            NoShardAvailableError,
            ShardIngressClient,
        )

        urls = ["http://a", "http://b"]
        cli = ShardIngressClient(urls, max_redirects=3,
                                 retry_sleep=lambda s: None)
        posts = []

        def _pingpong(url, payload):
            posts.append(url)
            other = urls[1] if url == urls[0] else urls[0]
            return 421, {"owner": "elsewhere", "location": other}

        cli._post = _pingpong
        with pytest.raises(NoShardAvailableError):
            cli.predict({"transaction_id": "t1", "user_id": "u9",
                         "merchant_id": "m1", "amount": 1.0})
        # initial attempt + exactly max_redirects follows — bounded
        assert len(posts) == 1 + 3
        assert cli.snapshot()["redirects_followed"] == 3
        # the ping-pong left NO poisoned affinity behind: the last 421
        # invalidated the entry the previous redirect had learned
        assert cli.snapshot()["affinity_size"] == 0

    def test_affinity_invalidated_on_421_for_confirmed_user(self):
        """A previously-CONFIRMED user→worker mapping that starts
        answering 421 (its partition moved) is dropped from the learned
        affinity even when the redirect cannot be followed — the next
        request must not re-route into the same refusal."""
        from realtime_fraud_detection_tpu.serving.ingress_client import (
            NoShardAvailableError,
            ShardIngressClient,
        )

        cli = ShardIngressClient(["http://a", "http://b"],
                                 retry_sleep=lambda s: None)
        script = {"phase": "confirm"}

        def _post(url, payload):
            if script["phase"] == "confirm":
                return 200, {"transaction_id": "t", "fraud_score": 0.1}
            # moved: the old owner refuses and (mid-rebalance) cannot
            # even name a successor yet
            if url == script["stale_url"]:
                return 421, {"owner": None, "location": ""}
            return 200, {"transaction_id": "t", "fraud_score": 0.2}

        cli._post = _post
        txn = {"transaction_id": "t", "user_id": "u1",
               "merchant_id": "m", "amount": 1.0}
        cli.predict(txn)                        # learns the affinity
        stale_url = cli._affinity["u1"]
        script.update(phase="moved", stale_url=stale_url)
        with pytest.raises(NoShardAvailableError):
            cli.predict(txn)                    # 421, no location
        assert "u1" not in cli._affinity        # poisoned entry dropped
        body = cli.predict(txn)                 # rotates to a live worker
        assert body["fraud_score"] == 0.2
        assert cli._affinity["u1"] != stale_url

    def test_follows_421_to_owner_and_learns_affinity(self):
        """Two live cluster-mode serving apps: a request for a user the
        second worker owns, sent to the first, follows the 421 to the
        owner and succeeds; the learned affinity sends the next request
        for that user direct (no second redirect)."""
        import asyncio

        from realtime_fraud_detection_tpu.cluster.hashring import (
            ShardRouter,
        )
        from realtime_fraud_detection_tpu.serving import ServingApp
        from realtime_fraud_detection_tpu.serving.ingress_client import (
            ShardIngressClient,
        )
        from realtime_fraud_detection_tpu.utils.config import Config

        def make_app(wid):
            config = Config()
            config.monitoring.prometheus_port = 0
            config.cluster.enabled = True
            config.cluster.worker_id = wid
            config.cluster.workers = {"w0": "", "w1": ""}
            return ServingApp(config, host="127.0.0.1", port=0)

        apps = {wid: make_app(wid) for wid in ("w0", "w1")}
        loop = asyncio.new_event_loop()
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)

            async def _start():
                for app in apps.values():
                    await app.start()
                started.set()

            loop.run_until_complete(_start())
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(timeout=60)
        try:
            urls = {wid: f"http://127.0.0.1:{app.port}"
                    for wid, app in apps.items()}
            for app in apps.values():
                app.cluster_router.addresses.update(urls)
            ref = ShardRouter(apps["w0"].config.cluster.n_partitions,
                              ["w0", "w1"])
            uid = next(f"user_{i:06d}" for i in range(10_000)
                       if ref.route(f"user_{i:06d}") == "w1")
            txn = {"transaction_id": "t_ingress_1", "user_id": uid,
                   "merchant_id": "m1", "amount": 12.5,
                   "timestamp": 1.0}
            # urls in w0-first order: the round-robin client hits the
            # WRONG shard first, by construction
            cli = ShardIngressClient([urls["w0"], urls["w1"]])
            res = cli.predict(txn)
            assert res.get("fraud_probability") is not None
            assert res["_ingress"]["redirects"] == 1
            assert res["_ingress"]["worker_url"] == urls["w1"]
            res2 = cli.predict({**txn, "transaction_id": "t_ingress_2"})
            assert res2["_ingress"]["redirects"] == 0      # affinity hit
            snap = cli.snapshot()
            assert snap["redirects_followed"] == 1
            assert snap["affinity_hits"] == 1
        finally:
            async def _stop():
                for app in apps.values():
                    await app.stop()

            asyncio.run_coroutine_threadsafe(_stop(),
                                             loop).result(timeout=10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# SIGTERM vs SIGKILL on a REAL worker subprocess (graceful-drain satellite)
# ---------------------------------------------------------------------------


def _one_worker_fleet(tmp_path, tag):
    from realtime_fraud_detection_tpu.cluster.handoff import HandoffServer
    from realtime_fraud_detection_tpu.cluster.procfleet import ProcessFleet
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer

    broker = BrokerServer(port=0).start()
    handoff = HandoffServer(blob_dir=str(tmp_path / f"b-{tag}")).start()
    fleet = ProcessFleet(
        f"127.0.0.1:{broker.port}", f"127.0.0.1:{handoff.port}",
        n_partitions=12,
        spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"},
        worker_spec={"batch": 32, "max_delay_ms": 5.0,
                     "checkpoint_every": 6, "base_ms": 5.0,
                     "per_txn_ms": 1.5})
    fleet.start(1)
    items = []
    for i in range(1800):
        uid = f"user_{i % 300}"
        items.append((uid, {"transaction_id": f"stx_{i}", "user_id": uid,
                            "merchant_id": f"m_{i % 40}",
                            "amount": 5.0 + i % 23,
                            "event_ts": i * 0.001}, time.time()))
    fleet.client.produce_batch_stamped(T.TRANSACTIONS, items)
    deadline = time.time() + 60
    while time.time() < deadline:
        committed = sum(
            fleet.client.committed(fleet.group_id, T.TRANSACTIONS, p)
            for p in range(12))
        if committed > 400 \
                and fleet.handoff.stats()["checkpoints_total"] >= 2:
            break
        time.sleep(0.02)
    else:
        raise AssertionError("worker made no progress")
    return broker, handoff, fleet


def _replay_depth(fleet):
    """Records a resuming inheritor would state-replay: committed offset
    minus last checkpointed offset, summed over partitions."""
    offsets = fleet.handoff.offsets()
    return sum(
        max(0, fleet.client.committed(fleet.group_id, T.TRANSACTIONS, p)
            - offsets.get(p, 0))
        for p in range(12))


class TestWorkerSignals:
    def test_sigterm_drains_to_zero_replay_sigkill_does_not(self,
                                                            tmp_path):
        """THE graceful-shutdown regression: SIGTERM mid-stream drains
        in-flight batches, commits, and writes a final handoff
        checkpoint — a resumer replays NOTHING. SIGKILL (by definition
        unhandled) leaves the committed-vs-checkpoint gap the handoff
        plane exists to replay."""
        broker, handoff, fleet = _one_worker_fleet(tmp_path, "term")
        try:
            st = fleet.workers["w0"]
            os.kill(st["pid"], signal.SIGTERM)
            assert st["proc"].wait(timeout=60) == 0
            deadline = time.time() + 10
            while "w0" not in fleet.all_byes() and time.time() < deadline:
                fleet.poll_events()
                time.sleep(0.02)
            bye = fleet.all_byes()["w0"]
            assert bye["graceful"] and bye["reason"] == "SIGTERM"
            assert bye["final_checkpoints"] == 12
            assert _replay_depth(fleet) == 0
        finally:
            fleet.terminate()
            handoff.stop()
            broker.stop()

        broker, handoff, fleet = _one_worker_fleet(tmp_path, "kill")
        try:
            st = fleet.workers["w0"]
            os.kill(st["pid"], signal.SIGKILL)
            assert st["proc"].wait(timeout=60) == -signal.SIGKILL
            fleet.poll_events()
            assert "w0" not in fleet.all_byes()
            assert _replay_depth(fleet) > 0
        finally:
            fleet.terminate()
            handoff.stop()
            broker.stop()


# ---------------------------------------------------------------------------
# tuner in-flight-depth dimension under cluster feedback (PR 6 follow-on)
# ---------------------------------------------------------------------------


class TestTunerDepthClusterFeedback:
    def test_depth_trial_reverts_and_freezes_on_ladder(self):
        """The tuner may trial the in-flight depth against live cluster
        latencies, but the moment the (cross-process) QoS ladder signal
        reports degradation the trial reverts and the tuner freezes —
        the freeze interaction holds when the feedback comes from a
        worker process, not just in-process."""
        from realtime_fraud_detection_tpu.tuning import TuningPlane
        from realtime_fraud_detection_tpu.utils.config import (
            TuningSettings,
        )

        plane = TuningPlane(TuningSettings(
            enabled=True, tune_interval_batches=4,
            tuner_cooldown_epochs=0))
        tuner = plane.tuner
        tuner._dim_i = 2                     # next proposal: "inflight"
        saved = tuner.inflight_depth

        def epoch(now0, p99_ms):
            for b in range(4):
                plane.on_batch_complete(
                    32, 0.05, now0 + b * 0.1,
                    latencies_ms=[p99_ms] * 8,
                    burn_rate=0.0, ladder_level=0)

        epoch(0.0, 40.0)                     # baseline epoch
        epoch(1.0, 40.0)                     # rolling baseline -> trial
        assert tuner.snapshot()["in_trial"]
        assert tuner.snapshot()["trial_dim"] == "inflight"
        assert tuner.inflight_depth != saved
        # cluster feedback: a worker's ladder went degraded mid-trial
        plane.on_batch_complete(32, 0.05, 2.0, latencies_ms=[500.0],
                                burn_rate=0.0, ladder_level=2)
        snap = tuner.snapshot()
        assert snap["frozen"] and not snap["in_trial"]
        assert tuner.inflight_depth == saved   # reverted, not kept
        assert plane.recommended_inflight_depth() == saved


# ---------------------------------------------------------------------------
# settings + lint scope + compact summary
# ---------------------------------------------------------------------------


class TestElasticSettingsAndScopes:
    def test_cluster_autoscale_validation(self):
        from realtime_fraud_detection_tpu.utils.config import (
            ClusterSettings,
        )

        ClusterSettings().validate()
        with pytest.raises(ValueError):
            ClusterSettings(min_workers=4, max_workers=2).validate()
        with pytest.raises(ValueError):
            ClusterSettings(per_worker_tps=0).validate()
        with pytest.raises(ValueError):
            ClusterSettings(autoscale_headroom=0.5).validate()
        with pytest.raises(ValueError):
            ClusterSettings(autoscale_down_patience=0).validate()

    def test_autoscale_in_lint_scopes(self):
        """cluster/autoscale.py (and the whole process plane) sit inside
        the wall-clock AND determinism lint scopes via the cluster
        subsystem — wall reads need justified pragmas, RNG must be
        seeded instances."""
        from realtime_fraud_detection_tpu.analysis.lint import (
            CLOCK_SUBSYSTEMS,
            DETERMINISM_SUBSYSTEMS,
        )

        assert "cluster" in CLOCK_SUBSYSTEMS
        assert "cluster" in DETERMINISM_SUBSYSTEMS

    def test_lockwatch_ninth_drill_registered(self):
        from realtime_fraud_detection_tpu.analysis.lockwatch import (
            LOCKWATCH_DRILLS,
        )

        assert "elastic-drill" in LOCKWATCH_DRILLS
        # thirteen since ISSUE 20 added obs-drill
        assert len(LOCKWATCH_DRILLS) == 13

    def test_compact_summary_under_2kb_even_when_bloated(self):
        from realtime_fraud_detection_tpu.cluster.elastic_drill import (
            compact_elastic_summary,
        )

        summary = {"metric": "elastic_drill", "passed": False,
                   "autoscale_events": {"up": 99, "down": 99},
                   "checks": {f"very_long_check_name_{i}" * 4: False
                              for i in range(64)}}
        compact = compact_elastic_summary(summary)
        assert len(json.dumps(compact,
                              separators=(",", ":")).encode()) < 2048
        assert compact["metric"] == "elastic_drill"


# ---------------------------------------------------------------------------
# tier-1 smoke: the full drill through the CLI
# ---------------------------------------------------------------------------


class TestElasticDrillSmoke:
    def test_elastic_drill_fast_cli(self):
        """Tier-1 acceptance: `rtfd elastic-drill --fast` — >= 8 real OS
        worker processes over the TCP netbroker, network handoff, a real
        SIGKILL mid-peak, autoscale up-then-drain, oracle equality, and
        the fresh-run determinism digest — passes end to end, final
        stdout line a parseable <2KB verdict."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "realtime_fraud_detection_tpu",
             "elastic-drill", "--fast"],
            capture_output=True, text=True, timeout=540, env=env)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        compact = json.loads(lines[-1])
        assert len(lines[-1].encode()) < 2048
        assert compact["metric"] == "elastic_drill"
        assert compact["passed"] is True
        assert compact["kill_returncode"] == -9
        assert compact["workers_joined"] >= 8
        assert compact["lost"] == 0 and compact["conflicting_scored"] == 0
        full = json.loads(lines[-2])
        assert full["checks"]["replay_deterministic"] is True
        assert full["checks"]["autoscale_ahead_of_ramp"] is True
        assert full["checks"]["state_equals_oracle"] is True

"""State store tests: velocity windows, caches, history rings, graph."""

import numpy as np

from realtime_fraud_detection_tpu.state import (
    AggregationStore,
    EntityGraphStore,
    ProfileStore,
    TransactionCache,
    UserHistoryStore,
    VelocityStore,
)


class TestVelocityStore:
    def test_accumulates_within_window(self):
        v = VelocityStore()
        v.update("u1", 10.0, now=1000.0)
        v.update("u1", 20.0, now=1100.0)
        m = v.get("u1", "5min", now=1150.0)
        assert m["count"] == 2 and m["amount"] == 30.0

    def test_five_minute_window_resets(self):
        v = VelocityStore()
        v.update("u1", 10.0, now=1000.0)
        v.update("u1", 20.0, now=1000.0 + 301)  # past 5min
        m5 = v.get("u1", "5min")
        m1h = v.get("u1", "1hour")
        assert m5["count"] == 1 and m5["amount"] == 20.0
        assert m1h["count"] == 2  # 1h window still accumulating

    def test_24h_window_outlives_an_hour(self):
        # the reference's 1h TTL on all windows is a bug; ours must not reset
        v = VelocityStore()
        v.update("u1", 10.0, now=0.0)
        v.update("u1", 10.0, now=7200.0)  # 2h later
        assert v.get("u1", "24hour")["count"] == 2
        assert v.get("u1", "1hour")["count"] == 1  # 1h window did reset

    def test_expired_read(self):
        v = VelocityStore()
        v.update("u1", 10.0, now=0.0)
        assert v.get("u1", "5min", now=1000.0) == {}

    def test_unknown_user_empty(self):
        assert VelocityStore().get_all("nobody") == {"5min": {}, "1hour": {}, "24hour": {}}


class TestTransactionCache:
    def test_ttl_expiry(self):
        c = TransactionCache(txn_ttl_s=100)
        c.cache_transaction({"transaction_id": "t1", "user_id": "u", "merchant_id": "m"}, now=0)
        assert c.get_transaction("t1", now=50) is not None
        assert c.get_transaction("t1", now=150) is None

    def test_user_list_bounded_lifo(self):
        c = TransactionCache(user_list_len=3)
        for i in range(5):
            c.cache_transaction(
                {"transaction_id": f"t{i}", "user_id": "u", "merchant_id": "m"}, now=0
            )
        assert c.get_user_transactions("u") == ["t4", "t3", "t2"]

    def test_features_ttl(self):
        c = TransactionCache(features_ttl_s=10)
        c.store_features("t1", [1.0, 2.0], now=0)
        assert c.get_features("t1", now=5) == [1.0, 2.0]
        assert c.get_features("t1", now=11) is None


class TestAggregationStore:
    def test_hourly_rollup(self):
        a = AggregationStore()
        ts = 3_600_000 * 10  # hour bucket 10
        a.record({"timestamp_ms": ts, "amount": 100.0, "is_fraud": True,
                  "fraud_score": 0.9, "merchant_id": "m1"}, now=0)
        a.record({"timestamp_ms": ts + 1000, "amount": 50.0, "is_fraud": False,
                  "fraud_score": 0.1, "merchant_id": "m1"}, now=0)
        agg = a.get("hourly:10", now=0)
        assert agg["total_count"] == 2
        assert agg["total_amount"] == 150.0
        assert agg["fraud_rate"] == 0.5
        assert agg["high_risk_count"] == 1
        assert a.get("merchant:m1:10", now=0)["total_count"] == 2


class TestUserHistoryStore:
    def test_front_padding_and_order(self):
        s = UserHistoryStore(seq_len=4, feature_dim=2)
        for i in range(3):
            s.append_batch(["u1"], np.array([[i + 1.0, 0.0]], np.float32))
        seqs, lengths = s.gather(["u1", "u2"])
        assert seqs.shape == (2, 4, 2)
        assert lengths.tolist() == [3, 0]
        # front-padded: [0, 1, 2, 3] with most recent last
        np.testing.assert_array_equal(seqs[0, :, 0], [0.0, 1.0, 2.0, 3.0])
        assert (seqs[1] == 0).all()

    def test_ring_wraps_keeping_latest(self):
        s = UserHistoryStore(seq_len=3, feature_dim=1)
        for i in range(7):
            s.append_batch(["u"], np.array([[float(i)]], np.float32))
        seqs, lengths = s.gather(["u"])
        assert lengths[0] == 3
        np.testing.assert_array_equal(seqs[0, :, 0], [4.0, 5.0, 6.0])


class TestEntityGraphStore:
    def test_neighbor_sampling_and_masks(self):
        g = EntityGraphStore(fanout=3)
        g.add_edges([1, 1, 2], [10, 11, 10])
        idx, mask = g.user_neighbors([1, 2, 3])
        assert idx.shape == (3, 3)
        assert set(idx[0][mask[0]]) == {10, 11}
        assert set(idx[1][mask[1]]) == {10}
        assert not mask[2].any()  # unseen user
        ridx, rmask = g.merchant_neighbors([10])
        assert set(ridx[0][rmask[0]]) == {1, 2}

    def test_fanout_bounded(self):
        g = EntityGraphStore(fanout=2)
        g.add_edges([1] * 5, [10, 11, 12, 13, 14])
        idx, mask = g.user_neighbors([1])
        assert mask[0].sum() == 2
        assert set(idx[0][mask[0]]) == {13, 14}  # most recent kept


class TestProfileStore:
    def test_seed_and_get(self):
        p = ProfileStore()
        p.seed({"u1": {"risk_score": 0.2}}, {"m1": {"category": "retail"}})
        assert p.get_user("u1")["risk_score"] == 0.2
        assert p.get_merchant("m1")["category"] == "retail"
        assert p.get_user("nope") is None


class TestReviewRegressions:
    def test_velocity_default_read_uses_stream_clock(self):
        v = VelocityStore()
        v.update("u1", 100.0, now=0.0)
        v.update("u2", 1.0, now=7200.0)  # advances the stream clock
        # u1's 5min/1hour windows are stale relative to stream time
        assert v.get("u1", "5min") == {}
        assert v.get("u1", "1hour") == {}
        assert v.get("u1", "24hour")["count"] == 1

    def test_aggregation_uses_iso_event_time(self):
        from datetime import datetime, timezone

        a = AggregationStore()
        ts = datetime(2026, 1, 5, 10, 30, tzinfo=timezone.utc)
        a.record({"timestamp": ts.isoformat(), "amount": 10.0,
                  "merchant_id": "m"}, now=0)
        hour_key = int(ts.timestamp() * 1000 // 3_600_000)
        assert a.get(f"hourly:{hour_key}", now=0)["total_count"] == 1

    def test_two_hop_neighbors(self):
        g = EntityGraphStore(fanout=2)
        g.add_edges([1, 2], [10, 10])   # users 1,2 -> merchant 10
        g.add_edges([1], [11])          # user 1 -> merchant 11
        hop1, m1, hop2, m2 = g.user_two_hop([1])
        assert set(hop1[0][m1[0]]) == {10, 11}
        # 2-hop: users reachable through merchant 10 include user 2
        flat = hop2[0][m2[0]]
        assert 2 in flat
        # masked slots carry no fabricated neighbors
        assert m2.shape == (1, 2, 2)

"""Parallel layer tests on the virtual 8-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from realtime_fraud_detection_tpu.core.mesh import MeshConfig, build_mesh
from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG, init_bert_params
from realtime_fraud_detection_tpu.models.gnn import init_gnn_params
from realtime_fraud_detection_tpu.models.lstm import init_lstm_params
from realtime_fraud_detection_tpu.parallel import (
    TrainBatch,
    init_train_state,
    joint_loss,
    make_train_step,
    neural_param_shardings,
    shard_train_batch,
)


def make_params(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "lstm": init_lstm_params(k1, feature_dim=64),
        "gnn": init_gnn_params(k2, node_dim=16, txn_dim=64),
        "bert": init_bert_params(k3, TINY_CONFIG),
    }


def make_batch(b=16, t=10, f=64, d=16, k=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    return TrainBatch(
        features=rng.standard_normal((b, f)).astype(np.float32),
        history=rng.standard_normal((b, t, f)).astype(np.float32),
        history_len=np.full((b,), t, np.int32),
        user_feat=rng.standard_normal((b, d)).astype(np.float32),
        merchant_feat=rng.standard_normal((b, d)).astype(np.float32),
        user_neigh_feat=rng.standard_normal((b, k, d)).astype(np.float32),
        user_neigh_mask=np.ones((b, k), bool),
        merch_neigh_feat=rng.standard_normal((b, k, d)).astype(np.float32),
        merch_neigh_mask=np.ones((b, k), bool),
        token_ids=rng.integers(0, 30522, (b, s)).astype(np.int32),
        token_mask=np.ones((b, s), bool),
        labels=rng.integers(0, 2, (b,)).astype(np.float32),
    )


@pytest.fixture(scope="module")
def tp_mesh():
    # 8 virtual devices -> data=4, model=2: DP x TP in one program
    return build_mesh(MeshConfig(model=2))


def test_train_step_dp_tp(tp_mesh):
    params = make_params()
    opt = optax.adamw(1e-3)
    state = init_train_state(tp_mesh, params, opt)
    step = make_train_step(opt, TINY_CONFIG, donate=False)
    batch = shard_train_batch(tp_mesh, make_batch())

    state1, m1 = step(state, batch)
    state2, m2 = step(state1, batch)
    assert np.isfinite(float(m1["loss"]))
    # same batch twice with adamw must strictly reduce the joint loss
    assert float(m2["loss"]) < float(m1["loss"])
    assert int(state2.step) == 2
    # params actually moved
    w0 = np.asarray(jax.device_get(state.params["lstm"]["w_gates"]))
    w2 = np.asarray(jax.device_get(state2.params["lstm"]["w_gates"]))
    assert not np.allclose(w0, w2)


def test_tp_matches_single_device_numerics(tp_mesh):
    """The TP-sharded loss must equal the unsharded loss (same math)."""
    params = make_params()
    batch = make_batch(b=8)
    expect, _ = jax.jit(
        lambda p, bt: joint_loss(p, bt, TINY_CONFIG)
    )(params, batch)

    sharded_params = jax.device_put(
        params, neural_param_shardings(tp_mesh, params)
    )
    sharded_batch = shard_train_batch(tp_mesh, batch)
    got, _ = jax.jit(
        lambda p, bt: joint_loss(p, bt, TINY_CONFIG)
    )(sharded_params, sharded_batch)
    np.testing.assert_allclose(float(got), float(expect), rtol=2e-5)


def test_bert_param_shardings_are_tensor_parallel(tp_mesh):
    """q/ffn1 split on output dim; o/ffn2 on input dim over ``model``."""
    params = make_params()
    sh = neural_param_shardings(tp_mesh, params)
    layer = sh["bert"]["layers"][0]
    assert layer["q"]["w"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["o"]["w"].spec == jax.sharding.PartitionSpec("model", None)
    assert layer["ffn1"]["w"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["ffn2"]["w"].spec == jax.sharding.PartitionSpec("model", None)

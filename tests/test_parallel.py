"""Parallel layer tests on the virtual 8-device CPU mesh (conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from realtime_fraud_detection_tpu.core.mesh import MeshConfig, build_mesh
from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG, init_bert_params
from realtime_fraud_detection_tpu.models.gnn import init_gnn_params
from realtime_fraud_detection_tpu.models.lstm import init_lstm_params
from realtime_fraud_detection_tpu.parallel import (
    TrainBatch,
    init_train_state,
    joint_loss,
    make_train_step,
    neural_param_shardings,
    shard_train_batch,
)


def make_params(seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "lstm": init_lstm_params(k1, feature_dim=64),
        "gnn": init_gnn_params(k2, node_dim=16, txn_dim=64),
        "bert": init_bert_params(k3, TINY_CONFIG),
    }


def make_batch(b=16, t=10, f=64, d=16, k=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    return TrainBatch(
        features=rng.standard_normal((b, f)).astype(np.float32),
        history=rng.standard_normal((b, t, f)).astype(np.float32),
        history_len=np.full((b,), t, np.int32),
        user_feat=rng.standard_normal((b, d)).astype(np.float32),
        merchant_feat=rng.standard_normal((b, d)).astype(np.float32),
        user_neigh_feat=rng.standard_normal((b, k, d)).astype(np.float32),
        user_neigh_mask=np.ones((b, k), bool),
        merch_neigh_feat=rng.standard_normal((b, k, d)).astype(np.float32),
        merch_neigh_mask=np.ones((b, k), bool),
        token_ids=rng.integers(0, 30522, (b, s)).astype(np.int32),
        token_mask=np.ones((b, s), bool),
        labels=rng.integers(0, 2, (b,)).astype(np.float32),
    )


@pytest.fixture(scope="module")
def tp_mesh():
    # 8 virtual devices -> data=4, model=2: DP x TP in one program
    return build_mesh(MeshConfig(model=2))


def test_train_step_dp_tp(tp_mesh):
    params = make_params()
    opt = optax.adamw(1e-3)
    state = init_train_state(tp_mesh, params, opt)
    step = make_train_step(opt, TINY_CONFIG, donate=False)
    batch = shard_train_batch(tp_mesh, make_batch())

    state1, m1 = step(state, batch)
    state2, m2 = step(state1, batch)
    assert np.isfinite(float(m1["loss"]))
    # same batch twice with adamw must strictly reduce the joint loss
    assert float(m2["loss"]) < float(m1["loss"])
    assert int(state2.step) == 2
    # params actually moved
    w0 = np.asarray(jax.device_get(state.params["lstm"]["w_gates"]))
    w2 = np.asarray(jax.device_get(state2.params["lstm"]["w_gates"]))
    assert not np.allclose(w0, w2)


def test_tp_matches_single_device_numerics(tp_mesh):
    """The TP-sharded loss must equal the unsharded loss (same math)."""
    params = make_params()
    batch = make_batch(b=8)
    expect, _ = jax.jit(
        lambda p, bt: joint_loss(p, bt, TINY_CONFIG)
    )(params, batch)

    sharded_params = jax.device_put(
        params, neural_param_shardings(tp_mesh, params)
    )
    sharded_batch = shard_train_batch(tp_mesh, batch)
    got, _ = jax.jit(
        lambda p, bt: joint_loss(p, bt, TINY_CONFIG)
    )(sharded_params, sharded_batch)
    np.testing.assert_allclose(float(got), float(expect), rtol=2e-5)


def test_bert_param_shardings_are_tensor_parallel(tp_mesh):
    """q/ffn1 split on output dim; o/ffn2 on input dim over ``model``."""
    params = make_params()
    sh = neural_param_shardings(tp_mesh, params)
    layer = sh["bert"]["layers"][0]
    assert layer["q"]["w"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["o"]["w"].spec == jax.sharding.PartitionSpec("model", None)
    assert layer["ffn1"]["w"].spec == jax.sharding.PartitionSpec(None, "model")
    assert layer["ffn2"]["w"].spec == jax.sharding.PartitionSpec("model", None)


# ---------------------------------------------------------------- ring attn
class TestRingAttention:
    """Context parallelism: ring attention over the seq axis must match
    dense attention exactly (same f32 online-softmax numerics)."""

    @staticmethod
    def _qkvm(b=8, h=2, s=32, d=8, pad=5, seed=0):
        rng = np.random.default_rng(seed)
        q = rng.standard_normal((b, h, s, d)).astype(np.float32)
        k = rng.standard_normal((b, h, s, d)).astype(np.float32)
        v = rng.standard_normal((b, h, s, d)).astype(np.float32)
        mask = np.ones((b, s), bool)
        mask[:, s - pad:] = False  # padded tail keys
        return q, k, v, mask

    @pytest.mark.parametrize("mesh_cfg", [
        MeshConfig(seq=4),              # data=2 x seq=4
        MeshConfig(data=1, seq=8),      # pure context parallel
        MeshConfig(seq=1),              # degenerate: all-data mesh
    ])
    def test_matches_dense(self, mesh_cfg):
        from realtime_fraud_detection_tpu.ops.attention import attention_reference
        from realtime_fraud_detection_tpu.parallel import ring_attention

        mesh = build_mesh(mesh_cfg)
        q, k, v, mask = self._qkvm()
        expect = np.asarray(attention_reference(q, k, v, mask))
        got = np.asarray(jax.jit(
            lambda *a: ring_attention(mesh, *a)
        )(q, k, v, mask))
        np.testing.assert_allclose(got, expect, rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_seq(self):
        from realtime_fraud_detection_tpu.parallel import ring_attention

        mesh = build_mesh(MeshConfig(data=1, seq=8))
        q, k, v, mask = self._qkvm(s=30, pad=0)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(mesh, q, k, v, mask)

    def test_bf16_inputs(self):
        """bf16 q/k/v accumulate in f32 and return bf16 (precision policy)."""
        from realtime_fraud_detection_tpu.ops.attention import attention_reference
        from realtime_fraud_detection_tpu.parallel import ring_attention

        mesh = build_mesh(MeshConfig(seq=4))
        q, k, v, mask = self._qkvm()
        qb, kb, vb = (jnp.asarray(x, jnp.bfloat16) for x in (q, k, v))
        got = jax.jit(lambda *a: ring_attention(mesh, *a))(qb, kb, vb, mask)
        assert got.dtype == jnp.bfloat16
        expect = np.asarray(
            attention_reference(np.asarray(qb, np.float32),
                                np.asarray(kb, np.float32),
                                np.asarray(vb, np.float32), mask))
        np.testing.assert_allclose(
            np.asarray(got, np.float32), expect, rtol=0.1, atol=0.1)


def test_bert_context_parallel_matches_single_device():
    """CP encoder (seq sharded + ring attention) must match the plain
    encoder: all non-attention ops are per-token, attention is exact."""
    from realtime_fraud_detection_tpu.models.bert import (
        TINY_CONFIG,
        bert_predict,
        init_bert_params,
    )
    from realtime_fraud_detection_tpu.parallel import (
        bert_context_parallel_predict,
    )

    mesh = build_mesh(MeshConfig(data=2, seq=4))
    params = init_bert_params(jax.random.PRNGKey(1), TINY_CONFIG)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, TINY_CONFIG.vocab_size, (4, 32)).astype(np.int32)
    mask = np.ones((4, 32), bool)
    mask[:, 28:] = False

    expect = np.asarray(bert_predict(params, ids, mask, TINY_CONFIG))
    got = np.asarray(bert_context_parallel_predict(
        mesh, params, ids, mask, TINY_CONFIG))
    np.testing.assert_allclose(got, expect, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------- pipeline parallel


class TestPipelineParallel:
    @staticmethod
    def _stage_fn(params, h):
        w, b = params["w"], params["b"]
        return jax.nn.relu(h @ w + b)

    def _setup(self, n_stages=4, n_micro=8, mb=4, dim=16, seed=0):
        from realtime_fraud_detection_tpu.parallel.pipeline import (
            stack_stage_params,
        )

        rng = np.random.default_rng(seed)
        per_stage = [
            {"w": jnp.asarray(rng.normal(0, 0.3, (dim, dim)), jnp.float32),
             "b": jnp.asarray(rng.normal(0, 0.1, (dim,)), jnp.float32)}
            for _ in range(n_stages)
        ]
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(0, 1, (n_micro, mb, dim)), jnp.float32)
        return per_stage, stacked, x

    def _sequential(self, per_stage, x):
        h = x
        for p in per_stage:
            h = jax.vmap(lambda m: self._stage_fn(p, m))(h)
        return h

    def test_matches_sequential(self):
        from realtime_fraud_detection_tpu.parallel.pipeline import (
            pipeline_forward,
        )

        per_stage, stacked, x = self._setup()
        mesh = build_mesh(MeshConfig(model=4))      # data=2 x pipe=4
        got = jax.jit(lambda p, xx: pipeline_forward(
            mesh, self._stage_fn, p, xx))(stacked, x)
        want = self._sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_flows_through_schedule(self):
        """jax.grad through the scan+ppermute schedule must equal the
        sequential model's gradients (the backward pipeline comes from the
        transpose, no hand-written schedule)."""
        from realtime_fraud_detection_tpu.parallel.pipeline import (
            pipeline_forward,
        )

        per_stage, stacked, x = self._setup(n_micro=6)
        mesh = build_mesh(MeshConfig(model=4))

        def loss_pipe(p):
            out = pipeline_forward(mesh, self._stage_fn, p, x)
            return jnp.mean(out ** 2)

        def loss_seq(p_list):
            return jnp.mean(self._sequential(p_list, x) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.grad(loss_seq)(per_stage)
        for s in range(4):
            np.testing.assert_allclose(
                np.asarray(g_pipe["w"][s]), np.asarray(g_seq[s]["w"]),
                rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(g_pipe["b"][s]), np.asarray(g_seq[s]["b"]),
                rtol=1e-4, atol=1e-5)

    def test_eight_stage_pure_pipeline(self):
        from realtime_fraud_detection_tpu.parallel.pipeline import (
            pipeline_forward,
        )

        per_stage, stacked, x = self._setup(n_stages=8, n_micro=16)
        mesh = build_mesh(MeshConfig(data=1, model=8))
        got = jax.jit(lambda p, xx: pipeline_forward(
            mesh, self._stage_fn, p, xx))(stacked, x)
        want = self._sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------ expert parallel


class TestExpertParallel:
    def _setup(self, n_experts=8, d=16, h=32, n_tokens=64, seed=0,
               capacity_factor=8.0):
        from realtime_fraud_detection_tpu.parallel.experts import (
            MoEConfig,
            init_moe_params,
        )

        cfg = MoEConfig(n_experts=n_experts, d_model=d, d_hidden=h,
                        capacity_factor=capacity_factor)
        params = init_moe_params(jax.random.PRNGKey(seed), cfg)
        x = jnp.asarray(
            np.random.default_rng(seed).normal(0, 1, (n_tokens, d)),
            jnp.float32)
        return cfg, params, x

    def test_matches_dense_reference(self):
        """With generous capacity (no drops), expert-parallel all_to_all
        dispatch must equal the dense every-token-through-its-expert
        reference."""
        from realtime_fraud_detection_tpu.parallel.experts import (
            moe_ffn,
            moe_ffn_reference,
        )

        cfg, params, x = self._setup()
        mesh = build_mesh(MeshConfig(model=4))     # data=2 x expert=4
        got = jax.jit(lambda p, xx: moe_ffn(mesh, p, xx, cfg))(params, x)
        want = moe_ffn_reference(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_capacity_drops_zero_not_garbage(self):
        """Over-capacity tokens must come back as exact zeros (Switch-style
        drop), never another token's output."""
        from realtime_fraud_detection_tpu.parallel.experts import (
            moe_ffn,
            moe_ffn_reference,
        )

        cfg, params, x = self._setup(capacity_factor=0.25)
        mesh = build_mesh(MeshConfig(model=4))
        got = np.asarray(
            jax.jit(lambda p, xx: moe_ffn(mesh, p, xx, cfg))(params, x))
        want = np.asarray(moe_ffn_reference(params, x))
        dropped = np.all(got == 0.0, axis=-1)
        assert dropped.any()                       # capacity actually bound
        assert not dropped.all()                   # some tokens survived
        np.testing.assert_allclose(got[~dropped], want[~dropped],
                                   rtol=2e-4, atol=2e-5)

    def test_rejects_indivisible_experts(self):
        from realtime_fraud_detection_tpu.parallel.experts import moe_ffn

        cfg, params, x = self._setup(n_experts=6)
        mesh = build_mesh(MeshConfig(model=4))
        with pytest.raises(ValueError, match="divisible"):
            moe_ffn(mesh, params, x, cfg)


    def test_moe_grads_match_dense_reference(self):
        """Training story: gradients must flow through the all_to_all
        dispatch/combine and equal the dense reference's (no capacity
        drops), for both expert weights and the router."""
        from realtime_fraud_detection_tpu.parallel.experts import (
            moe_ffn,
            moe_ffn_reference,
        )

        cfg, params, x = self._setup()
        mesh = build_mesh(MeshConfig(model=4))

        def loss_pp(p):
            return jnp.mean(moe_ffn(mesh, p, x, cfg) ** 2)

        def loss_ref(p):
            return jnp.mean(moe_ffn_reference(p, x) ** 2)

        g_pp = jax.jit(jax.grad(loss_pp))(params)
        g_ref = jax.grad(loss_ref)(params)
        for key in ("w1", "b1", "w2", "b2", "router"):
            np.testing.assert_allclose(
                np.asarray(g_pp[key]), np.asarray(g_ref[key]),
                rtol=5e-4, atol=1e-6, err_msg=key)


def test_bert_pipeline_encode_matches_sequential():
    """The flagship text encoder with its layers split over pipeline
    stages (mask riding the schedule as a pytree leaf) must match the
    sequential encoder exactly, padding included."""
    from realtime_fraud_detection_tpu.models.bert import (
        TINY_CONFIG,
        bert_encode,
        init_bert_params,
    )
    from realtime_fraud_detection_tpu.parallel.pipeline import (
        bert_pipeline_encode,
    )

    params = init_bert_params(jax.random.PRNGKey(5), TINY_CONFIG)
    rng = np.random.default_rng(7)
    b, s = 8, 16
    ids = jnp.asarray(rng.integers(0, TINY_CONFIG.vocab_size, (b, s)),
                      jnp.int32)
    mask = jnp.asarray(rng.random((b, s)) > 0.3)
    mask = mask.at[:, 0].set(True)            # CLS always valid
    mesh = build_mesh(MeshConfig(model=2))    # 2 stages x data=4
    got = jax.jit(lambda p, i, m: bert_pipeline_encode(
        mesh, p, i, m, TINY_CONFIG, n_micro=4))(params, ids, mask)
    want = bert_encode(params, ids, mask, TINY_CONFIG)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_multihost_two_process_step():
    """The DCN seam (core/mesh.py init_distributed/build_multihost_mesh):
    the same DP+TP train step runs across a REAL process boundary — two
    jax.distributed participants with 2 CPU devices each — and its loss
    matches a single-process evaluation of the identical global batch.
    Subprocess-based: this test's own 8-device backend is untouched."""
    import importlib.util
    from pathlib import Path

    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "graft_entry_for_test", root / "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._dryrun_multihost(2, 2, timeout_s=300.0)

"""Shared state tier: RESP codec/server units + store semantics + the
two-replicas-one-server story the k8s HPA scale-out depends on."""

import threading

import pytest

from realtime_fraud_detection_tpu.state import (
    MiniRedisServer,
    RespClient,
    SharedAggregationStore,
    SharedProfileStore,
    SharedTransactionCache,
    SharedVelocityStore,
)


@pytest.fixture(scope="module")
def server():
    s = MiniRedisServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    c = RespClient(port=server.port)
    c.flushdb()
    yield c
    c.close()


# ------------------------------------------------------------------ protocol


def test_resp_basic_commands(client):
    c = client
    assert c.ping()
    c.set("k", "v")
    assert c.get("k") == b"v"
    assert c.exists("k")
    assert c.delete("k") == 1
    assert c.get("k") is None
    assert c.incr("ctr") == 1 and c.incr("ctr") == 2
    assert c.incrbyfloat("f", 1.5) == 1.5
    assert c.incrbyfloat("f", 2.25) == 3.75


def test_resp_hash_and_list(client):
    c = client
    c.hset("h", "a", "1", "b", "2")
    assert c.hget("h", "a") == b"1"
    assert c.hgetall("h") == {"a": b"1", "b": b"2"}
    assert c.hincrby("h", "n", 5) == 5
    assert c.hincrbyfloat("h", "x", 0.5) == 0.5
    c.lpush("l", "c", "b", "a")
    assert c.lrange("l", 0, -1) == [b"a", b"b", b"c"]
    c.ltrim("l", 0, 1)
    assert c.llen("l") == 2


def test_resp_ttl_expiry(client):
    c = client
    c.set("t", "v", ex=0.05)
    assert c.get("t") == b"v"
    import time

    time.sleep(0.08)
    assert c.get("t") is None


def test_resp_wrongtype_errors(client):
    c = client
    c.set("s", "v")
    from realtime_fraud_detection_tpu.state.resp import RespError

    with pytest.raises(RespError, match="WRONGTYPE"):
        c.hgetall("s")


def test_resp_unicode_binary_safe(client):
    c = client
    c.set("u", "caffè ☕")
    assert c.get("u").decode() == "caffè ☕"
    c.set("b", b"\x00\xff\r\n$5")
    assert c.get("b") == b"\x00\xff\r\n$5"


# -------------------------------------------------------------------- stores


def test_shared_profile_round_trip(client):
    store = SharedProfileStore(client)
    prof = {"risk_score": 0.4, "kyc_status": "verified",
            "behavioral_patterns": {"weekend_activity": 0.7}}
    store.put_user("u1", prof)
    assert store.get_user("u1") == prof
    assert store.get_user("nope") is None


def test_shared_velocity_windows(client):
    v = SharedVelocityStore(client)
    v.update("u1", 100.0, now=1000.0)
    v.update("u1", 50.0, now=1001.0)
    got = v.get("u1", "5min")
    assert got["count"] == 2 and got["amount"] == 150.0
    assert set(v.get_all("u1")) == {"5min", "1hour", "24hour"}


def test_shared_txn_cache_lists(client):
    cache = SharedTransactionCache(client, user_list_len=3)
    for i in range(5):
        cache.cache_transaction(
            {"transaction_id": f"t{i}", "user_id": "u", "merchant_id": "m"})
    assert cache.get_transaction("t4")["transaction_id"] == "t4"
    assert cache.get_user_transactions("u") == ["t4", "t3", "t2"]  # last 3
    cache.store_features("t4", [1.0, 2.0])
    assert cache.get_features("t4") == [1.0, 2.0]


def test_shared_aggregations(client):
    agg = SharedAggregationStore(client)
    agg.record({"merchant_id": "m", "amount": 10.0, "is_fraud": True,
                "fraud_score": 0.9, "timestamp_ms": 3_600_000.0})
    agg.record({"merchant_id": "m", "amount": 30.0, "is_fraud": False,
                "fraud_score": 0.1, "timestamp_ms": 3_700_000.0})
    got = agg.get("hourly:1")
    assert got["total_count"] == 2
    assert got["fraud_rate"] == 0.5
    assert got["avg_amount"] == 20.0


def test_concurrent_replicas_no_lost_updates(server):
    """Two 'replicas' (connections) increment the same user's velocity
    concurrently: atomic HINCRBY must not lose a single update — the
    failure mode the reference's GET-then-SET pattern has."""
    c0 = RespClient(port=server.port)
    c0.flushdb()
    n_each = 200

    def replica():
        c = RespClient(port=server.port)
        v = SharedVelocityStore(c)
        for _ in range(n_each):
            v.update("hot_user", 1.0, now=1000.0)
        c.close()

    threads = [threading.Thread(target=replica) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    v = SharedVelocityStore(c0)
    got = v.get("hot_user", "1hour")
    assert got["count"] == 4 * n_each
    assert got["amount"] == 4.0 * n_each
    c0.close()


def test_scorer_runs_on_shared_stores(server):
    """FraudScorer wired to the shared tier scores and write-backs through
    the RESP server; a second scorer sees the first one's state."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    c1, c2 = RespClient(port=server.port), RespClient(port=server.port)
    c1.flushdb()
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=31)
    s1 = FraudScorer(scorer_config=ScorerConfig(text_len=32), state_client=c1)
    s1.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    records = gen.generate_batch(8)
    results = s1.score_batch(records, now=1000.0)
    assert len(results) == 8

    s2 = FraudScorer(scorer_config=ScorerConfig(text_len=32), state_client=c2)
    # replica 2 sees replica 1's profiles, velocity, and txn cache
    uid = str(records[0]["user_id"])
    assert s2.profiles.get_user(uid) is not None
    assert s2.velocity.get_all(uid)["24hour"]["count"] >= 1
    tid = str(records[0]["transaction_id"])
    assert s2.txn_cache.get_transaction(tid) is not None
    c1.close()
    c2.close()

"""Shared state tier: RESP codec/server units + store semantics + the
two-replicas-one-server story the k8s HPA scale-out depends on."""

import threading

import pytest

from realtime_fraud_detection_tpu.state import (
    MiniRedisServer,
    RespClient,
    SharedAggregationStore,
    SharedProfileStore,
    SharedTransactionCache,
    SharedVelocityStore,
)


@pytest.fixture(scope="module")
def server():
    s = MiniRedisServer().start()
    yield s
    s.stop()


@pytest.fixture()
def client(server):
    c = RespClient(port=server.port)
    c.flushdb()
    yield c
    c.close()


# ------------------------------------------------------------------ protocol


def test_resp_basic_commands(client):
    c = client
    assert c.ping()
    c.set("k", "v")
    assert c.get("k") == b"v"
    assert c.exists("k")
    assert c.delete("k") == 1
    assert c.get("k") is None
    assert c.incr("ctr") == 1 and c.incr("ctr") == 2
    assert c.incrbyfloat("f", 1.5) == 1.5
    assert c.incrbyfloat("f", 2.25) == 3.75


def test_resp_hash_and_list(client):
    c = client
    c.hset("h", "a", "1", "b", "2")
    assert c.hget("h", "a") == b"1"
    assert c.hgetall("h") == {"a": b"1", "b": b"2"}
    assert c.hincrby("h", "n", 5) == 5
    assert c.hincrbyfloat("h", "x", 0.5) == 0.5
    c.lpush("l", "c", "b", "a")
    assert c.lrange("l", 0, -1) == [b"a", b"b", b"c"]
    c.ltrim("l", 0, 1)
    assert c.llen("l") == 2


def test_resp_ttl_expiry(client):
    c = client
    c.set("t", "v", ex=0.05)
    assert c.get("t") == b"v"
    import time

    time.sleep(0.08)
    assert c.get("t") is None


def test_resp_wrongtype_errors(client):
    c = client
    c.set("s", "v")
    from realtime_fraud_detection_tpu.state.resp import RespError

    with pytest.raises(RespError, match="WRONGTYPE"):
        c.hgetall("s")


def test_resp_unicode_binary_safe(client):
    c = client
    c.set("u", "caffè ☕")
    assert c.get("u").decode() == "caffè ☕"
    c.set("b", b"\x00\xff\r\n$5")
    assert c.get("b") == b"\x00\xff\r\n$5"


# -------------------------------------------------------------------- stores


def test_shared_profile_round_trip(client):
    store = SharedProfileStore(client)
    prof = {"risk_score": 0.4, "kyc_status": "verified",
            "behavioral_patterns": {"weekend_activity": 0.7}}
    store.put_user("u1", prof)
    assert store.get_user("u1") == prof
    assert store.get_user("nope") is None


def test_shared_velocity_windows(client):
    v = SharedVelocityStore(client)
    v.update("u1", 100.0, now=1000.0)
    v.update("u1", 50.0, now=1001.0)
    got = v.get("u1", "5min")
    assert got["count"] == 2 and got["amount"] == 150.0
    assert set(v.get_all("u1")) == {"5min", "1hour", "24hour"}


def test_shared_txn_cache_lists(client):
    cache = SharedTransactionCache(client, user_list_len=3)
    for i in range(5):
        cache.cache_transaction(
            {"transaction_id": f"t{i}", "user_id": "u", "merchant_id": "m"})
    assert cache.get_transaction("t4")["transaction_id"] == "t4"
    assert cache.get_user_transactions("u") == ["t4", "t3", "t2"]  # last 3
    cache.store_features("t4", [1.0, 2.0])
    assert cache.get_features("t4") == [1.0, 2.0]


def test_shared_aggregations(client):
    agg = SharedAggregationStore(client)
    agg.record({"merchant_id": "m", "amount": 10.0, "is_fraud": True,
                "fraud_score": 0.9, "timestamp_ms": 3_600_000.0})
    agg.record({"merchant_id": "m", "amount": 30.0, "is_fraud": False,
                "fraud_score": 0.1, "timestamp_ms": 3_700_000.0})
    got = agg.get("hourly:1")
    assert got["total_count"] == 2
    assert got["fraud_rate"] == 0.5
    assert got["avg_amount"] == 20.0


def test_concurrent_replicas_no_lost_updates(server):
    """Two 'replicas' (connections) increment the same user's velocity
    concurrently: atomic HINCRBY must not lose a single update — the
    failure mode the reference's GET-then-SET pattern has."""
    c0 = RespClient(port=server.port)
    c0.flushdb()
    n_each = 200

    def replica():
        c = RespClient(port=server.port)
        v = SharedVelocityStore(c)
        for _ in range(n_each):
            v.update("hot_user", 1.0, now=1000.0)
        c.close()

    threads = [threading.Thread(target=replica) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    v = SharedVelocityStore(c0)
    got = v.get("hot_user", "1hour")
    assert got["count"] == 4 * n_each
    assert got["amount"] == 4.0 * n_each
    c0.close()


def test_scorer_runs_on_shared_stores(server):
    """FraudScorer wired to the shared tier scores and write-backs through
    the RESP server; a second scorer sees the first one's state."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    c1, c2 = RespClient(port=server.port), RespClient(port=server.port)
    c1.flushdb()
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=31)
    s1 = FraudScorer(scorer_config=ScorerConfig(text_len=32), state_client=c1)
    s1.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    records = gen.generate_batch(8)
    results = s1.score_batch(records, now=1000.0)
    assert len(results) == 8

    s2 = FraudScorer(scorer_config=ScorerConfig(text_len=32), state_client=c2)
    # replica 2 sees replica 1's profiles, velocity, and txn cache
    uid = str(records[0]["user_id"])
    assert s2.profiles.get_user(uid) is not None
    assert s2.velocity.get_all(uid)["24hour"]["count"] >= 1
    tid = str(records[0]["transaction_id"])
    assert s2.txn_cache.get_transaction(tid) is not None
    c1.close()
    c2.close()


# ---------------------------------------- eviction / persistence / replication


def test_lru_eviction_under_memory_cap():
    """maxmemory + allkeys-lru (reference redis-master.conf:17-18): a write
    burst beyond the cap evicts the least-recently-used keys, stays under
    the cap, and keeps the hot (recently touched) keys."""
    s = MiniRedisServer(maxmemory=20_000).start()
    c = RespClient(port=s.port)
    try:
        for i in range(200):
            c.set(f"k{i}", "x" * 80)
            c.get("k0")          # keep k0 hot the whole time
        assert s.used_memory <= 20_000
        assert s.evicted_keys > 0
        assert c.dbsize() < 200
        assert c.get("k0") == b"x" * 80          # hot key survived
        assert c.get("k199") == b"x" * 80        # newest key survived
        assert c.get("k1") is None               # cold early key evicted
        info = c.info()
        assert int(info["evicted_keys"]) == s.evicted_keys
        assert info["maxmemory_policy"] == "allkeys-lru"
    finally:
        c.close()
        s.stop()


def test_noeviction_policy_returns_oom():
    from realtime_fraud_detection_tpu.state.resp import RespError

    s = MiniRedisServer(maxmemory=2_000, policy="noeviction").start()
    c = RespClient(port=s.port)
    try:
        with pytest.raises(RespError, match="OOM"):
            for i in range(100):
                c.set(f"k{i}", "x" * 100)
        c.delete("k0")            # DEL is allowed over the cap
    finally:
        c.close()
        s.stop()


def test_aof_kill_and_restart_preserves_state(tmp_path):
    """Kill the state server, start a new one on the same AOF: profiles,
    velocity hashes, lists, counters and live TTLs all survive; expired
    TTLs stay dead (absolute PEXPIREAT rewriting)."""
    aof = str(tmp_path / "state.aof")
    s1 = MiniRedisServer(aof_path=aof).start()
    c1 = RespClient(port=s1.port)
    c1.set("profile:user:42", '{"avg":12.5}')
    c1.hset("velocity:u42:5min", "count", 3, "amount", 99.5)
    c1.hincrby("velocity:u42:5min", "count", 2)
    c1.lpush("txns:u42", "t1", "t2", "t3")
    c1.incr("counter")
    c1.set("live-ttl", "here", ex=3600)
    c1.set("dead-ttl", "gone", ex=0.05)
    c1.setnx("nx-miss", "a")
    c1.setnx("nx-miss", "b")     # no-op: must not corrupt replay
    import time as _t
    _t.sleep(0.1)
    c1.close()
    s1.stop()                    # hard stop: nothing flushed beyond the log

    s2 = MiniRedisServer(aof_path=aof).start()
    c2 = RespClient(port=s2.port)
    try:
        assert c2.get("profile:user:42") == b'{"avg":12.5}'
        h = c2.hgetall("velocity:u42:5min")
        assert h["count"] == b"5" and h["amount"] == b"99.5"
        assert c2.lrange("txns:u42", 0, -1) == [b"t3", b"t2", b"t1"]
        assert c2.get("counter") == b"1"
        assert c2.get("live-ttl") == b"here"
        assert c2.execute("TTL", "live-ttl") > 3000  # absolute, not re-armed
        assert c2.get("dead-ttl") is None
        assert c2.get("nx-miss") == b"a"
    finally:
        c2.close()
        s2.stop()


def test_aof_rewrite_compacts_and_replays(tmp_path):
    import os

    aof = str(tmp_path / "state.aof")
    s1 = MiniRedisServer(aof_path=aof).start()
    c1 = RespClient(port=s1.port)
    for i in range(50):
        c1.set("churn", f"v{i}")          # 50 log entries, 1 live key
    size_before = os.path.getsize(aof)
    s1.rewrite_aof()
    assert os.path.getsize(aof) < size_before
    c1.set("after-rewrite", "1")          # appends still work post-rewrite
    c1.close()
    s1.stop()

    s2 = MiniRedisServer(aof_path=aof).start()
    c2 = RespClient(port=s2.port)
    try:
        assert c2.get("churn") == b"v49"
        assert c2.get("after-rewrite") == b"1"
    finally:
        c2.close()
        s2.stop()


def _wait_for(pred, timeout_s=5.0):
    import time as _t

    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        if pred():
            return True
        _t.sleep(0.02)
    return False


def test_replication_snapshot_stream_and_failover():
    """Replica SYNCs existing state, converges on new writes, rejects
    client writes, and after promote() accepts them (the reference's
    3-master+3-replica failover story, docker-compose.yml redis services)."""
    from realtime_fraud_detection_tpu.state.resp import RespError

    primary = MiniRedisServer().start()
    cp = RespClient(port=primary.port)
    cp.set("pre-sync", "snapshot-me")
    cp.hset("h", "f", "1")

    replica = MiniRedisServer(replica_of=("127.0.0.1", primary.port)).start()
    cr = RespClient(port=replica.port)
    try:
        # snapshot
        assert _wait_for(lambda: cr.get("pre-sync") == b"snapshot-me")
        # live stream
        cp.set("post-sync", "stream-me")
        cp.hincrby("h", "f", 4)
        cp.set("ttl-key", "x", ex=3600)
        assert _wait_for(lambda: cr.get("post-sync") == b"stream-me")
        assert _wait_for(lambda: cr.hget("h", "f") == b"5")
        assert cr.execute("TTL", "ttl-key") > 3000
        assert cr.info()["role"] == "slave"
        # read-only
        with pytest.raises(RespError, match="READONLY"):
            cr.set("nope", "1")
        # failover: primary dies, replica promoted, writes flow again
        cp.close()
        primary.stop()
        replica.promote()
        assert _wait_for(lambda: cr.info()["role"] == "master")
        cr.set("after-failover", "1")
        assert cr.get("after-failover") == b"1"
        assert cr.get("pre-sync") == b"snapshot-me"  # nothing lost
    finally:
        cr.close()
        replica.stop()


def test_concurrent_load_with_eviction_replication_aof(tmp_path):
    """Stress the new production machinery together: N client threads
    hammer a bounded AOF-backed primary while a replica SYNCs mid-stream
    and evictions run. Invariants: no deadlock/timeouts, primary stays
    responsive, memory stays under the cap, counters converge on the
    replica, and a restart replays to the same live keys."""
    import threading

    aof = str(tmp_path / "stress.aof")
    primary = MiniRedisServer(maxmemory=150_000, aof_path=aof).start()
    errors: list = []

    def hammer(tid: int):
        try:
            c = RespClient(port=primary.port, timeout_s=10.0)
            for i in range(300):
                c.set(f"t{tid}:k{i}", "v" * 50)
                c.hincrby("shared:counter", f"t{tid}", 1)
                c.lpush(f"t{tid}:list", str(i))
                c.ltrim(f"t{tid}:list", 0, 9)
                if i % 50 == 0:
                    c.get(f"t{tid}:k{i}")
                    c.dbsize()
            c.close()
        except Exception as e:  # noqa: BLE001
            errors.append(f"t{tid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    # attach a replica while the write storm is running
    replica = MiniRedisServer(replica_of=("127.0.0.1", primary.port)).start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads), "hammer thread hung"

    c = RespClient(port=primary.port)
    assert primary.used_memory <= 150_000
    assert primary.evicted_keys > 0                  # cap actually bound
    counts = c.hgetall("shared:counter")
    assert {int(v) for v in counts.values()} == {300}   # atomic increments

    # replica converges on the final counter hash
    cr = RespClient(port=replica.port)
    assert _wait_for(
        lambda: cr.hgetall("shared:counter") == counts, timeout_s=10.0)

    # restart from AOF: the same live keyspace comes back
    live_before = c.dbsize()
    counter_before = c.hgetall("shared:counter")
    c.close()
    primary.stop()
    restarted = MiniRedisServer(aof_path=aof).start()
    c2 = RespClient(port=restarted.port)
    try:
        assert c2.hgetall("shared:counter") == counter_before
        assert c2.dbsize() == live_before
    finally:
        c2.close()
        cr.close()
        restarted.stop()
        replica.stop()


def test_aof_rewrite_under_concurrent_writes(tmp_path):
    """rewrite_aof() while clients are writing: nothing lost, appends keep
    flowing to the NEW file, and a restart replays the rewritten+appended
    log to the exact final state."""
    import threading

    aof = str(tmp_path / "rw.aof")
    s = MiniRedisServer(aof_path=aof).start()
    stop = threading.Event()
    errors: list = []

    def writer():
        try:
            c = RespClient(port=s.port, timeout_s=10.0)
            i = 0
            while not stop.is_set():
                c.set(f"w:{i % 50}", f"v{i}")
                c.hincrby("agg", "n", 1)
                i += 1
            c.close()
        except Exception as e:  # noqa: BLE001
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    import time as _t

    for _ in range(5):
        _t.sleep(0.1)
        s.rewrite_aof()
    stop.set()
    for t in threads:
        t.join(timeout=20)
    assert not errors, errors

    c = RespClient(port=s.port)
    final_agg = c.hgetall("agg")["n"]
    final_db = c.dbsize()
    c.close()
    s.stop()

    s2 = MiniRedisServer(aof_path=aof).start()
    c2 = RespClient(port=s2.port)
    try:
        assert c2.hgetall("agg")["n"] == final_agg
        assert c2.dbsize() == final_db
    finally:
        c2.close()
        s2.stop()


def test_scorer_connects_shared_tier_from_config_env():
    """state.backend="redis" + REDIS_HOST/REDIS_PORT (the reference's env
    contract) routes the scorer's state plane to the shared tier with no
    explicit client; close() releases the owned connection."""
    import os
    from unittest import mock

    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.state.shared import SharedProfileStore
    from realtime_fraud_detection_tpu.utils.config import Config

    s = MiniRedisServer().start()
    try:
        with mock.patch.dict(os.environ, {
                "RTFD_STATE_BACKEND": "redis",
                "REDIS_HOST": "127.0.0.1",
                "REDIS_PORT": str(s.port)}):
            cfg = Config()
        assert cfg.state.backend == "redis"
        gen = TransactionGenerator(num_users=12, num_merchants=6, seed=8)
        scorer = FraudScorer(config=cfg)
        assert isinstance(scorer.profiles, SharedProfileStore)
        assert scorer._owned_state_client is not None
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        res = scorer.score_batch(gen.generate_batch(4))
        assert len(res) == 4
        scorer.close()
        assert scorer._owned_state_client is None
    finally:
        s.stop()

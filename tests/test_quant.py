"""Quantized scoring plane (ISSUE 9): weight-only int8 BERT calibration,
the QuantSettings config surface, scorer threading, checkpoint quant-mode
arch stamps, the quant_* Prometheus mirror, and the `rtfd quant-drill`
tier-1 smoke."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.bert import (
    TINY_CONFIG,
    bert_predict,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.quant import (
    bert_param_bytes,
    is_quantized_bert,
    quant_error_bound,
    quantize_bert_params,
    quantize_dense,
    quantize_embedding,
)
from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.utils.config import Config, QuantSettings


def _quant_config() -> Config:
    return Config(quant=QuantSettings.full())


def _scorer_pair(seed=0, n_users=120, n_merch=40):
    """Identically seeded (f32, quantized) scorers with seeded profiles."""
    out = []
    for cfg in (Config(), _quant_config()):
        gen = TransactionGenerator(num_users=n_users, num_merchants=n_merch,
                                   seed=7)
        s = FraudScorer(cfg, scorer_config=ScorerConfig(), seed=seed)
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        out.append((gen, s))
    return out


class TestCalibration:
    def test_dense_reconstruction_within_half_lsb(self):
        rng = np.random.default_rng(0)
        w = rng.standard_normal((32, 16)).astype(np.float32) * 0.2
        q = quantize_dense({"w": w, "b": np.zeros(16, np.float32)})
        assert q["qw"].dtype == np.int8 and q["scale"].shape == (16,)
        recon = q["qw"].astype(np.float32) * q["scale"][None, :]
        # symmetric rounding: error bounded by half a step per channel
        assert np.all(np.abs(recon - w) <= q["scale"][None, :] * 0.5 + 1e-7)

    def test_zero_channel_stays_exact_zero(self):
        w = np.zeros((8, 4), np.float32)
        w[:, 0] = 1.0
        q = quantize_dense({"w": w, "b": np.zeros(4, np.float32)})
        recon = q["qw"].astype(np.float32) * q["scale"][None, :]
        assert np.array_equal(recon[:, 1:], np.zeros((8, 3), np.float32))
        np.testing.assert_allclose(recon[:, 0], w[:, 0], atol=1e-6)

    def test_embedding_per_row_scales(self):
        rng = np.random.default_rng(1)
        w = rng.standard_normal((10, 6)).astype(np.float32)
        w[3] *= 50.0                      # an outlier row must not crush
        q = quantize_embedding(w)         # the resolution of the others
        recon = q["qe"].astype(np.float32) * q["scale"][:, None]
        assert np.all(np.abs(recon - w) <= q["scale"][:, None] * 0.5 + 1e-6)

    def test_bert_pytree_layout_and_idempotence(self):
        params = init_bert_params(jax.random.PRNGKey(0), TINY_CONFIG)
        q = quantize_bert_params(jax.device_get(params))
        assert is_quantized_bert(q) and not is_quantized_bert(params)
        # head + norms stay f32; every per-layer dense went int8
        assert "w" in q["classifier"] and "qw" in q["layers"][0]["ffn1"]
        # idempotent: a hot-swap path can apply it unconditionally
        q2 = quantize_bert_params(q)
        assert q2 is q
        assert quant_error_bound(q) > 0.0

    def test_deterministic_calibration(self):
        params = jax.device_get(init_bert_params(jax.random.PRNGKey(3),
                                                 TINY_CONFIG))
        a, b = quantize_bert_params(params), quantize_bert_params(params)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_bytes_ratio_exceeds_floor(self):
        """Acceptance: quantized BERT branch >= 3.5x smaller than f32."""
        params = init_bert_params(jax.random.PRNGKey(0), TINY_CONFIG)
        q = quantize_bert_params(jax.device_get(params))
        assert bert_param_bytes(params) / bert_param_bytes(q) >= 3.5

    def test_forward_parity_close(self):
        params = init_bert_params(jax.random.PRNGKey(5), TINY_CONFIG)
        q = jax.device_put(quantize_bert_params(jax.device_get(params)))
        rng = np.random.default_rng(5)
        ids = jnp.asarray(rng.integers(0, TINY_CONFIG.vocab_size, (8, 16)),
                          jnp.int32)
        mask = jnp.ones((8, 16), bool)
        a = np.asarray(bert_predict(params, ids, mask, TINY_CONFIG))
        b = np.asarray(bert_predict(q, ids, mask, TINY_CONFIG))
        np.testing.assert_allclose(a, b, atol=2e-3)


class TestQuantSettings:
    def test_validation(self):
        with pytest.raises(ValueError):
            QuantSettings(bert_weights="int4").validate()
        with pytest.raises(ValueError):
            QuantSettings(tree_kernel="einsum").validate()
        QuantSettings.full().validate()

    def test_disabled_plane_serves_f32_gather(self):
        s = QuantSettings(bert_weights="int8", tree_kernel="gemm")
        assert not s.enabled
        assert s.bert_mode() == "f32"
        assert s.stamp() == {"bert_weights": "f32"}
        assert QuantSettings.full().stamp() == {"bert_weights": "int8"}

    def test_config_overlay_round_trip(self, tmp_path):
        p = tmp_path / "q.json"
        p.write_text(json.dumps({"quant": {"enabled": True,
                                           "bert_weights": "int8"}}))
        loaded = Config.from_file(str(p)).quant
        assert loaded.enabled and loaded.bert_mode() == "int8"
        assert loaded.tree_kernel == "gather"


class TestScorerThreading:
    def test_quant_scorer_serves_int8_and_gemm(self):
        (_, f32), (_, q) = _scorer_pair()
        assert not is_quantized_bert(f32.models.bert)
        assert is_quantized_bert(q.models.bert)
        assert q.quant_static() == {"tree_kernel": "gemm",
                                    "iforest_kernel": "gemm"}
        assert f32.quant_static() == {"tree_kernel": "gather",
                                      "iforest_kernel": "gather"}
        snap = q.quant_snapshot()
        assert snap["modes"] == {"bert_text": "int8",
                                 "xgboost_primary": "gemm",
                                 "isolation_forest": "gemm"}
        assert snap["param_bytes"]["bert_text"] < \
            f32.quant_snapshot()["param_bytes"]["bert_text"]

    def test_score_parity_and_zero_flips(self):
        (gen_f, f32), (gen_q, q) = _scorer_pair()
        ra = f32.score_batch(gen_f.generate_batch(48), now=1000.0)
        rb = q.score_batch(gen_q.generate_batch(48), now=1000.0)
        pa = np.asarray([r["fraud_probability"] for r in ra])
        pb = np.asarray([r["fraud_probability"] for r in rb])
        assert np.max(np.abs(pa - pb)) < 1e-3
        assert [r["decision"] for r in ra] == [r["decision"] for r in rb]

    def test_set_models_quantizes_incoming_f32(self):
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        (_, _), (_, q) = _scorer_pair()
        fresh = init_scoring_models(jax.random.PRNGKey(42),
                                    bert_config=q.bert_config,
                                    feature_dim=q.sc.feature_dim,
                                    node_dim=q.sc.node_dim)
        assert not is_quantized_bert(fresh.bert)
        q.set_models(fresh)     # hot swap: promotion / reload / drill
        assert is_quantized_bert(q.models.bert)

    def test_init_quantized_params_are_device_committed(self):
        """Regression pin: __init__ calibration must commit the int8
        pytree back onto the mesh (host numpy leaves in self.models would
        re-upload the whole BERT branch H2D on every non-pool dispatch —
        the exact payload this plane shrinks)."""
        (_, _), (_, q) = _scorer_pair()
        for leaf in jax.tree_util.tree_leaves(q.models.bert):
            assert isinstance(leaf, jax.Array), type(leaf)

    def test_gate_ledger_counts(self):
        (_, _), (_, q) = _scorer_pair()
        q.record_quant_gate(True)
        q.record_quant_gate(True)
        q.record_quant_gate(False)
        assert q.quant_snapshot()["gate"] == {"pass": 2, "fail": 1}


class TestCheckpointQuantStamp:
    def _mk(self, tmp_path, quantized: bool, seed=0):
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        cfg = _quant_config() if quantized else Config()
        s = FraudScorer(cfg, scorer_config=ScorerConfig(), seed=seed)
        mgr = CheckpointManager(tmp_path / "ck")
        return s, mgr

    def test_manifest_records_quant_mode(self, tmp_path):
        s, mgr = self._mk(tmp_path, quantized=True)
        mgr.save(1, params=s.models)
        assert mgr.manifest(1)["quant_mode"] == {"bert_weights": "int8"}
        s2, mgr2 = self._mk(tmp_path / "b", quantized=False)
        mgr2.save(1, params=s2.models)
        assert mgr2.manifest(1)["quant_mode"] == {"bert_weights": "f32"}

    def test_same_mode_round_trip_serves_identically(self, tmp_path):
        gen = TransactionGenerator(num_users=80, num_merchants=30, seed=3)
        s, mgr = self._mk(tmp_path, quantized=True)
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        mgr.save(2, params=s.models)
        ref = s.score_batch(gen.generate_batch(16), now=1000.0)

        gen2 = TransactionGenerator(num_users=80, num_merchants=30, seed=3)
        s2 = FraudScorer(_quant_config(), scorer_config=ScorerConfig(),
                         seed=9)        # different init: restore overwrites
        s2.seed_profiles(gen2.users.profiles(), gen2.merchants.profiles())
        ck = mgr.restore_into_scorer(s2)
        assert ck.step == 2 and is_quantized_bert(s2.models.bert)
        got = s2.score_batch(gen2.generate_batch(16), now=1000.0)
        assert [r["fraud_probability"] for r in ref] == \
            [r["fraud_probability"] for r in got]

    def test_cross_mode_restore_refused_both_ways(self, tmp_path):
        s_q, mgr_q = self._mk(tmp_path / "q", quantized=True)
        mgr_q.save(1, params=s_q.models)
        s_f, mgr_f = self._mk(tmp_path / "f", quantized=False)
        mgr_f.save(1, params=s_f.models)

        # int8 checkpoint into an f32 scorer: refused
        with pytest.raises(ValueError, match="quantization-mode mismatch"):
            mgr_q.restore_into_scorer(
                FraudScorer(Config(), scorer_config=ScorerConfig()))
        # f32 checkpoint into a quantized scorer: refused
        with pytest.raises(ValueError, match="quantization-mode mismatch"):
            mgr_f.restore_into_scorer(
                FraudScorer(_quant_config(), scorer_config=ScorerConfig()))

    def test_allow_arch_mismatch_serves_checkpoint_form(self, tmp_path):
        s_q, mgr_q = self._mk(tmp_path / "q", quantized=True)
        mgr_q.save(1, params=s_q.models)
        f32 = FraudScorer(Config(), scorer_config=ScorerConfig())
        mgr_q.restore_into_scorer(f32, allow_arch_mismatch=True)
        # the scorer serves the checkpoint's actual (int8) form, and the
        # observability snapshot reads the live-params truth
        assert is_quantized_bert(f32.models.bert)
        assert f32.quant_snapshot()["modes"]["bert_text"] == "int8"

    def test_stampless_manifest_restores_leniently(self, tmp_path):
        s, mgr = self._mk(tmp_path, quantized=False)
        mgr.save(1, params=s.models)
        mpath = mgr.directory / "step_0000000001" / "manifest.json"
        m = json.loads(mpath.read_text())
        del m["quant_mode"]               # an old, pre-ISSUE-9 checkpoint
        mpath.write_text(json.dumps(m))
        target = FraudScorer(_quant_config(), scorer_config=ScorerConfig())
        mgr.restore_into_scorer(target)   # no refusal
        # set_models quantized the incoming f32 params to the scorer's form
        assert is_quantized_bert(target.models.bert)


class TestSyncQuant:
    def test_counter_delta_mirror_and_modes(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        (_, _), (_, q) = _scorer_pair()
        q.record_quant_gate(True)
        m = MetricsCollector()
        m.sync_quant(q.quant_snapshot())
        m.sync_quant(q.quant_snapshot())        # re-sync: NOT double-counted
        assert m.quant_gate_verdicts.value(verdict="pass") == 1.0
        q.record_quant_gate(False)
        m.sync_quant(q.quant_snapshot())
        assert m.quant_gate_verdicts.value(verdict="pass") == 1.0
        assert m.quant_gate_verdicts.value(verdict="fail") == 1.0
        # branch-mode gauges are exhaustive: the inactive mode reads 0
        assert m.quant_branch_mode.value(branch="bert_text",
                                         mode="int8") == 1.0
        assert m.quant_branch_mode.value(branch="bert_text",
                                         mode="f32") == 0.0
        assert m.quant_branch_mode.value(branch="xgboost_primary",
                                         mode="gemm") == 1.0
        assert m.quant_param_bytes.value(branch="bert_text") > 0

    def test_stream_and_serving_render_identical(self):
        """Satellite pin: the stream job and the serving app mirror the
        SAME scorer snapshot into independent collectors — the rendered
        quant_* series must match line for line."""
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        (_, _), (_, q) = _scorer_pair()
        q.record_quant_gate(True)
        snap = q.quant_snapshot()
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_quant(snap)
        b.sync_quant(snap)

        def quant_lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if ln.startswith("quant_")]

        assert quant_lines(a) and quant_lines(a) == quant_lines(b)

    def test_serving_metrics_endpoint_exposes_quant(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        m = MetricsCollector()
        (_, _), (_, q) = _scorer_pair()
        m.sync_quant(q.quant_snapshot())
        text = m.render_prometheus()
        assert 'quant_branch_mode{branch="bert_text",mode="int8"} 1' in text
        assert "quant_gate_verdicts_total" in text


class TestCliFlags:
    def test_parse_quant_flags(self):
        from realtime_fraud_detection_tpu.cli import build_parser

        p = build_parser()
        assert p.parse_args(["run-job", "--quant"]).quant is True
        assert p.parse_args(["serve", "--quant"]).quant is True
        assert p.parse_args(["bench", "--quant"]).quant is True
        args = p.parse_args(["quant-drill", "--fast", "--no-replay",
                             "--seed", "5"])
        assert args.fast and args.no_replay and args.seed == 5


def test_quant_drill_fast_smoke(capsys):
    """Tier-1 acceptance: `rtfd quant-drill --fast` runs un-slow-marked on
    every pass — divergence below the calibration-noise bound, zero
    decision flips at the operating point, AUC unchanged on the quality
    protocol, exact GEMM-vs-gather leaves, >= 3.5x smaller BERT bytes,
    and a bit-identical replay."""
    from realtime_fraud_detection_tpu import cli

    rc = cli.main(["quant-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["divergence_below_noise"]
    assert checks["zero_decision_flips"]
    assert checks["auc_unchanged"]
    assert checks["gemm_leaves_identical"]
    assert checks["gemm_logits_within_tol"]
    assert checks["bytes_ratio_ge_min"]
    assert checks["replay_bit_identical"]
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["divergence"]["decision_flips"] == 0
    assert full["param_bytes"]["ratio"] >= 3.5
    assert full["divergence"]["max"] <= \
        full["divergence"]["noise_floor"]["bound"]

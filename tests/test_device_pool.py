"""Device-pool scoring plane: replicated multi-device dispatch
(scoring/device_pool.py), its drill (scoring/pool_drill.py), pooled
serving edge cases, and the mesh small-batch tolerance the pool's
drain/flush tails rely on (core/mesh.py)."""

import asyncio

import numpy as np
import pytest

from realtime_fraud_detection_tpu.core.mesh import (
    build_mesh,
    local_mesh_size,
    pad_batch_to_mesh,
    shard_batch,
)
from realtime_fraud_detection_tpu.scoring import (
    DevicePool,
    FraudScorer,
    ScorerConfig,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

BATCH = 16


def make_scorer(seed=3, model_seed=0):
    gen = TransactionGenerator(num_users=300, num_merchants=60, seed=seed)
    s = FraudScorer(scorer_config=ScorerConfig(), seed=model_seed)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, s


@pytest.fixture
def pooled():
    gen, scorer = make_scorer()
    pool = DevicePool(scorer, inflight_depth=2)
    return gen, scorer, pool


# ------------------------------------------------------------- mesh padding
class TestMeshSmallBatchTolerance:
    """Satellite: pad_batch_to_mesh/shard_batch must tolerate batches
    smaller than the device count (pool drain/flush tails)."""

    def test_pad_batch_smaller_than_mesh(self, mesh8):
        d = local_mesh_size(mesh8)
        assert d == 8
        assert pad_batch_to_mesh(3, mesh8) == 8
        assert pad_batch_to_mesh(8, mesh8) == 8
        assert pad_batch_to_mesh(9, mesh8) == 16
        assert pad_batch_to_mesh(0, mesh8) == 8   # degenerate, still shardable

    def test_shard_batch_pads_indivisible(self, mesh8):
        tree = {"x": np.arange(3 * 4, dtype=np.float32).reshape(3, 4),
                "y": np.arange(3, dtype=np.int32)}
        sharded = shard_batch(mesh8, tree)
        assert sharded["x"].shape == (8, 4)
        assert sharded["y"].shape == (8,)
        # pad rows replicate row 0 (the pad_to_bucket convention)
        x = np.asarray(sharded["x"])
        np.testing.assert_array_equal(x[:3], tree["x"])
        for i in range(3, 8):
            np.testing.assert_array_equal(x[i], tree["x"][0])

    def test_shard_batch_divisible_unchanged(self, mesh8):
        tree = {"x": np.arange(16, dtype=np.float32).reshape(16, 1)}
        np.testing.assert_array_equal(
            np.asarray(shard_batch(mesh8, tree)["x"]), tree["x"])

    def test_shard_batch_empty_passes_through(self, mesh8):
        # 0 rows divide any axis; an empty batch stays empty (dispatch
        # paths filter empties before the device seam anyway)
        out = shard_batch(mesh8, {"x": np.zeros((0, 4), np.float32)})
        assert out["x"].shape == (0, 4)

    def test_small_batch_scores_through_mesh(self, mesh8):
        # end-to-end: a 3-record tail scores through an 8-device mesh
        gen, scorer = make_scorer()
        res = scorer.score_batch(gen.generate_batch(3), now=1000.0)
        assert len(res) == 3
        assert all(np.isfinite(r["fraud_probability"]) for r in res)


# ------------------------------------------------------------------- pool
class TestDevicePool:
    def test_round_robin_and_fifo(self, pooled):
        gen, scorer, pool = pooled
        batches = [gen.generate_batch(BATCH) for _ in range(8)]
        pend = [scorer.dispatch(b, now=1000.0) for b in batches]
        results = [scorer.finalize(p, now=1000.0) for p in pend]
        # FIFO: results match submit order
        got = [r["transaction_id"] for batch in results for r in batch]
        want = [str(r["transaction_id"]) for b in batches for r in b]
        assert got == want
        st = pool.stats()
        assert [d["dispatched"] for d in st["devices"]] == [1] * 8
        assert st["completed"] == 8
        assert st["retries"] == 0

    def test_bit_identical_to_single_device(self):
        gen_a, serial = make_scorer()
        gen_b, pooled_scorer = make_scorer()
        DevicePool(pooled_scorer, inflight_depth=2)
        batches_a = [gen_a.generate_batch(BATCH) for _ in range(4)]
        batches_b = [gen_b.generate_batch(BATCH) for _ in range(4)]
        # identical dispatch/finalize interleaving on both sides
        pend_a = [serial.dispatch(b, now=1000.0) for b in batches_a]
        ref = [serial.finalize(p, now=1000.0) for p in pend_a]
        pend_b = [pooled_scorer.dispatch(b, now=1000.0) for b in batches_b]
        got = [pooled_scorer.finalize(p, now=1000.0) for p in pend_b]
        for rb, gb in zip(ref, got):
            for r, g in zip(rb, gb):
                assert r["fraud_probability"] == g["fraud_probability"]
                assert r["confidence"] == g["confidence"]
                assert r["decision"] == g["decision"]

    def test_device_loss_mid_flight_retries_on_healthy(self, pooled):
        """Fault-injected replica raises at result fetch -> the batch is
        relaunched on a healthy replica, counted in metrics."""
        gen, scorer, pool = pooled
        pend = scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
        victim = pend.pool_token.replica_idx
        pool.inject_fault(victim, 1)
        res = scorer.finalize(pend, now=1000.0)
        assert len(res) == BATCH
        assert all(np.isfinite(r["fraud_probability"]) for r in res)
        st = pool.stats()
        assert st["healthy"] == len(pool) - 1
        assert not st["devices"][victim]["healthy"]
        assert st["devices"][victim]["failures"] == 1
        assert st["retries"] == 1
        # the rescued batch completed on a DIFFERENT replica
        rescuer = pend.pool_token.replica_idx
        assert rescuer != victim
        # failed replica leaves the rotation until revived
        p2 = scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
        assert p2.pool_token.replica_idx != victim
        scorer.finalize(p2, now=1000.0)
        pool.revive(victim)
        assert pool.stats()["healthy"] == len(pool)

    def test_retry_with_all_replicas_at_full_depth(self, pooled):
        """Rescue must bypass depth backpressure: with the whole window in
        flight and a single-threaded caller, waiting for a slot on the
        rescue replica would deadlock."""
        gen, scorer, pool = pooled
        window = pool.total_slots()
        pend = [scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
                for _ in range(window)]
        pool.inject_fault(pend[0].pool_token.replica_idx, 1)
        results = [scorer.finalize(p, now=1000.0) for p in pend]
        assert all(len(r) == BATCH for r in results)
        st = pool.stats()
        assert st["retries"] == 1
        assert st["completed"] == window

    def test_all_replicas_dead_raises(self, pooled):
        gen, scorer, pool = pooled
        pend = scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
        for i in range(len(pool)):
            pool.inject_fault(i, 2)
        with pytest.raises(RuntimeError):
            pool.wait(pend.pool_token)

    def test_retry_metrics_mirrored_to_prometheus(self, pooled):
        from realtime_fraud_detection_tpu.obs import MetricsCollector

        gen, scorer, pool = pooled
        pend = scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
        pool.inject_fault(pend.pool_token.replica_idx, 1)
        scorer.finalize(pend, now=1000.0)
        mc = MetricsCollector()
        mc.sync_device_pool(pool.stats())
        assert mc.pool_retries.total() == 1
        assert mc.pool_dispatched.total() >= 1
        assert mc.pool_healthy.value() == len(pool) - 1
        text = mc.render_prometheus()
        assert "device_pool_dispatched_total" in text
        assert "device_pool_retries_total" in text
        # counter-delta mirror: a second sync with unchanged stats adds 0
        mc.sync_device_pool(pool.stats())
        assert mc.pool_retries.total() == 1

    def test_qos_ladder_transition_with_batches_in_flight(self, pooled):
        """A ladder step between dispatches: in-flight batches finalize
        under their dispatch-time mask; later batches run the new mask on
        every replica (atomic fan-out)."""
        gen, scorer, pool = pooled
        full = gen.generate_batch(BATCH)
        pend_full = scorer.dispatch(full, now=1000.0)
        # ladder steps to trees+iforest while pend_full is in flight
        mask = np.array([True, False, False, False, True])
        scorer.set_degradation(mask, level=2)
        pend_deg = [scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
                    for _ in range(4)]
        res_full = scorer.finalize(pend_full, now=1000.0)
        res_deg = [scorer.finalize(p, now=1000.0) for p in pend_deg]
        assert set(res_full[0]["model_predictions"]) == {
            "xgboost_primary", "lstm_sequential", "bert_text",
            "graph_neural", "isolation_forest"}
        for batch_res in res_deg:
            for r in batch_res:
                assert set(r["model_predictions"]) == {
                    "xgboost_primary", "isolation_forest"}
        # lifting the rung restores the full ensemble on all replicas
        scorer.set_degradation(None)
        pend_back = [scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
                     for _ in range(2)]
        for p in pend_back:
            for r in scorer.finalize(p, now=1000.0):
                assert len(r["model_predictions"]) == 5

    def test_hot_swap_fans_out_to_all_replicas(self, pooled):
        import jax

        from realtime_fraud_detection_tpu.scoring import (
            init_scoring_models,
        )

        gen, scorer, pool = pooled
        recs = [gen.generate_batch(BATCH) for _ in range(len(pool) + 1)]
        before = scorer.score_batch(recs[0], now=1000.0)
        new_models = init_scoring_models(
            jax.random.PRNGKey(99), bert_config=scorer.bert_config,
            feature_dim=scorer.sc.feature_dim, node_dim=scorer.sc.node_dim)
        scorer.set_models(new_models)
        # every replica serves the new params now
        pend = [scorer.dispatch(b, now=1000.0) for b in recs[1:]]
        seen = {p.pool_token.replica_idx for p in pend}
        results = [scorer.finalize(p, now=1000.0) for p in pend]
        assert len(seen) > 1    # the check spans several replicas
        assert all(len(r) == BATCH for r in results)
        # swapped params actually changed the scores
        after = results[0]
        assert any(
            b["fraud_probability"] != a["fraud_probability"]
            for b, a in zip(before, after))

    def test_total_slots_tracks_health(self, pooled):
        _, _, pool = pooled
        assert pool.total_slots() == len(pool) * 2
        pool.replicas[0].healthy = False
        assert pool.total_slots() == (len(pool) - 1) * 2

    def test_slow_replica_keeps_fifo(self, pooled):
        """Chaos satellite: a DELAYED replica (inject_slow) is not a dead
        one — no retry, no health change, and FIFO completion across the
        pool holds while one replica lags."""
        import time as _time

        gen, scorer, pool = pooled
        batches = [gen.generate_batch(BATCH) for _ in range(len(pool))]
        pend = [scorer.dispatch(b, now=1000.0) for b in batches]
        victim = pend[0].pool_token.replica_idx
        pool.inject_slow(victim, 0.05, n=1)
        t0 = _time.monotonic()
        results = [scorer.finalize(p, now=1000.0) for p in pend]
        elapsed = _time.monotonic() - t0
        got = [r["transaction_id"] for batch in results for r in batch]
        want = [str(r["transaction_id"]) for b in batches for r in b]
        assert got == want                     # FIFO survived the lag
        assert elapsed >= 0.05                 # the delay really applied
        st = pool.stats()
        assert st["retries"] == 0              # delayed != dead: no rescue
        assert st["healthy"] == len(pool)
        assert st["devices"][victim]["failures"] == 0
        assert pool.replicas[victim].slow_next == 0   # one-shot consumed

    def test_revive_clears_armed_faults(self, pooled):
        """Chaos satellite: revive() means HEALTHY — a stale armed fault
        or slow injection must not re-kill the replica after its fault
        window closed."""
        gen, scorer, pool = pooled
        victim = 0
        pool.inject_fault(victim, 3)
        pool.inject_slow(victim, 5.0, n=4)     # would hang a later fetch
        pool.revive(victim)
        assert pool.replicas[victim].fail_next == 0
        assert pool.replicas[victim].slow_next == 0
        pend = [scorer.dispatch(gen.generate_batch(BATCH), now=1000.0)
                for _ in range(len(pool))]     # round-robin hits victim
        assert victim in {p.pool_token.replica_idx for p in pend}
        for p in pend:
            assert len(scorer.finalize(p, now=1000.0)) == BATCH
        st = pool.stats()
        assert st["retries"] == 0 and st["healthy"] == len(pool)


# ------------------------------------------------- pooled stream job wiring
class TestPooledStreamJob:
    def test_job_with_device_pool_drains_and_utilizes(self):
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream import topics as T

        gen, scorer = make_scorer()
        broker = InMemoryBroker()
        job = StreamJob(broker, scorer, JobConfig(
            max_batch=BATCH, emit_features=False,
            device_pool=True, inflight_depth=2))
        assert job.pool is not None
        assert job._inflight_depth() == job.pool.total_slots()
        n = BATCH * 24
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(n),
                             key_fn=lambda r: str(r["user_id"]))
        scored = job.run_until_drained(now=1000.0)
        assert scored == n
        st = job.pool.stats()
        assert all(d["dispatched"] > 0 for d in st["devices"])
        assert st["retries"] == 0
        # predictions all arrived, in order within each batch
        preds = broker.consumer([T.PREDICTIONS], "t").poll(n + 10)
        assert len(preds) == n


# ------------------------------------- pooled RequestMicrobatcher races
class TestPooledMicrobatcherRaces:
    def _pooled_batcher(self, scorer, **kw):
        from realtime_fraud_detection_tpu.serving.batcher import (
            RequestMicrobatcher,
        )

        pool = scorer.pool
        return RequestMicrobatcher(
            lambda txns: scorer.finalize(scorer.dispatch(txns, now=1000.0),
                                         now=1000.0),
            dispatch_fn=lambda txns: scorer.dispatch(txns, now=1000.0),
            finalize_fn=lambda p: scorer.finalize(p, now=1000.0),
            pipeline_depth=pool.total_slots(),
            max_batch=8, deadline_ms=1.0, **kw)

    def test_submit_stop_race_all_waiters_resolve(self, pooled):
        gen, scorer, pool = pooled
        recs = gen.generate_batch(24)
        b = self._pooled_batcher(scorer)

        async def main():
            await b.start()
            subs = [asyncio.get_running_loop().create_task(b.submit(dict(r)))
                    for r in recs]
            await asyncio.sleep(0)          # submits pass the _closed check
            stop = asyncio.get_running_loop().create_task(b.stop())
            results = await asyncio.wait_for(
                asyncio.gather(*subs, return_exceptions=True), timeout=60)
            await stop
            return results

        results = asyncio.run(main())
        # every waiter resolved (result or explicit error), none hang
        assert len(results) == 24
        ok = [r for r in results if isinstance(r, dict)]
        assert ok, "at least the pre-stop submissions must score"
        for r in ok:
            assert np.isfinite(r["fraud_probability"])

    def test_submit_after_stop_raises(self, pooled):
        gen, scorer, pool = pooled
        b = self._pooled_batcher(scorer)

        async def main():
            await b.start()
            await b.stop()
            with pytest.raises(RuntimeError):
                await b.submit({"transaction_id": "t1"})

        asyncio.run(main())

    def test_pooled_batcher_keeps_request_order(self, pooled):
        gen, scorer, pool = pooled
        recs = gen.generate_batch(32)

        b = self._pooled_batcher(scorer)

        async def main():
            await b.start()
            results = await asyncio.gather(
                *[b.submit(dict(r)) for r in recs])
            await b.stop()
            return results

        results = asyncio.run(main())
        assert [r["transaction_id"] for r in results] == \
            [str(r["transaction_id"]) for r in recs]


# ------------------------------------- tracing under overlap + device pool
class TestTracePropagationPooled:
    """Satellite: trace contexts must never cross-attach between
    transactions when the overlapped assembler (stage thread) and the
    device pool (concurrent dispatch, depth >= 2) run together."""

    def _traced_pooled_job(self, overlap: bool):
        from realtime_fraud_detection_tpu.obs.tracing import Tracer
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.utils.config import (
            TracingSettings,
        )

        gen, scorer = make_scorer()
        tracer = Tracer(TracingSettings(enabled=True, ring_size=4096))
        broker = InMemoryBroker()
        job = StreamJob(broker, scorer, JobConfig(
            max_batch=BATCH, emit_features=False,
            device_pool=True, inflight_depth=2,
            overlap_assembly=overlap, tracing=tracer))
        return gen, broker, job, tracer

    def test_no_cross_attachment_under_overlap_and_pool(self):
        from realtime_fraud_detection_tpu.stream import topics as T

        gen, broker, job, tracer = self._traced_pooled_job(overlap=True)
        n = BATCH * 24
        txns = gen.generate_batch(n)
        broker.produce_batch(T.TRANSACTIONS, txns,
                             key_fn=lambda r: str(r["user_id"]))
        scored = job.run_until_drained(now=1000.0)
        job.close()
        assert scored == n
        traces = tracer.traces(terminal="scored")
        assert len(traces) == n
        # exactly one trace per transaction, ids exactly the input set
        ids = [t.txn_id for t in traces]
        assert len(set(ids)) == n
        assert set(ids) == {str(r["transaction_id"]) for r in txns}
        # every trace carries the full stage set with sane durations, and
        # its dispatch metadata names a real replica at a legal depth
        for t in traces:
            assert {"queue", "assemble", "pack", "dispatch",
                    "device_wait", "finalize"} <= set(t.stages)
            assert all(ms >= 0.0 for ms in t.stages.values())
            assert 0 <= t.meta["replica"] < len(job.pool)
            assert 1 <= t.meta["inflight_depth"] \
                <= job.pool.inflight_depth
        # batch-mates share ONE TraceBatch (meta dict identity), so their
        # batch-granular stage durations are identical; distinct batches
        # got distinct replica assignments matching the pool's log
        by_batch = {}
        for t in traces:
            by_batch.setdefault(id(t.meta), []).append(t)
        assert len(by_batch) == n // BATCH
        log = list(job.pool.assignment_log)
        assert sorted(ts[0].meta["replica"] for ts in by_batch.values()) \
            == sorted(log)
        for mates in by_batch.values():
            assert len({t.stages["assemble"] for t in mates}) == 1
            assert len({t.meta["replica"] for t in mates}) == 1
            # per-txn stages still differ where they should be able to
            # (queue is per-transaction, from each txn's own admission)
            assert all(t.stages["queue"] >= 0.0 for t in mates)

    def test_concurrent_depth2_dispatch_keeps_attachment(self):
        """Direct scorer-level check: several pooled batches in flight at
        depth >= 2, finalized out of the dispatch thread's cadence — every
        trace resolves to its own batch's txns and replica."""
        from realtime_fraud_detection_tpu.obs.tracing import Tracer
        from realtime_fraud_detection_tpu.utils.config import (
            TracingSettings,
        )

        gen, scorer = make_scorer()
        pool = DevicePool(scorer, inflight_depth=2)
        tracer = Tracer(TracingSettings(enabled=True))
        batches = [gen.generate_batch(BATCH) for _ in range(12)]
        traces, pend = [], []
        for b in batches:
            tb = tracer.batch(
                [tracer.begin(str(r["transaction_id"])) for r in b],
                batch_size=len(b))
            traces.append(tb)
            pend.append(scorer.dispatch(b, now=1000.0, trace=tb))
        for p, tb in zip(pend, traces):
            scorer.finalize(p, now=1000.0)
            tracer.finish_batch(tb)
        done = tracer.traces(terminal="scored")
        assert len(done) == 12 * BATCH
        by_batch = {}
        for t in done:
            by_batch.setdefault(id(t.meta), []).append(t)
        assert len(by_batch) == 12
        want = [{str(r["transaction_id"]) for r in b} for b in batches]
        got = [{t.txn_id for t in mates} for mates in by_batch.values()]
        for w in want:
            assert w in got
        # annotated replica matches the token each batch actually rode
        for p in pend:
            assert p.trace.meta["replica"] == p.pool_token.replica_idx


# --------------------------------------------------------- drill smoke (CI)
def test_pool_drill_fast_smoke(monkeypatch, capsys):
    """Satellite: the `rtfd pool-drill --fast` path runs un-slow-marked on
    every tier-1 pass — through the CLI entry (in-process child mode; the
    session already provides the 8-device host platform)."""
    from realtime_fraud_detection_tpu import cli

    monkeypatch.setenv("_RTFD_POOL_DRILL_CHILD", "1")
    rc = cli.main(["pool-drill", "--fast"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    import json

    compact = json.loads(out[-1])           # final line: compact verdict
    assert compact["passed"] is True
    assert len(out[-1].encode()) < 2048
    assert compact["checks"]["bit_identical"]
    assert compact["checks"]["scaling_ge_min"]
    full = json.loads(out[-2])
    assert all(n > 0 for n in full["per_device_dispatched"])

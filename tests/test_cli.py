"""CLI tests. The heavyweight commands (run-job, serve, bench) are driven
in their own layers' tests; here the parser contract, simulate, train, and
health-check paths are exercised in-process."""

import json

import pytest

from realtime_fraud_detection_tpu.cli import _auc, build_parser, main


class TestParser:
    def test_requires_command(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args([])

    @pytest.mark.parametrize("argv", [
        ["simulate", "--count", "10"],
        ["run-job", "--count", "100", "--analytics"],
        ["serve", "--port", "9999"],
        ["train", "--rows", "500"],
        ["bench"],
        ["lint", "--format", "json"],
        ["lint", "--lockwatch", "--fast"],
        ["health-check", "--url", "http://x"],
        ["topics"],
    ])
    def test_all_subcommands_parse(self, argv):
        args = build_parser().parse_args(argv)
        assert callable(args.fn)


class TestSimulate:
    def test_writes_jsonl(self, tmp_path, capsys):
        out = tmp_path / "txns.jsonl"
        rc = main(["simulate", "--count", "120", "--users", "50",
                   "--merchants", "20", "--output", str(out)])
        assert rc == 0
        lines = out.read_text().strip().split("\n")
        assert len(lines) == 120
        txn = json.loads(lines[0])
        assert {"transaction_id", "user_id", "merchant_id", "amount",
                "timestamp"} <= set(txn)


class TestTopics:
    def test_lists_contract(self, capsys):
        assert main(["topics"]) == 0
        out = capsys.readouterr().out
        assert "payment-transactions" in out and "partitions=12" in out


class TestTrain:
    def test_trains_and_checkpoints(self, tmp_path, capsys):
        rc = main(["train", "--rows", "2000", "--trees", "8",
                   "--users", "200", "--merchants", "50",
                   "--out", str(tmp_path / "ckpt")])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
        assert report["auc"] > 0.7          # trees learn the synthetic rule
        assert (tmp_path / "ckpt" / "step_0000000000" / "manifest.json").exists()


class TestValidate:
    def test_validate_gates_on_auc_and_writes_textfile(self, tmp_path, capsys):
        """train -> validate on a FRESH stream: the reference's
        model-validation CronJob analog (ci-cd-pipeline.yaml:351-390),
        exit code = quality gate."""
        assert main(["train", "--rows", "2500", "--trees", "10",
                     "--users", "300", "--merchants", "60",
                     "--out", str(tmp_path / "ckpt")]) == 0
        capsys.readouterr()
        prom = tmp_path / "val.prom"
        rc = main(["validate", "--checkpoint-dir", str(tmp_path / "ckpt"),
                   "--rows", "1024", "--users", "300", "--merchants", "60",
                   "--min-auc", "0.6", "--metrics-out", str(prom)])
        report = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
        assert rc == 0 and report["passed"] is True
        assert report["auc"] >= 0.6 and report["n"] == 1024
        text = prom.read_text()
        assert "rtfd_validation_auc" in text
        assert "rtfd_validation_passed 1" in text

        # an unreachable bar fails the job (the CronJob's failure signal)
        rc = main(["validate", "--checkpoint-dir", str(tmp_path / "ckpt"),
                   "--rows", "512", "--users", "300", "--merchants", "60",
                   "--min-auc", "0.999"])
        report = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
        assert rc == 1 and report["passed"] is False


class TestHealthCheck:
    def test_unreachable_is_unhealthy(self, capsys):
        rc = main(["health-check", "--url", "http://127.0.0.1:1",
                   "--timeout", "0.2"])
        assert rc == 1
        assert json.loads(capsys.readouterr().out)["healthy"] is False


class TestAuc:
    def test_auc_orders_correctly(self):
        import numpy as np

        y = np.array([0, 0, 1, 1], float)
        assert _auc(y, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
        assert _auc(y, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
        assert _auc(np.zeros(4), np.ones(4)) == 0.5


class TestAucTies:
    def test_tied_scores_average_ranks(self):
        import numpy as np

        # all-tied scores carry no information -> AUC must be 0.5 in both
        # label orders (ordinal ranks would give 1.0 / 0.0)
        assert _auc(np.array([0.0, 1.0]), np.array([0.5, 0.5])) == 0.5
        assert _auc(np.array([1.0, 0.0]), np.array([0.5, 0.5])) == 0.5


class TestAlertRouter:
    def test_routes_alerts_to_webhook_with_committed_offsets(self):
        """cli alert-router: the EventBridge->Lambda->SNS analog — consumes
        fraud-alerts, POSTs Alertmanager-v2 payloads to the webhook, commits
        offsets only after the receiver accepts (at-least-once)."""
        import http.server
        import json as _json
        import threading

        from realtime_fraud_detection_tpu.stream import topics as T
        from realtime_fraud_detection_tpu.stream.netbroker import (
            BrokerServer,
            NetBrokerClient,
        )

        received = []

        class Hook(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.extend(_json.loads(body))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        hook = http.server.HTTPServer(("127.0.0.1", 0), Hook)
        threading.Thread(target=hook.serve_forever, daemon=True).start()
        broker = BrokerServer(port=0).start()
        client = NetBrokerClient(port=broker.port)
        try:
            for i in range(5):
                client.produce(T.ALERTS, {
                    "alert_type": "FRAUD_DETECTED",
                    "transaction_id": f"t{i}",
                    "user_id": f"u{i}",
                    "amount": 100.0 + i,
                    "fraud_score": 0.9,
                    "risk_level": "HIGH",
                    "decision": "DECLINE" if i % 2 else "REVIEW",
                }, key=f"u{i}")
            rc = main([
                "alert-router", "--broker", f"127.0.0.1:{broker.port}",
                "--webhook",
                f"http://127.0.0.1:{hook.server_address[1]}/api/v2/alerts",
                "--once"])
            assert rc == 0
            assert len(received) == 5
            assert {r["annotations"]["transaction_id"]
                    for r in received} == {f"t{i}" for i in range(5)}
            assert all(r["labels"]["alertname"] == "FRAUD_DETECTED"
                       for r in received)
            sev = {r["annotations"]["transaction_id"]: r["labels"]["severity"]
                   for r in received}
            assert sev["t1"] == "critical" and sev["t0"] == "warning"
            # offsets committed: a re-run routes nothing new
            received.clear()
            rc = main([
                "alert-router", "--broker", f"127.0.0.1:{broker.port}",
                "--webhook",
                f"http://127.0.0.1:{hook.server_address[1]}/api/v2/alerts",
                "--once"])
            assert rc == 0 and received == []
        finally:
            client.close()
            broker.stop()
            hook.shutdown()

    def test_comma_broker_list_fails_over_dead_first_address(self):
        """--broker with a comma list builds an HaBrokerClient: a dead
        first address (the killed primary) must not stop the router."""
        import http.server
        import json as _json
        import socket
        import threading

        from realtime_fraud_detection_tpu.stream import topics as T
        from realtime_fraud_detection_tpu.stream.netbroker import (
            BrokerServer,
            NetBrokerClient,
        )

        received = []

        class Hook(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.extend(_json.loads(body))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        hook = http.server.HTTPServer(("127.0.0.1", 0), Hook)
        threading.Thread(target=hook.serve_forever, daemon=True).start()
        with socket.socket() as s:           # a port nobody listens on
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        broker = BrokerServer(port=0).start()
        client = NetBrokerClient(port=broker.port)
        try:
            client.produce(T.ALERTS, {
                "alert_type": "FRAUD_DETECTED", "transaction_id": "tx",
                "user_id": "u", "amount": 9.0, "fraud_score": 0.95,
                "risk_level": "HIGH", "decision": "DECLINE"}, key="u")
            rc = main([
                "alert-router",
                "--broker", f"127.0.0.1:{dead_port},127.0.0.1:{broker.port}",
                "--webhook",
                f"http://127.0.0.1:{hook.server_address[1]}/alerts",
                "--once"])
            assert rc == 0
            assert [r["annotations"]["transaction_id"]
                    for r in received] == ["tx"]
        finally:
            client.close()
            broker.stop()
            hook.shutdown()


class TestRunJobResume:
    def test_second_run_resumes_from_checkpoint(self, tmp_path, capsys):
        """run-job --checkpoint-dir restores models/host-state/offsets and
        continues step numbering (the Flink restore-from-checkpoint
        behavior) instead of starting over."""
        ckpt_dir = str(tmp_path / "ck")
        argv = ["run-job", "--count", "600", "--users", "50",
                "--merchants", "20", "--batch", "64",
                "--checkpoint-dir", ckpt_dir]
        assert main(argv) == 0
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        first_steps = CheckpointManager(ckpt_dir).steps()
        assert first_steps, "first run wrote no checkpoints"
        capsys.readouterr()

        assert main(argv) == 0
        err = capsys.readouterr().err
        assert f"resumed from checkpoint step {max(first_steps)}" in err
        second_steps = CheckpointManager(ckpt_dir).steps()
        # numbering continued past the first run's last step
        assert max(second_steps) > max(first_steps)

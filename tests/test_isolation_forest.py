"""Isolation forest tensorization tests."""

import numpy as np

from realtime_fraud_detection_tpu.models.isolation_forest import (
    IsolationForestTrainer,
    iforest_predict,
    iforest_scores,
)


def _data(seed=0, n=2000, f=8):
    rng = np.random.default_rng(seed)
    normal = rng.normal(0, 1, size=(n, f)).astype(np.float32)
    outliers = rng.normal(0, 1, size=(50, f)).astype(np.float32) + 8.0
    return normal, outliers


class TestIsolationForest:
    def test_outliers_score_higher(self):
        normal, outliers = _data()
        forest = IsolationForestTrainer(n_estimators=50, seed=1).fit(normal)
        s_norm = np.asarray(iforest_scores(forest, normal[:200]))
        s_out = np.asarray(iforest_scores(forest, outliers))
        assert s_out.mean() > s_norm.mean() + 0.1
        assert (s_out > 0).all() and (s_out <= 1).all()

    def test_sigmoid_probability_mapping(self):
        # model_manager.py:338-346: p = 1/(1+exp(0.5 - s)); anomalous rows
        # (s near 1) must map to higher fraud probability than normal rows
        normal, outliers = _data(seed=2)
        forest = IsolationForestTrainer(n_estimators=50, seed=3).fit(normal)
        p_norm = np.asarray(iforest_predict(forest, normal[:200]))
        p_out = np.asarray(iforest_predict(forest, outliers))
        assert p_out.mean() > p_norm.mean()
        assert (p_norm > 0).all() and (p_norm < 1).all()

    def test_agrees_with_sklearn_ranking(self):
        from sklearn.ensemble import IsolationForest as SkIF

        normal, outliers = _data(seed=4)
        x_test = np.concatenate([normal[:100], outliers[:20]])
        ours = IsolationForestTrainer(n_estimators=100, seed=5).fit(normal)
        sk = SkIF(n_estimators=100, random_state=5).fit(normal)
        ours_s = np.asarray(iforest_scores(ours, x_test))
        sk_s = -sk.score_samples(x_test)  # sklearn: higher = more anomalous
        # rank correlation between the two scorings should be strong
        from scipy.stats import spearmanr

        rho = spearmanr(ours_s, sk_s).statistic
        assert rho > 0.8, f"spearman {rho:.3f}"

    def test_deterministic(self):
        normal, _ = _data(seed=6)
        a = IsolationForestTrainer(n_estimators=10, seed=7).fit(normal)
        b = IsolationForestTrainer(n_estimators=10, seed=7).fit(normal)
        np.testing.assert_array_equal(np.asarray(a.threshold), np.asarray(b.threshold))


class TestEmptyChildRegression:
    def test_constant_feature_columns_no_crash(self):
        # constant / near-constant features force degenerate splits
        rng = np.random.default_rng(0)
        x = np.zeros((300, 6), np.float32)
        x[:, 0] = rng.normal(size=300)          # one informative column
        x[:, 1] = 7.0                            # constant
        x[:, 2] = np.repeat([1.0, 1.0 + 1e-7], 150)  # ulp-scale spread
        forest = IsolationForestTrainer(n_estimators=30, seed=3).fit(x)
        s = np.asarray(iforest_scores(forest, x[:50]))
        assert np.isfinite(s).all()


class TestGemmKernel:
    """GEMM-form traversal for the isolation forest (ISSUE 9): identical
    leaves to the gather oracle on trained forests, path-length sums and
    final scores inside float tolerance."""

    def test_leaf_equality_trained_forest(self):
        import jax.numpy as jnp

        from realtime_fraud_detection_tpu.models.trees import (
            descend_complete_trees,
            gemm_leaf_index,
        )

        normal, outliers = _data(seed=11)
        forest = IsolationForestTrainer(n_estimators=32, seed=11).fit(normal)
        x = jnp.asarray(np.concatenate([normal[:128], outliers[:32]]))
        a = descend_complete_trees(forest.feature, forest.threshold, x)
        b = gemm_leaf_index(forest.feature, forest.threshold, x)
        assert bool(jnp.all(a == b))

    def test_scores_and_predictions_agree(self):
        normal, outliers = _data(seed=12)
        forest = IsolationForestTrainer(n_estimators=32, seed=12).fit(normal)
        x = np.concatenate([normal[:128], outliers[:32]])
        s_g = np.asarray(iforest_scores(forest, x, kernel="gather"))
        s_m = np.asarray(iforest_scores(forest, x, kernel="gemm"))
        np.testing.assert_allclose(s_g, s_m, atol=1e-5)
        p_g = np.asarray(iforest_predict(forest, x, kernel="gather"))
        p_m = np.asarray(iforest_predict(forest, x, kernel="gemm"))
        np.testing.assert_allclose(p_g, p_m, atol=1e-5)

    def test_unknown_kernel_raises(self):
        import pytest

        normal, _ = _data(seed=13)
        forest = IsolationForestTrainer(n_estimators=4, seed=13).fit(normal)
        with pytest.raises(ValueError, match="kernel"):
            iforest_scores(forest, normal[:4], kernel="einsum")

"""Host-assembly plane tests: columnar equivalence, cache correctness,
staging-buffer padding, and the overlapped assembler stage drill.

The contract under test (docs/host_pipeline.md): the columnar
``FraudScorer.assemble`` is BIT-identical to the record-at-a-time path
(``assemble_serial``) on arbitrary record streams — including after profile
rewrites (generation invalidation) and under token-cache eviction pressure
— and the background assembler stage overlaps assembly with device compute
without reordering results or dropping QoS admission decisions.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np
import pytest

from realtime_fraud_detection_tpu.models.tokenizer import (
    FraudTokenizer,
    TokenLruCache,
)
from realtime_fraud_detection_tpu.models.wordpiece import WordPieceTokenizer
from realtime_fraud_detection_tpu.scoring import (
    AssemblerStage,
    FraudScorer,
    ScorerConfig,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.state.history import UserHistoryStore
from realtime_fraud_detection_tpu.stream import InMemoryBroker
from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
from realtime_fraud_detection_tpu.utils.config import QosSettings


def _mk_scorer(seed: int = 5, tokenizer: str = "wordpiece",
               users: int = 120, merchants: int = 40):
    gen = TransactionGenerator(num_users=users, num_merchants=merchants,
                               seed=seed)
    s = FraudScorer(scorer_config=ScorerConfig(tokenizer=tokenizer), seed=0)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, s


def _mutate(recs, rng):
    """Poke holes so default/unknown paths are exercised too."""
    for r in recs:
        if rng.random() < 0.2:
            r.pop("geolocation", None)
        if rng.random() < 0.15:
            r["payment_method"] = None
        if rng.random() < 0.1:
            r.pop("device_fingerprint", None)
        if rng.random() < 0.1:
            r["user_id"] = f"ghost_{int(rng.integers(4))}"
        if rng.random() < 0.1:
            r["merchant_id"] = f"ghostm_{int(rng.integers(4))}"
    return recs


def _assert_batches_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype
        assert np.array_equal(x, y)


class TestColumnarEquivalence:
    def test_columnar_equals_serial_on_randomized_records(self):
        """The acceptance oracle: columnar assemble() == record-at-a-time
        assemble_serial() leaf-for-leaf across a randomized stream, on two
        identically seeded scorers (both mutate history/graph state, so
        each path gets its own)."""
        gen, col = _mk_scorer()
        _, ser = _mk_scorer()
        rng = np.random.default_rng(7)
        for it in range(5):
            recs = _mutate(gen.generate_batch(int(rng.integers(1, 60))), rng)
            _assert_batches_equal(col.assemble(recs, now=1000.0 + it),
                                  ser.assemble_serial(recs, now=1000.0 + it))

    def test_identical_scores_end_to_end(self):
        """Same batch through the full device program on both paths ->
        identical §2.7 responses (the batches are identical, so the fused
        program sees identical inputs)."""
        gen, col = _mk_scorer()
        _, ser = _mk_scorer()
        recs = gen.generate_batch(24)
        pend_col = col.dispatch(recs, now=1000.0)
        batch_ser = ser.assemble_serial(recs, now=1000.0)
        pend_ser = ser.dispatch_assembled(batch_ser, recs)
        res_col = col.finalize(pend_col, now=1000.0)
        res_ser = ser.finalize(pend_ser, now=1000.0)
        for a, b in zip(res_col, res_ser):
            assert a["fraud_probability"] == b["fraud_probability"]
            assert a["decision"] == b["decision"]
            assert a["model_predictions"] == b["model_predictions"]

    def test_profile_rewrite_invalidates_join_cache(self):
        """A put_user between batches bumps the store generation; the
        columnar join cache must re-encode the row (not serve the stale
        one), staying equal to the always-fresh serial path."""
        gen, col = _mk_scorer()
        _, ser = _mk_scorer()
        recs = gen.generate_batch(40)
        _assert_batches_equal(col.assemble(recs, now=1.0),
                              ser.assemble_serial(recs, now=1.0))
        uid = str(recs[0]["user_id"])
        for s in (col, ser):
            prof = dict(s.profiles.get_user(uid) or {})
            prof["risk_score"] = 0.987
            prof["avg_transaction_amount"] = 9999.0
            s.profiles.put_user(uid, prof)
        b_col = col.assemble(recs, now=2.0)
        b_ser = ser.assemble_serial(recs, now=2.0)
        _assert_batches_equal(b_col, b_ser)
        # and the rewrite is actually visible, not silently cached
        i = [j for j, r in enumerate(recs)
             if str(r["user_id"]) == uid][0]
        assert np.asarray(b_col.txn.user_risk_score)[i] == np.float32(0.987)

    def test_velocity_updates_visible_next_batch(self):
        """Velocity windows move on write-back; the next batch's join must
        see them on both paths (velocity rows are per-batch, never
        cross-batch cached)."""
        gen, col = _mk_scorer()
        _, ser = _mk_scorer()
        recs = gen.generate_batch(30)
        for s in (col, ser):
            for r in recs:
                s.velocity.update(str(r["user_id"]), float(r["amount"]),
                                  1000.0)
        _assert_batches_equal(col.assemble(recs, now=1001.0),
                              ser.assemble_serial(recs, now=1001.0))

    def test_vocab_size_guard(self):
        """A tokenizer whose ids can exceed the embedding table is refused
        at construction (JAX would silently clamp the gather)."""
        from realtime_fraud_detection_tpu.models.bert import BertConfig

        with pytest.raises(ValueError, match="vocab_size"):
            FraudScorer(scorer_config=ScorerConfig(tokenizer="wordpiece"),
                        bert_config=BertConfig(vocab_size=64))


class TestTokenCaches:
    def _texts(self, rng, n):
        pool = [f"Merchant: shop_{i} | Category: retail" for i in range(9)]
        out = []
        for _ in range(n):
            if rng.random() < 0.7:
                out.append(pool[int(rng.integers(len(pool)))])
            else:
                out.append("Merchant: " + "".join(
                    chr(97 + int(c)) for c in rng.integers(0, 26, 8)))
        return out

    @pytest.mark.parametrize("mk", [
        lambda n: FraudTokenizer(max_length=32, cache_entries=n),
        lambda n: WordPieceTokenizer(max_length=32, cache_entries=n),
    ], ids=["word", "wordpiece"])
    def test_cached_encoding_bit_exact_under_eviction(self, mk):
        """A tiny LRU under eviction pressure returns exactly what an
        uncached tokenizer computes, text for text."""
        cached = mk(4)                      # heavy eviction
        fresh = mk(100_000)
        rng = np.random.default_rng(3)
        for texts in (self._texts(rng, 64), self._texts(rng, 64)):
            ids_a, mask_a = cached.encode_batch(texts)
            # fresh tokenizer re-created each round: no cache reuse at all
            ids_b, mask_b = mk(100_000).encode_batch(texts)
            assert np.array_equal(ids_a, ids_b)
            assert np.array_equal(mask_a, mask_b)
        st = cached.cache_stats()
        assert st["entries"] <= 4
        assert st["hits"] > 0 and st["misses"] > 0
        assert fresh.cache_stats()["hits"] == 0

    def test_lru_evicts_least_recently_used(self):
        c = TokenLruCache(2)
        c.put("a", [1])
        c.put("b", [2])
        assert c.get("a") == (1,)           # refresh a
        c.put("c", [3])                     # evicts b
        assert c.get("b") is None
        assert c.get("a") == (1,) and c.get("c") == (3,)

    def test_scorer_token_cache_hits_on_repeated_merchants(self):
        gen, s = _mk_scorer()
        s.assemble(gen.generate_batch(64))
        s.assemble(gen.generate_batch(64))
        st = s.tokenizer.cache_stats()
        assert st["hits"] > 0
        assert s.host_stats()["caches"]["tokens"]["hits"] == st["hits"]

    def test_host_stats_render_as_prometheus_series(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        gen, s = _mk_scorer()
        s.score_batch(gen.generate_batch(16), now=10.0)
        m = MetricsCollector()
        m.sync_host_stats(s.host_stats())
        text = m.render_prometheus()
        assert 'host_assembly_cache_hits_total{cache="tokens"}' in text
        assert 'host_assembly_cache_misses_total{cache="entity_rows"}' in text
        assert 'host_assembly_stage_ms{stage="assemble",stat="p50"}' in text
        assert 'host_assembly_stage_ms{stage="device_wait"' in text


class TestHistoryStore:
    def test_differential_vs_sequential_reference(self):
        """Vectorized slot-table store == naive per-row ring reference,
        including duplicate users inside one batch."""
        T_, F = 4, 3
        st = UserHistoryStore(T_, F)
        rings, counts = {}, {}

        def naive_append(uid, row):
            ring = rings.setdefault(uid, np.zeros((T_, F), np.float32))
            c = counts.get(uid, 0)
            ring[c % T_] = row
            counts[uid] = c + 1

        def naive_gather(uid):
            out = np.zeros((T_, F), np.float32)
            ring = rings.get(uid)
            if ring is None:
                return out, 0
            c = counts[uid]
            k = min(c, T_)
            pos = c % T_
            ordered = (np.concatenate([ring[pos:], ring[:pos]])
                       if c >= T_ else ring[:k])
            out[T_ - k:] = ordered[-k:]
            return out, k

        rng = np.random.default_rng(0)
        for _ in range(25):
            b = int(rng.integers(1, 30))
            uids = [f"u{int(rng.integers(0, 5))}" for _ in range(b)]
            feats = rng.normal(size=(b, F)).astype(np.float32)
            out, ln = st.append_and_gather(uids, feats)
            for i, uid in enumerate(uids):
                naive_append(uid, feats[i])
                o, k = naive_gather(uid)
                assert np.array_equal(out[i], o)
                assert ln[i] == k

    def test_slot_table_growth(self):
        st = UserHistoryStore(seq_len=2, feature_dim=1)
        feats = np.ones((1500, 1), np.float32)
        st.append_batch([f"u{i}" for i in range(1500)], feats)
        assert len(st) == 1500
        out, ln = st.gather(["u0", "u1499", "nobody"])
        assert ln.tolist() == [1, 1, 0]
        assert out[0, -1, 0] == 1.0 and out[2].sum() == 0.0


class TestCheckpointMigration:
    def test_legacy_pickled_host_state_restores(self):
        """Pre-host-plane checkpoints pickled the old object layouts
        (dict-of-rings history, stacked-row entity index, generation-less
        profile store); __setstate__ migrates them so old checkpoints keep
        restoring."""
        import pickle

        from realtime_fraud_detection_tpu.scoring.scorer import _EntityIndex
        from realtime_fraud_detection_tpu.state.stores import ProfileStore

        # legacy UserHistoryStore: _rings/_count layout
        hist = UserHistoryStore.__new__(UserHistoryStore)
        ring = np.zeros((3, 2), np.float32)
        ring[0] = [1.0, 2.0]
        ring[1] = [3.0, 4.0]
        hist.__dict__ = {"seq_len": 3, "feature_dim": 2,
                         "_rings": {"u1": ring}, "_count": {"u1": 2}}
        restored = pickle.loads(pickle.dumps(hist))
        out, ln = restored.gather(["u1", "u2"])
        assert ln.tolist() == [2, 0]
        assert np.array_equal(out[0, -1], [3.0, 4.0])
        restored.append_and_gather(["u1"], np.full((1, 2), 9.0, np.float32))

        # legacy _EntityIndex: _rows/_table layout
        idx = _EntityIndex.__new__(_EntityIndex)
        idx.__dict__ = {"node_dim": 16, "_idx": {"m1": 0},
                        "_profiled": {"m1"},
                        "_rows": [np.arange(16, dtype=np.float32)],
                        "_table": None}
        restored = pickle.loads(pickle.dumps(idx))
        assert np.array_equal(restored.table(),
                              np.arange(16, dtype=np.float32)[None])
        assert restored.lookup_batch(["m1", "m2"], {}, True).tolist() == \
            [0, 1]

        # legacy ProfileStore: no generation field
        ps = ProfileStore.__new__(ProfileStore)
        ps.__dict__ = {"users": {"u": {"risk_score": 0.4}}, "merchants": {}}
        restored = pickle.loads(pickle.dumps(ps))
        assert restored.generation == 0
        restored.put_user("u", {"risk_score": 0.5})
        assert restored.generation == 1


class TestStagingBuffers:
    def test_staging_pad_matches_pad_to_bucket(self):
        from realtime_fraud_detection_tpu.core.batching import pad_to_bucket
        from realtime_fraud_detection_tpu.scoring import make_example_batch
        from realtime_fraud_detection_tpu.scoring.scorer import (
            _StagingBuffers,
        )

        stager = _StagingBuffers()
        for n in (20, 7, 32):
            batch = make_example_batch(
                n, rng=np.random.default_rng(n))
            ref, ref_mask, size = pad_to_bucket(batch, n)
            got, got_mask = stager.pad(batch, n, size)
            assert np.array_equal(ref_mask, got_mask)
            _assert_batches_equal(ref, got)

    def test_staging_reuses_buffers(self):
        from realtime_fraud_detection_tpu.scoring import make_example_batch
        from realtime_fraud_detection_tpu.scoring.scorer import (
            _StagingBuffers,
        )

        stager = _StagingBuffers()
        b1 = make_example_batch(20, rng=np.random.default_rng(1))
        p1, _ = stager.pad(b1, 20, 32)
        first = np.asarray(p1.features)
        b2 = make_example_batch(9, rng=np.random.default_rng(2))
        p2, m2 = stager.pad(b2, 9, 32)
        # same backing arrays (write-into, not rebuild), fresh contents
        assert np.asarray(p2.features) is first
        assert np.array_equal(np.asarray(p2.features)[:9],
                              np.asarray(b2.features))
        assert not m2[9:].any()


class _DrillScorer(FraudScorer):
    """Scorer with injected assemble/device latency + an event timeline,
    for the overlap drill: events are (stage, start, end) perf_counter
    intervals appended from whichever thread runs the stage."""

    ASSEMBLE_S = 0.015
    DEVICE_S = 0.03

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.events = []

    def assemble(self, records, now=None):
        t0 = time.perf_counter()
        time.sleep(self.ASSEMBLE_S)
        batch = super().assemble(records, now)
        self.events.append(("assemble", t0, time.perf_counter()))
        return batch

    def finalize(self, pending, now=None, lock=None):
        t0 = time.perf_counter()
        time.sleep(self.DEVICE_S)       # stand-in for the device wait
        res = super().finalize(pending, now=now, lock=lock)
        self.events.append(("device", t0, time.perf_counter()))
        return res


def _run_drill(overlap: bool):
    gen = TransactionGenerator(num_users=60, num_merchants=20, seed=13)
    scorer = _DrillScorer()
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    broker = InMemoryBroker()
    qos = QosSettings(enabled=True, admission_rate=50.0,
                      admission_burst=120.0)
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=32, overlap_assembly=overlap, pipeline_depth=2, qos=qos,
        emit_features=False))
    rng = np.random.default_rng(3)
    recs = gen.generate_batch(192)
    for r in recs:      # spread priorities so sheds hit a defined subset
        r["amount"] = float(rng.choice([5.0, 100.0, 900.0]))
    broker.produce_batch(T.TRANSACTIONS, recs,
                         key_fn=lambda r: str(r["user_id"]))
    # virtual admission clock: every dispatch admits at t=500.0, so the
    # token bucket's refill sequence is identical in both runs
    job.run_until_drained(now=500.0)
    job.close()
    preds = broker.consumer([T.PREDICTIONS], "drill").poll(1000)
    order = [p.value["transaction_id"] for p in preds]
    shed = {p.value["transaction_id"] for p in preds
            if p.value.get("explanation", {}).get("shed")}
    return job, scorer, order, shed


class TestOverlapDrill:
    def test_overlap_preserves_order_and_admission(self):
        """The assembler stage must change WHEN work happens, not WHAT
        happens: identical prediction order and identical shed set vs the
        serial run, while some batch's assembly provably overlaps another
        batch's device wait."""
        job_a, sc_a, order_a, shed_a = _run_drill(overlap=False)
        job_b, sc_b, order_b, shed_b = _run_drill(overlap=True)
        assert order_a == order_b
        assert shed_a == shed_b
        assert job_a.counters["shed"] == job_b.counters["shed"] > 0
        assert job_a.counters["scored"] == job_b.counters["scored"] > 0
        # the drill's point: an assemble interval intersects a device
        # interval in the overlapped run (they ran on different threads)
        assembles = [e for e in sc_b.events if e[0] == "assemble"]
        devices = [e for e in sc_b.events if e[0] == "device"]
        overlapped = any(
            min(a_end, d_end) - max(a_start, d_start) > 0.005
            for _, a_start, a_end in assembles
            for _, d_start, d_end in devices)
        assert overlapped, "no assemble/device overlap observed"
        # and the serial run must NOT overlap (single thread)
        assembles = [e for e in sc_a.events if e[0] == "assemble"]
        devices = [e for e in sc_a.events if e[0] == "device"]
        assert not any(
            min(a_end, d_end) - max(a_start, d_start) > 0.0
            for _, a_start, a_end in assembles
            for _, d_start, d_end in devices)

    def test_stage_error_takes_degradation_path(self):
        """An assembly error inside the background stage surfaces at
        completion as the whole-batch REVIEW fallback — never a hang or a
        lost batch."""
        gen = TransactionGenerator(num_users=20, num_merchants=10, seed=2)
        scorer = FraudScorer()
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        broker = InMemoryBroker()
        job = StreamJob(broker, scorer,
                        JobConfig(max_batch=16, overlap_assembly=True,
                                  emit_features=False))
        def boom(*a, **kw):
            raise RuntimeError("assembly exploded")
        scorer.assemble = boom
        recs = gen.generate_batch(16)
        broker.produce_batch(T.TRANSACTIONS, recs,
                             key_fn=lambda r: str(r["user_id"]))
        job.run_until_drained(now=10.0)
        job.close()
        preds = broker.consumer([T.PREDICTIONS], "err").poll(100)
        assert len(preds) == 16
        assert all(p.value["decision"] == "REVIEW" for p in preds)
        assert job.counters["errors"] == 16

    def test_assembler_stage_direct(self):
        """AssemblerStage submit/finalize joins FIFO and matches the
        direct dispatch path's results."""
        gen, s = _mk_scorer(seed=21, tokenizer="word")
        stage = AssemblerStage(s, depth=2)
        try:
            batches = [gen.generate_batch(8) for _ in range(3)]
            handles = [stage.submit(b, now=100.0 + i)
                       for i, b in enumerate(batches)]
            results = [stage.finalize(h, now=100.0 + i)
                       for i, h in enumerate(handles)]
            assert [len(r) for r in results] == [8, 8, 8]
            assert all(r["transaction_id"] == str(rec["transaction_id"])
                       for batch, res in zip(batches, results)
                       for rec, r in zip(batch, res))
        finally:
            stage.close()


class TestPipelinedRequestBatcher:
    def test_two_phase_keeps_request_order_and_overlaps(self):
        import asyncio

        from realtime_fraud_detection_tpu.serving.batcher import (
            RequestMicrobatcher,
        )

        timeline = []
        tlock = threading.Lock()

        def dispatch(txns):
            with tlock:
                timeline.append(("dispatch", time.perf_counter()))
            time.sleep(0.01)
            return list(txns)

        def finalize(ctx):
            time.sleep(0.02)
            with tlock:
                timeline.append(("finalize", time.perf_counter()))
            return [{"i": t["i"]} for t in ctx]

        async def main():
            b = RequestMicrobatcher(lambda t: t, max_batch=4,
                                    deadline_ms=1.0, dispatch_fn=dispatch,
                                    finalize_fn=finalize)
            await b.start()
            futs = [b.submit(dict(i=i)) for i in range(24)]
            res = await asyncio.gather(*futs)
            await b.stop()
            return b, res

        b, res = asyncio.run(main())
        assert [r["i"] for r in res] == list(range(24))
        assert b.requests == 24 and b.batches >= 6
        # pipelining: at least one dispatch lands before the PREVIOUS
        # batch's finalize (the serial path would strictly alternate)
        dispatches = [t for k, t in timeline if k == "dispatch"]
        finalizes = [t for k, t in timeline if k == "finalize"]
        assert any(d < f for d, f in zip(dispatches[1:], finalizes[:-1]))

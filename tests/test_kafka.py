"""Kafka transport tests: wire codec units + the transport contract suite
over real sockets against the in-process protocol fake."""

import json
import struct
import threading
import time

import pytest

from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.kafka import (
    KafkaBroker,
    Reader,
    Writer,
    decode_message_set,
    encode_message_set,
)
from realtime_fraud_detection_tpu.stream.kafka_fake import FakeKafkaServer


# ---------------------------------------------------------------- wire codec


def test_message_set_round_trip():
    msgs = [(b"k1", b'{"a":1}', 123456), (None, b"v", 0), (b"k3", None, 7)]
    decoded = decode_message_set(encode_message_set(msgs))
    assert [(k, v, ts) for _o, k, v, ts in decoded] == msgs
    assert [o for o, *_ in decoded] == [0, 1, 2]


def test_message_set_truncated_tail_dropped():
    msgs = [(b"k", b"v1", 1), (b"k", b"v2", 2)]
    buf = encode_message_set(msgs)
    # chop mid-way through the second message (Kafka fetch semantics)
    decoded = decode_message_set(buf[: len(buf) - 3])
    assert len(decoded) == 1 and decoded[0][2] == b"v1"


def test_message_set_bad_crc_raises():
    buf = bytearray(encode_message_set([(b"k", b"value", 1)]))
    buf[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_message_set(bytes(buf))


def test_request_header_spec_shape():
    """The client must emit the spec header: api_key i16, api_version i16,
    correlation_id i32, client_id string — checked byte-for-byte, so a
    symmetric client/fake codec bug can't hide."""
    w = Writer().i16(3).i16(1).i32(42).string("cid")
    raw = w.done()
    assert raw == struct.pack(">hhi", 3, 1, 42) + struct.pack(">h", 3) + b"cid"
    r = Reader(raw)
    assert (r.i16(), r.i16(), r.i32(), r.string()) == (3, 1, 42, "cid")


# ------------------------------------------------------------ contract suite


@pytest.fixture()
def kafka_broker():
    server = FakeKafkaServer(port=0).start()
    broker = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        yield broker
    finally:
        broker.close()
        server.stop()


def test_kafka_keyed_ordering(kafka_broker):
    b = kafka_broker
    for i in range(20):
        b.produce(T.TRANSACTIONS, {"n": i}, key="user_7")
    c = b.consumer([T.TRANSACTIONS], "g1")
    recs = c.poll(100)
    assert [r.value["n"] for r in recs] == list(range(20))
    assert len({r.partition for r in recs}) == 1


def test_kafka_commit_replay(kafka_broker):
    b = kafka_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(4)) == 4
    c2 = b.consumer([T.TRANSACTIONS], "g")
    assert len(c2.poll(100)) == 10
    c2.commit()
    assert b.consumer([T.TRANSACTIONS], "g").poll(100) == []
    assert b.lag("g", T.TRANSACTIONS) == 0


def test_kafka_snapshot_commit(kafka_broker):
    b = kafka_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(6)) == 6
    snap = c.snapshot_positions()
    assert len(c.poll(10)) == 4
    c.commit(snap)
    assert b.lag("g", T.TRANSACTIONS) == 4


def test_kafka_produce_batch_spreads(kafka_broker):
    b = kafka_broker
    n = b.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(24)],
                        key_fn=lambda v: str(v["n"] % 5))
    assert n == 24
    assert sum(b.end_offsets(T.TRANSACTIONS)) == 24
    # per-key ordering survives the batch path
    c = b.consumer([T.TRANSACTIONS], "g")
    recs = c.poll(100)
    per_key = {}
    for r in recs:
        per_key.setdefault(r.key, []).append(r.value["n"])
    for key, ns in per_key.items():
        assert ns == sorted(ns), f"key {key} out of order: {ns}"


def test_kafka_unicode_and_null_values(kafka_broker):
    b = kafka_broker
    b.produce(T.TRANSACTIONS, {"désc": "caffè ☕", "amount": 12.5}, key="ü")
    recs = b.consumer([T.TRANSACTIONS], "g").poll(10)
    assert recs[0].value == {"désc": "caffè ☕", "amount": 12.5}
    assert recs[0].key == "ü"


def test_stream_job_over_kafka():
    """The scoring job runs unchanged over the Kafka wire protocol."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.stream import JobConfig, StreamJob

    server = FakeKafkaServer(port=0).start()
    broker = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        gen = TransactionGenerator(num_users=30, num_merchants=12, seed=29)
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(broker, scorer, JobConfig(max_batch=16,
                                                  max_delay_ms=1.0))
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(40),
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 40
        preds = broker.consumer([T.PREDICTIONS], "check").poll(1000)
        assert len(preds) == 40
        assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0
    finally:
        broker.close()
        server.stop()


# --------------------------------------- RecordBatch v2 / idempotent producer


def test_record_batch_v2_layout_and_round_trip():
    """Spec-shape check written independently of the encoder: fixed header
    offsets (kafka.apache.org/protocol RecordBatch), CRC32C coverage, and a
    decode round-trip. The CRC32C known-answer ('123456789' -> 0xE3069283)
    pins the polynomial to Castagnoli, not zlib's CRC32."""
    from realtime_fraud_detection_tpu.stream.kafka import (
        crc32c,
        decode_record_batch,
        encode_record_batch,
    )

    assert crc32c(b"123456789") == 0xE3069283
    msgs = [(b"k1", b'{"a":1}', 1000), (None, b"v2", 1003)]
    buf = encode_record_batch(msgs, producer_id=9, producer_epoch=2,
                              base_sequence=17)
    base_offset, batch_len = struct.unpack_from(">qi", buf)
    assert base_offset == 0
    assert batch_len == len(buf) - 12            # bytes after the length field
    assert struct.unpack_from(">i", buf, 12)[0] == -1   # partitionLeaderEpoch
    assert buf[16] == 2                                 # magic
    crc = struct.unpack_from(">I", buf, 17)[0]
    assert crc == crc32c(buf[21:])               # crc covers attributes..end
    (attrs, last_delta, first_ts, max_ts, pid, epoch, seq,
     count) = struct.unpack_from(">hiqqqhii", buf, 21)
    assert (attrs, last_delta, first_ts, max_ts) == (0, 1, 1000, 1003)
    assert (pid, epoch, seq, count) == (9, 2, 17, 2)
    decoded, dpid, depoch, dseq = decode_record_batch(buf)
    assert (dpid, depoch, dseq) == (9, 2, 17)
    assert [(k, v, ts) for _o, k, v, ts in decoded] == msgs


def test_record_batch_gzip_round_trip():
    """Codec bit 1 (gzip) — the v2 analog of the reference's
    compression.type producer setting (producer.properties:11)."""
    import gzip

    from realtime_fraud_detection_tpu.stream.kafka import (
        crc32c,
        decode_record_batch,
        encode_record_batch,
    )

    msgs = [(b"k", json.dumps({"i": i, "pad": "x" * 200}).encode(), 1000 + i)
            for i in range(50)]
    plain = encode_record_batch(msgs, producer_id=3, producer_epoch=1,
                                base_sequence=5)
    packed = encode_record_batch(msgs, producer_id=3, producer_epoch=1,
                                 base_sequence=5, compression="gzip")
    # attributes codec bits say gzip; the wire form is genuinely smaller
    attrs = struct.unpack_from(">h", packed, 21)[0]
    assert attrs & 0x07 == 1
    assert len(packed) < len(plain) // 2
    # CRC covers the COMPRESSED form
    assert struct.unpack_from(">I", packed, 17)[0] == crc32c(packed[21:])
    decoded, pid, epoch, seq = decode_record_batch(packed)
    assert (pid, epoch, seq) == (3, 1, 5)
    assert [(k, v, ts) for _o, k, v, ts in decoded] == msgs

    with pytest.raises(ValueError, match="unsupported compression"):
        encode_record_batch(msgs, compression="lz4")


def test_kafka_gzip_producer_end_to_end():
    """Compressed idempotent produce through the wire client against the
    protocol fake; the consumer transparently decompresses."""
    server = FakeKafkaServer(port=0).start()
    broker = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}",
                         idempotent=True, compression="gzip")
    try:
        for i in range(30):
            broker.produce(T.TRANSACTIONS, {"n": i, "pad": "y" * 100},
                           key="user_1")
        recs = broker.consumer([T.TRANSACTIONS], "gz").poll(100)
        assert [r.value["n"] for r in recs] == list(range(30))
    finally:
        broker.close()
        server.stop()

    with pytest.raises(ValueError, match="compression requires"):
        KafkaBroker(bootstrap="127.0.0.1:1", compression="gzip")


def test_fetch_decode_gzip_wrapper_and_raw_v2():
    """What a REAL broker can hand a Fetch v2 consumer (the protocol fake
    re-serves uncompressed v1, so these forms are constructed by hand):
    a gzip wrapper message whose value is the inner message set, and a raw
    RecordBatch v2 the broker chose not to down-convert."""
    import gzip
    import zlib as _zlib

    from realtime_fraud_detection_tpu.stream.kafka import (
        Writer,
        decode_message_set,
        encode_message_set,
        encode_record_batch,
    )

    msgs = [(b"k0", b"v0", 10), (b"k1", b"v1", 11), (b"k2", b"v2", 12)]

    # --- gzip v1 wrapper: value = gzip(inner message set), wrapper offset
    # is the LAST inner message's absolute offset (v1 down-convert rule)
    inner = encode_message_set(msgs)
    body = (Writer().i8(1).i8(1)                  # magic=1, codec=gzip
            .i64(99).bytes_(None).bytes_(gzip.compress(inner)).done())
    crc = _zlib.crc32(body) & 0xFFFFFFFF
    wrapper_msg = Writer().u32(crc).raw(body).done()
    wire = Writer().i64(42).i32(len(wrapper_msg)).raw(wrapper_msg).done()
    decoded = decode_message_set(wire)
    assert [(k, v, ts) for _o, k, v, ts in decoded] == msgs
    assert [o for o, *_ in decoded] == [40, 41, 42]   # rebased to wrapper

    # --- raw RecordBatch v2 passthrough (no down-conversion)
    batch = encode_record_batch(msgs, compression="gzip")
    decoded2 = decode_message_set(batch)
    assert [(k, v, ts) for _o, k, v, ts in decoded2] == msgs


def test_record_batch_bad_crc_raises():
    from realtime_fraud_detection_tpu.stream.kafka import (
        decode_record_batch,
        encode_record_batch,
    )

    buf = bytearray(encode_record_batch([(b"k", b"v", 1)]))
    buf[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC32C"):
        decode_record_batch(bytes(buf))


def test_idempotent_produce_dedupes_retried_batch():
    """enable.idempotence=true semantics: resending the SAME batch (same
    producer id + base sequence — what the client's retry path does after
    a lost ack) must append once; the broker acks the duplicate with the
    original base offset."""
    server = FakeKafkaServer(port=0).start()
    b = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}", idempotent=True)
    try:
        r1 = b.produce(T.TRANSACTIONS, {"n": 1}, key="k")
        # craft the retry: re-send the identical wire bytes (same sequence)
        from realtime_fraud_detection_tpu.stream.kafka import (
            encode_record_batch,
        )

        replay = encode_record_batch(
            [(b"k", b'{"n":1}', 1)], producer_id=b._pid,
            producer_epoch=b._pepoch, base_sequence=0)
        off = b._produce_request(T.TRANSACTIONS, r1.partition, replay,
                                 api_version=3)
        assert off == r1.offset                  # acked with original offset
        b.produce(T.TRANSACTIONS, {"n": 2}, key="k")   # next seq still works
        recs = b.read(T.TRANSACTIONS, r1.partition, 0, 100)
        assert [r.value["n"] for r in recs] == [1, 2]  # no duplicate append
    finally:
        b.close()
        server.stop()


def test_idempotent_sequence_gap_rejected():
    from realtime_fraud_detection_tpu.stream.kafka import (
        KafkaProtocolError,
        encode_record_batch,
    )

    server = FakeKafkaServer(port=0).start()
    b = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}", idempotent=True)
    try:
        r1 = b.produce(T.TRANSACTIONS, {"n": 1}, key="k")
        gap = encode_record_batch(
            [(b"k", b'{"n":9}', 1)], producer_id=b._pid,
            producer_epoch=b._pepoch, base_sequence=5)   # expected 1
        with pytest.raises(KafkaProtocolError, match="OUT_OF_ORDER"):
            b._produce_request(T.TRANSACTIONS, r1.partition, gap,
                               api_version=3)
    finally:
        b.close()
        server.stop()


# ------------------------------------------------------------ consumer groups


def _group_broker(server):
    return KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")


def test_group_two_members_split_partitions():
    """Two members of one group get disjoint range assignments covering
    every partition; after one leaves, the survivor owns them all."""
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b1, b2 = _group_broker(server), _group_broker(server)
    try:
        c1 = KafkaGroupConsumer(b1, [T.TRANSACTIONS], "g-split",
                                session_timeout_ms=2000,
                                heartbeat_interval_s=0.1)
        n_parts = b1.partitions(T.TRANSACTIONS)
        assert sorted(c1.assigned_partitions()[T.TRANSACTIONS]) == \
            list(range(n_parts))

        made = {}

        def _join_second():
            made["c2"] = KafkaGroupConsumer(
                b2, [T.TRANSACTIONS], "g-split",
                session_timeout_ms=2000, heartbeat_interval_s=0.1)

        t = threading.Thread(target=_join_second)
        t.start()
        # c1 discovers the rebalance via heartbeat inside poll and rejoins
        deadline = time.monotonic() + 8.0
        while "c2" not in made and time.monotonic() < deadline:
            c1.poll(10)
            time.sleep(0.05)
        t.join(timeout=8.0)
        c2 = made["c2"]
        p1 = set(c1.assigned_partitions().get(T.TRANSACTIONS, []))
        p2 = set(c2.assigned_partitions().get(T.TRANSACTIONS, []))
        assert p1 and p2 and not (p1 & p2)
        assert p1 | p2 == set(range(n_parts))
        # clean leave -> survivor reclaims everything
        c2.close()
        deadline = time.monotonic() + 8.0
        while (set(c1.assigned_partitions().get(T.TRANSACTIONS, []))
               != set(range(n_parts))
               and time.monotonic() < deadline):
            c1.poll(10)
            time.sleep(0.05)
        assert set(c1.assigned_partitions()[T.TRANSACTIONS]) == \
            set(range(n_parts))
        c1.close()
    finally:
        b1.close()
        b2.close()
        server.stop()


def test_group_kill_consumer_no_record_loss():
    """The VERDICT item-6 'done' criterion: kill a consumer mid-stream
    (process death: no LeaveGroup, heartbeats just stop). The survivor must
    adopt its partitions from the committed offsets — every record is
    consumed, nothing lost, and nothing the dead member committed is
    re-consumed."""
    import time as _time

    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b1, b2 = _group_broker(server), _group_broker(server)
    prod = _group_broker(server)
    try:
        prod.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(200)],
                           key_fn=lambda v: str(v["n"]))
        c1 = KafkaGroupConsumer(b1, [T.TRANSACTIONS], "g-kill",
                                session_timeout_ms=1000,
                                heartbeat_interval_s=0.1)
        seen_c1 = []
        # two-member group
        made = {}
        t = threading.Thread(target=lambda: made.update(c2=KafkaGroupConsumer(
            b2, [T.TRANSACTIONS], "g-kill", session_timeout_ms=1000,
            heartbeat_interval_s=0.1)))
        t.start()
        deadline = _time.monotonic() + 8.0
        while "c2" not in made and _time.monotonic() < deadline:
            c1.poll(0)          # heartbeat/rejoin only — read nothing, so
            _time.sleep(0.05)   # everything c1 commits is recorded below
        t.join(timeout=8.0)
        c2 = made["c2"]

        # c1 consumes + commits a first slice of its partitions, then DIES
        recs = c1.poll(40)
        seen_c1 = [r.value["n"] for r in recs]
        c1.commit()                               # committed: must not replay
        victim = c1.membership.member_id
        server.kill_member("g-kill", victim)      # session expiry, no leave

        # survivor polls until it has adopted everything and drained
        seen_c2 = []
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            for r in c2.poll(100):
                seen_c2.append(r.value["n"])
            c2.commit()
            n_parts = b2.partitions(T.TRANSACTIONS)
            owned = set(c2.assigned_partitions().get(T.TRANSACTIONS, []))
            if owned == set(range(n_parts)) and c2.lag() == 0:
                break
            _time.sleep(0.05)

        assert set(seen_c1) | set(seen_c2) == set(range(200))  # nothing lost
        # nothing c1 committed was re-delivered to the survivor
        assert not (set(seen_c1) & set(seen_c2))
        assert c2.membership.rebalances >= 2      # join + post-kill rejoin
        c2.close()
    finally:
        b1.close()
        b2.close()
        prod.close()
        server.stop()


def test_group_rebalance_mid_stream_survivor_no_double_processing():
    """Chaos satellite: `kill_member` MID-STREAM — records still arriving
    while the coordinator expires one member's session. The group
    rebalances onto the survivor (all partitions reassigned) and records
    produced across the rebalance all arrive. The surviving member never
    re-processes anything it COMMITTED (its committed positions survive
    the generation change); a round whose commit is fenced by the
    rebalance replays at-least-once — bounded, never a loop — and once
    the group settles the survivor replays nothing at all."""
    import time as _time

    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b1, b2 = _group_broker(server), _group_broker(server)
    prod = _group_broker(server)
    try:
        prod.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(120)],
                           key_fn=lambda v: str(v["n"]))
        c1 = KafkaGroupConsumer(b1, [T.TRANSACTIONS], "g-mid",
                                session_timeout_ms=1000,
                                heartbeat_interval_s=0.1)
        made = {}
        t = threading.Thread(target=lambda: made.update(c2=KafkaGroupConsumer(
            b2, [T.TRANSACTIONS], "g-mid", session_timeout_ms=1000,
            heartbeat_interval_s=0.1)))
        t.start()
        deadline = _time.monotonic() + 8.0
        while "c2" not in made and _time.monotonic() < deadline:
            c1.poll(0)
            _time.sleep(0.05)
        t.join(timeout=8.0)
        c2 = made["c2"]

        # both members consume mid-stream, committing every round; this
        # pre-kill commit lands in a stable group, so it MUST stick
        seen_c1, seen_c2 = [], []
        pre_slots = set()               # (topic, partition, offset) at c2
        for consumer, seen in ((c1, seen_c1), (c2, seen_c2)):
            for r in consumer.poll(30):
                seen.append(r.value["n"])
                if consumer is c2:
                    pre_slots.add((r.topic, r.partition, r.offset))
            consumer.commit()

        # the kill lands between commits, with more records still to come
        server.kill_member("g-mid", c1.membership.member_id)
        prod.produce_batch(T.TRANSACTIONS,
                           [{"n": i} for i in range(120, 200)],
                           key_fn=lambda v: str(v["n"]))

        post_slots: list = []
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            for r in c2.poll(100):
                seen_c2.append(r.value["n"])
                post_slots.append((r.topic, r.partition, r.offset))
            c2.commit()
            n_parts = b2.partitions(T.TRANSACTIONS)
            owned = set(c2.assigned_partitions().get(T.TRANSACTIONS, []))
            if owned == set(range(n_parts)) and c2.lag() == 0:
                break
            _time.sleep(0.05)

        # partitions reassigned: the survivor owns every one
        n_parts = b2.partitions(T.TRANSACTIONS)
        assert set(c2.assigned_partitions()[T.TRANSACTIONS]) == \
            set(range(n_parts))
        assert c2.membership.rebalances >= 2
        # nothing lost across the rebalance (c1's uncommitted reads are
        # re-delivered to the survivor — at-least-once across MEMBERS)
        assert set(seen_c1) | set(seen_c2) == set(range(200))
        # the survivor NEVER re-processed a record it committed...
        assert not pre_slots & set(post_slots)
        # ...and a rebalance-fenced round replays at most once (bounded
        # at-least-once, not a redelivery loop)
        counts: dict = {}
        for slot in post_slots:
            counts[slot] = counts.get(slot, 0) + 1
        assert max(counts.values()) <= 2
        # settled group: everything committed, nothing replays
        assert c2.poll(100) == []
        c2.close()
    finally:
        b1.close()
        b2.close()
        prod.close()
        server.stop()


def test_group_zombie_commit_is_fenced():
    """A member evicted by the coordinator must NOT be able to advance
    offsets (ILLEGAL_GENERATION/UNKNOWN_MEMBER fencing) — the new owner's
    position wins, so a zombie can't cause silent skips."""
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b1 = _group_broker(server)
    try:
        prod = _group_broker(server)
        prod.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(20)],
                           key_fn=lambda v: str(v["n"]))
        c1 = KafkaGroupConsumer(b1, [T.TRANSACTIONS], "g-fence",
                                session_timeout_ms=1000,
                                heartbeat_interval_s=0.1)
        c1.poll(20)
        positions = c1.snapshot_positions()
        # evict c1 (simulated zombie: it still thinks it's a member)
        server.kill_member("g-fence", c1.membership.member_id)
        c1.commit(positions)                      # fenced: swallowed + rejoin
        committed = {
            (t, p): b1.committed("g-fence", t, p) for (t, p) in positions
        }
        assert all(off == 0 for off in committed.values())
        prod.close()
    finally:
        b1.close()
        server.stop()


def test_group_background_heartbeat_survives_processing_gap():
    """A processing gap longer than the session timeout (e.g. a first-batch
    XLA compile) must NOT get the member evicted: the background heartbeat
    thread keeps the session alive between poll() calls, so the post-gap
    commit is not fenced."""
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    b = _group_broker(server)
    prod = _group_broker(server)
    try:
        prod.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(10)],
                           key_fn=lambda v: str(v["n"]))
        c = KafkaGroupConsumer(b, [T.TRANSACTIONS], "g-gap",
                               session_timeout_ms=800,
                               heartbeat_interval_s=0.2)
        recs = c.poll(10)
        assert recs
        gen_before = c.membership.generation
        time.sleep(2.0)                   # >2x the session timeout, no poll
        c.commit()                        # must not be fenced
        assert c.membership.generation == gen_before   # no eviction/rejoin
        committed = sum(
            b.committed("g-gap", T.TRANSACTIONS, p)
            for p in range(b.partitions(T.TRANSACTIONS)))
        assert committed == len(recs)
        c.close()
    finally:
        b.close()
        prod.close()
        server.stop()


# ------------------------------------------------- golden wire-byte fixtures
# No Kafka broker or JVM exists in this image (VERDICT r3 item 8 asked for
# real-broker bytes; that is impossible here), so these fixtures are the next
# strongest thing: complete frames hand-assembled with raw struct.pack from
# the PUBLIC spec (kafka.apache.org/protocol), sharing no code with the
# client's Writer/encoder — a symmetric client/fake codec bug cannot satisfy
# both the encoder test and these byte-level expectations.


def _raw_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _raw_bytes(b: bytes) -> bytes:
    return struct.pack(">i", len(b)) + b


def test_golden_produce_v2_request_bytes():
    """KafkaBroker's Produce v2 body must equal the spec frame assembled
    by hand: acks i16, timeout i32, [topic -> [partition, record_set]]."""
    import zlib

    from realtime_fraud_detection_tpu.stream.kafka import encode_message_set

    record_set = encode_message_set([(b"k", b"v", 1234)])
    # hand-build the same MessageSet: offset i64=0, size i32, crc u32,
    # magic i8=1, attrs i8=0, ts i64, key bytes, value bytes
    body = struct.pack(">bbq", 1, 0, 1234) + _raw_bytes(b"k") + _raw_bytes(b"v")
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    expected_set = struct.pack(">qi", 0, len(msg)) + msg
    assert record_set == expected_set

    got = (
        Writer().i16(-1).i32(30000)
        .array([None], lambda w, _:
               w.string("topic-a").array([None], lambda w2, _2:
                                         w2.i32(3).bytes_(record_set)))
        .done()
    )
    expected = (
        struct.pack(">hi", -1, 30000)
        + struct.pack(">i", 1) + _raw_str("topic-a")
        + struct.pack(">i", 1) + struct.pack(">i", 3)
        + _raw_bytes(expected_set)
    )
    assert got == expected


def test_golden_join_group_v1_request_bytes():
    """JoinGroup v1 body layout: group, session i32, rebalance i32, member,
    protocol_type, [protocol name + metadata bytes] — and the subscription
    metadata itself (version i16, topics array, user_data bytes)."""
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        encode_subscription,
    )

    meta = encode_subscription(["t-b", "t-a"])
    expected_meta = (
        struct.pack(">h", 0)                      # version
        + struct.pack(">i", 2) + _raw_str("t-a") + _raw_str("t-b")  # sorted
        + _raw_bytes(b"")                         # user_data
    )
    assert meta == expected_meta

    got = (
        Writer().string("grp").i32(10000).i32(10000).string("")
        .string("consumer")
        .array([("range", meta)], lambda w, p: w.string(p[0]).bytes_(p[1]))
        .done()
    )
    expected = (
        _raw_str("grp") + struct.pack(">ii", 10000, 10000) + _raw_str("")
        + _raw_str("consumer")
        + struct.pack(">i", 1) + _raw_str("range") + _raw_bytes(expected_meta)
    )
    assert got == expected


def test_golden_record_batch_v2_full_bytes():
    """A one-record idempotent batch, byte-for-byte: every header field at
    its spec offset, varint record body assembled by hand (zigzag LEB128)."""
    from realtime_fraud_detection_tpu.stream.kafka import (
        crc32c,
        encode_record_batch,
    )

    got = encode_record_batch([(b"K", b"VAL", 5000)], producer_id=77,
                              producer_epoch=3, base_sequence=9)
    # record: attrs i8=0, ts_delta varint(0)=0x00, offset_delta varint(0),
    # key len varint(1)=0x02 + b"K", val len varint(3)=0x06 + b"VAL",
    # headers varint(0)
    record_body = bytes([0, 0x00, 0x00, 0x02]) + b"K" + bytes([0x06]) + b"VAL" + bytes([0x00])
    record = bytes([len(record_body) << 1]) + record_body   # varint length
    after_crc = (
        struct.pack(">hiqqqhii", 0, 0, 5000, 5000, 77, 3, 9, 1) + record
    )
    expected = (
        struct.pack(">qi", 0, 4 + 1 + 4 + len(after_crc))   # base, length
        + struct.pack(">ibI", -1, 2, crc32c(after_crc))
        + after_crc
    )
    assert got == expected


def test_group_membership_churn_no_deadlock():
    """Members joining and leaving repeatedly while others poll must never
    deadlock the membership lock / background heartbeat thread, and the
    group must converge to full coverage after the churn stops."""
    from realtime_fraud_detection_tpu.stream.kafka_group import (
        KafkaGroupConsumer,
    )

    server = FakeKafkaServer(port=0).start()
    stable_b = _group_broker(server)
    try:
        stable = KafkaGroupConsumer(stable_b, [T.TRANSACTIONS], "g-churn",
                                    session_timeout_ms=2000,
                                    heartbeat_interval_s=0.1)
        stop = time.monotonic() + 6.0
        errors: list = []

        def churner(n: int):
            try:
                while time.monotonic() < stop:
                    b = _group_broker(server)
                    c = KafkaGroupConsumer(b, [T.TRANSACTIONS], "g-churn",
                                           session_timeout_ms=2000,
                                           heartbeat_interval_s=0.1)
                    c.poll(5)
                    time.sleep(0.1)
                    c.close()
                    b.close()
            except Exception as e:  # noqa: BLE001
                errors.append(f"churner {n}: {type(e).__name__}: {e}")

        churners = [threading.Thread(target=churner, args=(i,))
                    for i in range(3)]
        for t in churners:
            t.start()
        # the stable member keeps polling through the churn
        while time.monotonic() < stop:
            stable.poll(5)
            time.sleep(0.05)
        for t in churners:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in churners), "churner hung"
        assert not errors, errors

        # after the churn: stable member reconverges to ALL partitions
        n_parts = stable_b.partitions(T.TRANSACTIONS)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            stable.poll(5)
            owned = set(stable.assigned_partitions().get(T.TRANSACTIONS, []))
            if owned == set(range(n_parts)):
                break
            time.sleep(0.1)
        assert set(stable.assigned_partitions()[T.TRANSACTIONS]) == \
            set(range(n_parts))
        assert stable.membership.rebalances >= 2
        stable.close()
    finally:
        stable_b.close()
        server.stop()


def test_netbroker_three_node_rf3_minisr2_failover_drill():
    """The compose-topology failover drill (deploy/docker-compose.yml: one
    primary + TWO sync replicas, minISR=2 — the reference's 3-broker
    RF=3/minISR=2 cluster, create-topics.sh:9-12): kill the primary
    mid-traffic, promote replica 1, re-attach replica 2 to the survivor.
    Every acked record must survive, committed offsets must carry over
    (nothing already committed re-delivers), and the ISR must re-form."""
    from realtime_fraud_detection_tpu.stream.netbroker import (
        BrokerServer,
        HaBrokerClient,
        NetBrokerClient,
    )

    primary = BrokerServer(port=0, role="primary", min_isr=2).start()
    replica1 = BrokerServer(port=0, role="replica").start()
    replica2 = BrokerServer(port=0, role="replica").start()
    client = None
    try:
        primary.add_replica("127.0.0.1", replica1.port)
        primary.add_replica("127.0.0.1", replica2.port)
        assert primary.isr_size() == 3            # RF=3: self + 2 replicas

        addrs = [("127.0.0.1", primary.port), ("127.0.0.1", replica1.port),
                 ("127.0.0.1", replica2.port)]
        client = HaBrokerClient(addrs)
        acked = []
        for i in range(50):
            client.produce(T.TRANSACTIONS, {"n": i}, key="k")
            acked.append(i)                       # min_isr=2 ack: durable

        # a consumer group makes progress and commits on the primary;
        # commits forward to BOTH replicas synchronously
        consumer = client.consumer([T.TRANSACTIONS], "drill")
        first = consumer.poll(20)
        assert len(first) == 20
        consumer.commit()

        # ---- primary dies mid-traffic ----
        primary.stop()
        NetBrokerClient(port=replica1.port).promote()
        # the survivor re-forms the ISR with the remaining replica (its
        # link belonged to the dead primary)
        replica1.add_replica("127.0.0.1", replica2.port)
        assert replica1.isr_size() == 2

        # the SAME HA client keeps working: rotates off the dead address,
        # produces against the promoted node (an ack-lost retry may
        # duplicate — at-least-once, consumers dedupe by id)
        for i in range(50, 60):
            client.produce(T.TRANSACTIONS, {"n": i}, key="k")
            acked.append(i)

        # a post-failover consumer in the SAME group resumes from the
        # committed offset on the survivor: nothing committed re-delivers,
        # nothing acked is lost
        survivor_consumer = client.consumer([T.TRANSACTIONS], "drill")
        rest = [r.value["n"] for r in survivor_consumer.poll(1000)]
        seen_before = {r.value["n"] for r in first}
        assert not (set(rest) & seen_before)      # committed => not replayed
        assert set(rest) | seen_before >= set(acked)  # every ack survived
        survivor_consumer.commit()
        assert client.lag("drill", T.TRANSACTIONS) == 0

        # replica 2 kept replicating through the promotion: its log holds
        # every acked record too (read-only reads are allowed on replicas)
        r2 = NetBrokerClient(port=replica2.port)
        r2_total = sum(r2.end_offsets(T.TRANSACTIONS))
        assert r2_total >= len(acked)
        r2.close()
    finally:
        if client is not None:
            client.close()
        for server in (primary, replica1, replica2):
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — primary already stopped
                pass


def test_fetch_large_backlog_across_polls():
    """A backlog far larger than one fetch response (4 MiB cap, truncated
    tail per Kafka semantics) must stream completely and in order across
    successive polls."""
    server = FakeKafkaServer(port=0).start()
    b = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        big = "x" * 64_000                       # ~64 KB per record value
        n = 200                                  # ~12.8 MB total, 4 MiB cap
        b.produce_batch(T.TRANSACTIONS, [{"n": i, "pad": big}
                                         for i in range(n)],
                        key_fn=lambda v: "one-key")   # single partition
        c = b.consumer([T.TRANSACTIONS], "g-big")
        seen = []
        for _ in range(50):
            recs = c.poll(500)
            if not recs:
                break
            seen.extend(r.value["n"] for r in recs)
        assert seen == list(range(n))            # complete and ordered
    finally:
        b.close()
        server.stop()

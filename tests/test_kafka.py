"""Kafka transport tests: wire codec units + the transport contract suite
over real sockets against the in-process protocol fake."""

import struct

import pytest

from realtime_fraud_detection_tpu.stream import topics as T
from realtime_fraud_detection_tpu.stream.kafka import (
    KafkaBroker,
    Reader,
    Writer,
    decode_message_set,
    encode_message_set,
)
from realtime_fraud_detection_tpu.stream.kafka_fake import FakeKafkaServer


# ---------------------------------------------------------------- wire codec


def test_message_set_round_trip():
    msgs = [(b"k1", b'{"a":1}', 123456), (None, b"v", 0), (b"k3", None, 7)]
    decoded = decode_message_set(encode_message_set(msgs))
    assert [(k, v, ts) for _o, k, v, ts in decoded] == msgs
    assert [o for o, *_ in decoded] == [0, 1, 2]


def test_message_set_truncated_tail_dropped():
    msgs = [(b"k", b"v1", 1), (b"k", b"v2", 2)]
    buf = encode_message_set(msgs)
    # chop mid-way through the second message (Kafka fetch semantics)
    decoded = decode_message_set(buf[: len(buf) - 3])
    assert len(decoded) == 1 and decoded[0][2] == b"v1"


def test_message_set_bad_crc_raises():
    buf = bytearray(encode_message_set([(b"k", b"value", 1)]))
    buf[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_message_set(bytes(buf))


def test_request_header_spec_shape():
    """The client must emit the spec header: api_key i16, api_version i16,
    correlation_id i32, client_id string — checked byte-for-byte, so a
    symmetric client/fake codec bug can't hide."""
    w = Writer().i16(3).i16(1).i32(42).string("cid")
    raw = w.done()
    assert raw == struct.pack(">hhi", 3, 1, 42) + struct.pack(">h", 3) + b"cid"
    r = Reader(raw)
    assert (r.i16(), r.i16(), r.i32(), r.string()) == (3, 1, 42, "cid")


# ------------------------------------------------------------ contract suite


@pytest.fixture()
def kafka_broker():
    server = FakeKafkaServer(port=0).start()
    broker = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        yield broker
    finally:
        broker.close()
        server.stop()


def test_kafka_keyed_ordering(kafka_broker):
    b = kafka_broker
    for i in range(20):
        b.produce(T.TRANSACTIONS, {"n": i}, key="user_7")
    c = b.consumer([T.TRANSACTIONS], "g1")
    recs = c.poll(100)
    assert [r.value["n"] for r in recs] == list(range(20))
    assert len({r.partition for r in recs}) == 1


def test_kafka_commit_replay(kafka_broker):
    b = kafka_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(4)) == 4
    c2 = b.consumer([T.TRANSACTIONS], "g")
    assert len(c2.poll(100)) == 10
    c2.commit()
    assert b.consumer([T.TRANSACTIONS], "g").poll(100) == []
    assert b.lag("g", T.TRANSACTIONS) == 0


def test_kafka_snapshot_commit(kafka_broker):
    b = kafka_broker
    for i in range(10):
        b.produce(T.TRANSACTIONS, {"n": i}, key="k")
    c = b.consumer([T.TRANSACTIONS], "g")
    assert len(c.poll(6)) == 6
    snap = c.snapshot_positions()
    assert len(c.poll(10)) == 4
    c.commit(snap)
    assert b.lag("g", T.TRANSACTIONS) == 4


def test_kafka_produce_batch_spreads(kafka_broker):
    b = kafka_broker
    n = b.produce_batch(T.TRANSACTIONS, [{"n": i} for i in range(24)],
                        key_fn=lambda v: str(v["n"] % 5))
    assert n == 24
    assert sum(b.end_offsets(T.TRANSACTIONS)) == 24
    # per-key ordering survives the batch path
    c = b.consumer([T.TRANSACTIONS], "g")
    recs = c.poll(100)
    per_key = {}
    for r in recs:
        per_key.setdefault(r.key, []).append(r.value["n"])
    for key, ns in per_key.items():
        assert ns == sorted(ns), f"key {key} out of order: {ns}"


def test_kafka_unicode_and_null_values(kafka_broker):
    b = kafka_broker
    b.produce(T.TRANSACTIONS, {"désc": "caffè ☕", "amount": 12.5}, key="ü")
    recs = b.consumer([T.TRANSACTIONS], "g").poll(10)
    assert recs[0].value == {"désc": "caffè ☕", "amount": 12.5}
    assert recs[0].key == "ü"


def test_stream_job_over_kafka():
    """The scoring job runs unchanged over the Kafka wire protocol."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.stream import JobConfig, StreamJob

    server = FakeKafkaServer(port=0).start()
    broker = KafkaBroker(bootstrap=f"127.0.0.1:{server.port}")
    try:
        gen = TransactionGenerator(num_users=30, num_merchants=12, seed=29)
        scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        job = StreamJob(broker, scorer, JobConfig(max_batch=16,
                                                  max_delay_ms=1.0))
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(40),
                             key_fn=lambda r: str(r["user_id"]))
        assert job.run_until_drained(now=1000.0) == 40
        preds = broker.consumer([T.PREDICTIONS], "check").poll(1000)
        assert len(preds) == 40
        assert broker.lag(job.config.group_id, T.TRANSACTIONS) == 0
    finally:
        broker.close()
        server.stop()

"""Network fault plane (ISSUE 13): link chaos, producer generation
fencing, session eviction + rejoin, and the partition-drill smoke."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from realtime_fraud_detection_tpu.chaos.faults import ChaosPlan, FaultWindow
from realtime_fraud_detection_tpu.chaos.netfaults import (
    LinkDegrade,
    LinkFaultPlane,
    LinkState,
    NetworkPartition,
    ScheduledLink,
    scheduled_link_from_spec,
)
from realtime_fraud_detection_tpu.stream.netbroker import (
    BrokerServer,
    NetBrokerClient,
    StaleGenerationError,
)
from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker


# ---------------------------------------------------------------------------
# link state + injectors
# ---------------------------------------------------------------------------


class TestLinkState:
    def test_full_partition_refuses_at_send(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        link.set_partition("full")
        with pytest.raises(ConnectionResetError):
            link.before_send({"op": "produce", "topic": "t"})
        assert link.partitioned_sends == 1
        link.clear_partition()
        link.before_send({"op": "produce", "topic": "t"})  # heals

    def test_one_way_partition_loses_the_response(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        link.set_partition("one_way")
        link.before_send({"op": "produce"})          # request goes through
        with pytest.raises(ConnectionError):
            link.after_recv({"op": "produce"})       # ack lost
        assert link.lost_responses == 1

    def test_match_scopes_the_fault(self):
        """A control-plane-matched partition is the asymmetric scenario:
        matched frames bounce, data frames flow."""
        link = LinkState("w", "broker", sleep=lambda s: None)
        link.set_partition("full", match={"topics": ["cluster-control",
                                                     "cluster-events"]})
        with pytest.raises(ConnectionResetError):
            link.before_send({"op": "fetch", "topic": "cluster-control"})
        link.before_send({"op": "produce",
                          "topic": "payment-transactions"})   # data flows
        link.before_send({"op": "ping"})              # topicless op flows
        # ops match too (and create_topic's "name" field counts as topic)
        link2 = LinkState("w", "broker", sleep=lambda s: None)
        link2.set_partition("full", match={"ops": ["commit"]})
        with pytest.raises(ConnectionResetError):
            link2.before_send({"op": "commit"})
        link2.before_send({"op": "fetch", "topic": "x"})

    def test_latency_and_jitter_sleep_through_the_seam(self):
        slept = []
        link = LinkState("w", "broker", sleep=slept.append, seed=3)
        link.set_degrade(latency_s=0.02, jitter_s=0.01)
        link.before_send({"op": "fetch"})
        link.before_send({"op": "fetch"})
        assert len(slept) == 2 and all(0.02 <= s <= 0.03 for s in slept)
        assert link.delayed_sends == 2
        # seeded jitter replays identically on a fresh link
        slept2 = []
        link2 = LinkState("w", "broker", sleep=slept2.append, seed=3)
        link2.set_degrade(latency_s=0.02, jitter_s=0.01)
        link2.before_send({"op": "fetch"})
        link2.before_send({"op": "fetch"})
        assert slept2 == slept

    def test_throttle_scales_with_frame_size(self):
        slept = []
        link = LinkState("w", "broker", sleep=slept.append)
        link.set_degrade(throttle_bytes_per_s=1000.0)
        link.before_send({"op": "produce"}, nbytes=500)
        assert slept == [0.5]
        assert link.throttled_bytes == 500

    def test_bounded_drop_then_heals(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        link.set_degrade(drop_next=2)
        for _ in range(2):
            with pytest.raises(ConnectionResetError):
                link.before_send({"op": "fetch"})
        link.before_send({"op": "fetch"})             # drops exhausted
        assert link.dropped_sends == 2

    def test_validation(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        with pytest.raises(ValueError):
            link.set_partition("sideways")
        with pytest.raises(ValueError):
            link.set_degrade(latency_s=-1)
        with pytest.raises(ValueError):
            NetworkPartition([])
        with pytest.raises(ValueError):
            LinkDegrade([link])          # no effect


class TestInjectorsAndSchedule:
    def test_network_partition_injector_arms_and_clears(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        inj = NetworkPartition([link], mode="full")
        inj.begin(1.0)
        assert link.partition_mode == "full" and link.active()
        inj.end(2.0)
        assert link.partition_mode is None and not link.active()

    def test_scheduled_link_drives_plan_on_injected_clock(self):
        link = LinkState("w", "broker", sleep=lambda s: None)
        plan = ChaosPlan([FaultWindow("p", "netfault", 1.0, 2.0)])
        plan.bind("p", NetworkPartition([link], mode="full"))
        clock = {"t": 0.0}
        sched = ScheduledLink(link, plan, lambda: clock["t"])
        sched.before_send({"op": "fetch"})            # pre-window: clean
        clock["t"] = 1.5
        with pytest.raises(ConnectionResetError):
            sched.before_send({"op": "fetch"})
        clock["t"] = 2.5
        sched.before_send({"op": "fetch"})            # window closed
        # -inf epoch (worker before the epoch announcement): never fires
        link2 = LinkState("w", "broker", sleep=lambda s: None)
        plan2 = ChaosPlan([FaultWindow("p", "netfault", 0.0, 9.0)])
        plan2.bind("p", NetworkPartition([link2], mode="full"))
        sched2 = ScheduledLink(link2, plan2, lambda: float("-inf"))
        sched2.before_send({"op": "fetch"})
        assert link2.partition_mode is None

    def test_scheduled_link_from_spec_wire_form(self):
        """The JSON-able window dicts that ride a worker spec across the
        process boundary rebuild the same schedule."""
        windows = [
            {"name": "asym", "kind": "partition", "t_start": 1.0,
             "t_end": 2.0, "mode": "full",
             "match": {"topics": ["cluster-control"]}},
            {"name": "slow", "kind": "degrade", "t_start": 3.0,
             "t_end": 4.0, "latency_s": 0.01},
        ]
        clock = {"t": 0.0}
        slept = []
        sched = scheduled_link_from_spec(
            windows, role="worker-w1", peer="broker",
            clock=lambda: clock["t"], sleep=slept.append, seed=7)
        clock["t"] = 1.5
        with pytest.raises(ConnectionResetError):
            sched.before_send({"op": "fetch", "topic": "cluster-control"})
        sched.before_send({"op": "fetch", "topic": "payment-transactions"})
        clock["t"] = 3.5
        sched.before_send({"op": "fetch", "topic": "payment-transactions"})
        assert slept and abs(slept[0] - 0.01) < 1e-9
        with pytest.raises(ValueError):
            scheduled_link_from_spec(
                [{"name": "x", "kind": "meteor", "t_start": 0,
                  "t_end": 1}], role="w", peer="b",
                clock=lambda: 0.0)

    def test_plane_registry_and_snapshot(self):
        plane = LinkFaultPlane(sleep=lambda s: None, seed=1)
        a = plane.link("worker-w0", "broker")
        assert plane.link("worker-w0", "broker") is a
        a.set_partition("full")
        with pytest.raises(ConnectionResetError):
            a.before_send({"op": "ping"})
        snap = plane.snapshot(fencing={"fenced_produces": 3,
                                       "fenced_commits": 1})
        entry = snap["links"]["worker-w0->broker"]
        assert entry["active"] and entry["partitioned_sends_total"] == 1
        assert snap["fencing"] == {"fenced_produces_total": 3,
                                   "fenced_commits_total": 1}


# ---------------------------------------------------------------------------
# producer generation fencing
# ---------------------------------------------------------------------------


class TestGenerationFencing:
    def test_unstamped_passes_stale_refused_current_passes(self):
        b = InMemoryBroker()
        t = "fraud-predictions"
        b.produce(t, {"v": 1}, key="u1")              # unstamped: free
        p = b.select_partition(t, "u1")
        b.fence_producers(t, [p], 5)
        b.produce(t, {"v": 2}, key="u1")              # still unstamped
        with pytest.raises(StaleGenerationError):
            b.produce(t, {"v": 3}, key="u1", generation=4)
        b.produce(t, {"v": 4}, key="u1", generation=5)
        b.produce(t, {"v": 5}, key="u1", generation=6)
        stats = b.producer_fence_stats()
        assert stats["fenced_produces"] == 1
        assert b.producer_fence(t, p) == 5

    def test_fence_is_monotonic(self):
        b = InMemoryBroker()
        b.fence_producers("t", [0], 5)
        b.fence_producers("t", [0], 3)                # never moves back
        assert b.producer_fence("t", 0) == 5

    def test_stale_commit_refused_before_any_offset_applies(self):
        b = InMemoryBroker()
        t = "payment-transactions"
        b.fence_producers(t, [2], 5)
        with pytest.raises(StaleGenerationError):
            b.commit("g", {(t, 0): 7, (t, 2): 9}, generation=4)
        # all-or-nothing: the unfenced partition's offset did NOT move
        assert b.committed("g", t, 0) == 0
        assert b.producer_fence_stats()["fenced_commits"] == 1
        b.commit("g", {(t, 0): 7, (t, 2): 9}, generation=5)
        assert b.committed("g", t, 2) == 9

    def test_refused_batch_is_whole_frame_over_tcp(self):
        """A zombie's fan-out bounces atomically: no partial batch, no
        above-watermark residue, and the client raises the TYPED error."""
        srv = BrokerServer(port=0).start()
        try:
            cli = NetBrokerClient(port=srv.port, timeout_s=5.0,
                                  reconnect_attempts=1,
                                  retry_sleep=lambda s: None)
            t = "fraud-predictions"
            parts = {cli_partition(srv, t, f"u{i}") for i in range(8)}
            cli.fence_producers(t, sorted(parts), 3)
            ends_before = cli.end_offsets(t)
            cli.generation = 2
            with pytest.raises(StaleGenerationError):
                cli.produce_batch_keyed(
                    t, [(f"u{i}", {"v": i}) for i in range(8)])
            assert cli.end_offsets(t) == ends_before
            cli.generation = 3
            assert cli.produce_batch_keyed(
                t, [(f"u{i}", {"v": i}) for i in range(8)]) == 8
            status = cli.status()
            assert status["fenced_produces"] == 1
            cli.close()
        finally:
            srv.stop()

    def test_fence_forwards_to_replica_for_promotion(self):
        """A promoted replica keeps refusing the same zombies."""
        primary = BrokerServer(port=0, min_isr=2).start()
        replica = BrokerServer(port=0, role="replica").start()
        try:
            primary.add_replica("127.0.0.1", replica.port)
            cli = NetBrokerClient(port=primary.port, timeout_s=5.0,
                                  retry_sleep=lambda s: None)
            t = "payment-transactions"
            p = primary.broker.select_partition(t, "u1")
            cli.fence_producers(t, [p], 4)
            replica.promote()
            rcli = NetBrokerClient(port=replica.port, timeout_s=5.0,
                                   retry_sleep=lambda s: None)
            rcli.generation = 3
            with pytest.raises(StaleGenerationError):
                rcli.produce(t, {"v": 1}, key="u1")
            rcli.generation = 4
            rcli.produce(t, {"v": 2}, key="u1")
            cli.close()
            rcli.close()
        finally:
            replica.stop()
            primary.stop()


def cli_partition(srv: BrokerServer, topic: str, key: str) -> int:
    return srv.broker.select_partition(topic, key)


# ---------------------------------------------------------------------------
# real-seam one-way partition: applied op, lost ack, duplicate on retry
# ---------------------------------------------------------------------------


class TestClientPathFaults:
    def test_throttle_paces_by_real_frame_bytes(self):
        """Slow-link throttling must act from the REAL client request
        path (regression: before_send used to be called without the
        frame size, making throttle a silent no-op)."""
        srv = BrokerServer(port=0).start()
        try:
            slept = []
            link = LinkState("w", "broker", sleep=slept.append)
            cli = NetBrokerClient(port=srv.port, timeout_s=5.0,
                                  retry_sleep=lambda s: None, link=link)
            link.set_degrade(throttle_bytes_per_s=1e6)
            cli.produce("payment-transactions", {"v": "x" * 200}, key="k")
            assert link.throttled_bytes > 200
            assert slept and slept[0] == pytest.approx(
                link.throttled_bytes / 1e6)
            cli.close()
        finally:
            srv.stop()

    def test_socket_timeout_restored_after_deadline_read(self):
        """The whole-frame deadline shrinks the socket timeout to the
        residual budget mid-read; it must be restored afterwards so the
        next op's send never runs under a near-zero leftover."""
        srv = BrokerServer(port=0).start()
        try:
            cli = NetBrokerClient(port=srv.port, timeout_s=7.5,
                                  retry_sleep=lambda s: None)
            cli.ping()
            assert cli._sock.gettimeout() == pytest.approx(7.5)
            cli.produce("payment-transactions", {"v": 1}, key="k")
            assert cli._sock.gettimeout() == pytest.approx(7.5)
            cli.close()
        finally:
            srv.stop()


class TestOneWayOverRealTcp:
    def test_ack_loss_duplicates_then_heals(self):
        srv = BrokerServer(port=0).start()
        try:
            link = LinkState("w", "broker", sleep=lambda s: None)
            cli = NetBrokerClient(port=srv.port, timeout_s=5.0,
                                  reconnect_attempts=2,
                                  retry_sleep=lambda s: None, link=link)
            link.set_partition("one_way", {"ops": ["produce"]})
            with pytest.raises(ConnectionError):
                cli.produce("payment-transactions", {"v": 1}, key="k")
            # every retry APPLIED the op broker-side (at-least-once ack
            # loss): 1 + reconnect_attempts copies on the log
            assert sum(cli.end_offsets("payment-transactions")) == 3
            assert link.lost_responses == 3
            link.clear_partition()
            cli.produce("payment-transactions", {"v": 2}, key="k")
            assert sum(cli.end_offsets("payment-transactions")) == 4
            cli.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# sync_netfaults mirror (the stream-vs-serving parity pin)
# ---------------------------------------------------------------------------


def _netfault_render(mc) -> str:
    return "\n".join(
        line for line in mc.render_prometheus().splitlines()
        if "netfault" in line or "fenced_" in line)


class TestSyncNetfaults:
    def _snapshot(self, partitioned=5, fenced=2):
        return {
            "links": {"worker-w0->broker": {
                "active": True, "partition_mode": "full",
                "windows_begun": 1, "delayed_sends_total": 7,
                "dropped_sends_total": 1,
                "partitioned_sends_total": partitioned,
                "lost_responses_total": 0,
                "throttled_bytes_total": 2048,
            }},
            "fencing": {"fenced_produces_total": fenced,
                        "fenced_commits_total": 1},
        }

    def test_honest_counter_deltas(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        mc = MetricsCollector()
        mc.sync_netfaults(self._snapshot(partitioned=5, fenced=2))
        mc.sync_netfaults(self._snapshot(partitioned=5, fenced=2))
        assert mc.netfault_partitioned_sends.value(
            link="worker-w0->broker") == 5          # idempotent re-sync
        mc.sync_netfaults(self._snapshot(partitioned=9, fenced=3))
        assert mc.netfault_partitioned_sends.value(
            link="worker-w0->broker") == 9
        assert mc.fenced_produce.value() == 3
        assert mc.fenced_commit.value() == 1
        assert mc.netfault_link_active.value(
            link="worker-w0->broker") == 1.0

    def test_stream_vs_serving_render_identical(self):
        """The pin every sync_* mirror carries: a stream job's collector
        and a serving app's collector fed the same snapshots render
        byte-identical netfault_*/fenced_* series."""
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        stream_mc, serving_mc = MetricsCollector(), MetricsCollector()
        for snap in (self._snapshot(5, 2), self._snapshot(9, 4)):
            stream_mc.sync_netfaults(snap)
            serving_mc.sync_netfaults(snap)
        assert _netfault_render(stream_mc) == _netfault_render(serving_mc)
        assert "fenced_produce_total 4" in _netfault_render(stream_mc)

    def test_live_plane_snapshot_feeds_the_mirror(self):
        from realtime_fraud_detection_tpu.obs.metrics import (
            MetricsCollector,
        )

        plane = LinkFaultPlane(sleep=lambda s: None)
        link = plane.link("worker-w1", "broker")
        link.set_partition("full")
        for _ in range(3):
            with pytest.raises(ConnectionResetError):
                link.before_send({"op": "ping"})
        mc = MetricsCollector()
        mc.sync_netfaults(plane.snapshot(
            fencing={"fenced_produces": 1, "fenced_commits": 0}))
        assert mc.netfault_partitioned_sends.value(
            link="worker-w1->broker") == 3
        assert mc.fenced_produce.value() == 1


# ---------------------------------------------------------------------------
# session eviction + fenced rejoin against a REAL stopped worker process
# ---------------------------------------------------------------------------


class TestSessionEvictionRejoin:
    def test_sigstop_worker_evicted_then_rejoins_on_sigcont(self, tmp_path):
        """SIGSTOP a real worker: heartbeats stop → session expiry evicts
        it and moves its partitions; SIGCONT → it discovers the fence,
        abandons, and rejoins as a fresh member."""
        from realtime_fraud_detection_tpu.cluster.handoff import (
            HandoffServer,
        )
        from realtime_fraud_detection_tpu.cluster.procfleet import (
            ProcessFleet,
        )

        srv = BrokerServer(port=0).start()
        handoff = HandoffServer(blob_dir=str(tmp_path / "blobs")).start()
        fleet = None
        try:
            fleet = ProcessFleet(
                f"127.0.0.1:{srv.port}", f"127.0.0.1:{handoff.port}",
                n_partitions=8, session_timeout_s=1.5,
                spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"},
                worker_spec={"batch": 32, "max_delay_ms": 10.0,
                             "checkpoint_every": 4, "seq_len": 4,
                             "feature_dim": 4, "heartbeat_s": 0.3})
            fleet.start(2, now=0.0)
            victim = fleet.ready_ids()[0]
            pid = fleet.workers[victim]["pid"]
            os.kill(pid, signal.SIGSTOP)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                fleet.tick()
                if fleet.workers[victim].get("evicted"):
                    break
                time.sleep(0.05)
            assert fleet.workers[victim].get("evicted"), \
                "silent worker never evicted"
            assert victim not in fleet.ring.members()
            # its partitions moved to the survivor
            assign = fleet.assignment()
            assert victim not in assign
            os.kill(pid, signal.SIGCONT)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                fleet.tick()
                if not fleet.workers[victim].get("evicted") \
                        and victim in fleet.ring.members():
                    break
                time.sleep(0.05)
            assert victim in fleet.ring.members(), \
                "healed worker never rejoined"
            assert fleet.evictions >= 1 and fleet.rejoins >= 1
            byes = fleet.shutdown_all()
            assert set(byes) == set(fleet.workers)
        finally:
            if fleet is not None:
                fleet.terminate()
            handoff.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# compact summary + tenth-drill registration
# ---------------------------------------------------------------------------


class TestRegistrationAndSummary:
    def test_partition_drill_is_the_tenth_lockwatch_drill(self):
        from realtime_fraud_detection_tpu.analysis.lockwatch import (
            LOCKWATCH_DRILLS,
        )

        assert "partition-drill" in LOCKWATCH_DRILLS
        # thirteen since ISSUE 20 added obs-drill
        assert len(LOCKWATCH_DRILLS) == 13

    def test_netfaults_in_lint_scopes(self):
        from realtime_fraud_detection_tpu.analysis.lint import (
            CLOCK_SUBSYSTEMS,
            DETERMINISM_MODULES,
        )

        assert "chaos" in CLOCK_SUBSYSTEMS
        assert "chaos/netfaults.py" in DETERMINISM_MODULES

    def test_config_validation(self):
        import dataclasses

        from realtime_fraud_detection_tpu.chaos.partition_drill import (
            PartitionDrillConfig,
        )

        PartitionDrillConfig().validate()
        PartitionDrillConfig.fast().validate()
        with pytest.raises(ValueError):
            dataclasses.replace(PartitionDrillConfig(),
                                n_workers=3).validate()
        with pytest.raises(ValueError):
            # overlapping windows: a rejoin rebalance could wait on a
            # partitioned releaser
            dataclasses.replace(PartitionDrillConfig(),
                                slow_start=5.0).validate()

    def test_targets_are_deterministic_and_distinct(self):
        from realtime_fraud_detection_tpu.chaos.partition_drill import (
            PartitionDrillConfig,
            drill_targets,
        )

        cfg = PartitionDrillConfig.fast()
        t1, t2 = drill_targets(cfg), drill_targets(cfg)
        assert t1 == t2
        assert len({t1["zombie"], t1["slow"], t1["full"]}) == 3

    def test_compact_summary_under_2kb_even_when_bloated(self):
        from realtime_fraud_detection_tpu.chaos.partition_drill import (
            compact_partition_summary,
        )

        summary = {"metric": "partition_drill", "passed": False,
                   "detection_s": {f"w{i}": 1.0 for i in range(40)},
                   "checks": {f"very_long_check_name_{i}" * 4: False
                              for i in range(64)}}
        compact = compact_partition_summary(summary)
        assert len(json.dumps(compact,
                              separators=(",", ":")).encode()) < 2048
        assert compact["metric"] == "partition_drill"


# ---------------------------------------------------------------------------
# tier-1 smoke: the full drill through the CLI
# ---------------------------------------------------------------------------


class TestPartitionDrillSmoke:
    def test_partition_drill_fast_cli(self):
        """Tier-1 acceptance: `rtfd partition-drill --fast` — >= 4 real
        OS worker processes under link chaos, the zombie fenced at the
        broker's write seam (counted, nonzero), both evicted workers
        rejoining fresh, oracle equality, and the fresh-run determinism
        digest — passes end to end, final stdout line a parseable <2KB
        verdict."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "realtime_fraud_detection_tpu",
             "partition-drill", "--fast"],
            capture_output=True, text=True, timeout=540, env=env)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        compact = json.loads(lines[-1])
        assert len(lines[-1].encode()) < 2048
        assert compact["metric"] == "partition_drill"
        assert compact["passed"] is True
        assert compact["fenced_produces"] >= 1
        assert compact["lost"] == 0 and compact["conflicting_scored"] == 0
        assert compact["evictions"] >= 2 and compact["rejoins"] >= 2
        full = json.loads(lines[-2])
        assert full["checks"]["replay_deterministic"] is True
        assert full["checks"]["zombie_fenced_produce"] is True
        assert full["checks"]["state_equals_oracle"] is True
        assert full["checks"]["no_double_ownership"] is True


# ---------------------------------------------------------------------------
# trace-carrier loss inside a netfault window (ISSUE 20)
# ---------------------------------------------------------------------------


class TestCarrierLossUnderNetfault:
    def test_stripped_carriers_count_exactly_and_never_wedge(self):
        """A degrade/partition window that strips producer carriers must
        degrade every affected consume to a fresh LOCAL root: counted in
        ``trace_carrier_lost_total`` exactly once per stripped record,
        with zero cross-worker trace-id attachment and every started
        trace reaching a terminal (the no-wedge ledger)."""
        from realtime_fraud_detection_tpu.obs.tracing import (
            Tracer,
            make_carrier,
        )
        from realtime_fraud_detection_tpu.utils.config import (
            TracingSettings,
        )

        window = FaultWindow("carrier_strip", "netfault", 2.0, 4.0)
        clock = {"w0": [0.0], "w1": [0.0]}
        tracers = {w: Tracer(TracingSettings(enabled=True, ring_size=512,
                                             origin=w),
                             clock=lambda w=w: clock[w][0])
                   for w in ("w0", "w1")}
        stripped = {"w0": 0, "w1": 0}
        for i in range(60):
            wid = "w0" if i % 2 == 0 else "w1"
            tracer = tracers[wid]
            produced_ts = i * 0.1
            in_window = window.t_start <= produced_ts < window.t_end
            carrier = None if in_window else make_carrier(
                f"ting-{i:04x}", origin="ingress",
                produced_ts=produced_ts)
            if in_window:
                stripped[wid] += 1
            ctx = tracer.begin(f"tx{i}", carrier=carrier,
                               now_wall=produced_ts + 0.01,
                               expect_carrier=True)
            assert ctx is not None            # loss is never a wedge
            tb = tracer.batch([ctx])
            tb.mark("device_wait")
            clock[wid][0] += 0.002
            tracer.finish_batch(tb)
        for wid, tracer in tracers.items():
            c = tracer.counters
            assert c["carrier_lost"] == stripped[wid]
            assert c["carrier_adopted"] == 30 - stripped[wid]
            # no-wedge ledger: started == sum of terminals
            assert c["started"] == (c["completed"] + c["shed"]
                                    + c["errors"] + c["cached"])
        # zero cross-attachment: a trace id lands in exactly one
        # worker's ring, and fresh roots carry the minting worker's id
        ids = {w: {t.trace_id for t in tr.traces()}
               for w, tr in tracers.items()}
        assert not (ids["w0"] & ids["w1"])
        for w, tr in tracers.items():
            fresh = [t for t in tr.traces() if not t.origin]
            assert len(fresh) == stripped[w]
            assert all(t.trace_id.startswith(f"t{w}-") for t in fresh)

"""FeatureStore + MetadataStore tests (FeatureStore.java / init.sql
semantics, with the reference's store-nothing bug fixed)."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.state import FeatureStore, MetadataStore


class TestFeatureRegistry:
    def test_register_and_version_bump(self):
        fs = FeatureStore()
        m1 = fs.register_feature("amount", "NUMERICAL", "txn amount", now=10.0)
        assert m1["version"] == 1 and m1["created_at"] == 10.0
        m2 = fs.register_feature("amount", "NUMERICAL", "usd amount",
                                 properties={"unit": "usd"}, now=20.0)
        assert m2["version"] == 2
        assert m2["created_at"] == 10.0 and m2["updated_at"] == 20.0
        assert m2["properties"] == {"unit": "usd"}

    def test_rejects_unknown_type(self):
        with pytest.raises(ValueError, match="unknown feature type"):
            FeatureStore().register_feature("x", "COMPLEX")

    def test_registered_includes_the_64_contract(self):
        from realtime_fraud_detection_tpu.features.extract import FEATURE_NAMES

        fs = FeatureStore()
        fs.register_feature("custom_feature")
        names = fs.registered_features()
        assert set(FEATURE_NAMES) <= names
        assert "custom_feature" in names


class TestFeatureValues:
    def test_store_and_retrieve_strips_internal_fields(self):
        """The reference's storeFeatureValues never stored anything
        (FeatureStore.java:122-146); ours must round-trip."""
        fs = FeatureStore()
        fs.store_feature_values("t1", "transaction",
                                {"amount": 42.0, "is_fraud": False}, now=100.0)
        got = fs.get_feature_values("t1", "transaction", now=101.0)
        assert got == {"amount": 42.0, "is_fraud": False}

    def test_values_expire_after_ttl(self):
        fs = FeatureStore()
        fs.store_feature_values("t1", "transaction", {"a": 1.0}, now=0.0)
        assert fs.get_feature_values("t1", "transaction", now=7100.0)
        assert fs.get_feature_values("t1", "transaction", now=7300.0) == {}

    def test_batch_and_selected(self):
        fs = FeatureStore()
        for i in range(3):
            fs.store_feature_values(f"e{i}", "user", {"a": i, "b": -i},
                                    now=0.0)
        batch = fs.get_batch_feature_values(["e0", "e2", "missing"], "user",
                                            now=1.0)
        assert batch["e2"] == {"a": 2, "b": -2}
        assert batch["missing"] == {}
        sel = fs.get_selected_features("e1", "user", ["b"], now=1.0)
        assert sel == {"b": -1}


class TestFeatureStatistics:
    def test_welford_std_is_real(self):
        """The reference drops the M2 term so std is always 0
        (FeatureStore.java:268); ours matches numpy."""
        fs = FeatureStore()
        xs = [3.0, 7.0, 1.0, 9.0, 100.0]
        for i, x in enumerate(xs):
            fs.store_feature_values(f"t{i}", "transaction", {"amount": x},
                                    now=float(i))
        s = fs.get_feature_statistics("amount")
        assert s["count"] == 5
        assert s["mean"] == pytest.approx(np.mean(xs))
        assert s["std"] == pytest.approx(np.std(xs))
        assert s["min"] == 1.0 and s["max"] == 100.0

    def test_categorical_and_null_tracking(self):
        fs = FeatureStore()
        for v in ["visa", "visa", "amex", None, True]:
            fs.store_feature_values("e", "txn", {"card": v}, now=0.0)
        s = fs.get_feature_statistics("card")
        assert s["categorical_counts"] == {"visa": 2, "amex": 1, "true": 1}
        assert s["null_rate"] == pytest.approx(1 / 5)

    def test_health(self):
        fs = FeatureStore()
        fs.register_feature("a")
        fs.store_feature_values("e", "u", {"a": 1}, now=0.0)
        h = fs.health()
        assert h["healthy"] and h["registered_features"] == 1
        assert h["counters"]["stored"] == 1


class TestMetadataStore:
    def test_job_lifecycle(self):
        md = MetadataStore()
        md.register_job("j1", "fraud-detection-job", parallelism=8, now=1.0)
        assert md.get_job("j1")["status"] == "RUNNING"
        md.set_job_status("j1", "FINISHED", now=5.0)
        job = md.get_job("j1")
        assert job["status"] == "FINISHED" and job["end_time"] == 5.0

    def test_checkpoint_records(self):
        md = MetadataStore()
        md.register_job("j1", "job")
        md.record_checkpoint("j1", 1, "/ckpt/step_1", 1024, 12.5, now=2.0)
        md.record_checkpoint("j1", 2, "/ckpt/step_2", 2048, 10.0, now=3.0)
        md.record_checkpoint("j1", 3, "/c", status="FAILED", now=4.0)
        assert len(md.checkpoints("j1")) == 3
        latest = md.latest_checkpoint("j1")
        assert latest["step"] == 2 and latest["path"] == "/ckpt/step_2"

    def test_feature_values_ttl(self):
        md = MetadataStore()
        md.put_feature_values("txn", "t1", {"amount": 9.0}, ttl_s=100.0,
                              now=0.0)
        assert md.get_feature_values("txn", "t1", now=50.0) == {"amount": 9.0}
        assert md.get_feature_values("txn", "t1", now=200.0) == {}
        assert md.expire_feature_values(now=200.0) == 1

    def test_profiles_roundtrip_and_bulk_restore(self):
        md = MetadataStore()
        md.put_profiles(users={"u1": {"risk_score": 0.2}},
                        merchants={"m1": {"category": "retail"}})
        assert md.get_user_profile("u1") == {"risk_score": 0.2}
        allp = md.load_all_profiles()
        assert allp["users"]["u1"]["risk_score"] == 0.2
        assert allp["merchants"]["m1"]["category"] == "retail"

    def test_persistence_across_reopen(self, tmp_path):
        p = tmp_path / "meta.db"
        md = MetadataStore(p)
        md.register_job("j1", "job")
        md.record_checkpoint("j1", 7, "/x")
        md.close()
        md2 = MetadataStore(p)
        assert md2.latest_checkpoint("j1")["step"] == 7
        md2.close()

    def test_feature_registry(self):
        md = MetadataStore()
        md.register_feature_group("txn_features", schema={"width": 64})
        md.register_feature("amount", "txn_features")
        md.register_feature("amount_log", "txn_features")
        assert set(md.feature_names("txn_features")) == {"amount",
                                                         "amount_log"}
        assert md.stats()["feature_groups"] == 1


class TestJsonSafety:
    def test_categorical_only_stats_are_json_safe(self):
        import json as _json

        fs = FeatureStore()
        fs.store_feature_values("u1", "user", {"payment_method": "card"},
                                now=0.0)
        s = fs.get_feature_statistics("payment_method")
        assert s["min"] == 0.0 and s["max"] == 0.0
        # strict JSON (no Infinity tokens)
        _json.loads(_json.dumps(s))

"""QoS plane units: admission classes, ladder hysteresis, budgets, and the
degradation seam into the real scorer."""

import numpy as np
import pytest

from realtime_fraud_detection_tpu.qos import (
    AdmissionController,
    DegradationLadder,
    LadderConfig,
    LatencyBudget,
    QosPlane,
    TokenBucket,
)
from realtime_fraud_detection_tpu.utils.config import Config, QosSettings


class TestAdmission:
    def test_token_bucket_refills_at_rate(self):
        b = TokenBucket(rate=10.0, burst=5.0)
        b.refill(0.0)
        for _ in range(5):
            b.take()
        assert b.tokens == 0.0
        b.refill(0.25)                  # +2.5 tokens
        assert b.tokens == pytest.approx(2.5)
        b.refill(10.0)                  # capped at burst
        assert b.tokens == 5.0

    def test_high_never_shed_low_sheds_first(self):
        c = AdmissionController(rate=10.0, burst=4.0, low_reserve_frac=0.25)
        # drain the bucket with normal traffic at t=0
        decisions = [c.decide("normal", 0.0) for _ in range(6)]
        assert [d.admitted for d in decisions] == [True] * 4 + [False] * 2
        assert decisions[-1].reason == "shed:rate_limit"
        # empty bucket: high still admits (debt), low is refused with the
        # reserve reason
        assert c.decide("high", 0.0).admitted
        low = c.decide("low", 0.0)
        assert not low.admitted and low.reason == "shed:low_reserve"
        # low needs the reserve to remain AFTER its own draw: at 1.9
        # tokens (reserve = 1.0) it is still refused, normal admits
        c2 = AdmissionController(rate=10.0, burst=4.0, low_reserve_frac=0.25)
        c2.decide("normal", 0.0)
        c2.decide("normal", 0.0)
        c2.bucket.tokens = 1.9
        assert not c2.decide("low", 0.0).admitted
        assert c2.decide("normal", 0.0).admitted

    def test_rate_zero_is_unlimited(self):
        c = AdmissionController(rate=0.0)
        for p in ("high", "normal", "low"):
            d = c.decide(p, 0.0)
            assert d.admitted and d.reason == "unlimited"


class TestLadder:
    def test_hysteresis_requires_consecutive_observations(self):
        ladder = DegradationLadder(LadderConfig(
            high_backlog=100, low_backlog=10, patience=2))
        assert ladder.observe(500) == 0          # one high observation
        assert ladder.observe(50) == 0           # streak broken (band)
        assert ladder.observe(500) == 0
        assert ladder.observe(500) == 1          # two consecutive -> down
        assert ladder.transitions_down == 1
        # recovery also needs the streak
        assert ladder.observe(5) == 1
        assert ladder.observe(50) == 1           # band resets
        assert ladder.observe(5) == 1
        assert ladder.observe(5) == 0
        assert ladder.transitions_up == 1

    def test_up_patience_slows_recovery(self):
        ladder = DegradationLadder(LadderConfig(
            high_backlog=100, low_backlog=10, patience=2, up_patience=5))
        ladder.observe(500)
        ladder.observe(500)
        assert ladder.level == 1
        for _ in range(4):
            assert ladder.observe(0) == 1        # not yet
        assert ladder.observe(0) == 0            # 5th consecutive low

    def test_ladder_masks_follow_the_documented_rungs(self):
        from realtime_fraud_detection_tpu.scoring.pipeline import MODEL_NAMES

        ladder = DegradationLadder(LadderConfig(
            high_backlog=1, low_backlog=0, patience=1))
        masks = []
        for _ in range(3):
            ladder.observe(10)
            masks.append(ladder.level_mask(MODEL_NAMES))
        names = list(MODEL_NAMES)
        # level 1: drop BERT + GNN
        assert list(np.asarray(names)[~masks[0]]) == ["bert_text",
                                                      "graph_neural"]
        # level 2: trees + iforest only
        assert set(np.asarray(names)[masks[1]]) == {"xgboost_primary",
                                                    "isolation_forest"}
        # level 3: rules only
        assert not masks[2].any()
        assert ladder.current.rules_only

    def test_never_steps_past_the_ends(self):
        ladder = DegradationLadder(LadderConfig(
            high_backlog=1, low_backlog=0, patience=1))
        for _ in range(10):
            ladder.observe(100)
        assert ladder.level == 3
        for _ in range(10):
            ladder.observe(0)
        assert ladder.level == 0


class TestBudget:
    def test_remaining_and_close_by(self):
        b = LatencyBudget(budget_ms=20.0, margin_ms=2.0)
        assert b.remaining_ms(100.0, 100.0) == pytest.approx(20.0)
        assert b.remaining_ms(100.0, 100.015) == pytest.approx(5.0)
        assert b.remaining_ms(100.0, 100.025) == pytest.approx(-5.0)
        assert not b.should_close(100.0, 100.017)
        assert b.should_close(100.0, 100.0181)

    def test_config_validates_budget_and_watermarks(self):
        with pytest.raises(ValueError, match="assemble_margin_ms"):
            Config(qos=QosSettings(budget_ms=5.0, assemble_margin_ms=5.0))
        with pytest.raises(ValueError, match="watermarks"):
            Config(qos=QosSettings(ladder_low_backlog=100,
                                   ladder_high_backlog=10))


class TestPlane:
    def test_classify_by_amount_and_explicit_priority(self):
        plane = QosPlane(QosSettings(high_value_amount=500,
                                     low_value_amount=25))
        assert plane.classify({"amount": 900}) == "high"
        assert plane.classify({"amount": 100}) == "normal"
        assert plane.classify({"amount": 5}) == "low"
        assert plane.classify({"amount": 5, "priority": "high"}) == "high"
        assert plane.classify({"amount": "garbage"}) == "low"

    def test_shed_result_carries_reason_on_the_score_schema(self):
        plane = QosPlane(QosSettings(enabled=True, admission_rate=1.0,
                                     admission_burst=1.0))
        txn = {"transaction_id": "t1", "amount": 5.0}
        plane.admit(txn, 0.0)        # low: refused (reserve), counted
        decision = plane.admission.decide("low", 0.0)
        res = plane.shed_result(txn, decision)
        for field in ("transaction_id", "fraud_probability", "fraud_score",
                      "risk_level", "decision", "model_predictions",
                      "confidence", "processing_time_ms", "explanation"):
            assert field in res, field
        assert res["risk_level"] == "SHED"
        assert res["decision"] == "REVIEW"
        assert res["explanation"]["shed"] is True
        assert res["explanation"]["shed_reason"].startswith("shed:")
        assert res["explanation"]["priority"] == "low"

    def test_metrics_flow_to_prometheus_exposition(self):
        plane = QosPlane(QosSettings(enabled=True, admission_rate=2.0,
                                     admission_burst=2.0))
        plane.admit({"amount": 900}, 0.0)     # high admitted
        plane.admit({"amount": 5}, 0.0)       # low shed (reserve)
        plane.observe_backlog(0)
        text = plane.metrics.render_prometheus()
        assert 'qos_admitted_total{priority="high"} 1' in text
        assert 'qos_shed_total{priority="low",reason="shed:low_reserve"} 1' \
            in text
        assert "qos_ladder_level 0" in text
        assert "qos_budget_remaining_seconds_bucket" in text

    def test_configure_rejects_unknown_and_applies_known(self):
        plane = QosPlane(QosSettings())
        with pytest.raises(ValueError, match="unknown qos setting"):
            plane.configure({"nope": 1})
        applied = plane.configure({"enabled": True, "budget_ms": 15,
                                   "admission_rate": 100})
        assert applied == {"enabled": True, "budget_ms": 15.0,
                           "admission_rate": 100.0}
        assert plane.enabled
        assert plane.budget.budget_ms == 15.0
        assert plane.admission.bucket.rate == 100.0

    def test_configure_rederives_burst_from_the_new_rate(self):
        # a plane constructed unlimited (rate 0 -> burst 1) enabled at a
        # real rate must get a real bucket, not keep the 1-token one
        plane = QosPlane(QosSettings())
        assert plane.admission.bucket.burst == 1.0
        plane.configure({"enabled": True, "admission_rate": 20_000})
        assert plane.admission.bucket.burst == 20_000.0
        # an explicit burst still wins
        plane.configure({"admission_burst": 500.0})
        assert plane.admission.bucket.burst == 500.0

    def test_configure_enforces_load_time_invariants(self):
        plane = QosPlane(QosSettings())
        with pytest.raises(ValueError, match="assemble_margin_ms"):
            plane.configure({"assemble_margin_ms": 25.0})   # >= budget 20
        assert plane.settings.assemble_margin_ms == 2.0     # rolled back
        with pytest.raises(ValueError, match="watermarks"):
            plane.configure({"ladder_low_backlog": 5000.0})
        assert plane.settings.ladder_low_backlog == 256.0
        with pytest.raises(ValueError, match="budget"):
            plane.configure({"budget_ms": 0})

    def test_configure_rejects_stringly_typed_booleans(self):
        # bool("false") is True — a stringified boolean must 422, not
        # silently enable the plane
        plane = QosPlane(QosSettings())
        with pytest.raises(ValueError, match="boolean"):
            plane.configure({"enabled": "false"})
        assert not plane.enabled
        with pytest.raises(ValueError, match="number"):
            plane.configure({"admission_rate": "100"})


class TestScorerDegradation:
    """The ladder seam into the REAL fused scorer: masks narrow the blend
    with zero recompiles; rules-only serves the rule score."""

    @pytest.fixture(scope="class")
    def scorer(self):
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )

        gen = TransactionGenerator(num_users=16, num_merchants=8, seed=5)
        s = FraudScorer(scorer_config=ScorerConfig(text_len=32))
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        return s, gen

    def test_mask_narrows_model_predictions(self, scorer):
        from realtime_fraud_detection_tpu.scoring.pipeline import MODEL_NAMES

        s, gen = scorer
        txns = gen.generate_batch(4)
        full = s.score_batch(txns, now=1000.0)
        assert set(full[0]["model_predictions"]) == set(MODEL_NAMES)

        mask = np.asarray([n not in ("bert_text", "graph_neural")
                           for n in MODEL_NAMES])
        s.set_degradation(mask, rules_only=False, level=1)
        try:
            degraded = s.score_batch(gen.generate_batch(4), now=1001.0)
        finally:
            s.set_degradation(None)
        assert set(degraded[0]["model_predictions"]) == \
            set(MODEL_NAMES) - {"bert_text", "graph_neural"}

    def test_rules_only_serves_the_rule_score(self, scorer):
        s, gen = scorer
        txns = gen.generate_batch(4)
        s.set_degradation(np.zeros(5, bool), rules_only=True, level=3)
        try:
            results = s.score_batch(txns, now=1002.0)
        finally:
            s.set_degradation(None)
        for r in results:
            assert r["model_predictions"] == {}
            assert r["explanation"]["degraded"] == "rules_only"
            # the served probability IS the rule score
            assert r["fraud_probability"] == pytest.approx(
                r["explanation"]["rule_score"], abs=1e-6)
            assert r["confidence"] == 1.0
            assert r["decision"] in ("APPROVE", "APPROVE_WITH_MONITORING",
                                     "REVIEW", "DECLINE")


class TestCalibrationFixes:
    """Round-5 advisor satellites: platt_fit robustness + the calibration
    split guard."""

    def test_platt_fit_handles_shifted_logits(self):
        from realtime_fraud_detection_tpu.training.calibrate import platt_fit

        rng = np.random.default_rng(3)
        # class-weighted regime: logit mean ~ +3 (pos_weight inflation)
        z = rng.normal(3.0, 1.5, 4000)
        y = (rng.random(4000) < 1 / (1 + np.exp(-(z - 3.5)))).astype(
            np.float32)
        a, b = platt_fit(z, y)
        assert a == pytest.approx(1.0, abs=0.15)
        assert b == pytest.approx(-3.5, abs=0.4)

    def test_platt_fit_never_inverts_the_branch(self):
        from realtime_fraud_detection_tpu.training.calibrate import platt_fit

        # anti-correlated labels would fit a < 0 — the guard must fall
        # back to identity rather than serve a branch-inverting transform
        rng = np.random.default_rng(4)
        z = rng.normal(0.0, 2.0, 1000)
        y = (rng.random(1000) < 1 / (1 + np.exp(z))).astype(np.float32)
        assert platt_fit(z, y) == (1.0, 0.0)

    def test_platt_fit_degenerate_inputs_identity(self):
        from realtime_fraud_detection_tpu.training.calibrate import platt_fit

        assert platt_fit(np.array([]), np.array([])) == (1.0, 0.0)
        assert platt_fit(np.array([np.inf, 1.0]),
                         np.array([1.0, 0.0])) == (1.0, 0.0)

    def test_calibration_split_disables_on_tiny_datasets(self):
        from realtime_fraud_detection_tpu.training.neural import (
            _calibration_split,
        )

        # big dataset: 10% tail (>= 200 rows)
        assert _calibration_split(10_000) == 1000
        assert _calibration_split(3000) == 300
        # small dataset: min_rows floor would eat >= half -> disabled
        assert _calibration_split(300) == 0
        assert _calibration_split(200) == 0
        assert _calibration_split(50) == 0
        # just big enough: 401 rows leaves 201 training rows
        assert _calibration_split(401) == 200

"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip TPU hardware is unavailable in CI; sharding code is validated on
XLA's host platform with 8 virtual devices (the same path the driver's
``dryrun_multichip`` uses). Must run before any ``import jax`` resolves a
backend.
"""

import os

# Force CPU even when the session env points JAX at real TPU hardware
# (e.g. JAX_PLATFORMS=axon): tests must be hermetic and multi-device.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache, shared between the pytest process and
# every drill-CLI subprocess the smokes spawn (they recompile the same
# scorer programs from scratch otherwise — the cache is content-addressed
# over HLO + compile options, so code changes miss safely). Exported via
# env so subprocesses inherit; min-compile-time 0 because the suite is
# dominated by many sub-second compiles, not a few large ones.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/rtfd_xla_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax

# The image's site config pins jax_platforms to the TPU tunnel ("axon,cpu")
# regardless of env; override via jax.config before any backend is touched.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", float(
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

import numpy as np
import pytest


@pytest.fixture(scope="session")
def mesh8():
    from realtime_fraud_detection_tpu.core import build_mesh

    return build_mesh()


@pytest.fixture
def rng():
    return np.random.default_rng(0)

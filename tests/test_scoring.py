"""Tests for the fused scoring pipeline + host orchestrator."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
from realtime_fraud_detection_tpu.features.rules import DECISIONS, RISK_LEVEL_NAMES
from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG
from realtime_fraud_detection_tpu.scoring import (
    MODEL_NAMES,
    FraudScorer,
    ScorerConfig,
    init_scoring_models,
    make_example_batch,
    score_fused,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.utils.config import Config


@pytest.fixture(scope="module")
def models():
    return init_scoring_models(jax.random.PRNGKey(0), bert_config=TINY_CONFIG)


@pytest.fixture(scope="module")
def ens_params():
    return EnsembleParams.from_config(Config(), list(MODEL_NAMES))


def test_score_fused_shapes(models, ens_params):
    b = 8
    batch = make_example_batch(b)
    out = score_fused(
        models, batch, ens_params, jnp.ones((len(MODEL_NAMES),), bool),
        bert_config=TINY_CONFIG,
    )
    assert out["fraud_probability"].shape == (b,)
    assert out["model_predictions"].shape == (b, len(MODEL_NAMES))
    assert out["decision"].shape == (b,)
    p = np.asarray(out["fraud_probability"])
    assert np.all((p >= 0) & (p <= 1))


def test_score_fused_model_failure_mask(models, ens_params):
    """A disabled/failed branch is excluded and the rest renormalize
    (ensemble_predictor.py:175-182)."""
    batch = make_example_batch(4)
    all_valid = score_fused(models, batch, ens_params,
                            jnp.ones((5,), bool), bert_config=TINY_CONFIG)
    no_bert = score_fused(models, batch, ens_params,
                          jnp.asarray([True, True, False, True, True]),
                          bert_config=TINY_CONFIG)
    preds = np.asarray(all_valid["model_predictions"])
    w = np.asarray(ens_params.weights)
    mask = np.asarray([1.0, 1.0, 0.0, 1.0, 1.0])
    expect = (preds * w * mask).sum(1) / (w * mask).sum()
    np.testing.assert_allclose(
        np.asarray(no_bert["fraud_probability"]), expect, rtol=1e-5
    )


def test_fraud_scorer_end_to_end():
    gen = TransactionGenerator(num_users=50, num_merchants=20, seed=1)
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    records = gen.generate_batch(12)
    results = scorer.score_batch(records, now=1000.0)
    assert len(results) == 12
    for r in results:
        assert 0.0 <= r["fraud_probability"] <= 1.0
        assert r["decision"] in DECISIONS
        assert r["risk_level"] in RISK_LEVEL_NAMES
        assert set(r["model_predictions"]) == set(MODEL_NAMES)
        assert "model_contributions" in r["explanation"]


def test_fraud_scorer_state_accumulates():
    """Velocity and history state must accumulate across calls."""
    gen = TransactionGenerator(num_users=3, num_merchants=3, seed=2)
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    recs = gen.generate_batch(6)
    scorer.score_batch(recs, now=1000.0)
    uid = str(recs[0]["user_id"])
    vel = scorer.velocity.get_all(uid, now=1001.0)
    assert vel["5min"]["count"] >= 1
    assert len(scorer.history) >= 1
    scorer.score_batch(gen.generate_batch(4), now=1010.0)
    assert scorer.stats["scored"] == 10


def test_processing_time_excludes_pipeline_queue_wait():
    """Under pipelining, the gap between dispatch() returning and finalize()
    being called is queue wait, not processing — reported processing_time_ms
    must not include it (ADVICE r2, scorer.py elapsed_ms)."""
    import time

    gen = TransactionGenerator(num_users=10, num_merchants=5, seed=4)
    scorer = FraudScorer(scorer_config=ScorerConfig(text_len=32))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    recs = gen.generate_batch(4)
    # warm up compile so the timed run measures steady state
    scorer.score_batch(recs[:1], now=999.0)

    pending = scorer.dispatch(recs, now=1000.0)
    jax.block_until_ready(pending.out)   # device done BEFORE the queue wait
    time.sleep(0.3)                      # simulated pipeline queue wait
    results = scorer.finalize(pending, now=1000.0)
    assert results[0]["processing_time_ms"] * len(recs) < 250.0


def test_fraud_scorer_padding_invariance():
    """Bucket padding must not change real-row scores."""
    gen = TransactionGenerator(num_users=20, num_merchants=10, seed=3)
    recs = gen.generate_batch(8)

    def run(batch_records):
        s = FraudScorer(scorer_config=ScorerConfig(text_len=32), seed=0)
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        return s.score_batch(batch_records, now=1000.0)

    r5 = run(recs[:5])   # pads 5 -> bucket 8
    r8 = run(recs[:8])   # exact bucket
    for a, b in zip(r5, r8[:5]):
        assert a["fraud_probability"] == pytest.approx(b["fraud_probability"], rel=1e-5)


def test_enable_explanation_config_gates_explanations():
    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.utils.config import Config

    gen = TransactionGenerator(num_users=16, num_merchants=8, seed=2)
    cfg = Config()
    cfg.ensemble.enable_explanation = False
    s = FraudScorer(config=cfg)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    res = s.score_batch(gen.generate_batch(4))
    assert all(r["explanation"] == {} for r in res)
    assert all("fraud_probability" in r for r in res)

"""Pallas kernel plane (ISSUE 17): interpret-mode parity for the fused
int8 dequant-matmul, the on-chip score-and-blend epilogue and flash
attention against their XLA references, the KernelSettings config
surface, scorer threading + honest dispatch/fallback accounting, the
kernel_* Prometheus mirror, checkpoint hygiene (kernel selection is
runtime config, never serialized), device-pool/mesh composition, and the
`rtfd kernel-drill --fast` tier-1 smoke."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from realtime_fraud_detection_tpu.core.mesh import build_mesh
from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
from realtime_fraud_detection_tpu.models.bert import (
    TINY_CONFIG,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.quant import (
    is_quantized_bert,
    quantize_bert_params,
    quantize_dense,
    quantize_embedding,
)
from realtime_fraud_detection_tpu.ops import (
    attention_reference,
    dequant_matmul,
    dequant_matmul_reference,
    dequant_rows,
    dequant_rows_reference,
    epilogue_reference,
    epilogue_supported,
    flash_attention,
    fused_epilogue,
    matmul_supported,
    rows_supported,
)
from realtime_fraud_detection_tpu.qos.ladder import LADDER_LEVELS
from realtime_fraud_detection_tpu.scoring import (
    MODEL_NAMES,
    DevicePool,
    FraudScorer,
    MeshExecutor,
    ScorerConfig,
)
from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
from realtime_fraud_detection_tpu.utils.config import (
    Config,
    KernelSettings,
    QuantSettings,
)

BATCH = 16


def _kernel_config(kernels=True, quant=True) -> Config:
    return Config(
        quant=QuantSettings.full() if quant else QuantSettings(),
        kernels=KernelSettings.full() if kernels else KernelSettings())


def _scorer(kernels=True, quant=True, seed=0, gen_seed=7, one_device=False):
    gen = TransactionGenerator(num_users=150, num_merchants=40,
                               seed=gen_seed)
    mesh = build_mesh(devices=jax.devices()[:1]) if one_device else None
    s = FraudScorer(_kernel_config(kernels, quant),
                    scorer_config=ScorerConfig(), mesh=mesh, seed=seed)
    s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    return gen, s


def _rows(results):
    return [(r["transaction_id"], r["fraud_probability"], r["confidence"],
             r["decision"], r["risk_level"]) for r in results]


def _random_int8_dense(rng, k, n):
    w = rng.standard_normal((k, n)).astype(np.float32) * 0.2
    return quantize_dense({"w": w, "b": rng.standard_normal(n)
                           .astype(np.float32)})


# ------------------------------------------------------ fused dequant-matmul
class TestDequantMatmul:
    def test_f32_compute_parity_random(self, rng):
        q = _random_int8_dense(rng, 256, 128)
        x = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32)
        ref = dequant_matmul_reference(x, q["qw"], q["scale"], q["b"],
                                       jnp.float32)
        got = dequant_matmul(x, jnp.asarray(q["qw"]), jnp.asarray(q["scale"]),
                             jnp.asarray(q["b"]), compute_dtype=jnp.float32,
                             interpret=True)
        assert got.dtype == jnp.float32
        scale = max(1.0, float(jnp.abs(ref).max()))
        assert float(jnp.abs(got - ref).max()) / scale <= 1e-5

    def test_bf16_compute_parity_random(self, rng):
        q = _random_int8_dense(rng, 128, 256)
        x = jnp.asarray(rng.standard_normal((32, 128)), jnp.float32)
        ref = dequant_matmul_reference(x, q["qw"], q["scale"], q["b"],
                                       jnp.bfloat16).astype(jnp.float32)
        got = dequant_matmul(x, jnp.asarray(q["qw"]), jnp.asarray(q["scale"]),
                             jnp.asarray(q["b"]), interpret=True)
        scale = max(1.0, float(jnp.abs(ref).max()))
        # bf16 reassociation slack only — rounding scale, not bit-exact
        assert float(jnp.abs(got - ref).max()) / scale <= 0.02

    def test_trained_params_parity_both_dtypes(self, rng):
        params = quantize_bert_params(jax.device_get(
            init_bert_params(jax.random.PRNGKey(2), TINY_CONFIG)))
        x = jnp.asarray(rng.standard_normal(
            (16, TINY_CONFIG.hidden_size)), jnp.float32)
        for name in ("q", "ffn1"):
            p = params["layers"][0][name]
            for cd, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 0.02)):
                ref = dequant_matmul_reference(
                    x, p["qw"], p["scale"], p["b"], cd).astype(jnp.float32)
                got = dequant_matmul(x, jnp.asarray(p["qw"]),
                                     jnp.asarray(p["scale"]),
                                     jnp.asarray(p["b"]), compute_dtype=cd,
                                     interpret=True)
                scale = max(1.0, float(jnp.abs(ref).max()))
                assert float(jnp.abs(got - ref).max()) / scale <= tol

    def test_unsupported_shapes_raise(self, rng):
        q = _random_int8_dense(rng, 256, 128)
        x = jnp.asarray(rng.standard_normal((7, 256)), jnp.float32)
        with pytest.raises(ValueError, match="unsupported"):  # odd M
            dequant_matmul(x, jnp.asarray(q["qw"]), jnp.asarray(q["scale"]),
                           jnp.asarray(q["b"]), interpret=True)

    def test_supported_predicate_is_the_guard(self):
        assert matmul_supported(64, 256, 128)
        assert not matmul_supported(7, 256, 128)     # no row block divides 7
        assert not matmul_supported(64, 200, 128)    # K not lane-aligned
        assert not matmul_supported(64, 256, 100)    # N not lane-aligned
        assert not matmul_supported(64, 4224, 128)   # K over the VMEM cap


# --------------------------------------------------------- per-row dequant
class TestDequantRows:
    def test_parity_exact_random(self, rng):
        q = jnp.asarray(rng.integers(-127, 128, (64, 128)), jnp.int8)
        s = jnp.asarray(rng.uniform(1e-4, 0.1, (64,)), jnp.float32)
        got = dequant_rows(q, s, interpret=True)
        ref = dequant_rows_reference(q, s)
        # one widen + one multiply: bit-exact, zero tolerance
        assert bool(jnp.all(got == ref))

    def test_trained_embedding_rows_exact(self, rng):
        emb = quantize_embedding(np.asarray(jax.device_get(
            init_bert_params(jax.random.PRNGKey(3),
                             TINY_CONFIG))["word_emb"]))
        idx = rng.integers(0, emb["qe"].shape[0], (32,))
        q = jnp.asarray(emb["qe"][idx])
        s = jnp.asarray(emb["scale"][idx])
        assert bool(jnp.all(dequant_rows(q, s, interpret=True)
                            == dequant_rows_reference(q, s)))

    def test_unsupported_shapes_raise(self, rng):
        q = jnp.asarray(rng.integers(-127, 128, (30, 128)), jnp.int8)
        s = jnp.ones((30,), jnp.float32)
        with pytest.raises(ValueError, match="unsupported"):  # rows % 32
            dequant_rows(q, s, interpret=True)
        assert not rows_supported(64, 100)            # H not lane-aligned
        assert not rows_supported(1 << 16, 128)       # over the VMEM cap
        assert rows_supported(64, 128)


# ----------------------------------------------------------- fused epilogue
class TestFusedEpilogue:
    def _params(self):
        return EnsembleParams.from_config(Config(), list(MODEL_NAMES))

    def test_parity_all_strategies(self, rng):
        base = self._params()
        preds = jnp.asarray(rng.uniform(0, 1, (32, 5)), jnp.float32)
        valid = jnp.asarray(rng.uniform(0, 1, (32, 5)) > 0.25)
        rule = jnp.asarray(rng.uniform(0, 1, (32,)), jnp.float32)
        for strat in range(3):
            params = base.replace(strategy=strat)
            ref = epilogue_reference(preds, valid, rule, params)
            got = fused_epilogue(preds, valid, rule, params, interpret=True)
            np.testing.assert_allclose(
                np.asarray(got["fraud_probability"]),
                np.asarray(ref["fraud_probability"]), atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(got["model_contributions"]),
                np.asarray(ref["model_contributions"]), atol=1e-6)
            for key in ("decision", "risk_level", "rule_decision",
                        "rule_risk"):
                assert bool(jnp.all(got[key] == ref[key])), (strat, key)

    def test_masked_rung_equality_all_ladder_levels(self, rng):
        """Satellite pin: the on-chip blend under every QoS ladder rung's
        validity mask matches the host reference exactly on the ladders —
        including the rules_only rung's all-invalid blend."""
        params = self._params()
        preds = jnp.asarray(rng.uniform(0, 1, (24, 5)), jnp.float32)
        rule = jnp.asarray(rng.uniform(0, 1, (24,)), jnp.float32)
        assert len(LADDER_LEVELS) == 4
        for level in LADDER_LEVELS:
            mask = jnp.asarray([n not in level.dropped_branches
                                for n in MODEL_NAMES])
            ref = epilogue_reference(preds, mask, rule, params)
            got = fused_epilogue(preds, mask, rule, params, interpret=True)
            np.testing.assert_allclose(
                np.asarray(got["fraud_probability"]),
                np.asarray(ref["fraud_probability"]), atol=1e-6)
            for key in ("decision", "risk_level", "rule_decision",
                        "rule_risk"):
                assert bool(jnp.all(got[key] == ref[key])), (level.name, key)

    def test_unsupported_shape_raises(self, rng):
        params = self._params()
        preds = jnp.zeros((0, 5), jnp.float32)
        with pytest.raises(ValueError, match="unsupported"):
            fused_epilogue(preds, jnp.ones((5,), bool),
                           jnp.zeros((0,), jnp.float32), params,
                           interpret=True)
        assert not epilogue_supported(0, 5)
        assert not epilogue_supported((1 << 16) + 1, 5)
        assert epilogue_supported(512, 5)


# ---------------------------------------------------------- flash attention
class TestFlashAttention:
    def test_parity_masked(self, rng):
        q, k, v = (jnp.asarray(rng.standard_normal((2, 4, 128, 64)),
                               jnp.float32) for _ in range(3))
        mask = jnp.asarray(rng.uniform(0, 1, (2, 128)) > 0.1)
        got = flash_attention(q, k, v, mask, interpret=True)
        ref = attention_reference(q, k, v, mask)
        assert float(jnp.abs(got - ref).max()) <= 5e-5

    def test_indivisible_blocks_raise(self, rng):
        q, k, v = (jnp.asarray(rng.standard_normal((1, 2, 120, 32)),
                               jnp.float32) for _ in range(3))
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, interpret=True)


# ----------------------------------------------------------- config surface
class TestKernelSettings:
    def test_validation(self):
        with pytest.raises(ValueError):
            KernelSettings(dequant_matmul="cuda").validate()
        with pytest.raises(ValueError):
            KernelSettings(attention="paged").validate()
        KernelSettings.full().validate()

    def test_disabled_plane_reports_off_modes(self):
        s = KernelSettings(dequant_matmul="pallas", epilogue="pallas",
                           attention="flash")       # enabled=False gates all
        assert s.site_modes() == {"dequant_matmul": "off",
                                  "epilogue": "off",
                                  "attention": "reference",
                                  "megakernel": "off"}
        assert KernelSettings.full().site_modes() == {
            "dequant_matmul": "pallas", "epilogue": "pallas",
            "attention": "flash", "megakernel": "off"}
        assert KernelSettings.mega().site_modes() == {
            "dequant_matmul": "pallas", "epilogue": "pallas",
            "attention": "flash", "megakernel": "pallas"}

    def test_config_overlay_round_trip(self, tmp_path):
        p = tmp_path / "k.json"
        p.write_text(json.dumps({"kernels": {"enabled": True,
                                             "attention": "flash"}}))
        loaded = Config.from_file(str(p)).kernels
        assert loaded.enabled and loaded.attention == "flash"
        assert loaded.dequant_matmul == "off"       # per-site independence


# --------------------------------------------------------- scorer threading
class TestScorerKernelPlane:
    def test_off_by_default_statics_are_legacy(self):
        _, s = _scorer(kernels=False, quant=False)
        assert s.kernel_static() == {"dequant_kernel": "off",
                                     "epilogue_kernel": "off",
                                     "kernel_interpret": False,
                                     "megakernel": "off",
                                     "mega_valid": None}
        assert s.effective_use_pallas() == bool(s.sc.use_pallas)
        assert s.kernel_snapshot()["dispatch"] == {
            "dequant_matmul": 0, "epilogue": 0, "attention": 0,
            "megakernel": 0}

    def test_kernel_statics_on(self):
        _, s = _scorer()
        static = s.kernel_static()
        assert static["dequant_kernel"] == "pallas"
        assert static["epilogue_kernel"] == "pallas"
        assert static["kernel_interpret"] is True   # no TPU in CI
        assert s.effective_use_pallas()             # flash selected

    def test_score_parity_and_zero_flips(self):
        (gen_a, off), (gen_b, on) = (_scorer(kernels=False),
                                     _scorer(kernels=True))
        ra = off.score_batch(gen_a.generate_batch(2 * BATCH), now=1000.0)
        rb = on.score_batch(gen_b.generate_batch(2 * BATCH), now=1000.0)
        pa = np.asarray([r["fraud_probability"] for r in ra])
        pb = np.asarray([r["fraud_probability"] for r in rb])
        assert np.max(np.abs(pa - pb)) < 1e-3
        assert [r["decision"] for r in ra] == [r["decision"] for r in rb]
        assert [r["risk_level"] for r in ra] == \
            [r["risk_level"] for r in rb]

    def test_dispatch_counters_with_zero_fallbacks(self):
        gen, s = _scorer()
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        snap = s.kernel_snapshot()
        assert snap["interpret"] is True
        # full() leaves the megakernel site off — the per-site chain runs
        assert all(snap["dispatch"][site] == 2 for site in snap["dispatch"]
                   if site != "megakernel")
        assert snap["dispatch"]["megakernel"] == 0
        assert all(v == 0 for v in snap["fallback"].values())

    def test_f32_params_count_dequant_fallback(self):
        """Honesty pin: kernels on over an f32 (unquantized) scorer — the
        dequant site has no int8 layout to fuse, so every launch counts a
        dispatch AND a fallback; the other sites stay clean."""
        gen, s = _scorer(quant=False)
        assert not is_quantized_bert(s.models.bert)
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        snap = s.kernel_snapshot()
        assert snap["dispatch"]["dequant_matmul"] == 1
        assert snap["fallback"]["dequant_matmul"] == 1
        assert snap["fallback"]["epilogue"] == 0
        assert snap["fallback"]["attention"] == 0


# -------------------------------------------------------- kernel_* metrics
class TestSyncKernels:
    def test_counter_delta_mirror_and_exhaustive_modes(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        gen, s = _scorer()
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        m = MetricsCollector()
        m.sync_kernels(s.kernel_snapshot())
        m.sync_kernels(s.kernel_snapshot())     # re-sync: NOT double-counted
        assert m.kernel_dispatches.value(site="epilogue") == 1.0
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        m.sync_kernels(s.kernel_snapshot())
        assert m.kernel_dispatches.value(site="epilogue") == 2.0
        assert m.kernel_fallbacks.value(site="dequant_matmul") == 0.0
        # site-mode gauges are exhaustive: the inactive mode reads 0
        assert m.kernel_site_mode.value(site="epilogue", mode="pallas") == 1.0
        assert m.kernel_site_mode.value(site="epilogue", mode="off") == 0.0
        assert m.kernel_site_mode.value(site="attention",
                                        mode="flash") == 1.0
        assert m.kernel_site_mode.value(site="attention",
                                        mode="reference") == 0.0
        assert m.kernel_interpret.value() == 1.0

    def test_stream_and_serving_render_identical(self):
        from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector

        gen, s = _scorer()
        s.score_batch(gen.generate_batch(BATCH), now=1000.0)
        snap = s.kernel_snapshot()
        a, b = MetricsCollector(), MetricsCollector()
        a.sync_kernels(snap)
        b.sync_kernels(snap)

        def kernel_lines(mc):
            return [ln for ln in mc.render_prometheus().splitlines()
                    if ln.startswith("kernel_")]

        assert kernel_lines(a) and kernel_lines(a) == kernel_lines(b)
        text = a.render_prometheus()
        assert 'kernel_site_mode{mode="pallas",site="epilogue"} 1' in text \
            or 'kernel_site_mode{site="epilogue",mode="pallas"} 1' in text
        assert "kernel_dispatch_total" in text


# ------------------------------------------------------- checkpoint hygiene
class TestCheckpointKernelHygiene:
    def test_manifest_carries_no_kernel_stamp(self, tmp_path):
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        _, s = _scorer()
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(1, params=s.models)
        manifest = mgr.manifest(1)
        assert not any("kernel" in key for key in manifest)
        assert manifest["quant_mode"] == {"bert_weights": "int8"}

    def test_restore_round_trips_identically_kernels_on_off(self, tmp_path):
        """Kernel selection is runtime config: one checkpoint restores
        into kernels-on and kernels-off scorers alike, each keeps its own
        (unserialized) kernel selection, and both serve the same
        decisions."""
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        _, src = _scorer(kernels=False, seed=0)
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(2, params=src.models)

        gen_off, off = _scorer(kernels=False, seed=9)
        gen_on, on = _scorer(kernels=True, seed=9)
        assert mgr.restore_into_scorer(off).step == 2
        assert mgr.restore_into_scorer(on).step == 2
        # the restore moved params only — each side's kernel plane stands
        assert off.kernel_static()["epilogue_kernel"] == "off"
        assert on.kernel_static()["epilogue_kernel"] == "pallas"
        ra = off.score_batch(gen_off.generate_batch(BATCH), now=1000.0)
        rb = on.score_batch(gen_on.generate_batch(BATCH), now=1000.0)
        assert [r["decision"] for r in ra] == [r["decision"] for r in rb]
        pa = np.asarray([r["fraud_probability"] for r in ra])
        pb = np.asarray([r["fraud_probability"] for r in rb])
        assert np.max(np.abs(pa - pb)) < 1e-3


# ------------------------------------------------- pool / mesh composition
class TestPoolMeshComposition:
    def test_pooled_kernels_bit_identical_to_serial(self):
        gen_a, serial = _scorer()
        gen_b, pooled = _scorer()
        DevicePool(pooled, inflight_depth=2)
        batches_a = [gen_a.generate_batch(BATCH) for _ in range(4)]
        batches_b = [gen_b.generate_batch(BATCH) for _ in range(4)]
        pend_a = [serial.dispatch(b, now=1000.0) for b in batches_a]
        want = [_rows(serial.finalize(p, now=1000.0)) for p in pend_a]
        pend_b = [pooled.dispatch(b, now=1000.0) for b in batches_b]
        got = [_rows(pooled.finalize(p, now=1000.0)) for p in pend_b]
        assert got == want
        snap = pooled.kernel_snapshot()
        assert all(v == 0 for v in snap["fallback"].values())

    def test_pool_hot_swap_no_mixed_kernel_batch(self):
        """Replica-by-replica hot swap under the score lock with the
        kernel plane on: the swapped-in f32 params are re-quantized so
        the fused dequant kernel keeps engaging (zero fallbacks), and the
        pooled sequence stays bit-identical to a serial scorer running
        the SAME dispatch/swap/dispatch interleaving."""
        from realtime_fraud_detection_tpu.scoring.pipeline import (
            init_scoring_models,
        )

        sides = []
        for use_pool in (False, True):
            gen, s = _scorer()
            if use_pool:
                DevicePool(s, inflight_depth=2)
            fresh = init_scoring_models(jax.random.PRNGKey(42),
                                        bert_config=s.bert_config,
                                        feature_dim=s.sc.feature_dim,
                                        node_dim=s.sc.node_dim)
            batches = [gen.generate_batch(BATCH) for _ in range(3)]
            out = _rows(s.finalize(s.dispatch(batches[0], now=1000.0),
                                   now=1000.0))
            s.set_models(fresh)         # fans out under the score lock
            assert is_quantized_bert(s.models.bert)
            pend = [s.dispatch(b, now=1000.0) for b in batches[1:]]
            for p in pend:
                out.extend(_rows(s.finalize(p, now=1000.0)))
            assert all(v == 0 for v in
                       s.kernel_snapshot()["fallback"].values())
            sides.append(out)
        assert sides[0] == sides[1]

    @staticmethod
    def _pipelined(scorer, batches):
        """Depth-2 pipelined drive: two launches in flight before the
        first finalize, never out-dispatching an attached executor's
        slots (a single-threaded dispatcher past depth would deadlock by
        design) — the SAME interleaving on reference and meshed sides so
        state evolution matches step for step."""
        from collections import deque

        pend, got = deque(), []
        for b in batches:
            pend.append(scorer.dispatch(b, now=1000.0))
            if len(pend) >= 2:
                got.append(_rows(scorer.finalize(pend.popleft(),
                                                 now=1000.0)))
        while pend:
            got.append(_rows(scorer.finalize(pend.popleft(), now=1000.0)))
        return got

    def test_mesh_executor_kernels_pipelined_depth2(self):
        gen_a, ref = _scorer(one_device=True)
        want = self._pipelined(
            ref, [gen_a.generate_batch(BATCH) for _ in range(3)])

        gen_b, meshed = _scorer(one_device=True)
        MeshExecutor(meshed, model_axis=2, inflight_depth=2,
                     shard_branches=("bert_text",))
        got = self._pipelined(
            meshed, [gen_b.generate_batch(BATCH) for _ in range(3)])
        assert got == want
        snap = meshed.kernel_snapshot()
        assert snap["dispatch"]["dequant_matmul"] == 3
        assert all(v == 0 for v in snap["fallback"].values())


# ----------------------------------------------------------------- CLI
class TestCliFlags:
    def test_parse_kernel_flags(self):
        from realtime_fraud_detection_tpu.cli import build_parser

        p = build_parser()
        assert p.parse_args(["run-job", "--kernels"]).kernels is True
        assert p.parse_args(["serve", "--kernels"]).kernels is True
        assert p.parse_args(["bench", "--kernels"]).kernels is True
        args = p.parse_args(["kernel-drill", "--fast", "--no-replay",
                             "--seed", "5"])
        assert args.fast and args.no_replay and args.seed == 5


def test_kernel_drill_fast_smoke():
    """Tier-1 acceptance: `rtfd kernel-drill --fast` runs un-slow-marked
    on every pass — divergence below the measured bf16 calibration-noise
    bound, zero decision flips, exact masked rungs, per-kernel parity,
    every site dispatched with zero fallbacks (replay runs in the full
    drill; the fast smoke pins the gates themselves). Runs as a real CLI
    subprocess in the single-device serving env (the netfault/elastic
    drill-CLI convention): the harness's 8-virtual-device mesh exists for
    sharding tests and makes interpret-mode Pallas pay ~2.6x for nothing
    this drill measures."""
    import os
    import pathlib
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    proc = subprocess.run(
        [sys.executable, "-m", "realtime_fraud_detection_tpu",
         "kernel-drill", "--fast", "--no-replay"],
        capture_output=True, text=True, timeout=600,
        cwd=str(pathlib.Path(__file__).resolve().parents[1]), env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout.strip().splitlines()
    compact = json.loads(out[-1])               # final line: compact verdict
    assert len(out[-1].encode()) < 2048
    assert compact["passed"] is True
    checks = compact["checks"]
    assert checks["divergence_below_noise"]
    assert checks["zero_decision_flips"]
    assert checks["masked_rungs_equal"]
    assert checks["rules_only_exact"]
    assert checks["dequant_matmul_parity"]
    assert checks["dequant_rows_parity"]
    assert checks["epilogue_parity"]
    assert checks["attention_parity"]
    assert checks["all_sites_dispatched"]
    assert checks["zero_fallbacks"]
    full = json.loads(out[-2])                  # preceding line: full result
    assert full["divergence"]["decision_flips"] == 0
    assert full["divergence"]["max"] <= \
        full["divergence"]["noise_scale"] * \
        full["divergence"]["noise_floor"]["bound"]
    assert full["modes"]["off"]["epilogue"] == "off"
    assert full["modes"]["on"]["epilogue"] == "pallas"

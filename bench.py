"""Benchmark: fused 5-branch ensemble scoring on one TPU chip.

Prints ONE JSON line: the headline metric is full-ensemble scoring throughput
(transactions/sec/chip) at microbatch 256, with p50/p99 scoring latency at
batch 1/32/256 attached (BASELINE.json driver metric). ``vs_baseline``
compares against the reference's claimed 15,000 TPS sustained for its entire
multi-node cluster (reference README.md:201) — our number is one chip.

Timing discipline (axon tunnel): everything is measured with
``block_until_ready`` BEFORE any device->host result pull — the first
transfer drops the tunnel into synchronous mode and would poison later
configs.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
    from realtime_fraud_detection_tpu.models.bert import BertConfig
    from realtime_fraud_detection_tpu.scoring import (
        MODEL_NAMES,
        ScorerConfig,
        init_scoring_models,
        make_example_batch,
        score_fused,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    on_tpu = jax.devices()[0].platform != "cpu"
    # Real DistilBERT-base dimensions for the text branch (config.py:165-170),
    # trimmed to 4 layers on CPU so local runs stay tractable.
    bert_config = BertConfig() if on_tpu else BertConfig(num_layers=2)
    sc = ScorerConfig(text_len=64, use_pallas=False)

    models = init_scoring_models(
        jax.random.PRNGKey(0), bert_config=bert_config,
        feature_dim=sc.feature_dim, node_dim=sc.node_dim,
    )
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    model_valid = jnp.ones((len(MODEL_NAMES),), bool)

    fn = jax.jit(
        lambda m, b, p, v: score_fused(
            m, b, p, v, bert_config=bert_config, use_pallas=sc.use_pallas,
            with_model_preds=False,
        )
    )

    lat: dict[int, dict[str, float]] = {}
    batches: dict[int, object] = {}
    for bsz, iters in ((1, 200), (32, 100), (256, 50)):
        batch = make_example_batch(bsz, sc, rng=np.random.default_rng(bsz))
        batches[bsz] = batch
        out = fn(models, batch, params, model_valid)   # compile
        jax.block_until_ready(out)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            out = fn(models, batch, params, model_valid)
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        times_ms = np.asarray(times) * 1e3
        lat[bsz] = {
            "p50_ms": float(np.percentile(times_ms, 50)),
            "p99_ms": float(np.percentile(times_ms, 99)),
        }

    # Throughput: pipelined dispatch at batch 256 — JAX's async dispatch
    # keeps the device fed while the host enqueues the next microbatch,
    # exactly how the production path runs (stream/microbatch.py
    # DoubleBufferedScorer). Per-dispatch round-trip latency (dominated by
    # the axon tunnel here, ~45 ms) is reported separately above; blocking
    # per batch would measure the tunnel, not the chip. The batch-256
    # program and example batch are already compiled + warm from the
    # latency sweep (selected explicitly — no reliance on loop ordering).
    bsz, iters = 256, 50
    batch = batches[bsz]
    t0 = time.perf_counter()
    outs = [fn(models, batch, params, model_valid) for _ in range(iters)]
    jax.block_until_ready(outs)
    pipelined_s = time.perf_counter() - t0
    throughput = bsz * iters / pipelined_s

    baseline_tps = 15_000.0  # reference README.md:201 (whole cluster)
    print(json.dumps({
        "metric": "full-ensemble scoring throughput (5 branches, batch=256, "
                  "pipelined)",
        "value": round(throughput, 1),
        "unit": "txn/s/chip",
        "vs_baseline": round(throughput / baseline_tps, 3),
        "latency": {str(k): v for k, v in lat.items()},
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()

"""Benchmark: the 5 BASELINE.json configs + latency decomposition, one chip.

Prints the full result JSON line, then a compact (<2 KB) machine-parseable
summary as the FINAL stdout line, and ALWAYS exits 0 — even when the TPU
relay is wedged. (The driver tail-parses the last line; the full result's
tens of KB used to get truncated mid-JSON — two rounds of ``parsed: null``.)

Budget contract (VERDICT r4 item 1): the WHOLE script fits in
``RTFD_BENCH_BUDGET_S`` (default 840 s ≈ 14 min) wall-clock, and a valid
JSON line lands on stdout no matter what:

- TPU probing is capped at 2 × 90 s attempts with a short gap (~3.5 min
  worst case), then the orchestrator moves on immediately.
- The inner bench receives the global deadline via env and writes a JSON
  snapshot to a side file after EVERY completed stage; stages are ordered
  headline-first so an early kill still leaves the 5 BASELINE configs.
- The parent keeps the best-known result in memory and installs
  SIGTERM/SIGALRM handlers that kill the child, print that JSON, and exit 0
  — an external timeout can never leave ``parsed: null`` again.
- If the TPU run dies or times out, its latest snapshot is recovered; a CPU
  fallback (clean backend, relay never touched) fills any configs the TPU
  partial is missing.

Architecture: the parent process is a jax-free orchestrator. It probes TPU
availability in a short-timeout subprocess (backend init on this host can
HANG, not just raise — the axon PJRT plugin wedges inside ``jax.devices()``),
then runs the actual bench as ``bench.py --inner`` in a child. CPU fallback
runs with ``PALLAS_AXON_POOL_IPS`` removed so the sitecustomize TPU
registration never happens.

Headline metric: full-ensemble scoring throughput (transactions/sec/chip,
batch=256, pipelined dispatch — how the production StreamJob /
DoubleBufferedScorer paths run). ``vs_baseline`` compares against the
reference's claimed 15,000 TPS sustained for its entire multi-node cluster
(reference README.md:201); our number is ONE chip.

Also reported:
- ``configs``: per-config txn/s/chip for each BASELINE.json config —
  XGB batch=1, XGB+IsolationForest µbatch=32, BERT encoder, LSTM,
  GraphSAGE + full ensemble (the reference's unbatched hot path analog is
  main.py:235-248, which loops batch=1).
- ``bucket_sweep``: the p99<20 ms operating-point table (VERDICT r4
  item 3) — per microbatch bucket {32, 64, 128, 256}: blocked-call
  p50/p99, the same net of the measured tunnel null RTT, the pipelined
  batch period, and sustained txn/s; ``passing`` names every bucket whose
  p99 net of transport meets the 20 ms budget. This is the measurement the
  reference's never-exercised TF-Serving batching config implies
  (k8s/manifests/ml-models-deployment.yaml:270-290).
- ``latency``: p50/p99 per batch size for the full ensemble, measured two
  ways: ``e2e`` (host-resident args, includes H2D + dispatch round-trip —
  what a caller over the axon tunnel sees) and ``device`` (device-resident
  args, isolates chip compute). The gap IS the tunnel/transfer cost.
- ``pallas``: DistilBERT-base branch with the Pallas flash-attention kernel
  vs plain XLA attention on this chip; the faster one is used for the
  headline ensemble program.
- ``mfu``: throughput-derived (batch / pipelined txn_per_s — no dispatch or
  cache artifact can inflate it) over analytic matmul FLOPs of ALL branches
  (BERT + LSTM + GNN matmuls; tree/iforest branches are gather/compare
  programs whose matmul FLOPs are genuinely ~0, recorded as such). An
  implausible value (outside (0, 1)) is REFUSED and reported as an error
  instead of a number (VERDICT r4 item 4).
- ``e2e_stream``: StreamJob soak over the in-memory broker (assemble +
  device + fan-out + commit, pipelined) — the whole-framework number, not
  just the device program.

Timing discipline (axon tunnel): everything is measured with
``block_until_ready`` BEFORE any device->host result pull — the first
transfer drops the tunnel into synchronous mode and would poison later
configs. See utils/timing.py.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

_T0 = time.monotonic()

BASELINE_TPS = 15_000.0  # reference README.md:201 (whole cluster)
METRIC_NAME = (
    "full-ensemble scoring throughput "
    "(5 branches, batch=256, text seq 64, pipelined)"
)
TOTAL_BUDGET_S = float(os.environ.get("RTFD_BENCH_BUDGET_S", "840"))
# reserved for the CPU fallback when the TPU path fails outright
CPU_RESERVE_S = 240.0
# the 5 BASELINE.json configs the driver's JSON must always contain
REQUIRED_CONFIGS = ("xgboost_batch1", "xgb_iforest_mb32", "bert_encoder",
                    "lstm_seq", "graphsage_full_ensemble")
# Per-chip bf16 peak for MFU accounting, by platform substring. Checked
# in order: the r1 chip printed as "TPU v5 lite0" (neither "v5e" nor
# "v5p"), so the lite spellings must come first (VERDICT r3 weak-6).
_PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5lite", 197.0), ("v5e", 197.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0), ("v5", 459.0), ("v4", 275.0),
)


def _log(msg: str) -> None:
    """Stage progress on stderr (stdout is reserved for the one JSON line)."""
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Orchestrator (jax-free: must never initialize a backend in this process)
# --------------------------------------------------------------------------

_BEST: dict = {"metric": METRIC_NAME, "value": 0.0, "unit": "txn/s/chip",
               "vs_baseline": 0.0, "device": "none",
               "error": "no stage completed"}
_CHILD = None          # active inner-bench Popen, killed by the emergency path
_EMITTED = False


def _compact_summary(result: dict) -> dict:
    """The driver-facing digest of a full bench result.

    Two rounds of BENCH_r*.json carried ``parsed: null`` because the driver
    captures only the stdout TAIL and the full result line (bucket sweeps,
    per-config latency tables, probe timelines) ran tens of KB — the line
    got truncated mid-JSON and nothing parsed. The FINAL stdout line is now
    this compact (<2 KB) summary; the full result is printed on the
    preceding stdout line and duplicated to stderr-adjacent logs.
    """
    cfgs = {
        name: cfg.get("txn_per_s")
        for name, cfg in (result.get("configs") or {}).items()
        if isinstance(cfg, dict)
    }
    sweep = result.get("bucket_sweep") or {}
    op = sweep.get("operating_point") or None
    e2e = result.get("e2e_stream") or {}
    quality = result.get("quality") or {}
    mfu = (result.get("mfu") or {}).get("mfu")
    ha = result.get("host_assembly") or {}
    overlap = ha.get("overlap") or {}
    ps = result.get("pool_scaling") or {}
    compact = {
        "metric": result.get("metric", METRIC_NAME),
        "value": result.get("value", 0.0),
        "unit": result.get("unit", "txn/s/chip"),
        "vs_baseline": result.get("vs_baseline", 0.0),
        "device": result.get("device", "none"),
        "partial": bool(result.get("partial", False)),
        "wall_s": result.get("wall_s"),
        "configs_txn_per_s": cfgs,
        "sweep_passing": sweep.get("passing"),
        "operating_point": ({"batch": op.get("batch"),
                             "txn_per_s": op.get("txn_per_s"),
                             "p99_net_of_rtt_ms": op.get(
                                 "p99_net_of_rtt_ms")}
                            if isinstance(op, dict) else None),
        # tuner-selected bucket set measured on the same sweep grid: the
        # reconciled second source of bucket truth (full detail in the
        # preceding line's bucket_sweep)
        "sweep_tuned": ({"set": sweep.get("tuned_set"),
                         "passing": sweep.get("tuned_set_passing"),
                         "operating_batch": (opt.get("batch")
                                             if isinstance(
                                                 opt := sweep.get(
                                                     "operating_point_tuned"),
                                                 dict) else None)}
                        if sweep.get("tuned_set") else None),
        "e2e_stream_txn_per_s": e2e.get("txn_per_s"),
        "pool_scaling": ({
            "n_devices": ps.get("n_devices"),
            "aggregate_txn_per_s": ps.get("aggregate_txn_per_s"),
            "per_device_txn_per_s": ps.get("per_device_txn_per_s"),
            "scaling_efficiency": ps.get("scaling_efficiency"),
            "error": (str(ps["error"])[:120] if ps.get("error") else None),
        } if ps else None),
        "mesh_scaling": ({
            "placements": {
                name: {"txn_per_s": p.get("txn_per_s"),
                       "per_chip_param_frac": p.get("per_chip_param_frac")}
                for name, p in (ms.get("placements") or {}).items()},
            "n_devices": ms.get("n_devices"),
            "error": (str(ms["error"])[:120] if ms.get("error") else None),
        } if (ms := result.get("mesh_scaling") or {}) else None),
        "host_assembly": ({
            "columnar_us_per_txn": ha.get("columnar_us_per_txn"),
            "serial_us_per_txn": ha.get("serial_us_per_txn"),
            "speedup_vs_serial": ha.get("speedup_vs_serial"),
            "overlap_ratio": overlap.get("overlap_ratio"),
        } if ha and not ha.get("error") else None),
        "trace_overhead": ({
            "on_off_ratio": to.get("on_off_ratio"),
            "on_us_per_txn": to.get("on_us_per_txn"),
            "p99_dominant_stage": to.get("p99_dominant_stage"),
        } if (to := result.get("trace_overhead") or {})
            and not to.get("error") else None),
        "autotune": ({
            "passed": at.get("passed"),
            "controller_p99_ms": at.get("controller_p99_ms"),
            "best_static_p99_ms": at.get("best_static_p99_ms"),
            "p99_improvement_vs_best_static": at.get(
                "p99_improvement_vs_best_static"),
        } if (at := result.get("autotune") or {})
            and not at.get("error") else None),
        "chaos": ({
            "passed": ch.get("passed"),
            "in_fault_p99_ms": ch.get("in_fault_p99_ms"),
            "in_fault_tps": ch.get("in_fault_tps"),
            "post_fault_p99_ms": ch.get("post_fault_p99_ms"),
            "post_fault_tps": ch.get("post_fault_tps"),
            "high_value_sheds": ch.get("high_value_sheds"),
        } if (ch := result.get("chaos") or {})
            and not ch.get("error") else None),
        "degraded_network": ({
            "passed": dn.get("passed"),
            "healthy_p99_ms": dn.get("healthy_p99_ms"),
            "healthy_tps": dn.get("healthy_tps"),
            "slow_link_p99_ms": dn.get("slow_link_p99_ms"),
            "slow_link_tps": dn.get("slow_link_tps"),
            "p99_ratio": dn.get("p99_ratio"),
            "fenced_produces": dn.get("fenced_produces"),
        } if (dn := result.get("degraded_network") or {})
            and not dn.get("error") else None),
        "graph_sampling": ({
            "sampler_cold_us_per_txn": (gs.get("micro") or {}).get(
                "sampler_cold_us_per_txn"),
            "sampler_cached_us_per_txn": (gs.get("micro") or {}).get(
                "sampler_cached_us_per_txn"),
            "remote_batch_amortization": (gs.get("micro") or {}).get(
                "remote_batch_amortization"),
            "ring_phase_lift": (gs.get("drill") or {}).get(
                "ring_phase_lift"),
            "ring_auc_graph_on": (gs.get("drill") or {}).get(
                "ring_auc_graph_on"),
            "ring_auc_incumbent": (gs.get("drill") or {}).get(
                "ring_auc_incumbent"),
            "passed": (gs.get("drill") or {}).get("passed"),
        } if (gs := result.get("graph_sampling") or {})
            and not gs.get("error") else None),
        "fleet_observability": ({
            "passed": fo.get("passed"),
            "overhead_ratio": fo.get("overhead_ratio"),
            "broker_transit_p99_ms": fo.get("broker_transit_p99_ms"),
            "stitch_rate": fo.get("stitch_rate"),
            "crossed_process": fo.get("crossed_process"),
            "carriers_lost": fo.get("carriers_lost"),
        } if (fo := result.get("fleet_observability") or {})
            and not fo.get("error") else None),
        "shard_scaling": ({
            "single_worker_txn_per_s": sh.get("single_worker_txn_per_s"),
            "aggregate_txn_per_s": sh.get("aggregate_txn_per_s"),
            "scaling_vs_single": sh.get("scaling_vs_single"),
            "scaling_efficiency": sh.get("scaling_efficiency"),
            "handoff_pause_s": (sh.get("handoff") or {}).get("pause_s"),
            "handoff_replayed": (sh.get("handoff") or {}).get("replayed"),
        } if (sh := result.get("shard_scaling") or {})
            and not sh.get("error") else None),
        "elastic_scaling": ({
            "aggregate_txn_per_s": el.get("aggregate_txn_per_s"),
            "scaling_vs_min": el.get("scaling_vs_min"),
            "scaling_efficiency": el.get("scaling_efficiency"),
            "kill_rebalance_pause_s": (el.get("kill_run")
                                       or {}).get("rebalance_pause_s"),
            "kill_replayed": (el.get("kill_run") or {}).get("replayed"),
        } if (el := result.get("elastic_scaling") or {})
            and not el.get("error") else None),
        "quantization": ({
            "bytes_ratio": (qz.get("param_bytes") or {}).get("ratio"),
            "bert_quant_us_per_txn": ((qz.get("branches") or {}).get(
                "bert_text") or {}).get("quant_us_per_txn"),
            "bert_speedup": ((qz.get("branches") or {}).get(
                "bert_text") or {}).get("speedup"),
            "trees_gemm_speedup": ((qz.get("branches") or {}).get(
                "xgboost_primary") or {}).get("speedup"),
            "max_divergence": max(
                (v for v in (qz.get("divergence") or {}).values()
                 if isinstance(v, (int, float))), default=None),
        } if (qz := result.get("quantization") or {})
            and not qz.get("error") else None),
        "kernel_fusion": ({
            **{name: {"pallas_us": k.get("pallas_interpret_us_per_txn"),
                      "xla_us": k.get("xla_reference_us_per_txn")}
               for name, k in (kf.get("kernels") or {}).items()},
            **({"mega_launches": {
                "chain": mk.get("programs_per_microbatch_chain"),
                "mega": mk.get("programs_per_microbatch_mega"),
                "hbm_bytes_eliminated":
                    mk.get("intermediate_hbm_bytes_eliminated"),
            }} if (mk := (kf.get("kernels") or {}).get("megakernel"))
                else {}),
        } if (kf := result.get("kernel_fusion") or {})
            and not kf.get("error") else None),
        "quality": ({"auc": quality.get("auc"),
                     "accuracy": quality.get("accuracy")}
                    if quality else None),
        "mfu": mfu,
        # compact arch stamp: layers x hidden / vocab @ seq (full record
        # in the preceding line's text_encoder)
        "text_encoder": (
            f"{te['num_layers']}x{te['hidden_size']}"
            f"/{te['vocab_size']}@{te['text_len']}"
            if (te := result.get("text_encoder")) else None),
        "summary_of": "full result JSON on the preceding stdout line",
    }
    if result.get("latest_committed_tpu_capture"):
        cap = result["latest_committed_tpu_capture"]
        headline = cap.get("headline")
        if isinstance(headline, dict):
            headline = headline.get("value", headline.get("txn_per_s"))
        compact["latest_committed_tpu_capture"] = {
            "round": cap.get("round"),
            "file": cap.get("file"),
            "headline_txn_per_s": headline,
        }
    if result.get("error"):
        compact["error"] = str(result["error"])[:300]
    # hard cap: the contract is < 2 KB, machine-parseable, on ONE line
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("configs_txn_per_s", "operating_point", "quality",
                       "host_assembly", "mesh_scaling", "pool_scaling",
                       "autotune", "chaos", "degraded_network",
                       "graph_sampling", "fleet_observability",
                       "shard_scaling",
                       "elastic_scaling", "quantization", "kernel_fusion",
                       "latest_committed_tpu_capture",
                       "text_encoder", "error"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": compact.get("metric"),
                       "value": compact.get("value"),
                       "device": compact.get("device")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact


def _emit_and_exit() -> None:
    """Print the full result, then the compact summary as the FINAL stdout
    line (the driver parses the last line; see _compact_summary), exactly
    once, and exit 0."""
    global _EMITTED
    if _EMITTED:
        os._exit(0)
    _EMITTED = True
    try:
        print(json.dumps(_BEST), flush=True)
        print(json.dumps(_compact_summary(_BEST), separators=(",", ":")),
              flush=True)
    finally:
        os._exit(0)


def _emergency(signum, frame) -> None:
    _log(f"signal {signum}: emitting best-known result and exiting")
    try:
        if _CHILD is not None and _CHILD.poll() is None:
            _CHILD.kill()
    except Exception:
        pass
    _emit_and_exit()


def _deadline() -> float:
    """Absolute monotonic deadline for the whole script."""
    return _T0 + TOTAL_BUDGET_S


def _remaining() -> float:
    return _deadline() - time.monotonic()


def _probe_tpu_once(timeout_s: float) -> tuple[str | None, str | None]:
    """(platform, error): init the backend in a throwaway subprocess."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM=' + d[0].platform, flush=True)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"backend init hang (probe timeout {timeout_s:.0f}s)"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return None, (tail[-1][:300] if tail else f"probe rc={proc.returncode}")
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe produced no PLATFORM line"


def _probe_tpu(attempts: int = 2, timeout_s: float = 90.0,
               gap_s: float = 20.0) -> tuple[str | None, list[dict]]:
    """Short, budget-bounded TPU probe: 2 × 90 s + one 20 s gap ≈ 3.5 min
    worst case (VERDICT r4 item 1 capped this from r4's 5 × 150 s + gaps,
    which alone could eat the driver's whole window)."""
    timeline: list[dict] = []
    for i in range(attempts):
        t0 = time.monotonic() - _T0
        platform, err = _probe_tpu_once(timeout_s)
        timeline.append({
            "attempt": i + 1, "t_s": round(t0, 1),
            "result": platform or f"fail: {err}",
        })
        if platform and platform != "cpu":
            return platform, timeline
        why = err if err is not None else f"got '{platform}' backend, not tpu"
        _log(f"TPU probe attempt {i + 1}/{attempts} failed ({why}); "
             f"{'retrying' if i + 1 < attempts else 'giving up'}")
        if i + 1 < attempts:
            time.sleep(gap_s)
    return None, timeline


def _read_snapshot(path: str) -> dict | None:
    try:
        with open(path) as f:
            snap = json.load(f)
        return snap if isinstance(snap, dict) and "metric" in snap else None
    except (OSError, ValueError):
        return None


def _run_inner(env: dict, timeout_s: float, snap_path: str) -> dict | None:
    """Run ``bench.py --inner``; return its final JSON, or — if it dies or
    times out — the latest per-stage snapshot it wrote (marked partial).

    stderr is inherited so per-stage progress streams to the driver log
    even if this parent is later killed.
    """
    global _CHILD
    env = dict(env)
    env["RTFD_BENCH_SNAPSHOT"] = snap_path
    env["RTFD_BENCH_DEADLINE_UNIX"] = str(time.time() + timeout_s - 10.0)
    _CHILD = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--inner"],
        stdout=subprocess.PIPE, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        stdout, _ = _CHILD.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _CHILD.kill()
        try:
            _CHILD.communicate(timeout=10.0)
        except Exception:
            pass
        _log(f"inner bench timed out after {timeout_s:.0f}s; "
             f"recovering last stage snapshot")
        snap = _read_snapshot(snap_path)
        if snap is not None:
            snap["partial"] = True
            snap.setdefault("error", "inner bench hit the time budget; "
                                     "result is the last completed stage")
        return snap
    finally:
        _CHILD = None
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed
    _log(f"inner bench rc={_CHILD.returncode if _CHILD else '?'} produced no "
         f"JSON line; recovering snapshot")
    snap = _read_snapshot(snap_path)
    if snap is not None:
        snap["partial"] = True
        snap.setdefault("error", "inner bench died; result is the last "
                                 "completed stage snapshot")
    return snap


def _cpu_env() -> dict:
    env = dict(os.environ)
    # Gate for the sitecustomize axon/TPU plugin registration: without it a
    # fresh interpreter never touches the (possibly wedged) relay.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["RTFD_BENCH_DEVICE_LABEL"] = "cpu-fallback"
    return env


def _attach_tpu_capture(result: dict) -> None:
    """When the relay is down at bench time, surface the newest committed
    on-chip capture so a wedged relay can't erase measured TPU performance.

    Named ``latest_committed_tpu_capture`` (it is the newest COMMITTED
    capture, possibly from an earlier round — the old ``same_round_``
    name overclaimed) with an explicit ``round`` parsed from the filename.
    """
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    captures = sorted(glob.glob(os.path.join(here, "BENCH_r*_tpu_capture.json")))
    if not captures:
        return
    try:
        with open(captures[-1]) as f:
            cap = json.load(f)
        fname = os.path.basename(captures[-1])
        m = re.match(r"BENCH_r(\d+)_tpu_capture", fname)
        result["latest_committed_tpu_capture"] = {
            "headline": cap.get("headline"),
            "file": fname,
            "round": int(m.group(1)) if m else None,
            "note": "newest committed capture from a live relay window "
                    "(NOT necessarily this round); see capture_note inside "
                    "the file for methodology, and MEASUREMENTS_r*.json "
                    "for the instrumented soak/sweep data",
        }
    except (OSError, ValueError):
        pass


def orchestrate() -> None:
    global _BEST
    signal.signal(signal.SIGTERM, _emergency)
    signal.signal(signal.SIGALRM, _emergency)
    # hard internal alarm: even if everything below wedges, a JSON line
    # lands before the driver's own timeout can produce rc=124/parsed:null
    signal.alarm(int(TOTAL_BUDGET_S) + 20)

    errors: list[str] = []
    result: dict | None = None
    snap_dir = tempfile.mkdtemp(prefix="rtfd_bench_")

    platform, timeline = _probe_tpu()
    if platform and platform != "cpu":
        budget = _remaining() - CPU_RESERVE_S
        _log(f"TPU probe ok (platform={platform}); "
             f"running bench on it (budget {budget:.0f}s)")
        if budget > 60:
            tpu_snap = os.path.join(snap_dir, "tpu.json")
            try:
                result = _run_inner(dict(os.environ), budget, tpu_snap)
            except Exception as e:  # noqa: BLE001 — must always emit JSON
                errors.append(f"tpu bench failed: {type(e).__name__}: {e}"[:300])
                _log(errors[-1])
            if result is not None:
                _BEST = dict(result)
        else:
            errors.append("tpu probed ok but no budget left for the bench")
    else:
        errors.append(
            f"tpu unavailable after {len(timeline)} probe attempts "
            f"(last: {timeline[-1]['result'] if timeline else 'none'})")
        _log(errors[-1])

    missing = [c for c in REQUIRED_CONFIGS
               if c not in (result or {}).get("configs", {})]
    if (result is None or missing) and _remaining() > 90:
        # CPU pass: either the whole bench (TPU path yielded nothing) or a
        # gap-filler for the configs the TPU partial is missing
        _log(f"running CPU fallback "
             f"({'full' if result is None else 'fill ' + ','.join(missing)}; "
             f"budget {_remaining() - 30:.0f}s)")
        cpu_snap = os.path.join(snap_dir, "cpu.json")
        cpu_res: dict | None = None
        try:
            cpu_res = _run_inner(_cpu_env(), max(60.0, _remaining() - 30.0),
                                 cpu_snap)
        except Exception as e:  # noqa: BLE001
            errors.append(f"cpu fallback failed: {type(e).__name__}: {e}"[:300])
            _log(errors[-1])
        if cpu_res is not None:
            if result is None:
                result = cpu_res
            else:
                # graft only the missing configs; tag their provenance
                for name in missing:
                    cfg = cpu_res.get("configs", {}).get(name)
                    if cfg is not None:
                        cfg = dict(cfg)
                        cfg["device"] = "cpu-fallback"
                        result.setdefault("configs", {})[name] = cfg
                still = [c for c in REQUIRED_CONFIGS
                         if c not in result.get("configs", {})]
                result["cpu_fill"] = {
                    "filled": [c for c in missing if c not in still],
                    "still_missing": still,
                }
                # the TPU partial may have died before its headline stage:
                # a zero headline with a measured CPU one must not ship as
                # value 0.0 — take the CPU number, labeled
                if (not result.get("value")) and cpu_res.get("value"):
                    result["value"] = cpu_res["value"]
                    result["vs_baseline"] = cpu_res.get("vs_baseline", 0.0)
                    result["value_device"] = "cpu-fallback"
            _BEST = dict(result)

    if result is None:
        result = {"metric": METRIC_NAME, "value": 0.0, "unit": "txn/s/chip",
                  "vs_baseline": 0.0, "device": "none"}
    result["probe_attempts"] = timeline
    result["wall_s"] = round(time.monotonic() - _T0, 1)
    history = _session_probe_history()
    if history:
        result["session_probe_history"] = history
    if result.get("device", "").startswith(("cpu", "none")):
        _attach_tpu_capture(result)
    if errors:
        existing = result.get("error")
        result["error"] = "; ".join(([existing] if existing else []) + errors)[:600]
    _BEST = result
    _emit_and_exit()


def _session_probe_history() -> dict | None:
    """Summarize /tmp/tpu_probe.log (a background probe loop retries the
    relay every ~5-10 min across the whole build session) so a full-round
    outage is evidenced by dozens of timestamped attempts, not just the
    bench-start probes."""
    try:
        with open("/tmp/tpu_probe.log") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    attempts = [ln for ln in lines if ln.startswith("[probe ")]
    successes = [ln for ln in lines if ln.startswith("PLATFORM ")]
    if not attempts:
        return None
    return {
        "attempts": len(attempts),
        "first": attempts[0],
        "last": attempts[-1],
        "successes": len(successes),
    }


# --------------------------------------------------------------------------
# Inner bench (the only process that imports jax)
# --------------------------------------------------------------------------

def _percentiles(times_s) -> dict:
    # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
    ms = np.asarray(times_s) * 1e3
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 3),
        "p99_ms": round(float(np.percentile(ms, 99)), 3),
        "max_ms": round(float(ms.max()), 3),
    }


def _time_blocked(fn, iters: int) -> list:
    """Shared discipline: see utils/timing.py (varied inputs, no pulls)."""
    from realtime_fraud_detection_tpu.utils.timing import time_blocked

    return time_blocked(fn, iters)


def _throughput_pipelined(fn, batch_size: int, iters: int) -> float:
    """Shared discipline: see utils/timing.py (varied inputs, no pulls)."""
    from realtime_fraud_detection_tpu.utils.timing import (
        throughput_pipelined,
    )

    return throughput_pipelined(fn, batch_size, iters)


def _null_rtt_ms(iters: int = 10) -> dict:
    """Measured floor of one blocked host->device->host round trip (a tiny
    h2d + add + block). On a tunneled TPU this is the network RTT every
    blocked call pays regardless of compute — recorded so latency numbers
    can be read against the transport floor they sit on."""
    import jax

    g = jax.jit(lambda x: x + 1)
    jax.block_until_ready(g(jax.device_put(np.float32(0))))
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(g(jax.device_put(np.float32(i))))
        ts.append(time.perf_counter() - t0)
    return _percentiles(ts)


def _ensemble_matmul_flops(bert_config, sc, batch: int) -> dict:
    """Analytic matmul FLOPs per fused-ensemble call (counting 2*M*N*K),
    itemized per branch so the accounting visibly covers all five.

    BERT dominates; LSTM/GNN are included; the tree and isolation-forest
    branches are gather/compare programs — their matmul FLOP count is
    genuinely 0 (they cost HBM gathers, not MXU cycles), recorded as such.
    """
    h, i_, l_, t = (bert_config.hidden_size, bert_config.intermediate_size,
                    bert_config.num_layers, sc.text_len)
    per_tok_layer = 2 * (4 * h * h + 2 * h * i_)      # qkv+o, ffn up+down
    attn = 2 * 2 * t * t * h                          # scores + weighted sum
    bert = l_ * (t * per_tok_layer + attn) + t * 2 * h * h  # + pooler-ish head
    lstm_h = 128
    lstm = sc.seq_len * 2 * (sc.feature_dim + lstm_h) * 4 * lstm_h
    gnn = 2 * (2 * sc.fanout * sc.node_dim * 64 + 3 * 64 * 64)  # rough, tiny
    return {
        "bert_text": float(batch * bert),
        "lstm_sequential": float(batch * lstm),
        "graph_neural": float(batch * gnn),
        "xgboost": 0.0,            # gather/compare over tree nodes
        "isolation_forest": 0.0,   # gather/compare over split tables
        "total": float(batch * (bert + lstm + gnn)),
    }


def run_bench() -> None:
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.ensemble.combine import (
        EnsembleParams,
        combine_predictions,
    )
    from realtime_fraud_detection_tpu.models.bert import BertConfig, bert_predict
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.lstm import lstm_logits
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_predict
    from realtime_fraud_detection_tpu.scoring import (
        MODEL_NAMES,
        ScorerConfig,
        init_scoring_models,
        make_example_batch,
        score_fused,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    # ---------------------------------------------------------- budget plumbing
    deadline_unix = float(os.environ.get("RTFD_BENCH_DEADLINE_UNIX", "0"))
    snap_path = os.environ.get("RTFD_BENCH_SNAPSHOT", "")

    def remaining() -> float:
        return (deadline_unix - time.time()) if deadline_unix else float("inf")

    result: dict = {"metric": METRIC_NAME, "value": 0.0, "unit": "txn/s/chip",
                    "vs_baseline": 0.0, "configs": {}, "partial": True}

    def snapshot(stage: str) -> None:
        result["last_stage"] = stage
        if not snap_path:
            return
        tmp = snap_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(result, f)
            os.replace(tmp, snap_path)
        except OSError:
            pass

    on_tpu = jax.devices()[0].platform != "cpu"
    device_label = os.environ.get("RTFD_BENCH_DEVICE_LABEL",
                                  str(jax.devices()[0]))
    result["device"] = device_label
    # Real DistilBERT-base dimensions for the text branch (config.py:165-170),
    # trimmed to 2 layers on CPU so fallback runs stay tractable.
    bert_config = BertConfig() if on_tpu else BertConfig(num_layers=2)
    sc = ScorerConfig(text_len=64)
    # record the EXACT text-encoder architecture these numbers were
    # measured with (VERDICT Weak #5: a bench model and a quality-artifact
    # model must be comparable by inspection, never by assumption)
    result["text_encoder"] = {
        "num_layers": bert_config.num_layers,
        "hidden_size": bert_config.hidden_size,
        "intermediate_size": bert_config.intermediate_size,
        "num_heads": bert_config.num_heads,
        "vocab_size": bert_config.vocab_size,
        "text_len": sc.text_len,
    }
    # Iteration scale: full on TPU; reduced on the CPU fallback so a wedged
    # relay still yields a complete JSON well inside the orchestrator budget.
    it = (lambda n: n) if on_tpu else (lambda n: max(3, n // 30))

    models = init_scoring_models(
        jax.random.PRNGKey(0), bert_config=bert_config,
        feature_dim=sc.feature_dim, node_dim=sc.node_dim,
    )
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    model_valid = jnp.ones((len(MODEL_NAMES),), bool)

    _log(f'start device={jax.devices()[0]} remaining={remaining():.0f}s')
    BUCKETS = (1, 32, 64, 128, 256)
    batches = {
        bsz: make_example_batch(bsz, sc, rng=np.random.default_rng(bsz))
        for bsz in BUCKETS
    }
    dev_batches = {b: jax.device_put(v) for b, v in batches.items()}
    dev_models = jax.device_put(models)
    jax.block_until_ready((dev_batches, dev_models))

    # K pre-staged input variants per batch size: every timed call cycles
    # through fresh buffers so no layer (jit, relay, transfer cache) can
    # serve a repeat. K=8 bounds the extra device memory to a few MB.
    K = 8
    var_feats = {
        b: [jax.device_put(batches[b].features + np.float32(j) * 1e-4)
            for j in range(K)]
        for b in BUCKETS
    }
    vocab = bert_config.vocab_size
    var_toks = [
        # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
        jax.device_put(((np.asarray(batches[256].token_ids) + j) % vocab)
                       .astype(np.int32))
        for j in range(K)
    ]
    var_hist = [
        jax.device_put(batches[256].history + np.float32(j) * 1e-4)
        for j in range(K)
    ]
    jax.block_until_ready((var_feats, var_toks, var_hist))
    rtt = _null_rtt_ms() if on_tpu else None
    result["tunnel_null_rtt_ms"] = rtt
    snapshot("staged")

    # ---------------------------------------------------- pallas vs XLA (BERT)
    # The repo's custom kernel (ops/attention.py) measured head-to-head on
    # this chip; the winner runs in the headline ensemble program.
    _log(f'batches staged on device; null round trip {rtt}')
    pallas_report = {}
    use_pallas = False
    tokm = dev_batches[256].token_mask
    bert_times = {}
    for flag in ((False, True) if on_tpu else (False,)):
        bfn = jax.jit(
            lambda p, t, m, _flag=flag: bert_predict(
                p, t, m, bert_config, use_pallas=_flag)
        )
        try:
            bert_times[flag] = _time_blocked(
                lambda i: bfn(dev_models.bert, var_toks[i % K], tokm), it(30))
        except Exception as e:  # pallas unavailable on this platform
            pallas_report["error"] = f"{type(e).__name__}: {e}"[:200]
    if True in bert_times:
        xla_ms = float(np.median(bert_times[False])) * 1e3
        pal_ms = float(np.median(bert_times[True])) * 1e3
        use_pallas = pal_ms < xla_ms
        pallas_report = {
            "xla_p50_ms": round(xla_ms, 3),
            "pallas_p50_ms": round(pal_ms, 3),
            "headline_uses_pallas": use_pallas,
        }
    result["pallas"] = pallas_report
    snapshot("pallas_ab")

    _log(f'pallas A/B done: {pallas_report}')
    fn = jax.jit(
        lambda m, b, p, v: score_fused(
            m, b, p, v, bert_config=bert_config, use_pallas=use_pallas,
            with_model_preds=False,
        )
    )

    # ------------------------------------------- headline + config 5 FIRST
    # (stage order is importance order: if the budget kills us early, the
    # snapshot already carries the headline and config table)
    db = dev_batches[256]
    headline_tp = round(_throughput_pipelined(
        lambda i: fn(dev_models, db.replace(features=var_feats[256][i % K]),
                     params, model_valid), 256, it(50)), 1)
    configs: dict = result["configs"]
    configs["graphsage_full_ensemble"] = {
        "batch": 256,
        "txn_per_s": headline_tp,
    }
    result["value"] = headline_tp
    result["vs_baseline"] = round(headline_tp / BASELINE_TPS, 3)
    _log(f'headline (config 5) done: {headline_tp} txn/s')
    snapshot("headline")

    # -------------------------------------------------------------------- MFU
    # Achieved matmul TFLOP/s of the fused batch=256 program against the
    # chip's bf16 peak. FLOPs are analytic (2*M*N*K per matmul, all five
    # branches itemized); time per batch is derived from the PIPELINED
    # throughput (batch/txn_per_s): with the device kept fed, the
    # steady-state batch period is bounded below by pure device compute, so
    # the resulting MFU is an honest lower bound that no transfer cache or
    # async-dispatch artifact can inflate (r3's blocked-call timing produced
    # an impossible 647% MFU through exactly such an artifact).
    flops = _ensemble_matmul_flops(bert_config, sc, 256)
    sec_per_batch = 256.0 / max(headline_tp, 1e-9)
    achieved_tflops = flops["total"] / sec_per_batch / 1e12
    peak = next((v for k, v in _PEAK_BF16_TFLOPS
                 if k in str(jax.devices()[0]).lower()), None)
    mfu_val = (achieved_tflops / peak) if peak else None
    mfu = {
        "matmul_flops_batch256_by_branch": flops,
        "sec_per_batch_pipelined": round(sec_per_batch, 6),
        "achieved_tflops": round(achieved_tflops, 3),
        "peak_bf16_tflops": peak,
        "method": "throughput-derived (batch / pipelined txn_per_s); "
                  "tree + iforest branches are gather/compare programs "
                  "with 0 matmul FLOPs by construction",
        "expected": "BERT-distil (6x768, seq 64) dominates at ~1.4 TFLOP "
                    "per 256-batch; at ~10k txn/s that is ~50 TFLOP/s — "
                    "tens of percent of a v5e peak, a latency-oriented "
                    "inference program, not a saturating training step",
    }
    # VERDICT r4 item 4: a bogus MFU must never be emitted. Outside (0, 1)
    # the number is refused and the violation itself is reported.
    if mfu_val is not None and not (0.0 < mfu_val < 1.0):
        mfu["mfu"] = None
        mfu["error"] = (f"implausible mfu {mfu_val:.4f} (must be in (0,1)) — "
                        f"refusing to report; timing or peak mapping is wrong")
    else:
        mfu["mfu"] = round(mfu_val, 4) if mfu_val is not None else None
    result["mfu"] = mfu
    snapshot("mfu")

    # ------------------------------------------- the other 4 BASELINE configs
    # 1. XGBoost batch=1 (the reference's unbatched hot path, main.py:235-248)
    tfn = jax.jit(lambda t, f: tree_ensemble_predict(t, f))
    configs["xgboost_batch1"] = {
        "latency": _percentiles(_time_blocked(
            lambda i: tfn(dev_models.trees, var_feats[1][i % K]), it(200))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: tfn(dev_models.trees, var_feats[1][i % K]),
            1, it(200)), 1),
    }
    snapshot("config1")
    _log('config 1 (xgb b=1) done')
    # 2. XGB + IsolationForest ensemble, microbatch=32
    v2 = jnp.asarray([True, False, False, False, True])

    def _xgb_if(trees, iforest, f):
        preds = jnp.stack(
            [tree_ensemble_predict(trees, f),
             jnp.zeros(f.shape[0]), jnp.zeros(f.shape[0]),
             jnp.zeros(f.shape[0]),
             iforest_predict(iforest, f)], axis=1)
        valid = jnp.broadcast_to(v2[None, :], preds.shape)
        return combine_predictions(preds, valid, params)

    xifn = jax.jit(_xgb_if)
    configs["xgb_iforest_mb32"] = {
        "batch": 32,
        "latency": _percentiles(_time_blocked(
            lambda i: xifn(dev_models.trees, dev_models.iforest,
                           var_feats[32][i % K]), it(100))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: xifn(dev_models.trees, dev_models.iforest,
                           var_feats[32][i % K]),
            32, it(200)), 1),
    }
    snapshot("config2")

    _log('config 2 (xgb+iforest mb32) done')
    # 3. BERT encoder -> fraud head (DistilBERT-base on TPU, seq 64)
    bfn = jax.jit(lambda p, t, m: bert_predict(
        p, t, m, bert_config, use_pallas=use_pallas))
    configs["bert_encoder"] = {
        "batch": 256,
        "latency": _percentiles(_time_blocked(
            lambda i: bfn(dev_models.bert, var_toks[i % K], tokm), it(50))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: bfn(dev_models.bert, var_toks[i % K], tokm),
            256, it(50)), 1),
        "layers": bert_config.num_layers,
        "hidden": bert_config.hidden_size,
    }
    snapshot("config3")

    # 4. LSTM per-user sequential model
    hlen = dev_batches[256].history_len
    lfn = jax.jit(lambda p, h, l: jax.nn.sigmoid(lstm_logits(p, h, l)))
    configs["lstm_seq"] = {
        "batch": 256,
        "latency": _percentiles(_time_blocked(
            lambda i: lfn(dev_models.lstm, var_hist[i % K], hlen), it(100))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: lfn(dev_models.lstm, var_hist[i % K], hlen),
            256, it(100)), 1),
    }
    snapshot("config4")
    _log('configs 1-5 done; all 5 BASELINE configs in the snapshot')

    # ------------------------------------------------- pool-scaling stage
    # Replicated multi-device dispatch (scoring/device_pool.py): aggregate
    # txn/s across every addressable device vs the single-device baseline
    # measured the same way. Pre-pull safe: slots drain via
    # block_until_ready (complete_no_fetch), never device_get, so on the
    # tunneled TPU this runs BEFORE the d2h phase without flipping the
    # relay into sync-dispatch mode. With 1 addressable device (the CPU
    # fallback) it degrades to a 1-replica measurement — the 8-virtual-
    # device CPU bar lives in `rtfd pool-drill`.
    if remaining() > 60:
        try:
            _pool_scaling_stage(result, models, sc, bert_config, use_pallas,
                                it, snapshot)
        except Exception as e:  # noqa: BLE001
            result["pool_scaling"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'pool-scaling stage done: '
             f'{ {k: v for k, v in (result.get("pool_scaling") or {}).items() if not isinstance(v, (dict, list))} }')

    # ------------------------------------------------- mesh-scaling stage
    # GSPMD data x model serving (scoring/mesh_executor.py): replicated vs
    # data-sharded vs data x model txn/s + per-chip param bytes from the
    # committed shardings. Pre-pull safe (complete_no_fetch only). On the
    # CPU fallback it always runs (the honest model-sharding-may-lose
    # number); on a tunneled TPU it is opt-in via --mesh so the relay
    # window's budget stays the operator's choice.
    if ((not on_tpu or os.environ.get("RTFD_BENCH_MESH") == "1")
            and remaining() > 60):
        try:
            _mesh_scaling_stage(result, models, sc, bert_config, use_pallas,
                                it, snapshot)
        except Exception as e:  # noqa: BLE001
            result["mesh_scaling"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'mesh-scaling stage done: '
             f'{ {k: v for k, v in (result.get("mesh_scaling") or {}).items() if not isinstance(v, (dict, list))} }')

    # ------------------------------------------------- host-assembly stage
    # Columnar vs record-at-a-time assemble throughput + cache hit rates +
    # (CPU) assembler-stage overlap. The assemble comparison is host-only
    # (feature extraction is pinned to the CPU backend), so it is safe in
    # the pre-pull regime and runs even when the TPU relay is down — the
    # CPU bench sees the host-plane win regardless of the accelerator.
    if remaining() > 45:
        try:
            _host_assembly_stage(result, on_tpu, remaining, snapshot)
        except Exception as e:  # noqa: BLE001
            result["host_assembly"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'host-assembly stage done: '
             f'{ {k: v for k, v in (result.get("host_assembly") or {}).items() if not isinstance(v, dict)} }')

    # ------------------------------------------------ trace-overhead stage
    # Tracing plane cost (obs/tracing.py): the same fixed fake-Kafka
    # workload scored with tracing off vs on; the per-txn wall-clock ratio
    # is the number the tier-1 overhead guard pins. CPU only — the traced
    # job's finalize pulls results (device_get), which would flip the
    # tunneled TPU into sync-dispatch mode in the pre-pull regime.
    if not on_tpu and remaining() > 60:
        try:
            _trace_overhead_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["trace_overhead"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'trace-overhead stage done: '
             f'{ {k: v for k, v in (result.get("trace_overhead") or {}).items() if not isinstance(v, dict)} }')

    # ----------------------------------------------------- autotune stage
    # Self-tuning host pipeline (tuning/): the deterministic drill's
    # canned diurnal+burst load replayed through the pinned static grid
    # and the JIT controller — static-best vs controller admitted p99 and
    # throughput. Pure virtual-clock host arithmetic (no device work), so
    # it is cheap and safe anywhere, but it reads as a host-plane result:
    # the on-chip p99 wins live in the sweep stages above.
    if remaining() > 45:
        try:
            _autotune_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["autotune"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'autotune stage done: '
             f'{ {k: v for k, v in (result.get("autotune") or {}).items() if not isinstance(v, dict)} }')

    # -------------------------------------------------------- chaos stage
    # Combined recovery drill (chaos/): fast config, single pass, in a
    # subprocess — the CLI parent re-execs the drill onto a virtual
    # multi-device CPU host platform, so this is safe on any box
    # (including a tunneled TPU session: the child never touches the
    # tunnel). Records degraded-mode throughput/p99 during vs after the
    # fault window; the drill and the tier-1 smoke pin the pass/fail bar.
    if remaining() > 90:
        try:
            _chaos_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["chaos"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'chaos stage done: '
             f'{ {k: v for k, v in (result.get("chaos") or {}).items() if not isinstance(v, dict)} }')

    # --------------------------------------------- degraded-network stage
    # Network fault plane (chaos/netfaults.py): one fast, no-replay pass
    # of the partition drill in a subprocess, reporting the slow-link
    # victim's scored-traffic p99 + txn/s on a healthy link vs inside
    # the seeded slow-link window (same shape as the chaos stage), plus
    # the broker's producer-generation fence counters. Real OS worker
    # processes on the CPU platform — safe on any box including a
    # tunneled TPU session.
    if remaining() > 90:
        try:
            _degraded_network_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["degraded_network"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'degraded-network stage done: '
             f'{ {k: v for k, v in (result.get("degraded_network") or {}).items() if not isinstance(v, dict)} }')

    # ----------------------------------------------- graph-sampling stage
    # Entity-graph plane (graph/): typed-sampler µs/txn cold vs cached +
    # remote-fetch amortization in-process, plus a fast no-replay
    # graph-drill subprocess reporting the ring-phase AUC lift of the
    # graph-on blend vs the trees-only incumbent. The drill subprocess is
    # pinned to the CPU platform — safe on any box including a tunneled
    # TPU session.
    if remaining() > 90:
        try:
            _graph_sampling_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["graph_sampling"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'graph-sampling stage done: '
             f'{ {k: v for k, v in ((result.get("graph_sampling") or {}).get("drill") or {}).items() if not isinstance(v, dict)} }')

    # ------------------------------------------ fleet-observability stage
    # Fleet observability plane (obs/): a fast no-replay obs-drill
    # subprocess — ≥2 real OS worker processes with producer-stamped
    # trace carriers — reporting the traced-vs-untraced overhead ratio,
    # stitched broker-transit p99, cross-process stitch rate, and the
    # netfault window's carrier-loss ledger. The subprocess is pinned to
    # the CPU platform — safe on any box including a tunneled TPU
    # session.
    if remaining() > 90:
        try:
            _fleet_observability_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["fleet_observability"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'fleet-observability stage done: '
             f'{ {k: v for k, v in (result.get("fleet_observability") or {}).items() if not isinstance(v, (dict, list))} }')

    # ------------------------------------------------ shard-scaling stage
    # Partition-parallel worker plane (cluster/): aggregate virtual txn/s
    # at 1/2/4 workers + the kill run's handoff pause, from the shard
    # drill's machinery at fast sizes. Pure host arithmetic on a virtual
    # clock — safe on any box, including a tunneled TPU session.
    if remaining() > 30:
        try:
            _shard_scaling_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["shard_scaling"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'shard-scaling stage done: '
             f'{ {k: v for k, v in (result.get("shard_scaling") or {}).items() if not isinstance(v, dict)} }')

    # ---------------------------------------------- elastic-scaling stage
    # Process-boundary cluster (cluster/procfleet.py): REAL aggregate
    # txn/s at 2/4/8 OS worker processes over the TCP netbroker +
    # network handoff, plus a SIGKILL run's rebalance pause and replay
    # depth. Workers are forced onto the CPU platform (host arithmetic
    # only), so this is safe on any box including a tunneled TPU
    # session — the subprocesses never touch the tunnel.
    if remaining() > 90:
        try:
            _elastic_scaling_stage(result, snapshot)
        except Exception as e:  # noqa: BLE001
            result["elastic_scaling"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'elastic-scaling stage done: '
             f'{ {k: v for k, v in (result.get("elastic_scaling") or {}).items() if not isinstance(v, dict)} }')

    # ------------------------------------------------- quantization stage
    # Quantized scoring plane (models/quant.py): per-branch f32-vs-quant
    # µs/txn, param bytes, divergence magnitudes. CPU only — the int8
    # calibration pulls the f32 weights host-side once, which would flip
    # a tunneled TPU into sync-dispatch mode in the pre-pull regime; the
    # on-chip quantized numbers come from the --quant relay switches.
    if not on_tpu and remaining() > 45:
        try:
            _quantization_stage(result, models, sc, bert_config,
                                use_pallas, it, snapshot)
        except Exception as e:  # noqa: BLE001
            result["quantization"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'quantization stage done: '
             f'{ {k: v for k, v in (result.get("quantization") or {}).items() if not isinstance(v, (dict, list))} }')

    # ------------------------------------------------ kernel-fusion stage
    # Pallas kernel plane (ops/): per-kernel µs/txn interpret-vs-XLA-
    # reference + the host finalize math the fused epilogue removes. CPU
    # only — interpret mode is the CPU serving path and the calibration
    # pulls weights host-side once; compiled on-chip numbers come from
    # the --kernels relay switches.
    if not on_tpu and remaining() > 30:
        try:
            _kernel_fusion_stage(result, models, sc, bert_config, it,
                                 snapshot)
        except Exception as e:  # noqa: BLE001
            result["kernel_fusion"] = {
                "error": f"{type(e).__name__}: {e}"[:200]}
        _log(f'kernel-fusion stage done: '
             f'{ {k: v for k, v in (result.get("kernel_fusion") or {}).items() if not isinstance(v, (dict, list))} }')

    # 3b. honest sequence lengths (VERDICT r3 missing-6): the reference
    # tokenizes at max_length 512 (bert_text_analyzer.py:201-202); seq 64
    # is the production truncation for short merchant/description strings.
    # Bench 128 everywhere and 512 on the real chip so the text branch's
    # cost at reference length is on the record.
    # CPU fallback runs the soak FIRST (no tunnel => no pull-ordering
    # constraint; quality is worth more than long-seq/sweep detail there)
    if not on_tpu and remaining() > 100:
        try:
            _e2e_soak(result, models, sc, bert_config, use_pallas, on_tpu,
                      remaining, snapshot)
        except Exception as e:
            result["e2e_stream"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    seq_variants = (128, 512) if (on_tpu and remaining() > 240) else \
                   ((128,) if remaining() > 180 else ())
    for seq_len in seq_variants:
        rng = np.random.default_rng(seq_len)
        toks_l = [jax.device_put(rng.integers(
            0, 30_000, (256, seq_len)).astype(np.int32)) for _ in range(K)]
        mask_l = jax.device_put(np.ones((256, seq_len), bool))
        configs[f"bert_encoder_seq{seq_len}"] = {
            "batch": 256,
            "latency": _percentiles(_time_blocked(
                lambda i: bfn(dev_models.bert, toks_l[i % K], mask_l),
                it(30))),
            "txn_per_s": round(_throughput_pipelined(
                lambda i: bfn(dev_models.bert, toks_l[i % K], mask_l),
                256, it(30)), 1),
        }
        snapshot(f"bert_seq{seq_len}")
    _log('long-seq BERT variants done')

    # ------------------------------------------ bucket sweep + latency decomp
    # VERDICT r4 item 3: the p99<20 ms operating point. For each microbatch
    # bucket: blocked-call latency (raw AND net of the measured tunnel null
    # RTT — the transport floor a local-PCIe deployment would not pay), the
    # pipelined batch period, and the throughput the bucket sustains.
    #
    # ORDERING CONTRACT: nothing before the `d2h` phase below may call
    # jax.device_get / np.asarray on a device array. On the axon tunnel the
    # FIRST device->host pull permanently flips the process into synchronous
    # round-trip dispatch (~70-170 ms per call) — real v5e PCIe has no such
    # mode, so every latency/throughput number must be captured in the
    # pre-pull regime to be representative of the hardware.
    lat: dict[str, dict] = {}
    sweep: dict[str, dict] = {}
    rtt_floor = (rtt or {}).get("p50_ms", 0.0)
    # Decision-relevant buckets FIRST (VERDICT r5 weak #6): 128/64 are the
    # ones expected to pass the 20 ms budget, and two rounds of driver runs
    # trimmed them because they sat at the tail — now a tight budget cuts
    # the least informative buckets, on the CPU fallback included.
    sweep_buckets = (128, 64, 32, 256, 1)
    # Reconcile the two sources of bucket truth (ISSUE 7 / PR 6 follow-on):
    # the online tuner picks a bucket SET from live arrivals (the autotune
    # stage above records the set its drill run settled on); the sweep's
    # static grid is the measured latency/throughput truth per bucket.
    # Sweep the union — tuned buckets not already in the grid ride along
    # (before the b=1 tail, after the decision-relevant sizes) — and the
    # result names both views so they can disagree loudly, not silently.
    tuned_set = tuple((result.get("autotune") or {})
                      .get("tuned_bucket_set") or ())
    extra = tuple(b for b in tuned_set if b not in sweep_buckets)
    if extra:
        sweep_buckets = sweep_buckets[:-1] + extra + sweep_buckets[-1:]
        _log(f'bucket sweep: adding tuned-set buckets {list(extra)}')
    for bsz in sweep_buckets:
        if remaining() < 60:
            _log(f'bucket sweep: budget exhausted before b={bsz}; '
                 f'trimming the tail')
            break
        _log(f'bucket sweep b={bsz}')
        iters = it(100 if bsz >= 128 else 150)
        host_b, dev_b = batches[bsz], dev_batches[bsz]

        # Variation must cover the byte-dominant leaves too (history is
        # ~45% of the payload): a transfer cache keyed on content would
        # otherwise still serve most of the repeated bytes.
        def _host_variant(i, hb=host_b):
            return hb.replace(
                features=hb.features + np.float32(i) * 1e-4,
                history=hb.history + np.float32(i) * 1e-4,
                token_ids=((hb.token_ids + i) % vocab).astype(np.int32),
            )

        device = _time_blocked(
            lambda i: fn(dev_models,
                         dev_b.replace(features=var_feats[bsz][i % K]),
                         params, model_valid), iters)
        tp = _throughput_pipelined(
            lambda i: fn(dev_models,
                         dev_b.replace(features=var_feats[bsz][i % K]),
                         params, model_valid), bsz, iters)
        dp = _percentiles(device)
        entry = {
            "batch": bsz,
            "blocked_p50_ms": dp["p50_ms"],
            "blocked_p99_ms": dp["p99_ms"],
            "p50_net_of_rtt_ms": round(max(dp["p50_ms"] - rtt_floor, 0.0), 3),
            "p99_net_of_rtt_ms": round(max(dp["p99_ms"] - rtt_floor, 0.0), 3),
            "pipelined_ms_per_batch": round(1e3 * bsz / max(tp, 1e-9), 3),
            "txn_per_s": round(tp, 1),
        }
        entry["meets_p99_20ms"] = entry["p99_net_of_rtt_ms"] < 20.0
        sweep[str(bsz)] = entry
        lat[str(bsz)] = {"device": dp}

        # host-resident e2e (includes H2D + dispatch round trip) for the
        # three canonical sizes only — it costs a full h2d per call
        if bsz in (1, 32, 256):
            e2e = _time_blocked(
                lambda i: fn(dev_models, _host_variant(i), params,
                             model_valid), min(iters, it(100)))
            h2d = []
            for i in range(min(iters, 50)):
                hb = _host_variant(i + 1000)
                t0 = time.perf_counter()
                jax.block_until_ready(jax.device_put(hb))
                h2d.append(time.perf_counter() - t0)
            lat[str(bsz)]["e2e"] = _percentiles(e2e)
            lat[str(bsz)]["h2d"] = _percentiles(h2d)
        snapshot(f"sweep_{bsz}")

    passing = [e for e in sweep.values() if e.get("meets_p99_20ms")]
    tuned_swept = [sweep[str(b)] for b in tuned_set if str(b) in sweep]
    tuned_passing = [e for e in tuned_swept if e.get("meets_p99_20ms")]
    result["bucket_sweep"] = {
        # the tuner's selected set, measured on the same grid: both bucket
        # truths in one table (static grid + tuned set), reconciled below
        "tuned_set": sorted(tuned_set),
        "tuned_set_passing": sorted(e["batch"] for e in tuned_passing),
        "operating_point_tuned": (
            max(tuned_passing, key=lambda e: e["txn_per_s"])
            if tuned_passing else None),
        "note": "p99 net of the measured tunnel null RTT (the transport "
                "floor; local-PCIe deployments do not pay it). The "
                "operating point is the largest passing bucket — latency "
                "budget met at the highest sustained throughput.",
        "rtt_floor_ms": rtt_floor,
        "buckets": sweep,
        "passing": sorted((e["batch"] for e in passing)),
        "operating_point": (max(passing, key=lambda e: e["txn_per_s"])
                            if passing else None),
    }
    result["latency"] = lat
    configs["graphsage_full_ensemble"]["latency"] = \
        lat.get("256", {}).get("device")
    snapshot("bucket_sweep")
    _log(f'bucket sweep done; passing buckets: '
         f'{result["bucket_sweep"]["passing"]}')

    # Derived device-resident batch period: batch / pipelined-throughput.
    # Blocked per-call latency on a tunneled chip is dominated by the ~85 ms
    # network RTT (see tunnel_null_rtt_ms); the pipelined period is the
    # honest "what the chip itself costs per batch" number a local host
    # would observe (real v5e PCIe round trips are microseconds).
    for cfg in configs.values():
        b = cfg.get("batch", 1)
        if cfg.get("txn_per_s"):
            cfg["ms_per_batch_pipelined"] = round(1e3 * b / cfg["txn_per_s"], 3)

    # ---------------------------------------------------------- d2h phase
    # The FIRST device->host pulls in this process — deliberately last (see
    # the ordering contract above): after these, the tunnel pins every
    # dispatch to synchronous round trips, which the e2e soak below (whose
    # scorer inherently pulls results per batch) already has to live with.
    if remaining() > 45:
        for bsz in (1, 32, 256):
            if str(bsz) not in lat:      # bucket skipped under low budget
                continue
            dev_b = dev_batches[bsz]
            d2h = []
            # several rounds of K fresh outputs: each Array is pulled exactly
            # once (a re-pull reads jax's cached _npy_value), and 3*K samples
            # keep the p99 from being a single worst pull
            for rnd in range(3):
                outs = [fn(dev_models,
                           dev_b.replace(
                               features=var_feats[bsz][j] + np.float32(rnd)),
                           params, model_valid) for j in range(K)]
                jax.block_until_ready(outs)
                for o in outs:
                    t0 = time.perf_counter()
                    # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
                    jax.device_get(o)
                    d2h.append(time.perf_counter() - t0)
            lat[str(bsz)]["d2h"] = _percentiles(d2h)
        snapshot("d2h")
        _log('d2h phase done (process now in tunnel sync-dispatch mode)')

        # native C++ tree kernel, the true CPU baseline for config 1 (pulls
        # the tree params to host, hence scheduled in the post-pull phase)
        try:
            from realtime_fraud_detection_tpu.native import NativeTreeScorer

            # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
            scorer_cpu = NativeTreeScorer(jax.device_get(models.trees))
            # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
            feats1 = np.asarray(batches[1].features)
            t0 = time.perf_counter()
            n_iters = it(2000)
            for _ in range(n_iters):
                scorer_cpu.predict(feats1)
            cpu_s = (time.perf_counter() - t0) / n_iters
            configs["xgboost_batch1"]["cpu_native_p50_ms"] = round(
                cpu_s * 1e3, 4)
        except Exception:
            pass

    # ------------------------------------------------------- e2e stream soak
    # Runs with TRAINED models so the soak measures the production pipeline,
    # and doubles as the detection-quality measurement: the reference CLAIMS
    # 96.8% accuracy with no benchmark harness (README.md:203, SURVEY.md §6);
    # this is a measured number on a stream with a known injected fraud mix.
    # (On the TPU it must run LAST: its result pulls flip the tunnel into
    # sync-dispatch mode. The CPU fallback already ran it earlier.)
    if "e2e_stream" not in result:
        if remaining() > 150.0:
            try:
                _e2e_soak(result, models, sc, bert_config, use_pallas,
                          on_tpu, remaining, snapshot)
            except Exception as e:
                result["e2e_stream"] = {
                    "error": f"{type(e).__name__}: {e}"[:200]}
        else:
            result["e2e_stream"] = {
                "skipped": f"budget ({remaining():.0f}s left < 150s soak "
                           f"minimum)"}

    result["partial"] = False
    snapshot("complete")
    _log(f'done: e2e_stream={result.get("e2e_stream")}; '
         f'quality={result.get("quality")}')
    print(json.dumps(result), flush=True)


def _pool_scaling_stage(result: dict, models, sc, bert_config,
                        use_pallas: bool, it, snapshot) -> None:
    """Replicated-dispatch scaling across all addressable devices.

    Measures aggregate pooled txn/s (round-robin, in-flight depth 2 per
    replica) and the same pool limited to ONE device, packed blobs in /
    no result pulls (pre-pull regime). The single-device fused-program
    numbers elsewhere in the bench are untouched — this stage only ADDS
    the multi-device view. The aggregate is REFUSED (error field instead
    of numbers) when any replica fell back to retry or dropped out of
    the rotation mid-measurement: a silently-degraded pool must never
    produce the headline scaling number.
    """
    from collections import deque

    import jax

    from realtime_fraud_detection_tpu.core.packing import pack_tree
    from realtime_fraud_detection_tpu.scoring import (
        DevicePool,
        FraudScorer,
        make_example_batch,
    )

    devices = jax.devices()
    batch = 256
    depth = 2
    base = make_example_batch(batch, sc, rng=np.random.default_rng(17))
    blobs, spec = pack_tree(base)
    # --quant (RTFD_BENCH_QUANT): measure the QUANTIZED pool — int8 BERT
    # replicas + GEMM-form tree kernels, the rtfd quant-drill gated
    # configuration — so one relay window captures f32 and quantized
    # scaling side by side. Calibration pulls the f32 weights host-side
    # once, HERE, before any timed dispatch.
    # --kernels (RTFD_BENCH_KERNELS): the same pool with the Pallas
    # kernel plane on (fused dequant-matmul + fused epilogue + flash
    # attention, the rtfd kernel-drill gated configuration); composes
    # with --quant so one relay window captures all four corners.
    # --mega (RTFD_BENCH_MEGA): the kernel plane's persistent-megakernel
    # mode (ONE program per microbatch, the kernel-drill --mega gated
    # configuration) — implies the kernel plane on.
    quantized = os.environ.get("RTFD_BENCH_QUANT") == "1"
    mega_on = os.environ.get("RTFD_BENCH_MEGA") == "1"
    kernels_on = os.environ.get("RTFD_BENCH_KERNELS") == "1" or mega_on
    if quantized or kernels_on:
        from realtime_fraud_detection_tpu.utils.config import (
            Config,
            KernelSettings,
            QuantSettings,
        )

        cfg = Config()
        if quantized:
            cfg.quant = QuantSettings.full()
        if kernels_on:
            cfg.kernels = (KernelSettings.mega() if mega_on
                           else KernelSettings.full())
        scorer = FraudScorer(cfg, models=models, scorer_config=sc,
                             bert_config=bert_config)
    else:
        scorer = FraudScorer(models=models, scorer_config=sc,
                             bert_config=bert_config)
    scorer.sc.use_pallas = use_pallas
    f32 = blobs["f32"]

    def blob_variant(i: int) -> dict:
        # vary the float payload so no transfer/jit layer can serve a
        # repeat (the utils/timing.py discipline)
        out = dict(blobs)
        out["f32"] = f32 + np.float32(i) * 1e-4
        return out

    def measure(devs, iters: int):
        pool = DevicePool(scorer, devices=devs, inflight_depth=depth)
        ens = scorer.ensemble_params
        mv = scorer.effective_model_valid()
        try:
            warm = [pool.dispatch_packed(blob_variant(j), spec, ens, mv)
                    for j in range(len(devs))]
            for t in warm:
                pool.complete_no_fetch(t)
            inflight: deque = deque()
            t0 = time.perf_counter()
            for i in range(iters):
                inflight.append(
                    pool.dispatch_packed(blob_variant(i), spec, ens, mv))
                while len(inflight) >= pool.total_slots():
                    pool.complete_no_fetch(inflight.popleft())
            while inflight:
                pool.complete_no_fetch(inflight.popleft())
            dt = time.perf_counter() - t0
        finally:
            scorer.attach_pool(None)
        return iters * batch / dt, pool.stats()

    iters = it(40)
    single_tp, single_st = measure(devices[:1], iters)
    entry: dict = {
        "batch": batch,
        "inflight_depth": depth,
        "n_devices": len(devices),
        "quantized": quantized,
        "kernels": kernels_on,
        "mega": mega_on,
        "single_device_txn_per_s": round(single_tp, 1),
    }
    if len(devices) == 1:
        entry["aggregate_txn_per_s"] = round(single_tp, 1)
        entry["per_device_txn_per_s"] = round(single_tp, 1)
        entry["scaling_efficiency"] = 1.0
        entry["note"] = ("1 addressable device: pooled == single; the "
                         "multi-replica CPU bar is `rtfd pool-drill`, the "
                         "multi-chip bar needs a TPU relay window")
    else:
        agg_tp, agg_st = measure(devices, it(40) * max(2, len(devices) // 2))
        # Refusal gate: a hard replica failure RAISES out of measure()
        # (complete_no_fetch never retries), landing in the stage's error
        # field — so the aggregate below can only exist for a clean run.
        # The healthy/retries checks are the belt for anything softer: a
        # replica dropped from rotation without failing a drained batch,
        # or a future pooled path that rescues instead of raising.
        degraded = (agg_st["retries"] > 0 or single_st["retries"] > 0
                    or agg_st["healthy"] < len(devices))
        if degraded:
            entry["error"] = (
                f"replica fallback during measurement (retries="
                f"{agg_st['retries']}, healthy={agg_st['healthy']}/"
                f"{len(devices)}): refusing to report a degraded "
                f"aggregate as the scaling headline")
            entry["stats"] = agg_st
        else:
            entry["aggregate_txn_per_s"] = round(agg_tp, 1)
            entry["per_device_txn_per_s"] = round(agg_tp / len(devices), 1)
            entry["scaling_efficiency"] = round(
                agg_tp / (len(devices) * single_tp), 3)
            entry["per_device_dispatched"] = [
                d["dispatched"] for d in agg_st["devices"]]
    result["pool_scaling"] = entry
    snapshot("pool_scaling")


def _mesh_scaling_stage(result: dict, models, sc, bert_config,
                        use_pallas: bool, it, snapshot) -> None:
    """GSPMD mesh-sharded serving throughput (scoring/mesh_executor.py).

    Three placements over the same packed microbatch stream, all
    pre-pull-safe (slots drain via complete_no_fetch — block_until_ready
    only, never device_get):

    - ``replicated``: one device, everything replicated (the baseline the
      other two are normalized against);
    - ``data_sharded``: one mesh over every addressable device, batch
      split over ``data``, params replicated;
    - ``data_x_model``: the same mesh reshaped to data x 2, BERT branch
      params STORED sharded over ``model`` and re-gathered at use.

    The honest caveat rides in the entry: model-sharding is an HBM bet
    (per-chip param bytes, reported from the committed shardings), not a
    CPU-throughput bet — the gather collective costs real time and on a
    virtual-device CPU host it usually LOSES, exactly like the GEMM-form
    tree kernels. The memory win is the number that must hold everywhere.
    """
    from collections import deque

    import jax

    from realtime_fraud_detection_tpu.core.packing import pack_tree
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        MeshExecutor,
        make_example_batch,
    )

    devices = jax.devices()
    batch = 256
    depth = 2
    base = make_example_batch(batch, sc, rng=np.random.default_rng(19))
    blobs, spec = pack_tree(base)
    quantized = os.environ.get("RTFD_BENCH_QUANT") == "1"
    if quantized:
        from realtime_fraud_detection_tpu.utils.config import (
            Config,
            QuantSettings,
        )

        scorer = FraudScorer(Config(quant=QuantSettings.full()),
                             models=models, scorer_config=sc,
                             bert_config=bert_config)
    else:
        scorer = FraudScorer(models=models, scorer_config=sc,
                             bert_config=bert_config)
    scorer.sc.use_pallas = use_pallas
    f32 = blobs["f32"]

    def blob_variant(i: int) -> dict:
        out = dict(blobs)
        out["f32"] = f32 + np.float32(i) * 1e-4
        return out

    def measure(iters: int, **kwargs):
        ex = MeshExecutor(scorer, inflight_depth=depth, **kwargs)
        ens = scorer.ensemble_params
        mv = scorer.effective_model_valid()
        try:
            warm = [ex.dispatch_packed(blob_variant(j), spec, ens, mv)
                    for j in range(max(2, len(ex)))]
            for t in warm:
                ex.complete_no_fetch(t)
            inflight: deque = deque()
            t0 = time.perf_counter()
            for i in range(iters):
                inflight.append(
                    ex.dispatch_packed(blob_variant(i), spec, ens, mv))
                while len(inflight) >= ex.total_slots():
                    ex.complete_no_fetch(inflight.popleft())
            while inflight:
                ex.complete_no_fetch(inflight.popleft())
            dt = time.perf_counter() - t0
        finally:
            scorer.attach_pool(None)
        bert_pb = ex.param_bytes()["bert_text"]
        return {
            "txn_per_s": round(iters * batch / dt, 1),
            "bert_param_bytes_per_chip": bert_pb["per_chip"],
            "bert_param_bytes_replicated": bert_pb["replicated"],
        }

    iters = it(30)
    entry: dict = {
        "batch": batch,
        "inflight_depth": depth,
        "n_devices": len(devices),
        "quantized": quantized,
        "note": ("model-sharding is an HBM/FLOPs bet like the GEMM-form "
                 "tree kernels: the per-chip param-byte shrink holds on "
                 "every backend; the throughput column only pays off "
                 "where HBM or per-chip FLOPs were the binding "
                 "constraint — on CPU it may lose to the gather cost"),
        "placements": {},
    }
    entry["placements"]["replicated"] = measure(
        iters, devices=devices[:1], model_axis=1, shard_branches=())
    if len(devices) > 1:
        entry["placements"]["data_sharded"] = measure(
            iters * 2, model_axis=1, shard_branches=())
        if len(devices) % 2 == 0:
            entry["placements"]["data_x_model"] = measure(
                iters * 2, model_axis=2, shard_branches=("bert_text",))
    else:
        entry["note"] += ("; 1 addressable device: sharded placements "
                          "need a multi-chip relay window (the 8-virtual-"
                          "device CPU bar is `rtfd mesh-drill`)")
    base_tps = entry["placements"]["replicated"]["txn_per_s"]
    for name, p in entry["placements"].items():
        p["vs_replicated"] = round(p["txn_per_s"] / max(base_tps, 1e-9), 3)
        p["per_chip_param_frac"] = round(
            p["bert_param_bytes_per_chip"]
            / max(p["bert_param_bytes_replicated"], 1), 4)
    result["mesh_scaling"] = entry
    snapshot("mesh_scaling")


def _host_assembly_stage(result: dict, on_tpu: bool, remaining,
                         snapshot) -> None:
    """Deterministic host-assembly measurement (ISSUE 2 acceptance gate).

    Reports assemble µs/txn for the columnar path vs the record-at-a-time
    baseline (``FraudScorer.assemble_serial`` — the reference's per-request
    loop cost profile, main.py:235-248) on identical record streams and
    identically seeded state, plus token/entity cache hit rates and the
    per-stage span breakdown. On CPU it additionally runs the overlapped
    assembler stage head-to-head against the serial loop and reports the
    overlap ratio (fraction of assembly wall-time hidden behind device
    compute); on the tunneled TPU that soak would flip the process into
    sync-dispatch mode, so it is skipped there (the e2e soak at the tail
    covers pipelining on-chip).
    """
    import time as _time

    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    def mk(seed: int = 3):
        gen = TransactionGenerator(num_users=2000, num_merchants=500,
                                   seed=seed)
        s = FraudScorer(scorer_config=ScorerConfig(tokenizer="wordpiece"))
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        return gen, s

    batch = 256
    n_col, n_ser = 17, 3
    gen, s = mk()
    batches = [gen.generate_batch(batch) for _ in range(n_col + 1)]
    s.assemble(batches[0])                      # warm (jit the extractor)
    t0 = _time.perf_counter()
    for b in batches[1:]:
        s.assemble(b)
    col_s = (_time.perf_counter() - t0) / n_col
    gen2, s2 = mk()
    batches2 = [gen2.generate_batch(batch) for _ in range(n_ser + 1)]
    s2.assemble_serial(batches2[0])
    t0 = _time.perf_counter()
    for b in batches2[1:]:
        s2.assemble_serial(b)
    ser_s = (_time.perf_counter() - t0) / n_ser
    stage = {
        "batch": batch,
        "tokenizer": "wordpiece",
        "columnar_us_per_txn": round(col_s / batch * 1e6, 2),
        "serial_us_per_txn": round(ser_s / batch * 1e6, 2),
        "speedup_vs_serial": round(ser_s / col_s, 2),
        "token_cache": s.tokenizer.cache_stats(),
        "entity_cache": s._join_cache.stats(),
        "spans_ms": {k: round(v["mean_ms"], 3)
                     for k, v in s.spans.stats().items()},
    }
    result["host_assembly"] = stage
    snapshot("host_assembly")

    if on_tpu or remaining() < 90:
        return
    # overlap drill (CPU only): same stream scored with and without the
    # background assembler stage; the ratio is how much of the assembly
    # wall-time the pipeline hid behind device compute. Failures here must
    # not discard the already-captured assemble measurements (the
    # acceptance-gate numbers above), so the drill errors into
    # stage["overlap"] instead of propagating.
    try:
        _host_assembly_overlap(stage, batch, snapshot)
    except Exception as e:  # noqa: BLE001
        stage["overlap"] = {"error": f"{type(e).__name__}: {e}"[:200]}


def _host_assembly_overlap(stage: dict, batch: int, snapshot) -> None:
    import time as _time

    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T

    def soak(overlap: bool):
        gen3 = TransactionGenerator(num_users=2000, num_merchants=500,
                                    seed=9)
        broker = InMemoryBroker()
        sc3 = FraudScorer(scorer_config=ScorerConfig(tokenizer="wordpiece"))
        sc3.seed_profiles(gen3.users.profiles(), gen3.merchants.profiles())
        job = StreamJob(broker, sc3, JobConfig(
            max_batch=batch, emit_features=False,
            overlap_assembly=overlap))
        recs = gen3.generate_batch(4096)
        broker.produce_batch(T.TRANSACTIONS, recs,
                             key_fn=lambda r: str(r["user_id"]))
        sc3.score_batch(gen3.generate_batch(batch))   # compile outside
        t0 = _time.perf_counter()
        job.run_until_drained(now=1000.0)
        wall = _time.perf_counter() - t0
        job.close()         # joins the stage thread: busy_s is final
        busy = job._stage.busy_s if job._stage is not None else 0.0
        return wall, busy

    wall_off, _ = soak(False)
    wall_on, busy_on = soak(True)
    stage["overlap"] = {
        "wall_serial_s": round(wall_off, 3),
        "wall_overlapped_s": round(wall_on, 3),
        "assembler_busy_s": round(busy_on, 3),
        "speedup": round(wall_off / max(wall_on, 1e-9), 3),
        # fraction of the background stage's busy time that vanished from
        # the wall clock: 1.0 = assembly fully hidden behind device compute
        "overlap_ratio": round(
            min(1.0, max(0.0, (wall_off - wall_on) / max(busy_on, 1e-9))),
            3),
    }
    snapshot("host_assembly_overlap")


def _trace_overhead_stage(result: dict, snapshot) -> None:
    """Tracing-plane overhead on the real stream path (ISSUE 5 bench
    satellite): one fixed fake-Kafka workload scored twice on identically
    seeded state — tracing off, then on — reporting per-txn wall-clock
    for both, the on/off ratio, and the traced run's p99 breakdown (the
    analyzer's output on real timings, as a sanity row). The drill and
    the tier-1 guard pin the bounds; the bench records the measurement.
    """
    import time as _time

    from realtime_fraud_detection_tpu.obs.tracing import Tracer
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.utils.config import TracingSettings

    batch, n_txn = 256, 4096

    def soak(traced: bool):
        gen = TransactionGenerator(num_users=2000, num_merchants=500,
                                   seed=11)
        broker = InMemoryBroker()
        s = FraudScorer(scorer_config=ScorerConfig(tokenizer="wordpiece"))
        s.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        tracer = Tracer(TracingSettings(enabled=True)) if traced else None
        job = StreamJob(broker, s, JobConfig(
            max_batch=batch, emit_features=False, tracing=tracer))
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(n_txn),
                             key_fn=lambda r: str(r["user_id"]))
        s.score_batch(gen.generate_batch(batch))      # compile outside
        t0 = _time.perf_counter()
        job.run_until_drained(now=1000.0)
        wall = _time.perf_counter() - t0
        return wall, tracer

    wall_off, _ = soak(False)
    wall_on, tracer = soak(True)
    bd = tracer.breakdown()
    p99 = bd["quantiles"].get("p99") or {}
    result["trace_overhead"] = {
        "batch": batch,
        "n_txn": n_txn,
        "off_us_per_txn": round(wall_off / n_txn * 1e6, 3),
        "on_us_per_txn": round(wall_on / n_txn * 1e6, 3),
        "on_off_ratio": round(wall_on / max(wall_off, 1e-9), 4),
        "traces_recorded": bd["n"],
        "p99_dominant_stage": p99.get("dominant_stage"),
        "p99_stage_ms": p99.get("stage_ms"),
    }
    snapshot("trace_overhead")


def _autotune_stage(result: dict, snapshot) -> None:
    """Self-tuning host pipeline (ISSUE 6 bench satellite): the drill's
    canned nonstationary load (fast config — deterministic, ~2 s of wall
    time) through every pinned static deadline AND the JIT controller.
    The drill and the tier-1 smoke pin the pass/fail bar; the bench
    records the measured static-best-vs-controller comparison."""
    from realtime_fraud_detection_tpu.tuning.drill import (
        AutotuneDrillConfig,
        run_autotune_drill,
    )

    s = run_autotune_drill(AutotuneDrillConfig.fast())
    ctrl = s["controller"]
    static_p99 = {k: v["p99_ms"] for k, v in s["static_grid"].items()}
    best_static = min(static_p99, key=static_p99.get)
    result["autotune"] = {
        "passed": s["passed"],
        "controller_p99_ms": ctrl["p99_ms"],
        "controller_p50_ms": ctrl["p50_ms"],
        "controller_tps": ctrl["throughput_tps"],
        "best_static": best_static,
        "best_static_p99_ms": static_p99[best_static],
        "static_p99_ms": static_p99,
        "p99_improvement_vs_best_static": round(
            1.0 - ctrl["p99_ms"] / max(static_p99[best_static], 1e-9), 4),
        "mean_batch": ctrl["mean_batch"],
        "close_reasons": ctrl["close_reasons"],
        "offered_n": s["offered"].get("n"),
        # the bucket set the online tuner settled on over the drill's
        # nonstationary load — fed into the bucket sweep so the two
        # sources of bucket truth reconcile in one table (ISSUE 7)
        "tuned_bucket_set": sorted(
            ctrl.get("tuning", {}).get("tuner", {}).get("bucket_set", [])),
    }
    snapshot("autotune")


def _chaos_stage(result: dict, snapshot) -> None:
    """Chaos plane (ISSUE 8 bench satellite): one fast, no-replay pass of
    the combined recovery drill in a subprocess, reporting degraded-mode
    service quality — scored-traffic p99 + virtual throughput inside the
    fault windows vs in the post-fault recovery phase — plus the fault
    ledger's headline counters. The chaos-drill CLI parent re-execs onto
    a virtual multi-device CPU platform, so the bench process's backend
    (TPU tunnel included) is never touched."""
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "chaos-drill", "--fast", "--no-replay"]
    # 600 > the CLI parent's own 540 s child timeout: a wedged drill is
    # killed by the PARENT (which owns the grandchild), so bench never
    # blocks on a captured-stdout pipe the grandchild still holds open
    proc = subprocess.run(argv, capture_output=True, text=True, timeout=600,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    full: dict = {}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "plan" in parsed:        # the FULL result line (the final
                full = parsed           # line is the compact verdict)
                break
    if not full:
        raise RuntimeError(
            f"chaos-drill produced no parseable result "
            f"(rc={proc.returncode}): {(proc.stderr or '')[-200:]}")
    deg = full.get("degraded") or {}
    result["chaos"] = {
        "passed": bool(full.get("passed")),
        "failed_checks": sorted(k for k, v in
                                (full.get("checks") or {}).items() if not v),
        "in_fault_p99_ms": (deg.get("in_fault") or {}).get("p99_ms"),
        "in_fault_tps": (deg.get("in_fault") or {}).get("tps"),
        "post_fault_p99_ms": (deg.get("post_fault") or {}).get("p99_ms"),
        "post_fault_tps": (deg.get("post_fault") or {}).get("tps"),
        "high_value_sheds": full.get("high_value_sheds"),
        "shed": full.get("shed"),
        "produce_failures": full.get("produce_failures"),
        "pool_retries": (full.get("pool") or {}).get("retries"),
        "max_ladder_level": full.get("max_ladder_level"),
        "max_burn": full.get("max_burn"),
        "phase_auc": full.get("phase_auc"),
        "virtual_duration_s": full.get("virtual_duration_s"),
    }
    snapshot("chaos")


def _degraded_network_stage(result: dict, snapshot) -> None:
    """Network fault plane (ISSUE 13 bench satellite): one fast,
    no-replay pass of the split-brain partition drill in a subprocess,
    reporting the slow-link victim's scored-traffic p99 + txn/s on a
    healthy link vs inside the seeded slow-link window, the injected
    per-frame latency, and the broker's producer-generation fence
    counters. The worker processes are pinned to the CPU platform, so a
    tunneled TPU session is never touched; the pass/fail bar lives in
    ``rtfd partition-drill`` and the tier-1 smoke."""
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "partition-drill", "--fast", "--no-replay"]
    proc = subprocess.run(argv, capture_output=True, text=True, timeout=600,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    full: dict = {}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "degraded_network" in parsed:   # the FULL result line
                full = parsed                  # (final line = verdict)
                break
    if not full:
        raise RuntimeError(
            f"partition-drill produced no parseable result "
            f"(rc={proc.returncode}): {(proc.stderr or '')[-200:]}")
    deg = full.get("degraded_network") or {}
    result["degraded_network"] = {
        "passed": bool(full.get("passed")),
        "failed_checks": sorted(k for k, v in
                                (full.get("checks") or {}).items() if not v),
        "worker": deg.get("worker"),
        "injected_latency_ms": deg.get("injected_latency_ms"),
        "healthy_p99_ms": (deg.get("healthy") or {}).get("p99_ms"),
        "healthy_tps": (deg.get("healthy") or {}).get("tps"),
        "slow_link_p99_ms": (deg.get("slow_link") or {}).get("p99_ms"),
        "slow_link_tps": (deg.get("slow_link") or {}).get("tps"),
        "p99_ratio": deg.get("p99_ratio"),
        "fenced_produces": full.get("fenced_produces"),
        "fenced_commits": full.get("fenced_commits"),
        "evictions": full.get("evictions"),
        "rejoins": full.get("rejoins"),
        "scored_duplicates": full.get("scored_duplicates"),
    }
    snapshot("degraded_network")


def _graph_sampling_stage(result: dict, snapshot) -> None:
    """Entity-graph plane (ISSUE 14 bench satellite). Two halves:

    (1) in-process micro numbers (graph.drill.run_graph_sampling_bench):
    per-txn typed-sampler cost cold vs cached on a seeded synthetic
    graph, and remote-fetch amortization (per-node requests vs one
    batched request) against a live local TCP fetch server — pure host
    work, safe anywhere including a tunneled TPU session;

    (2) one fast, no-replay pass of ``rtfd graph-drill`` in a CPU-pinned
    subprocess, reporting the ring-phase AUC lift of the graph-on blend
    over the trees-only incumbent plus the fetch/degrade headline
    counters. The pass/fail bar lives in ``rtfd graph-drill`` and the
    tier-1 smoke."""
    from realtime_fraud_detection_tpu.graph.drill import (
        run_graph_sampling_bench,
    )

    stage: dict = {"micro": run_graph_sampling_bench()}
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "graph-drill", "--fast", "--no-replay"]
    proc = subprocess.run(argv, capture_output=True, text=True, timeout=600,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    full: dict = {}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "auc" in parsed and "graph" in parsed:  # the FULL result
                full = parsed                          # (final = verdict)
                break
    if not full:
        raise RuntimeError(
            f"graph-drill produced no parseable result "
            f"(rc={proc.returncode}): {(proc.stderr or '')[-200:]}")
    auc = full.get("auc") or {}
    stage["drill"] = {
        "passed": bool(full.get("passed")),
        "failed_checks": sorted(k for k, v in
                                (full.get("checks") or {}).items() if not v),
        "ring_phase_lift": auc.get("ring_phase_lift"),
        "ring_auc_graph_on": (auc.get("ring") or {}).get("graph_on"),
        "ring_auc_incumbent": (auc.get("ring") or {}).get(
            "incumbent_trees"),
        "healthy_auc_graph_on": (auc.get("healthy") or {}).get("graph_on"),
        "remote_fetches": full.get("remote_fetches"),
        "remote_nodes": full.get("remote_nodes"),
        "degraded_in_window": full.get("degraded_in_window"),
        "ring_workers": full.get("ring_workers"),
    }
    result["graph_sampling"] = stage
    snapshot("graph_sampling")


def _fleet_observability_stage(result: dict, snapshot) -> None:
    """Fleet-wide observability plane (ISSUE 20 bench satellite): one
    fast, no-replay pass of ``rtfd obs-drill`` in a CPU-pinned
    subprocess — ≥2 real OS worker processes with producer-stamped
    trace carriers over the TCP netbroker. Reports the traced-vs-
    untraced overhead ratio, the stitched broker-transit p99, the
    cross-process stitch rate, and the carrier-loss ledger from the
    netfault window. The pass/fail bar lives in ``rtfd obs-drill`` and
    the tier-1 smoke; the bench records the headline numbers."""
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "obs-drill", "--fast", "--no-replay"]
    proc = subprocess.run(argv, capture_output=True, text=True, timeout=600,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    full: dict = {}
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "breakdown_p99" in parsed and "wall" in parsed:
                full = parsed  # the FULL result (final line = verdict)
                break
    if not full:
        raise RuntimeError(
            f"obs-drill produced no parseable result "
            f"(rc={proc.returncode}): {(proc.stderr or '')[-200:]}")
    wall = full.get("wall") or {}
    stitch = full.get("stitch") or {}
    ledger = full.get("carriers") or {}
    p99 = full.get("breakdown_p99") or {}
    result["fleet_observability"] = {
        "passed": bool(full.get("passed")),
        "failed_checks": sorted(k for k, v in
                                (full.get("checks") or {}).items() if not v),
        "n_workers": full.get("n_workers"),
        "produced": full.get("produced"),
        "overhead_ratio": wall.get("overhead_ratio"),
        "makespan_traced_s": wall.get("makespan_traced_s"),
        "makespan_untraced_s": wall.get("makespan_untraced_s"),
        "broker_transit_p99_ms": (wall.get("broker_transit_ms")
                                  or {}).get("p99"),
        "stitch_rate": stitch.get("stitch_rate"),
        "crossed_process": stitch.get("crossed_process"),
        "with_remote_span": stitch.get("with_remote_span"),
        "carriers_stripped": ledger.get("stripped"),
        "carriers_lost": ledger.get("lost_total"),
        "carriers_adopted": ledger.get("adopted_total"),
        "redirects": ledger.get("redirects"),
        "slow_worker": full.get("slow_worker"),
        "p99_dominant_stage": p99.get("dominant_stage"),
        "p99_dominant_worker": p99.get("dominant_worker"),
    }
    snapshot("fleet_observability")


def _shard_scaling_stage(result: dict, snapshot) -> None:
    """Partition-parallel worker plane (ISSUE 10 bench satellite):
    aggregate virtual txn/s at 1/2/4 workers over one saturating seeded
    schedule vs the single-worker baseline, plus the worker-kill run's
    handoff pause + state-replay depth. Pure virtual-clock host
    arithmetic (cluster/drill.run_shard_scaling — no device work, no
    subprocess), so it is cheap and safe anywhere including a tunneled
    TPU session; the pass/fail bar lives in ``rtfd shard-drill`` and the
    tier-1 smoke."""
    from realtime_fraud_detection_tpu.cluster.drill import (
        run_shard_scaling,
    )

    result["shard_scaling"] = run_shard_scaling()
    snapshot("shard_scaling")


def _elastic_scaling_stage(result: dict, snapshot) -> None:
    """Process-boundary cluster (ISSUE 12 bench satellite): real
    aggregate txn/s of the ``ProcessFleet`` at pinned 2/4/8 OS worker
    processes over the TCP netbroker + network handoff store, plus a
    SIGKILL run's rebalance pause and committed-gap replay depth. The
    per-batch service-cost model is fixed, so the ratio prices the
    orchestration overhead (TCP round trips, partition-scoped
    consumption, commit + checkpoint traffic) on top of
    perfectly-parallel modeled compute. The pass/fail bar lives in
    ``rtfd elastic-drill`` and the tier-1 smoke."""
    from realtime_fraud_detection_tpu.cluster.elastic_drill import (
        run_elastic_scaling,
    )

    result["elastic_scaling"] = run_elastic_scaling()
    snapshot("elastic_scaling")


def _quantization_stage(result: dict, models, sc, bert_config,
                        use_pallas: bool, it, snapshot) -> None:
    """Quantized scoring plane (ISSUE 9 bench stage): per-branch µs/txn
    f32-vs-quant, param bytes per branch, and host-side divergence stats.

    Weight-only int8 BERT (models/quant.py) and the GEMM-form tree
    kernels (models/trees.py) against their f32/gather baselines, each
    timed with the shared varied-input/no-pull discipline. CPU only —
    int8 calibration itself pulls the f32 weights device->host once
    (host-side by contract), which would flip the tunneled TPU into
    sync-dispatch mode in the pre-pull regime; the on-chip quantized
    numbers come from the ``--quant`` switches on tune_tpu.py /
    soak_tpu.py / this bench's pool_scaling stage in a dedicated relay
    run. The pass/fail bar lives in ``rtfd quant-drill``; this stage
    records the measured speed/bytes/divergence triple.
    """
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.models.bert import bert_predict
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.quant import (
        bert_param_bytes,
        quant_error_bound,
        quantize_bert_params,
    )
    from realtime_fraud_detection_tpu.models.trees import (
        tree_ensemble_predict,
    )

    batch, K = 256, 8
    rng = np.random.default_rng(23)
    # rtfd-lint: allow[d2h] host-side int8 calibration by contract (CPU-only stage, before any timed section)
    host_bert = jax.device_get(models.bert)
    qbert_host = quantize_bert_params(host_bert)
    bytes_f32 = bert_param_bytes(models.bert)
    bytes_int8 = bert_param_bytes(qbert_host)
    qbert = jax.device_put(qbert_host)
    entry: dict = {
        "batch": batch,
        "param_bytes": {
            "bert_f32": bytes_f32,
            "bert_int8": bytes_int8,
            "ratio": round(bytes_f32 / max(bytes_int8, 1), 3),
            "weight_reconstruction_bound": round(
                quant_error_bound(qbert_host), 6),
        },
    }

    toks = [jnp.asarray(rng.integers(0, bert_config.vocab_size,
                                     (batch, sc.text_len)), jnp.int32)
            for _ in range(K)]
    tokm = jnp.ones((batch, sc.text_len), bool)
    feats = [jnp.asarray(rng.standard_normal((batch, sc.feature_dim)),
                         jnp.float32) for _ in range(K)]

    bfn = jax.jit(lambda p, t, m: bert_predict(
        p, t, m, bert_config, use_pallas=use_pallas))
    branches: dict = {}
    for name, fn_pair in (
        ("bert_text", (
            lambda i: bfn(models.bert, toks[i % K], tokm),
            lambda i: bfn(qbert, toks[i % K], tokm))),
        ("xgboost_primary", (
            lambda i: tree_ensemble_predict(
                models.trees, feats[i % K], kernel="gather"),
            lambda i: tree_ensemble_predict(
                models.trees, feats[i % K], kernel="gemm"))),
        ("isolation_forest", (
            lambda i: iforest_predict(
                models.iforest, feats[i % K], kernel="gather"),
            lambda i: iforest_predict(
                models.iforest, feats[i % K], kernel="gemm"))),
    ):
        base_fn, quant_fn = fn_pair
        iters = it(50 if name == "bert_text" else 200)
        base_t = np.median(_time_blocked(base_fn, iters))
        quant_t = np.median(_time_blocked(quant_fn, iters))
        branches[name] = {
            "f32_us_per_txn": round(base_t / batch * 1e6, 3),
            "quant_us_per_txn": round(quant_t / batch * 1e6, 3),
            "speedup": round(base_t / max(quant_t, 1e-12), 3),
        }
    entry["branches"] = branches

    # host-side divergence stats over the same varied inputs (the gated
    # bounds live in rtfd quant-drill; these are the observed magnitudes)
    div_bert = max(
        float(jnp.max(jnp.abs(bfn(models.bert, t, tokm)
                              - bfn(qbert, t, tokm)))) for t in toks)
    div_trees = max(
        float(jnp.max(jnp.abs(
            tree_ensemble_predict(models.trees, f, kernel="gather")
            - tree_ensemble_predict(models.trees, f, kernel="gemm"))))
        for f in feats)
    div_if = max(
        float(jnp.max(jnp.abs(
            iforest_predict(models.iforest, f, kernel="gather")
            - iforest_predict(models.iforest, f, kernel="gemm"))))
        for f in feats)
    entry["divergence"] = {
        "bert_int8_max": div_bert,
        "trees_gemm_max": div_trees,
        "iforest_gemm_max": div_if,
    }
    result["quantization"] = entry
    snapshot("quantization")


def _kernel_fusion_stage(result: dict, models, sc, bert_config, it,
                         snapshot) -> None:
    """Pallas kernel plane (ISSUE 17 bench stage): per-kernel µs/txn,
    interpret-mode Pallas vs the XLA reference lowering, plus the host
    math the fused epilogue removes from finalize.

    CPU only and pre-pull-safe by construction: every timed callable
    keeps its output on device (time_blocked's block_until_ready is the
    only sync), inputs are varied per iteration, and the int8
    calibration pulls weights host-side once before any timed section.
    The interpret numbers are a CORRECTNESS-cost record (the Pallas
    interpreter is expected to lose to XLA on CPU) — the on-chip compiled
    numbers come from the ``--kernels`` relay switches on tune_tpu.py /
    soak_tpu.py / this bench's pool_scaling stage. The pass/fail bar
    lives in ``rtfd kernel-drill``.
    """
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.ensemble.combine import EnsembleParams
    from realtime_fraud_detection_tpu.models.quant import (
        quantize_bert_params,
    )
    from realtime_fraud_detection_tpu.ops import (
        attention_reference,
        dequant_matmul,
        dequant_matmul_reference,
        dequant_rows,
        dequant_rows_reference,
        epilogue_reference,
        flash_attention,
        fused_epilogue,
    )
    from realtime_fraud_detection_tpu.scoring import MODEL_NAMES
    from realtime_fraud_detection_tpu.utils.config import Config

    batch, K = 128, 4
    rng = np.random.default_rng(29)
    # rtfd-lint: allow[d2h] host-side int8 calibration by contract (CPU-only stage, before any timed section)
    qbert = jax.device_put(quantize_bert_params(jax.device_get(models.bert)))
    layer = qbert["layers"][0]
    h = bert_config.hidden_size
    entry: dict = {"batch": batch}
    kernels: dict = {}

    def per_txn(fn, iters, n_txn):
        return round(float(np.median(_time_blocked(fn, iters)))
                     / n_txn * 1e6, 3)

    # fused dequant-matmul on the served int8 q projection (bf16 compute)
    xs = [jnp.asarray(rng.standard_normal((batch, h)), jnp.float32)
          for _ in range(K)]
    p = layer["q"]
    ref_mm = jax.jit(lambda x: dequant_matmul_reference(
        x, p["qw"], p["scale"], p["b"]))
    iters = it(60)
    kernels["dequant_matmul"] = {
        "pallas_interpret_us_per_txn": per_txn(
            lambda i: dequant_matmul(xs[i % K], p["qw"], p["scale"],
                                     p["b"], interpret=True),
            iters, batch),
        "xla_reference_us_per_txn": per_txn(
            lambda i: ref_mm(xs[i % K]), iters, batch),
    }

    # per-row embedding dequant on served word_emb rows
    emb = qbert["word_emb"]
    rows = 256
    idxs = [jnp.asarray(rng.integers(0, emb["qe"].shape[0], (rows,)))
            for _ in range(K)]
    ref_rows = jax.jit(lambda q, s: dequant_rows_reference(q, s))
    kernels["dequant_rows"] = {
        "pallas_interpret_us_per_txn": per_txn(
            lambda i: dequant_rows(emb["qe"][idxs[i % K]],
                                   emb["scale"][idxs[i % K]],
                                   interpret=True), iters, rows),
        "xla_reference_us_per_txn": per_txn(
            lambda i: ref_rows(emb["qe"][idxs[i % K]],
                               emb["scale"][idxs[i % K]]), iters, rows),
    }

    # fused score-and-blend epilogue vs the XLA combine+ladder reference
    m = len(MODEL_NAMES)
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    preds = [jnp.asarray(rng.uniform(0, 1, (batch, m)), jnp.float32)
             for _ in range(K)]
    valid = jnp.ones((batch, m), bool)
    rules = [jnp.asarray(rng.uniform(0, 1, (batch,)), jnp.float32)
             for _ in range(K)]
    ref_ep = jax.jit(lambda pr, r: epilogue_reference(pr, valid, r, params))
    kernels["epilogue"] = {
        "pallas_interpret_us_per_txn": per_txn(
            lambda i: fused_epilogue(preds[i % K], valid, rules[i % K],
                                     params, interpret=True), iters, batch),
        "xla_reference_us_per_txn": per_txn(
            lambda i: ref_ep(preds[i % K], rules[i % K]), iters, batch),
        # what the fusion removes from FraudScorer.finalize: the per-batch
        # host numpy blend math (weights*preds contributions [B,M] f32 +
        # the nested rules-only decision/risk ladders, ~4 [B] f32
        # temporaries) moves inside the fused program's device_wait
        "host_math_bytes_saved_per_batch": batch * (m + 4) * 4,
        "extra_packed_cols_shipped": m + 2,
    }

    # flash attention vs the full-softmax reference at the drill shape
    heads, d = bert_config.num_heads, bert_config.head_dim
    s = sc.text_len
    ab = 8
    qkvs = [[jnp.asarray(rng.standard_normal((ab, heads, s, d)),
                         jnp.float32) for _ in range(3)] for _ in range(K)]
    amask = jnp.ones((ab, s), bool)
    ref_att = jax.jit(lambda q, k, v: attention_reference(q, k, v, amask))
    kernels["attention"] = {
        "pallas_interpret_us_per_txn": per_txn(
            lambda i: flash_attention(*qkvs[i % K], amask, interpret=True),
            iters, ab),
        "xla_reference_us_per_txn": per_txn(
            lambda i: ref_att(*qkvs[i % K]), iters, ab),
    }

    # persistent megakernel (ISSUE 19): the whole packed microbatch as ONE
    # program vs the verbatim-composition XLA reference, on the quantized
    # text branch (the form whose VMEM plan fits the persistent grid),
    # plus the launch/HBM accounting the fusion claim is measured by —
    # the device programs a microbatch costs collapse from the per-branch
    # chain to 1, and the per-branch logit/stack/pack intermediates stop
    # round-tripping HBM entirely
    from realtime_fraud_detection_tpu.ops import (
        fused_megakernel,
        mega_launch_accounting,
        mega_plan,
        megakernel_reference,
    )
    from realtime_fraud_detection_tpu.scoring.pipeline import (
        make_example_batch,
    )

    qmodels = models.replace(bert=qbert)
    mv = (True,) * m
    plan = mega_plan(qmodels, bert_config, b=batch, text_len=sc.text_len,
                     seq_len=sc.seq_len, feature_dim=sc.feature_dim,
                     has_two_hop=False)
    acct = mega_launch_accounting(batch, m, mega_valid=mv)
    mk: dict = {
        "supported": bool(plan["supported"]),
        "block": int(plan["block"]),
        "programs_per_microbatch_chain": acct["programs_chain"],
        "programs_per_microbatch_mega": acct["programs_mega"],
        "intermediate_hbm_bytes_eliminated":
            acct["intermediate_bytes_eliminated"],
    }
    if plan["supported"]:
        exs = [make_example_batch(batch, config=sc,
                                  rng=np.random.default_rng(31 + i))
               for i in range(K)]
        ref_mega = jax.jit(lambda b_: megakernel_reference(
            qmodels, b_, params, mega_valid=mv, bert_config=bert_config))
        mk.update({
            "pallas_interpret_us_per_txn": per_txn(
                lambda i: fused_megakernel(
                    qmodels, exs[i % K], params, mega_valid=mv,
                    bert_config=bert_config, interpret=True,
                    block=plan["block"]), it(6), batch),
            "xla_reference_us_per_txn": per_txn(
                lambda i: ref_mega(exs[i % K]), it(6), batch),
        })
    kernels["megakernel"] = mk
    entry["kernels"] = kernels
    result["kernel_fusion"] = entry
    snapshot("kernel_fusion")


def _e2e_soak(result: dict, models, sc, bert_config, use_pallas: bool,
              on_tpu: bool, remaining, snapshot) -> None:
    """The whole-framework StreamJob soak + measured detection quality."""
    import numpy as np

    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
    )
    from realtime_fraud_detection_tpu.scoring import (
        MODEL_NAMES as _MN,
        FraudScorer,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.training import GBDTTrainer

    _log('e2e soak: start')
    gen = TransactionGenerator(num_users=2000, num_merchants=500, seed=3)
    broker = InMemoryBroker()
    scorer = FraudScorer(
        models=models, scorer_config=sc, bert_config=bert_config)
    scorer.sc.use_pallas = use_pallas
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    # Train on STREAMED features: run the training transactions through
    # the production assemble path (live velocity/history/graph state)
    # so the trees see the distribution they will score — training on
    # offline-encoded features costs ~2pp accuracy / ~0.04 AUC on the
    # stream (r4 measurement). assemble() is host-only, so this phase
    # costs no device time. The reference never wired its trainer to
    # its stream at all (SURVEY.md §0.3).
    _log('e2e soak: streaming training features')
    tr_feats, tr_labels = [], []
    n_train_batches = 48 if remaining() > 240 else 24
    for _ in range(n_train_batches):
        recs = gen.generate_batch(256)
        b = scorer.assemble(recs)
        # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
        tr_feats.append(np.asarray(b.features))
        # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
        tr_labels.append(np.asarray(
            [bool(r.get("is_fraud")) for r in recs], np.float32))
        ts = time.time()
        for r in recs:
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
    x_tr = np.concatenate(tr_feats)
    y_tr = np.concatenate(tr_labels)
    _log('e2e soak: fitting trees + isolation forest')
    gtr = GBDTTrainer(n_estimators=40, max_depth=5, seed=2)
    trees = gtr.fit(x_tr, y_tr)
    iforest = IsolationForestTrainer(n_estimators=100, seed=4).fit(
        x_tr[y_tr < 0.5][:6000])
    # rtfd-lint: allow[lock-order] bench soak is single-threaded at the swap
    scorer.set_models(models.replace(trees=trees, iforest=iforest))
    scorer.set_feature_importances(gtr.feature_importances_)
    # Production blend: the untrained neural branches stay ENABLED on
    # device (they execute in the fused program — the throughput number
    # is the full 5-branch program) but are masked out of the score
    # blend via the per-branch validity feature (§2.2) exactly as a
    # deployment would gate cold models; weights renormalize to the
    # trained branches.
    for name in ("lstm_sequential", "bert_text", "graph_neural"):
        scorer.model_valid[list(_MN).index(name)] = False
    # VERDICT r4 item 2 levers: batch 512 (fewer per-batch overheads per
    # txn), depth 3 (result transfer off the critical path)
    soak_batch = int(os.environ.get("RTFD_SOAK_MAX_BATCH",
                                    "512" if on_tpu else "256"))
    job = StreamJob(broker, scorer,
                    JobConfig(max_batch=soak_batch, emit_features=False,
                              pipeline_depth=3))
    labels: dict = {}

    def _produce(n_txn: int) -> None:
        recs = gen.generate_batch(n_txn)
        labels.update(
            (str(r["transaction_id"]), bool(r.get("is_fraud")))
            for r in recs)
        broker.produce_batch(T.TRANSACTIONS, recs,
                             key_fn=lambda r: str(r["user_id"]))

    if on_tpu:
        # sustained soak (VERDICT r3 item 5): pre-fill well past what
        # the chip can score in the window so the job never starves,
        # then run_for a fixed wall-clock window — sustained txn/s,
        # not a drain of a finite backlog
        soak_s = min(30.0, max(10.0, remaining() - 60.0))
        _log('e2e soak: generating backlog')
        for _ in range(12):
            _produce(20_000)
        # Warm the streaming scorer OUTSIDE the window: the first call
        # compiles the bucket fused program (tens of seconds over the
        # tunnel), which in r4's first run silently ate most of the
        # 30 s window (76 txn/s "sustained" was ~25 s of XLA compile).
        _log('e2e soak: warming (compile outside the window)')
        scorer.score_batch(gen.generate_batch(soak_batch))
        t0 = time.perf_counter()
        scored = job.run_for(soak_s)
        dt = time.perf_counter() - t0
    else:
        _produce(3_000)
        t0 = time.perf_counter()
        scored = job.run_until_drained(now=1000.0)
        dt = time.perf_counter() - t0
    result["e2e_stream"] = {
        "txn_per_s": round(scored / dt, 1),
        "scored": scored,
        "window_s": round(dt, 1),
        "sustained": bool(on_tpu),
        "batches": job.counters["batches"],
        # configuration the number was measured under
        "pipeline_depth": job.config.pipeline_depth,
        "transfer_bf16": scorer.sc.transfer_bf16,
        "max_batch": job.config.max_batch,
    }
    snapshot("e2e_stream")

    # detection quality from the soak's own predictions
    preds = broker.consumer([T.PREDICTIONS], "bench-quality").poll(
        max(scored, 1))
    y, s = [], []
    for p in preds:
        lab = labels.get(p.value.get("transaction_id"))
        if lab is not None:
            y.append(float(lab))
            s.append(float(p.value["fraud_probability"]))
    # rtfd-lint: allow[d2h] host-side stats/assembly arrays (or the deliberate post-contract d2h phase)
    y_arr, s_arr = np.asarray(y), np.asarray(s)
    if len(y_arr) and 0 < y_arr.sum() < len(y_arr):
        order = np.argsort(s_arr)
        rank = np.empty(len(s_arr))
        rank[order] = np.arange(1, len(s_arr) + 1)
        pos = y_arr > 0.5
        n_pos, n_neg = int(pos.sum()), int((~pos).sum())
        auc = float((rank[pos].sum() - n_pos * (n_pos + 1) / 2)
                    / (n_pos * n_neg))
        flag = s_arr >= 0.5
        tp = float((flag & pos).sum())
        result["quality"] = {
            "n_scored": len(y_arr),
            "fraud_rate": round(float(pos.mean()), 4),
            "auc": round(auc, 4),
            "accuracy": round(float((flag == pos).mean()), 4),
            "precision": round(tp / max(int(flag.sum()), 1), 4),
            "recall": round(tp / max(n_pos, 1), 4),
            "blend": "trees+iforest trained on streamed features; "
                     "untrained neural branches execute on device but "
                     "are blend-masked (per-branch validity, §2.2). The "
                     "full ≥3-branch blend decision + per-branch "
                     "ablations: QUALITY_r05.json (rtfd quality-eval, "
                     "training/blend_eval.py protocol)",
            "reference_claim": "96.8% accuracy, unmeasured "
                               "(reference README.md:203)",
        }
        snapshot("quality")


def main() -> None:
    """Entry point for ``rtfd bench`` (cli.py cmd_bench)."""
    if "--quant" in sys.argv:
        # quantized pool_scaling (the rtfd quant-drill gated config);
        # propagates to the inner process through the inherited env
        os.environ["RTFD_BENCH_QUANT"] = "1"
    if "--mesh" in sys.argv:
        # mesh_scaling on a tunneled TPU (always-on for CPU runs);
        # propagates to the inner process through the inherited env
        os.environ["RTFD_BENCH_MESH"] = "1"
    if "--kernels" in sys.argv:
        # kernel-plane pool_scaling (the rtfd kernel-drill gated config);
        # propagates to the inner process through the inherited env
        os.environ["RTFD_BENCH_KERNELS"] = "1"
    if "--mega" in sys.argv:
        # persistent-megakernel pool_scaling (the rtfd kernel-drill
        # --mega gated config); propagates through the inherited env
        os.environ["RTFD_BENCH_MEGA"] = "1"
    orchestrate()


if __name__ == "__main__":
    if "--quant" in sys.argv:
        os.environ["RTFD_BENCH_QUANT"] = "1"
    if "--mesh" in sys.argv:
        os.environ["RTFD_BENCH_MESH"] = "1"
    if "--kernels" in sys.argv:
        os.environ["RTFD_BENCH_KERNELS"] = "1"
    if "--mega" in sys.argv:
        os.environ["RTFD_BENCH_MEGA"] = "1"
    if "--inner" in sys.argv:
        run_bench()
    else:
        orchestrate()

"""Benchmark: the 5 BASELINE.json configs + latency decomposition, one chip.

Prints ONE JSON line and ALWAYS exits 0 — even when the TPU relay is wedged.

Architecture (VERDICT r2 item 1): the parent process is a jax-free
orchestrator. It probes TPU availability in a short-timeout subprocess
(backend init on this host can HANG, not just raise — the axon PJRT plugin
wedges inside ``jax.devices()``), then runs the actual bench as
``bench.py --inner`` in a child. If the TPU probe or the TPU bench fails or
times out, it re-runs the child on a clean CPU backend (``PALLAS_AXON_POOL_IPS``
removed so the sitecustomize TPU registration never happens,
``JAX_PLATFORMS=cpu``) and still emits the one JSON line, with
``"device": "cpu-fallback"`` and an ``"error"`` field naming the TPU failure.

Headline metric: full-ensemble scoring throughput (transactions/sec/chip,
batch=256, pipelined dispatch — how the production StreamJob /
DoubleBufferedScorer paths run). ``vs_baseline`` compares against the
reference's claimed 15,000 TPS sustained for its entire multi-node cluster
(reference README.md:201); our number is ONE chip.

Also reported:
- ``configs``: per-config txn/s/chip for each BASELINE.json config —
  XGB batch=1, XGB+IsolationForest µbatch=32, BERT encoder, LSTM,
  GraphSAGE + full ensemble (the reference's unbatched hot path analog is
  main.py:235-248, which loops batch=1).
- ``latency``: p50/p99 per batch size for the full ensemble, measured two
  ways: ``e2e`` (host-resident args, includes H2D + dispatch round-trip —
  what a caller over the axon tunnel sees) and ``device`` (device-resident
  args, isolates chip compute). The gap IS the tunnel/transfer cost.
- ``pallas``: DistilBERT-base branch with the Pallas flash-attention kernel
  vs plain XLA attention on this chip; the faster one is used for the
  headline ensemble program.
- ``mfu``: analytic matmul FLOPs of the fused batch=256 ensemble program
  (BERT + LSTM + GNN; tree gathers contribute ~0 FLOPs) divided by
  device-resident p50 time and the chip's bf16 peak (VERDICT r2 item 8).
- ``e2e_stream``: StreamJob soak over the in-memory broker (assemble +
  device + fan-out + commit, two-deep pipelined) — the whole-framework
  number, not just the device program.

Timing discipline (axon tunnel): everything is measured with
``block_until_ready`` BEFORE any device->host result pull — the first
transfer drops the tunnel into synchronous mode and would poison later
configs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_T0 = time.monotonic()

BASELINE_TPS = 15_000.0  # reference README.md:201 (whole cluster)
METRIC_NAME = (
    "full-ensemble scoring throughput "
    "(5 branches, batch=256, text seq 64, pipelined)"
)
# Per-chip bf16 peak for MFU accounting, by platform substring. Checked
# in order: the r1 chip printed as "TPU v5 lite0" (neither "v5e" nor
# "v5p"), so the lite spellings must come first (VERDICT r3 weak-6).
_PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5lite", 197.0), ("v5e", 197.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v5p", 459.0), ("v5", 459.0), ("v4", 275.0),
)


def _log(msg: str) -> None:
    """Stage progress on stderr (stdout is reserved for the one JSON line)."""
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Orchestrator (jax-free: must never initialize a backend in this process)
# --------------------------------------------------------------------------

def _probe_tpu_once(timeout_s: float) -> tuple[str | None, str | None]:
    """(platform, error): init the backend in a throwaway subprocess."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM=' + d[0].platform, flush=True)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"backend init hang (probe timeout {timeout_s:.0f}s)"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return None, (tail[-1][:300] if tail else f"probe rc={proc.returncode}")
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe produced no PLATFORM line"


def _probe_tpu(attempts: int = 5, timeout_s: float = 150.0,
               gap_s: float = 120.0) -> tuple[str | None, list[dict]]:
    """Retry the TPU probe across ~the first 20 min of the bench window —
    a transiently wedged relay must not silently cost the round its perf
    story (VERDICT r3 weak-1). Returns (platform|None, attempt timeline)."""
    timeline: list[dict] = []
    for i in range(attempts):
        t0 = time.monotonic() - _T0
        platform, err = _probe_tpu_once(timeout_s)
        timeline.append({
            "attempt": i + 1, "t_s": round(t0, 1),
            "result": platform or f"fail: {err}",
        })
        if platform and platform != "cpu":
            return platform, timeline
        why = err if err is not None else f"got '{platform}' backend, not tpu"
        _log(f"TPU probe attempt {i + 1}/{attempts} failed ({why}); "
             f"{'retrying' if i + 1 < attempts else 'giving up'}")
        if i + 1 < attempts:
            time.sleep(gap_s)
    return None, timeline


def _run_inner(env: dict, timeout_s: float) -> dict:
    """Run ``bench.py --inner``; return the parsed JSON result line.

    stderr is inherited so per-stage progress streams to the driver log
    even if this parent is later killed.
    """
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--inner"],
        stdout=subprocess.PIPE, text=True, env=env, timeout=timeout_s,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    for line in reversed((proc.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed
    raise RuntimeError(f"inner bench rc={proc.returncode}, no JSON line")


def _cpu_env() -> dict:
    env = dict(os.environ)
    # Gate for the sitecustomize axon/TPU plugin registration: without it a
    # fresh interpreter never touches the (possibly wedged) relay.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["RTFD_BENCH_DEVICE_LABEL"] = "cpu-fallback"
    return env


def orchestrate() -> None:
    errors: list[str] = []
    result: dict | None = None

    platform, timeline = _probe_tpu()
    if platform and platform != "cpu":
        _log(f"TPU probe ok (platform={platform}); running bench on it")
        try:
            result = _run_inner(dict(os.environ), timeout_s=1800.0)
        except Exception as e:  # noqa: BLE001 — must always emit JSON
            errors.append(f"tpu bench failed: {type(e).__name__}: {e}"[:300])
            _log(errors[-1])
    else:
        errors.append(
            f"tpu unavailable after {len(timeline)} probe attempts "
            f"(last: {timeline[-1]['result'] if timeline else 'none'})")
        _log(errors[-1])

    if result is None:
        _log("falling back to clean CPU backend")
        try:
            result = _run_inner(_cpu_env(), timeout_s=1500.0)
        except Exception as e:  # noqa: BLE001
            errors.append(f"cpu fallback failed: {type(e).__name__}: {e}"[:300])
            _log(errors[-1])

    if result is None:
        result = {"metric": METRIC_NAME, "value": 0.0, "unit": "txn/s/chip",
                  "vs_baseline": 0.0, "device": "none"}
    result["probe_attempts"] = timeline
    history = _session_probe_history()
    if history:
        result["session_probe_history"] = history
    if result.get("device", "").startswith(("cpu", "none")):
        # relay down at bench time: surface the round's real on-chip
        # capture (committed during a live relay window) so a wedged relay
        # can't erase the round's measured TPU performance
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_r04_tpu_capture.json")) as f:
                cap = json.load(f)
            result["same_round_tpu_capture"] = {
                "headline": cap.get("headline"),
                "file": "BENCH_r04_tpu_capture.json",
                "note": "see capture_note in the file for methodology; "
                        "instrumented on-chip soak/sweep measurements are "
                        "recorded in MEASUREMENTS_r04_onchip.json and the "
                        "post-fix quality measurement in "
                        "BENCH_r04_quality_cpu.json",
            }
        except (OSError, ValueError):
            pass
    if errors:
        result["error"] = "; ".join(errors)[:600]
    print(json.dumps(result), flush=True)
    sys.exit(0)


def _session_probe_history() -> dict | None:
    """Summarize /tmp/tpu_probe.log (a background probe loop retries the
    relay every ~10 min across the whole build session) so a full-round
    outage is evidenced by dozens of timestamped attempts, not just the
    bench-start probes."""
    try:
        with open("/tmp/tpu_probe.log") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    attempts = [ln for ln in lines if ln.startswith("[probe ")]
    successes = [ln for ln in lines if ln.startswith("PLATFORM ")]
    if not attempts:
        return None
    return {
        "attempts": len(attempts),
        "first": attempts[0],
        "last": attempts[-1],
        "successes": len(successes),
    }


# --------------------------------------------------------------------------
# Inner bench (the only process that imports jax)
# --------------------------------------------------------------------------

def _percentiles(times_s) -> dict:
    ms = np.asarray(times_s) * 1e3
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 3),
        "p99_ms": round(float(np.percentile(ms, 99)), 3),
        "max_ms": round(float(ms.max()), 3),
    }


def _time_blocked(fn, iters: int) -> list:
    """Shared discipline: see utils/timing.py (varied inputs, no pulls)."""
    from realtime_fraud_detection_tpu.utils.timing import time_blocked

    return time_blocked(fn, iters)


def _throughput_pipelined(fn, batch_size: int, iters: int) -> float:
    """Shared discipline: see utils/timing.py (varied inputs, no pulls)."""
    from realtime_fraud_detection_tpu.utils.timing import (
        throughput_pipelined,
    )

    return throughput_pipelined(fn, batch_size, iters)


def _null_rtt_ms(iters: int = 10) -> dict:
    """Measured floor of one blocked host->device->host round trip (a tiny
    h2d + add + block). On a tunneled TPU this is the network RTT every
    blocked call pays regardless of compute — recorded so latency numbers
    can be read against the transport floor they sit on."""
    import jax

    g = jax.jit(lambda x: x + 1)
    jax.block_until_ready(g(jax.device_put(np.float32(0))))
    ts = []
    for i in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(g(jax.device_put(np.float32(i))))
        ts.append(time.perf_counter() - t0)
    return _percentiles(ts)


def _ensemble_matmul_flops(bert_config, sc, batch: int) -> float:
    """Analytic matmul FLOPs per fused-ensemble call (counting 2*M*N*K).

    BERT dominates; LSTM/GNN are included; tree + isolation-forest branches
    are gather/compare programs with ~0 matmul FLOPs.
    """
    h, i_, l_, t = (bert_config.hidden_size, bert_config.intermediate_size,
                    bert_config.num_layers, sc.text_len)
    per_tok_layer = 2 * (4 * h * h + 2 * h * i_)      # qkv+o, ffn up+down
    attn = 2 * 2 * t * t * h                          # scores + weighted sum
    bert = l_ * (t * per_tok_layer + attn) + t * 2 * h * h  # + pooler-ish head
    lstm_h = 128
    lstm = sc.seq_len * 2 * (sc.feature_dim + lstm_h) * 4 * lstm_h
    gnn = 2 * (2 * sc.fanout * sc.node_dim * 64 + 3 * 64 * 64)  # rough, tiny
    return float(batch * (bert + lstm + gnn))


def run_bench() -> None:
    import jax
    import jax.numpy as jnp

    from realtime_fraud_detection_tpu.ensemble.combine import (
        EnsembleParams,
        combine_predictions,
    )
    from realtime_fraud_detection_tpu.models.bert import BertConfig, bert_predict
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.lstm import lstm_logits
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_predict
    from realtime_fraud_detection_tpu.scoring import (
        MODEL_NAMES,
        ScorerConfig,
        init_scoring_models,
        make_example_batch,
        score_fused,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    on_tpu = jax.devices()[0].platform != "cpu"
    device_label = os.environ.get("RTFD_BENCH_DEVICE_LABEL",
                                  str(jax.devices()[0]))
    # Real DistilBERT-base dimensions for the text branch (config.py:165-170),
    # trimmed to 2 layers on CPU so fallback runs stay tractable.
    bert_config = BertConfig() if on_tpu else BertConfig(num_layers=2)
    sc = ScorerConfig(text_len=64)
    # Iteration scale: full on TPU; reduced on the CPU fallback so a wedged
    # relay still yields a complete JSON well inside the orchestrator timeout.
    it = (lambda n: n) if on_tpu else (lambda n: max(5, n // 10))

    models = init_scoring_models(
        jax.random.PRNGKey(0), bert_config=bert_config,
        feature_dim=sc.feature_dim, node_dim=sc.node_dim,
    )
    params = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    model_valid = jnp.ones((len(MODEL_NAMES),), bool)

    _log(f'start device={jax.devices()[0]}')
    batches = {
        bsz: make_example_batch(bsz, sc, rng=np.random.default_rng(bsz))
        for bsz in (1, 32, 256)
    }
    dev_batches = {b: jax.device_put(v) for b, v in batches.items()}
    dev_models = jax.device_put(models)
    jax.block_until_ready((dev_batches, dev_models))

    # K pre-staged input variants per batch size: every timed call cycles
    # through fresh buffers so no layer (jit, relay, transfer cache) can
    # serve a repeat. K=8 bounds the extra device memory to a few MB.
    K = 8
    var_feats = {
        b: [jax.device_put(batches[b].features + np.float32(j) * 1e-4)
            for j in range(K)]
        for b in (1, 32, 256)
    }
    vocab = bert_config.vocab_size
    var_toks = [
        jax.device_put(((np.asarray(batches[256].token_ids) + j) % vocab)
                       .astype(np.int32))
        for j in range(K)
    ]
    var_hist = [
        jax.device_put(batches[256].history + np.float32(j) * 1e-4)
        for j in range(K)
    ]
    jax.block_until_ready((var_feats, var_toks, var_hist))
    rtt = _null_rtt_ms() if on_tpu else None

    # ---------------------------------------------------- pallas vs XLA (BERT)
    # The repo's custom kernel (ops/attention.py) measured head-to-head on
    # this chip; the winner runs in the headline ensemble program.
    _log(f'batches staged on device; null round trip {rtt}')
    pallas_report = {}
    use_pallas = False
    tokm = dev_batches[256].token_mask
    bert_times = {}
    for flag in ((False, True) if on_tpu else (False,)):
        bfn = jax.jit(
            lambda p, t, m, _flag=flag: bert_predict(
                p, t, m, bert_config, use_pallas=_flag)
        )
        try:
            bert_times[flag] = _time_blocked(
                lambda i: bfn(dev_models.bert, var_toks[i % K], tokm), it(30))
        except Exception as e:  # pallas unavailable on this platform
            pallas_report["error"] = f"{type(e).__name__}: {e}"[:200]
    if True in bert_times:
        xla_ms = float(np.median(bert_times[False])) * 1e3
        pal_ms = float(np.median(bert_times[True])) * 1e3
        use_pallas = pal_ms < xla_ms
        pallas_report = {
            "xla_p50_ms": round(xla_ms, 3),
            "pallas_p50_ms": round(pal_ms, 3),
            "headline_uses_pallas": use_pallas,
        }

    _log(f'pallas A/B done: {pallas_report}')
    fn = jax.jit(
        lambda m, b, p, v: score_fused(
            m, b, p, v, bert_config=bert_config, use_pallas=use_pallas,
            with_model_preds=False,
        )
    )

    # ------------------------------------------------- latency decomposition
    # ORDERING CONTRACT: nothing before the `d2h` phase below may call
    # jax.device_get / np.asarray on a device array. On the axon tunnel the
    # FIRST device->host pull permanently flips the process into synchronous
    # round-trip dispatch (~70-170 ms per call) — real v5e PCIe has no such
    # mode, so every latency/throughput number must be captured in the
    # pre-pull regime to be representative of the hardware. The d2h phase
    # and the e2e soak (whose scorer inherently pulls results) run last.
    lat: dict[str, dict] = {}
    for bsz, iters in ((1, it(200)), (32, it(100)), (256, it(100))):
        _log(f'latency decomposition b={bsz}')
        host_b, dev_b = batches[bsz], dev_batches[bsz]

        # Variation must cover the byte-dominant leaves too (history is
        # ~45% of the payload): a transfer cache keyed on content would
        # otherwise still serve most of the repeated bytes.
        def _host_variant(i, hb=host_b):
            return hb.replace(
                features=hb.features + np.float32(i) * 1e-4,
                history=hb.history + np.float32(i) * 1e-4,
                token_ids=((hb.token_ids + i) % vocab).astype(np.int32),
            )

        e2e = _time_blocked(
            lambda i: fn(dev_models, _host_variant(i), params, model_valid),
            iters)
        device = _time_blocked(
            lambda i: fn(dev_models,
                         dev_b.replace(features=var_feats[bsz][i % K]),
                         params, model_valid), iters)
        # H2D in isolation: push a fresh host batch each call, block
        h2d = []
        for i in range(min(iters, 50)):
            hb = _host_variant(i + 1000)
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(hb))
            h2d.append(time.perf_counter() - t0)
        lat[str(bsz)] = {
            "e2e": _percentiles(e2e),
            "device": _percentiles(device),
            "h2d": _percentiles(h2d),
        }

    # --------------------------------------------------- the 5 BASELINE configs
    _log('latency decomposition done')
    configs: dict[str, dict] = {}

    # 1. XGBoost batch=1 (the reference's unbatched hot path, main.py:235-248)
    tfn = jax.jit(lambda t, f: tree_ensemble_predict(t, f))
    configs["xgboost_batch1"] = {
        "latency": _percentiles(_time_blocked(
            lambda i: tfn(dev_models.trees, var_feats[1][i % K]), it(200))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: tfn(dev_models.trees, var_feats[1][i % K]),
            1, it(200)), 1),
    }
    _log('config 1 (xgb b=1) done')
    # 2. XGB + IsolationForest ensemble, microbatch=32
    v2 = jnp.asarray([True, False, False, False, True])

    def _xgb_if(trees, iforest, f):
        preds = jnp.stack(
            [tree_ensemble_predict(trees, f),
             jnp.zeros(f.shape[0]), jnp.zeros(f.shape[0]),
             jnp.zeros(f.shape[0]),
             iforest_predict(iforest, f)], axis=1)
        valid = jnp.broadcast_to(v2[None, :], preds.shape)
        return combine_predictions(preds, valid, params)

    xifn = jax.jit(_xgb_if)
    configs["xgb_iforest_mb32"] = {
        "batch": 32,
        "latency": _percentiles(_time_blocked(
            lambda i: xifn(dev_models.trees, dev_models.iforest,
                           var_feats[32][i % K]), it(100))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: xifn(dev_models.trees, dev_models.iforest,
                           var_feats[32][i % K]),
            32, it(200)), 1),
    }

    _log('config 2 (xgb+iforest mb32) done')
    # 3. BERT encoder -> fraud head (DistilBERT-base on TPU, seq 64)
    bfn = jax.jit(lambda p, t, m: bert_predict(
        p, t, m, bert_config, use_pallas=use_pallas))
    configs["bert_encoder"] = {
        "batch": 256,
        "latency": _percentiles(_time_blocked(
            lambda i: bfn(dev_models.bert, var_toks[i % K], tokm), it(50))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: bfn(dev_models.bert, var_toks[i % K], tokm),
            256, it(50)), 1),
        "layers": bert_config.num_layers,
        "hidden": bert_config.hidden_size,
    }

    # 3b. honest sequence lengths (VERDICT r3 missing-6): the reference
    # tokenizes at max_length 512 (bert_text_analyzer.py:201-202); seq 64
    # is the production truncation for short merchant/description strings.
    # Bench 128 everywhere and 512 on the real chip so the text branch's
    # cost at reference length is on the record.
    for seq_len in (128, 512) if on_tpu else (128,):
        rng = np.random.default_rng(seq_len)
        toks_l = [jax.device_put(rng.integers(
            0, 30_000, (256, seq_len)).astype(np.int32)) for _ in range(K)]
        mask_l = jax.device_put(np.ones((256, seq_len), bool))
        configs[f"bert_encoder_seq{seq_len}"] = {
            "batch": 256,
            "latency": _percentiles(_time_blocked(
                lambda i: bfn(dev_models.bert, toks_l[i % K], mask_l),
                it(30))),
            "txn_per_s": round(_throughput_pipelined(
                lambda i: bfn(dev_models.bert, toks_l[i % K], mask_l),
                256, it(30)), 1),
        }

    _log('config 3 (bert, + long-seq variants) done')
    # 4. LSTM per-user sequential model
    hlen = dev_batches[256].history_len
    lfn = jax.jit(lambda p, h, l: jax.nn.sigmoid(lstm_logits(p, h, l)))
    configs["lstm_seq"] = {
        "batch": 256,
        "latency": _percentiles(_time_blocked(
            lambda i: lfn(dev_models.lstm, var_hist[i % K], hlen), it(100))),
        "txn_per_s": round(_throughput_pipelined(
            lambda i: lfn(dev_models.lstm, var_hist[i % K], hlen),
            256, it(100)), 1),
    }

    _log('config 4 (lstm) done')
    # 5. GraphSAGE + full 4-model ensemble = the fused headline program
    db = dev_batches[256]
    configs["graphsage_full_ensemble"] = {
        "batch": 256,
        "latency": lat["256"]["device"],
        "txn_per_s": round(_throughput_pipelined(
            lambda i: fn(dev_models,
                         db.replace(features=var_feats[256][i % K]),
                         params, model_valid), 256, it(50)), 1),
    }

    throughput = configs["graphsage_full_ensemble"]["txn_per_s"]

    # Derived device-resident batch period: batch / pipelined-throughput.
    # Blocked per-call latency on a tunneled chip is dominated by the ~85 ms
    # network RTT (see tunnel_null_rtt_ms); the pipelined period is the
    # honest "what the chip itself costs per batch" number a local host
    # would observe (real v5e PCIe round trips are microseconds).
    for cfg in configs.values():
        b = cfg.get("batch", 1)
        if cfg.get("txn_per_s"):
            cfg["ms_per_batch_pipelined"] = round(1e3 * b / cfg["txn_per_s"], 3)

    _log('config 5 (full ensemble) done')
    # -------------------------------------------------------------------- MFU
    # Achieved matmul TFLOP/s of the fused batch=256 program against the
    # chip's bf16 peak (VERDICT r2 item 8). FLOPs are analytic (counted from
    # the model dims, 2*M*N*K per matmul); time per batch is derived from the
    # PIPELINED throughput (batch/txn_per_s): with the device kept fed, the
    # steady-state batch period is bounded below by pure device compute, so
    # the resulting MFU is an honest lower bound that no transfer cache or
    # async-dispatch artifact can inflate (r3's blocked-call timing produced
    # an impossible 647% MFU through exactly such an artifact).
    flops = _ensemble_matmul_flops(bert_config, sc, 256)
    sec_per_batch = 256.0 / max(throughput, 1e-9)
    achieved_tflops = flops / sec_per_batch / 1e12
    peak = next((v for k, v in _PEAK_BF16_TFLOPS
                 if k in str(jax.devices()[0]).lower()), None)
    mfu = {
        "matmul_flops_batch256": flops,
        "sec_per_batch_pipelined": round(sec_per_batch, 6),
        "achieved_tflops": round(achieved_tflops, 3),
        "peak_bf16_tflops": peak,
        "mfu": round(achieved_tflops / peak, 4) if peak else None,
        "method": "throughput-derived (batch / pipelined txn_per_s)",
    }

    # ---------------------------------------------------------- d2h phase
    # The FIRST device->host pulls in this process — deliberately last (see
    # the ordering contract above): after these, the tunnel pins every
    # dispatch to synchronous round trips, which the e2e soak below (whose
    # scorer inherently pulls results per batch) already has to live with.
    for bsz in (1, 32, 256):
        dev_b = dev_batches[bsz]
        d2h = []
        # several rounds of K fresh outputs: each Array is pulled exactly
        # once (a re-pull reads jax's cached _npy_value), and 3*K samples
        # keep the p99 from being a single worst pull
        for rnd in range(3):
            outs = [fn(dev_models,
                       dev_b.replace(
                           features=var_feats[bsz][j] + np.float32(rnd)),
                       params, model_valid) for j in range(K)]
            jax.block_until_ready(outs)
            for o in outs:
                t0 = time.perf_counter()
                jax.device_get(o)
                d2h.append(time.perf_counter() - t0)
        lat[str(bsz)]["d2h"] = _percentiles(d2h)
    _log('d2h phase done (process now in tunnel sync-dispatch mode)')

    # native C++ tree kernel, the true CPU baseline for config 1 (pulls the
    # tree params to host, hence scheduled in the post-pull phase)
    try:
        from realtime_fraud_detection_tpu.native import NativeTreeScorer

        scorer_cpu = NativeTreeScorer(jax.device_get(models.trees))
        feats1 = np.asarray(batches[1].features)
        t0 = time.perf_counter()
        n_iters = it(2000)
        for _ in range(n_iters):
            scorer_cpu.predict(feats1)
        cpu_s = (time.perf_counter() - t0) / n_iters
        configs["xgboost_batch1"]["cpu_native_p50_ms"] = round(cpu_s * 1e3, 4)
    except Exception:
        pass

    # ------------------------------------------------------- e2e stream soak
    # Runs with TRAINED trees so the soak measures the production pipeline,
    # and doubles as the detection-quality measurement: the reference CLAIMS
    # 96.8% accuracy with no benchmark harness (README.md:203, SURVEY.md §6);
    # this is a measured number on a stream with a known injected fraud mix.
    e2e_stream = {}
    quality = {}
    try:
        from realtime_fraud_detection_tpu.scoring import FraudScorer
        from realtime_fraud_detection_tpu.sim.simulator import (
            TransactionGenerator,
        )
        from realtime_fraud_detection_tpu.stream import (
            InMemoryBroker,
            JobConfig,
            StreamJob,
        )
        from realtime_fraud_detection_tpu.stream import topics as T
        from realtime_fraud_detection_tpu.training import GBDTTrainer

        from realtime_fraud_detection_tpu.models.isolation_forest import (
            IsolationForestTrainer,
        )
        from realtime_fraud_detection_tpu.scoring import MODEL_NAMES as _MN

        gen = TransactionGenerator(num_users=2000, num_merchants=500, seed=3)
        broker = InMemoryBroker()
        scorer = FraudScorer(
            models=models, scorer_config=sc, bert_config=bert_config)
        scorer.sc.use_pallas = use_pallas
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

        # Train on STREAMED features: run the training transactions through
        # the production assemble path (live velocity/history/graph state)
        # so the trees see the distribution they will score — training on
        # offline-encoded features costs ~2pp accuracy / ~0.04 AUC on the
        # stream (r4 measurement). assemble() is host-only, so this phase
        # costs no device time. The reference never wired its trainer to
        # its stream at all (SURVEY.md §0.3).
        _log('e2e soak: streaming training features')
        tr_feats, tr_labels = [], []
        for _ in range(48):
            recs = gen.generate_batch(256)
            b = scorer.assemble(recs)
            tr_feats.append(np.asarray(b.features))
            tr_labels.append(np.asarray(
                [bool(r.get("is_fraud")) for r in recs], np.float32))
            ts = time.time()
            for r in recs:
                scorer.velocity.update(str(r.get("user_id", "")),
                                       float(r.get("amount", 0.0)), ts)
        x_tr = np.concatenate(tr_feats)
        y_tr = np.concatenate(tr_labels)
        _log('e2e soak: fitting trees + isolation forest')
        gtr = GBDTTrainer(n_estimators=40, max_depth=5, seed=2)
        trees = gtr.fit(x_tr, y_tr)
        iforest = IsolationForestTrainer(n_estimators=100, seed=4).fit(
            x_tr[y_tr < 0.5][:6000])
        scorer.set_models(models.replace(trees=trees, iforest=iforest))
        scorer.set_feature_importances(gtr.feature_importances_)
        # Production blend: the untrained neural branches stay ENABLED on
        # device (they execute in the fused program — the throughput number
        # is the full 5-branch program) but are masked out of the score
        # blend via the per-branch validity feature (§2.2) exactly as a
        # deployment would gate cold models; weights renormalize to the
        # trained branches.
        for name in ("lstm_sequential", "bert_text", "graph_neural"):
            scorer.model_valid[list(_MN).index(name)] = False
        job = StreamJob(broker, scorer,
                        JobConfig(max_batch=256, emit_features=False,
                                  pipeline_depth=3))
        labels: dict = {}

        def _produce(n_txn: int) -> None:
            recs = gen.generate_batch(n_txn)
            labels.update(
                (str(r["transaction_id"]), bool(r.get("is_fraud")))
                for r in recs)
            broker.produce_batch(T.TRANSACTIONS, recs,
                                 key_fn=lambda r: str(r["user_id"]))

        if on_tpu:
            # sustained soak (VERDICT r3 item 5): pre-fill well past what
            # the chip can score in the window so the job never starves,
            # then run_for a fixed wall-clock window — sustained txn/s,
            # not a drain of a finite backlog
            soak_s = 30.0
            _log('e2e soak: generating backlog')
            for _ in range(12):
                _produce(20_000)
            # Warm the streaming scorer OUTSIDE the window: the first call
            # compiles the bucket-256 fused program (tens of seconds over
            # the tunnel), which in r4's first run silently ate most of the
            # 30 s window (76 txn/s "sustained" was ~25 s of XLA compile).
            _log('e2e soak: warming (compile outside the window)')
            scorer.score_batch(gen.generate_batch(256))
            t0 = time.perf_counter()
            scored = job.run_for(soak_s)
            dt = time.perf_counter() - t0
        else:
            _produce(3_000)
            t0 = time.perf_counter()
            scored = job.run_until_drained(now=1000.0)
            dt = time.perf_counter() - t0
        e2e_stream = {
            "txn_per_s": round(scored / dt, 1),
            "scored": scored,
            "window_s": round(dt, 1),
            "sustained": bool(on_tpu),
            "batches": job.counters["batches"],
            # configuration the number was measured under
            "pipeline_depth": job.config.pipeline_depth,
            "transfer_bf16": scorer.sc.transfer_bf16,
            "max_batch": job.config.max_batch,
        }

        # detection quality from the soak's own predictions
        preds = broker.consumer([T.PREDICTIONS], "bench-quality").poll(
            max(scored, 1))
        y, s = [], []
        for p in preds:
            lab = labels.get(p.value.get("transaction_id"))
            if lab is not None:
                y.append(float(lab))
                s.append(float(p.value["fraud_probability"]))
        y_arr, s_arr = np.asarray(y), np.asarray(s)
        if len(y_arr) and 0 < y_arr.sum() < len(y_arr):
            order = np.argsort(s_arr)
            rank = np.empty(len(s_arr))
            rank[order] = np.arange(1, len(s_arr) + 1)
            pos = y_arr > 0.5
            n_pos, n_neg = int(pos.sum()), int((~pos).sum())
            auc = float((rank[pos].sum() - n_pos * (n_pos + 1) / 2)
                        / (n_pos * n_neg))
            flag = s_arr >= 0.5
            tp = float((flag & pos).sum())
            quality = {
                "n_scored": len(y_arr),
                "fraud_rate": round(float(pos.mean()), 4),
                "auc": round(auc, 4),
                "accuracy": round(float((flag == pos).mean()), 4),
                "precision": round(tp / max(int(flag.sum()), 1), 4),
                "recall": round(tp / max(n_pos, 1), 4),
                "blend": "trees+iforest trained on streamed features; "
                         "untrained neural branches execute on device but "
                         "are blend-masked (per-branch validity, §2.2)",
                "reference_claim": "96.8% accuracy, unmeasured "
                                   "(reference README.md:203)",
            }
    except Exception as e:
        e2e_stream = {"error": f"{type(e).__name__}: {e}"[:200]}

    _log(f'e2e stream soak done: {e2e_stream}; quality: {quality}')
    print(json.dumps({
        "metric": METRIC_NAME,
        "value": throughput,
        "unit": "txn/s/chip",
        "vs_baseline": round(throughput / BASELINE_TPS, 3),
        "configs": configs,
        "latency": lat,
        "tunnel_null_rtt_ms": rtt,
        "pallas": pallas_report,
        "mfu": mfu,
        "e2e_stream": e2e_stream,
        "quality": quality,
        "device": device_label,
    }), flush=True)


if __name__ == "__main__":
    if "--inner" in sys.argv:
        run_bench()
    else:
        orchestrate()

"""StreamJob e2e soak on the live chip: the 6,250 txn/s/chip measurement.

VERDICT r4 item 2: clear the per-chip share of the 50k-TPS north star
(BASELINE.json; 50,000 / 8 chips = 6,250) with a MEASUREMENT through the
production ``stream/job.py`` path, not arithmetic. This runner sweeps the
levers the round-4 analysis named — microbatch 512 vs 256, pipeline depth
2 vs 3, bf16 wire format, explanation assembly on/off — each as a
sustained ``run_for`` soak over a pre-filled backlog (the job never
starves; compile warmed outside the window), plus the decomposition
(scorer-direct device rate, host assemble-only rate) that shows WHERE the
e2e number comes from.

Varied-input methodology: every scored microbatch is freshly generated
simulator traffic — no repeated tensors for any cache layer to serve
(utils/timing.py rule 1); state (velocity/history/graph) evolves live.

Usage: python soak_tpu.py            # exits 3 immediately if no TPU
Writes MEASUREMENTS_r05_onchip.json (repo root) and prints one JSON line
per config on stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _probe() -> bool:
    code = "import jax; print(jax.devices()[0].platform, flush=True)"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=150)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0 and "cpu" not in proc.stdout


def run() -> None:
    import numpy as np

    import jax

    from realtime_fraud_detection_tpu.models.bert import BertConfig
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.utils.config import Config

    t0 = time.monotonic()

    def log(m):
        print(f"[soak +{time.monotonic() - t0:6.1f}s] {m}",
              file=sys.stderr, flush=True)

    out = {
        "device": str(jax.devices()[0]),
        "when": "live relay window",
        "pass_line_txn_per_s_per_chip": 6250.0,
        "methodology": (
            "sustained StreamJob.run_for over a pre-filled backlog of "
            "freshly generated simulator traffic (varied inputs by "
            "construction, live state evolution); per-config compile "
            "warmed outside the timed window; in-memory broker so the "
            "measurement isolates assemble+device+fan-out+commit"),
        "configs": [],
    }
    log(f"device: {out['device']}")

    gen = TransactionGenerator(num_users=2000, num_merchants=500, seed=3)
    smoke = os.environ.get("RTFD_SOAK_SMOKE") == "1"
    # --quant: every config serves the quantized scoring plane (weight-
    # only int8 BERT + GEMM-form tree kernels — the rtfd quant-drill
    # gated configuration), so one relay window captures f32 and
    # quantized e2e rates in two invocations. Calibration pulls the f32
    # weights host-side once per scorer build, before any timed window.
    quant = "--quant" in sys.argv
    out["quantized"] = quant
    # --kernels: every config serves the Pallas kernel plane (fused
    # dequant-matmul + fused score-and-blend epilogue + flash attention —
    # the rtfd kernel-drill gated configuration), so one relay window
    # captures kernel-on e2e rates next to the f32/--quant ones.
    # Composes with --quant: the dequant kernel engages on the int8 form.
    # --mega: the kernel plane serves the persistent megakernel (one
    # Pallas program scoring the whole packed microbatch — the rtfd
    # kernel-drill --mega gated configuration). Implies --kernels;
    # labels gain a -mega suffix.
    mega_on = "--mega" in sys.argv
    kernels_on = "--kernels" in sys.argv or mega_on
    out["kernels"] = kernels_on
    out["mega"] = mega_on
    # --mesh: every config scores through a MeshExecutor (GSPMD
    # data x model over all addressable chips, BERT branch stored sharded
    # over ``model`` — the rtfd mesh-drill gated path) instead of the
    # single-device program, so one relay window captures the mesh e2e
    # rate next to the f32/--quant ones. Composes with --quant: the
    # sharded storage carries the int8 form for free.
    mesh_on = "--mesh" in sys.argv
    mesh_model_axis = 0
    if mesh_on:
        n_dev = len(jax.devices())
        mesh_model_axis = 2 if n_dev > 1 and n_dev % 2 == 0 else 1
    out["mesh"] = ({"model_axis": mesh_model_axis} if mesh_on else None)

    def attach_mesh(scorer, depth):
        if not mesh_on:
            return
        from realtime_fraud_detection_tpu.scoring import MeshExecutor

        # the executor's slot count BECOMES the job's in-flight window
        # (StreamJob._inflight_depth follows an attached pool's
        # total_slots), so each sweep config's depth knob must flow into
        # the executor or the d2-vs-d3 comparison would silently measure
        # one window twice. A single-threaded dispatcher must also never
        # out-dispatch the slots — it would deadlock waiting for a
        # completion only it can perform — hence depth is passed, never
        # hardcoded below a caller's hand-rolled loop depth.
        MeshExecutor(scorer, model_axis=mesh_model_axis,
                     inflight_depth=depth,
                     shard_branches=(("bert_text",)
                                     if mesh_model_axis > 1 else ()))
    if smoke:
        # CPU smoke: tiny arch + one config — proves the measurement path
        # end-to-end so a bug can never burn a live relay window
        from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG

        bert_config = TINY_CONFIG
        sweep = [(64, 3, False, False), (64, 2, True, True)]
        soak_s = 5.0
    else:
        bert_config = BertConfig()        # full DistilBERT-base dims
        sweep = [
            # (max_batch, depth, bf16_wire, explanation)
            (512, 3, False, False),
            (512, 3, True, False),
            (512, 2, False, False),
            (256, 3, False, False),
            (512, 3, False, True),        # explanation cost on the record
        ]
        soak_s = 20.0
    for max_batch, depth, bf16, explain in sweep:
        label = (f"b{max_batch}-d{depth}"
                 f"{'-bf16' if bf16 else ''}{'-explain' if explain else ''}"
                 f"{'-quant' if quant else ''}{'-mesh' if mesh_on else ''}"
                 f"{'-kern' if kernels_on else ''}"
                 f"{'-mega' if mega_on else ''}")
        log(f"config {label}: building scorer")
        cfg = Config()
        cfg.ensemble.enable_explanation = explain
        if quant:
            from realtime_fraud_detection_tpu.utils.config import (
                QuantSettings,
            )

            cfg.quant = QuantSettings.full()
        if kernels_on:
            from realtime_fraud_detection_tpu.utils.config import (
                KernelSettings,
            )

            cfg.kernels = (KernelSettings.mega() if mega_on
                           else KernelSettings.full())
        scorer = FraudScorer(
            config=cfg,
            scorer_config=ScorerConfig(text_len=64, transfer_bf16=bf16),
            bert_config=bert_config)
        attach_mesh(scorer, depth)
        scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
        broker = InMemoryBroker()
        job = StreamJob(broker, scorer,
                        JobConfig(max_batch=max_batch, emit_features=False,
                                  pipeline_depth=depth))
        # backlog must exceed (max plausible rate x window) or the job
        # starves mid-window and the clamp — not the chip — sets the
        # number: 600k over 20 s caps measurement at 30k txn/s, ~3x the
        # best rate any per-chip config has shown
        log(f"config {label}: backlog + warm")
        backlog = 0
        for _ in range(1 if smoke else 24):
            backlog += broker.produce_batch(
                T.TRANSACTIONS, gen.generate_batch(500 if smoke else 25_000),
                key_fn=lambda r: str(r["user_id"]))
        scorer.score_batch(gen.generate_batch(max_batch))  # compile, unwarmed
        t1 = time.perf_counter()
        scored = job.run_for(soak_s)
        dt = time.perf_counter() - t1
        entry = {
            "label": label,
            "max_batch": max_batch,
            "pipeline_depth": depth,
            "transfer_bf16": bf16,
            "explanation": explain,
            "txn_per_s": round(scored / dt, 1),
            "scored": scored,
            "window_s": round(dt, 2),
            "batches": job.counters["batches"],
            "meets_6250": scored / dt >= 6250.0,
            # a drained backlog means the number is a floor set by supply,
            # not the chip — flagged so it can never be read as sustained
            "starved": scored >= int(0.95 * backlog),
        }
        out["configs"].append(entry)
        print(json.dumps(entry), flush=True)

    # ------------------------------------------------- decomposition
    # scorer-direct (no job loop) pipelined rate + host assemble-only rate
    log("decomposition: scorer-direct depth-3")
    cfg = Config()
    cfg.ensemble.enable_explanation = False
    if quant:
        from realtime_fraud_detection_tpu.utils.config import QuantSettings

        cfg.quant = QuantSettings.full()
    if kernels_on:
        from realtime_fraud_detection_tpu.utils.config import KernelSettings

        cfg.kernels = (KernelSettings.mega() if mega_on
                       else KernelSettings.full())
    scorer = FraudScorer(config=cfg, scorer_config=ScorerConfig(text_len=64),
                         bert_config=bert_config)
    attach_mesh(scorer, 4)   # >= the hand-rolled depth-3 loop below
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    batch_recs = [gen.generate_batch(64 if smoke else 512)
                  for _ in range(6 if smoke else 40)]
    scorer.score_batch(batch_recs[0])     # warm
    from collections import deque
    t1 = time.perf_counter()
    inflight: deque = deque()
    n = 0
    for recs in batch_recs:
        inflight.append(scorer.dispatch(recs))
        if len(inflight) >= 3:
            n += len(scorer.finalize(inflight.popleft()))
    while inflight:
        n += len(scorer.finalize(inflight.popleft()))
    dt = time.perf_counter() - t1
    direct = round(n / dt, 1)
    log("decomposition: assemble-only")
    t1 = time.perf_counter()
    m = 0
    for recs in batch_recs[:20]:
        scorer.assemble(recs)
        m += len(recs)
    assemble_rate = round(m / (time.perf_counter() - t1), 1)
    out["decomposition"] = {
        "scorer_direct_depth3_txn_per_s": direct,
        "host_assemble_only_txn_per_s": assemble_rate,
        "note": "e2e = job loop over (assemble || device || fan-out); "
                "scorer-direct bounds the device+assemble pipeline, "
                "assemble-only bounds the host stage alone",
    }
    print(json.dumps(out["decomposition"]), flush=True)

    best = max(out["configs"], key=lambda e: e["txn_per_s"])
    out["best"] = best
    here = os.path.dirname(os.path.abspath(__file__))
    suffix = (f"{'_quant' if quant else ''}{'_mesh' if mesh_on else ''}"
              f"{'_kern' if kernels_on else ''}")
    fname = ("MEASUREMENTS_smoke.json" if smoke
             else (f"MEASUREMENTS_r05_onchip{suffix}.json" if suffix
                   else "MEASUREMENTS_r05_onchip.json"))
    path = (os.path.join("/tmp", fname) if smoke
            else os.path.join(here, fname))
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    log(f"wrote {path}; best {best['label']} = {best['txn_per_s']} txn/s "
        f"({'PASS' if best['meets_6250'] else 'below'} 6,250/chip)")


if __name__ == "__main__":
    if not _probe():
        print("no TPU reachable", file=sys.stderr)
        sys.exit(3)
    run()

#!/bin/bash
# Verify every tier answers on its own protocol (reference health-check.sh
# analog: HTTP checks + port checks + per-service probes).
set -uo pipefail
HOST="${RTFD_HOST:-127.0.0.1}"
fails=0
check() {  # name, python-expr (truthy = healthy)
  printf "%-28s" "$1"
  if python -c "$2" >/dev/null 2>&1; then echo "OK"; else echo "FAIL"; fails=$((fails+1)); fi
}
check "broker (wire protocol)" "
from realtime_fraud_detection_tpu.stream import NetBrokerClient
NetBrokerClient(host='$HOST', port=9092).ping()"
check "state (Redis protocol)" "
from realtime_fraud_detection_tpu.state import RespClient
assert RespClient(host='$HOST', port=6379).ping()"
check "state role/memory" "
from realtime_fraud_detection_tpu.state import RespClient
i = RespClient(host='$HOST', port=6379).info(); assert i['role']"
check "scoring API /health" "
import urllib.request
assert urllib.request.urlopen('http://$HOST:8080/health', timeout=5).status == 200"
check "scoring API /metrics" "
import urllib.request
assert b'rtfd' in urllib.request.urlopen('http://$HOST:8080/metrics/prometheus', timeout=5).read()"
check "topic contract" "
from realtime_fraud_detection_tpu.stream import NetBrokerClient
from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS
c = NetBrokerClient(host='$HOST', port=9092)
assert all(c.partitions(t.name) >= 1 for t in TOPIC_SPECS[:3])"
exit $fails

#!/bin/bash
# Fleet utilization / cost report — the reference's cost-monitor Lambda
# analog (fraud-detection-additional-resources.yaml: Lambda + schedule that
# emailed a cost summary; the README's "40% cost optimization" claim,
# README.md:205, had no mechanism behind it).
#
# This one has a mechanism: scrape every scorer replica's Prometheus
# endpoint, compute per-replica throughput against the configured per-chip
# capacity, and flag replicas the HPA should be allowed to reclaim. Run it
# as the rtfd-cost-monitor CronJob (deploy/k8s/cost-monitor.yaml) or ad hoc.
set -uo pipefail
HOSTS="${RTFD_SCORER_HOSTS:-127.0.0.1:8080}"   # comma-separated host:port
# measured per-chip capacity (bench.py headline on v5e-1); override per fleet
CAPACITY="${RTFD_CHIP_CAPACITY_TPS:-9973}"
python - "$HOSTS" "$CAPACITY" <<'EOF'
import json, socket, sys, urllib.request
raw, capacity = sys.argv[1].split(","), float(sys.argv[2])
# A headless-service name resolves to EVERY pod IP — expand each entry to
# all its A records so the report covers the fleet, not one sampled pod
hosts = []
for h in raw:
    h = h.strip()
    name, _, port = h.partition(":")
    try:
        ips = sorted({ai[4][0] for ai in socket.getaddrinfo(
            name, None, family=socket.AF_INET)})
    except OSError:
        ips = [name]
    hosts.extend(f"{ip}:{port or 8080}" for ip in ips)
rows, total_tps = [], 0.0
for h in hosts:
    try:
        with urllib.request.urlopen(f"http://{h}/metrics", timeout=5) as r:
            m = json.loads(r.read())
        # obs/metrics.py summary(): 60s-window prediction throughput
        tps = float(m.get("throughput_tps_60s") or 0.0)
    except Exception as e:
        rows.append({"replica": h, "error": str(e)[:120]})
        continue
    util = tps / capacity if capacity else 0.0
    rows.append({"replica": h, "txn_per_s": round(tps, 1),
                 "utilization": round(util, 4),
                 "reclaimable": util < 0.15})
    total_tps += tps
n_ok = sum(1 for r in rows if "error" not in r)
report = {
    "replicas": rows,
    "fleet_txn_per_s": round(total_tps, 1),
    "fleet_capacity_txn_per_s": capacity * max(n_ok, 1),
    "fleet_utilization": round(total_tps / (capacity * max(n_ok, 1)), 4),
    "recommendation": (
        "scale down: >1 replica under 15% utilization"
        if sum(1 for r in rows if r.get("reclaimable")) > 1
        else "sized correctly for current load"),
}
print(json.dumps(report))
EOF

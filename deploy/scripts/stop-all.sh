#!/bin/bash
# Stop the stack (reference scripts/setup/stop-all.sh analog).
#   ./stop-all.sh             # compose stack down
#   ./stop-all.sh --local     # kill localhost processes
#   ./stop-all.sh --wipe      # compose down + volumes (reference -v path)
set -euo pipefail
cd "$(dirname "$0")/.."
case "${1:-}" in
  --local) pkill -f "realtime_fraud_detection_tpu (broker|state-server|run-job|serve|simulate)" || true
           echo ">> local processes stopped" ;;
  --wipe)  docker compose -f docker-compose.yml down -v
           echo ">> stack + volumes removed" ;;
  *)       docker compose -f docker-compose.yml down
           echo ">> stack stopped (volumes kept; --wipe to remove)" ;;
esac

#!/bin/bash
# Materialize the 29-topic contract on the broker (reference
# scripts/setup/create-topics.sh analog — topic names/partitions live in
# code, stream/topics.py, instead of a 189-line shell table).
set -euo pipefail
exec python -m realtime_fraud_detection_tpu topics --broker "${1:-127.0.0.1:9092}" --create

#!/bin/bash
# Run the benchmark as soon as the TPU backend is reachable.
#
# The development TPU sits behind a relay whose availability flaps on
# tens-of-minutes timescales (backend init HANGS rather than failing —
# see .claude/skills/verify/SKILL.md). This probes cheaply on an interval
# and fires `python bench.py` exactly once, the first time a probe
# answers. `timeout -k` matters: a wedged probe ignores plain SIGTERM.
#
# Usage: deploy/scripts/bench-when-up.sh [out.json] [max_probes] [gap_s]
set -u
OUT="${1:-bench_out.json}"
MAX_PROBES="${2:-60}"
GAP_S="${3:-300}"
cd "$(dirname "$0")/../.."

for i in $(seq 1 "$MAX_PROBES"); do
  echo "[bench-when-up] probe $i/$MAX_PROBES at $(date -u +%H:%M:%S)" >&2
  if timeout -k 10 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[bench-when-up] backend up; running bench" >&2
    # the relay can wedge BETWEEN the probe and (or during) the bench —
    # same hang-not-fail failure mode, same hard-kill timeout treatment;
    # on a timeout keep probing instead of hanging forever
    if timeout -k 30 2400 python bench.py > "$OUT"; then
      echo "[bench-when-up] bench ok -> $OUT" >&2
      exit 0
    else
      rc=$?
      case "$rc" in
        124|137)   # timeout's TERM / -k KILL: a wedge, keep probing
          echo "[bench-when-up] bench timed out (rc=$rc, wedge?); resuming probes" >&2 ;;
        *)         # deterministic failure (incl. 125-127): retrying won't help
          echo "[bench-when-up] bench FAILED rc=$rc -> giving up" >&2
          exit "$rc" ;;
      esac
    fi
  fi
  sleep "$GAP_S"
done
echo "[bench-when-up] backend never came up after $MAX_PROBES probes" >&2
exit 3

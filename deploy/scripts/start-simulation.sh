#!/bin/bash
# Drive the transaction simulator (reference start-simulation.sh analog,
# same knobs: --tps/--users/--merchants/--brokers; reference defaults
# TPS=100 USERS=10000 MERCHANTS=5000, start-simulation.sh:15-17).
set -euo pipefail
TPS=100; USERS=10000; MERCHANTS=5000; BROKER="127.0.0.1:9092"; COUNT=0
while [[ $# -gt 0 ]]; do
  case $1 in
    --tps) TPS="$2"; shift 2 ;;
    --users) USERS="$2"; shift 2 ;;
    --merchants) MERCHANTS="$2"; shift 2 ;;
    --brokers|--broker) BROKER="$2"; shift 2 ;;
    --count) COUNT="$2"; shift 2 ;;
    *) echo "unknown flag $1"; exit 2 ;;
  esac
done
echo ">> simulating: tps=$TPS users=$USERS merchants=$MERCHANTS -> $BROKER"
exec python -m realtime_fraud_detection_tpu simulate \
    --broker "$BROKER" --tps "$TPS" --users "$USERS" \
    --merchants "$MERCHANTS" --count "$COUNT"

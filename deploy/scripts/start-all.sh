#!/bin/bash
# Start the full local stack (reference scripts/setup/start-all.sh analog).
#
# The reference sequences 18 containers with fixed sleeps (ZK -> Kafka ->
# Redis -> Postgres -> Flink -> registry -> monitoring); this framework's
# topology is 7 services and ordering is expressed as compose healthcheck
# dependencies, so "start all" is one command — readiness is polled, not
# slept. Modes:
#   ./start-all.sh            # docker compose stack (broker/state/job/...)
#   ./start-all.sh --local    # no docker: processes on localhost
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--local" ]]; then
    echo ">> starting local process stack (no docker)"
    mkdir -p /tmp/rtfd/{broker,checkpoints}
    python -m realtime_fraud_detection_tpu broker \
        --host 127.0.0.1 --port 9092 --log-dir /tmp/rtfd/broker &
    echo "broker      pid $! :9092"
    python -m realtime_fraud_detection_tpu state-server \
        --host 127.0.0.1 --port 6379 --maxmemory $((1 << 30)) \
        --aof /tmp/rtfd/state.aof &
    echo "state       pid $! :6379 (1GiB LRU cap + AOF, redis-master.conf analog)"
    sleep 1
    python -m realtime_fraud_detection_tpu run-job --count 0 \
        --broker 127.0.0.1:9092 --state 127.0.0.1:6379 \
        --checkpoint-dir /tmp/rtfd/checkpoints &
    echo "stream-job  pid $!"
    python -m realtime_fraud_detection_tpu serve \
        --host 127.0.0.1 --port 8080 --state 127.0.0.1:6379 &
    echo "scorer      pid $! :8080"
    echo ">> stack up; run ./scripts/health-check.sh, then ./scripts/start-simulation.sh"
else
    command -v docker >/dev/null || { echo "docker not found; use --local"; exit 1; }
    docker compose -f docker-compose.yml up --build -d \
        broker state stream-job scorer prometheus grafana
    docker compose -f docker-compose.yml ps
    echo ""
    echo "Service URLs:"
    echo "  scoring API   http://localhost:8080  (/health /predict /metrics)"
    echo "  prometheus    http://localhost:9090"
    echo "  grafana       http://localhost:3000"
    echo "  broker        localhost:9092 (framework wire protocol)"
    echo "  state         localhost:6379 (Redis protocol)"
    echo ">> next: ./scripts/start-simulation.sh"
fi

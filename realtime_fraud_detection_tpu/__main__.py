"""``python -m realtime_fraud_detection_tpu`` entry point."""

from realtime_fraud_detection_tpu.cli import configure_process_logging, main

configure_process_logging()
raise SystemExit(main())

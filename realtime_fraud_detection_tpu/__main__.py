"""``python -m realtime_fraud_detection_tpu`` entry point."""

from realtime_fraud_detection_tpu.cli import main

raise SystemExit(main())

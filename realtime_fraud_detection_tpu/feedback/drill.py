"""Deterministic closed-loop drill: prove the continuous-learning plane.

Runs the WHOLE loop on a virtual clock, on CPU, with the real components —
simulator → broker → StreamJob → FraudScorer (real fused program, real
GBDT/iforest training) → label join → prequential evaluation → retrain
policy → promotion gate → the /reload-models promotion recipe:

1. **Train** an incumbent (gbdt + isolation forest) on a historical
   labeled segment through the production assemble path; deploy it.
2. **Healthy stream**: delayed labels match back; prequential AUC settles
   at the incumbent's baseline.
3. **Drift**: ``TransactionGenerator.inject_drift`` adds a novel fraud MO
   the incumbent never saw — prequential sliding AUC dips, the policy
   fires a retrain trigger.
4. **Gate negative control**: a candidate retrained on permuted labels is
   submitted first; the gate MUST reject it, and the serving blend must
   be bit-identical afterwards (models, weights, validity, strategy).
5. **Genuine retrain** on the labeled buffer (which now holds drifted
   positives) → gate pass → promotion through the /reload-models recipe.
6. **Recovery**: the drifted pattern keeps flowing; prequential AUC
   recovers to the baseline band.

``rtfd feedback-drill`` prints the full summary then a compact (<2 KB)
parseable verdict as the FINAL stdout line (the bench.py convention);
tier-1 pins the whole loop via ``--fast`` sizes.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["FeedbackDrillConfig", "run_feedback_drill",
           "compact_drill_summary"]


@dataclasses.dataclass
class FeedbackDrillConfig:
    """Drill sizes. Defaults = the full drill; ``fast()`` = tier-1."""

    seed: int = 5
    num_users: int = 600
    num_merchants: int = 200
    tps: float = 64.0                 # virtual txns/sec
    batch: int = 128
    n_train: int = 2_048              # historical labeled segment
    # drift phase is deliberately the long one: the retrainer's gate split
    # reserves the NEWEST labels, so the training segment must still hold
    # enough drifted positives to learn the new pattern from
    n_healthy: int = 1_024
    n_drift: int = 2_560
    n_recovery: int = 3_072
    drift_rate: float = 0.08
    n_trees: int = 32
    tree_depth: int = 4
    sliding_window: int = 512
    fading_gamma: float = 0.998
    auc_drop: float = 0.10
    auc_floor: float = 0.82
    min_labels: int = 256
    # virtual seconds; generous enough that exactly one trigger fires per
    # degradation episode
    cooldown_s: float = 30.0
    # compresses the chargeback delay distribution onto the virtual clock:
    # ~9 virtual seconds for a fraud label, ~2 for a legit confirmation
    label_delay_scale: float = 1e-5

    @classmethod
    def fast(cls) -> "FeedbackDrillConfig":
        return cls(n_train=1_536, n_healthy=896, n_drift=1_792,
                   n_recovery=2_048, n_trees=24, sliding_window=448,
                   min_labels=224)


def _train_incumbent(cfg, gen, scorer) -> Dict[str, Any]:
    """Historical segment through the production assemble path -> deployed
    trees + iforest (the blend_eval recipe, drill-sized)."""
    import jax

    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
    )
    from realtime_fraud_detection_tpu.training import GBDTTrainer

    xs, ys = [], []
    done = 0
    ts = 0.0
    while done < cfg.n_train:
        n = min(cfg.batch, cfg.n_train - done)
        recs = gen.generate_batch(n)
        batch = scorer.assemble(recs, now=ts)
        xs.append(np.asarray(batch.features))
        ys.append(np.asarray([bool(r.get("is_fraud")) for r in recs],
                             np.float32))
        for r in recs:   # serving's write-back: later segments see state
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
        done += n
        ts += n / cfg.tps
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    trees = GBDTTrainer(n_estimators=cfg.n_trees, max_depth=cfg.tree_depth,
                        seed=cfg.seed).fit(x, y)
    iforest = IsolationForestTrainer(n_estimators=48,
                                     seed=cfg.seed + 1).fit(
        x[y < 0.5][:4000])
    # rtfd-lint: allow[lock-order] drill is single-threaded here (no batch in flight during the swap)
    scorer.set_models(scorer.models.replace(trees=trees, iforest=iforest))
    jax.block_until_ready(scorer.models.trees)
    return {"rows": int(len(y)), "fraud_rate": round(float(y.mean()), 4),
            "virtual_end_s": ts}


def _blend_fingerprint(scorer, config) -> Dict[str, Any]:
    """Everything a promotion could change, as comparable host arrays."""
    import jax

    leaves = [np.asarray(leaf) for leaf in
              jax.tree_util.tree_leaves((scorer.models.trees,
                                         scorer.models.iforest))]
    return {
        "leaves": leaves,
        "weights": np.asarray(scorer.ensemble_params.weights).copy(),
        "model_valid": np.asarray(scorer.model_valid).copy(),
        "strategy": config.ensemble.strategy,
    }


def _fingerprints_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    return (len(a["leaves"]) == len(b["leaves"])
            and all(np.array_equal(x, y)
                    for x, y in zip(a["leaves"], b["leaves"]))
            and np.array_equal(a["weights"], b["weights"])
            and np.array_equal(a["model_valid"], b["model_valid"])
            and a["strategy"] == b["strategy"])


def run_feedback_drill(config: Optional[FeedbackDrillConfig] = None,
                       fast: bool = False,
                       return_state: bool = False) -> Any:
    """Run the closed-loop drill; returns a JSON-able summary (and, with
    ``return_state``, the live plane + job + scorer for assertions)."""
    from realtime_fraud_detection_tpu.feedback.plane import FeedbackPlane
    from realtime_fraud_detection_tpu.obs.drift import (
        DriftConfig,
        FeatureDriftMonitor,
    )
    from realtime_fraud_detection_tpu.scoring import (
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker
    from realtime_fraud_detection_tpu.utils.config import (
        Config,
        FeedbackSettings,
    )

    cfg = config or (FeedbackDrillConfig.fast() if fast
                     else FeedbackDrillConfig())

    # serving pair (the round-4 production baseline): trees + iforest
    app_config = Config()
    for name, mc in app_config.models.items():
        mc.enabled = name in ("xgboost_primary", "isolation_forest")
    app_config.models["xgboost_primary"].weight = 0.8
    app_config.models["isolation_forest"].weight = 0.2

    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed, tps=cfg.tps)
    scorer = FraudScorer(app_config,
                         scorer_config=ScorerConfig(text_len=16,
                                                    tokenizer="word"))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    incumbent = _train_incumbent(cfg, gen, scorer)

    clock = [incumbent["virtual_end_s"]]
    settings = FeedbackSettings(
        enabled=True,
        label_horizon_s=120.0, label_ooo_s=0.5, pred_ooo_s=0.5,
        label_delay_scale=cfg.label_delay_scale,
        buffer_size=max(cfg.n_healthy + cfg.n_drift + cfg.n_recovery, 4096),
        sliding_window=cfg.sliding_window, fading_gamma=cfg.fading_gamma,
        operating_threshold=0.5,
        auc_drop=cfg.auc_drop, auc_floor=cfg.auc_floor,
        min_labels=cfg.min_labels, cooldown_s=cfg.cooldown_s,
        retrain_trees=cfg.n_trees, retrain_depth=cfg.tree_depth + 1,
        gate_min_positives=12,
        # keep the gate honest but small: the drifted positives the
        # candidate must LEARN from live in the recent half of the buffer
        gate_select_frac=0.1, gate_holdout_frac=0.15,
    )
    drift_monitor = FeatureDriftMonitor(DriftConfig(
        num_features=scorer.sc.feature_dim,
        warmup_rows=min(768, cfg.n_healthy // 2), window_rows=512,
        min_report_rows=256))
    plane = FeedbackPlane(settings, scorer=scorer, config=app_config,
                          drift_monitor=drift_monitor,
                          clock=lambda: clock[0])
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=cfg.batch, emit_enriched=False, feedback=plane))

    label_heap: List = []     # (label_ts, seq, event)
    seq = [0]

    def push_labels(txns, ts_list) -> None:
        for ev in gen.label_events(txns, event_ts=ts_list,
                                   delay_scale=cfg.label_delay_scale):
            heapq.heappush(label_heap, (ev["label_ts"], seq[0], ev))
            seq[0] += 1

    def release_labels() -> None:
        due = []
        while label_heap and label_heap[0][0] <= clock[0]:
            due.append(heapq.heappop(label_heap)[2])
        for ev in due:
            broker.produce(T.LABELS, ev, key=ev["transaction_id"],
                           timestamp=ev["label_ts"])

    def process_available() -> None:
        while True:
            batch = (job.assembler.next_batch(block=False)
                     or job.assembler.flush())
            if not batch:
                break
            ctx = job.dispatch_batch(batch, now=clock[0])
            if ctx is not None:
                job.complete_batch(ctx, now=clock[0])

    def stream(n: int, auc_trace: List[float]) -> None:
        done = 0
        while done < n:
            k = min(cfg.batch, n - done)
            txns = gen.generate_batch(k)
            ts_list = []
            for txn in txns:
                clock[0] += 1.0 / cfg.tps
                txn["timestamp_ms"] = clock[0] * 1000.0
                ts_list.append(clock[0])
            broker.produce_batch(T.TRANSACTIONS, txns,
                                 key_fn=lambda r: str(r["user_id"]))
            push_labels(txns, ts_list)
            release_labels()
            process_available()
            done += k
            a = plane.evaluator.auc()
            if not math.isnan(a) and len(plane.evaluator) >= cfg.min_labels:
                auc_trace.append(round(a, 4))

    def settle_labels(horizon_s: float = 30.0) -> None:
        """Advance virtual time so the delayed-label tail lands."""
        t_end = clock[0] + horizon_s
        while label_heap and clock[0] < t_end:
            clock[0] = min(max(label_heap[0][0], clock[0] + 0.25), t_end)
            release_labels()
            job.drain_labels()
            plane.check_trigger(now=clock[0])

    # ---- phase 2: healthy stream ------------------------------------------
    healthy_trace: List[float] = []
    stream(cfg.n_healthy, healthy_trace)
    settle_labels()
    baseline_auc = plane.evaluator.auc()

    # ---- phase 3: drift ----------------------------------------------------
    gen.inject_drift(cfg.drift_rate)
    drift_trace: List[float] = []
    stream(cfg.n_drift, drift_trace)
    settle_labels()
    dip_auc = min(drift_trace) if drift_trace else float("nan")
    trigger = plane.pending_trigger or plane.check_trigger(now=clock[0])
    auc_dipped = (not math.isnan(dip_auc)
                  and baseline_auc - dip_auc >= cfg.auc_drop / 2)

    # ---- phase 4: gate negative control -----------------------------------
    # a candidate trained on permuted labels MUST be rejected, and the
    # serving blend must be bit-identical afterwards
    before = _blend_fingerprint(scorer, app_config)
    control_verdict: Dict[str, Any] = {"passed": None,
                                       "reason": "not_run"}
    blend_unchanged = True
    try:
        bad = plane.retrainer.retrain(
            plane.buffer.arrays(),
            weights=app_config.normalized_weights(),
            label_noise_seed=cfg.seed)
        control_verdict = plane.submit_candidate(bad, now=clock[0])
    except ValueError as e:
        control_verdict = {"passed": False, "reason": f"skipped: {e}"}
    blend_unchanged = _fingerprints_equal(before,
                                          _blend_fingerprint(scorer,
                                                             app_config))

    # ---- phase 5: genuine retrain + gated promotion ------------------------
    verdict = plane.react(now=clock[0]) if plane.pending_trigger else None
    promoted = bool(verdict and verdict.get("passed")
                    and "promoted" in verdict)

    # ---- phase 6: recovery (drifted pattern keeps flowing) -----------------
    recovery_trace: List[float] = []
    stream(cfg.n_recovery, recovery_trace)
    settle_labels()
    recovered_auc = plane.evaluator.auc()
    auc_recovered = (promoted and not math.isnan(recovered_auc)
                     and recovered_auc >= baseline_auc - 0.05)

    snap = plane.snapshot()
    passed = bool(
        auc_dipped and trigger is not None
        and control_verdict.get("passed") is False and blend_unchanged
        and promoted and auc_recovered)
    summary: Dict[str, Any] = {
        "metric": "feedback_drill",
        "passed": passed,
        "baseline_auc": round(baseline_auc, 4),
        "dip_auc": (None if math.isnan(dip_auc) else round(dip_auc, 4)),
        "recovered_auc": (None if math.isnan(recovered_auc)
                          else round(recovered_auc, 4)),
        "auc_dipped": bool(auc_dipped),
        "retrain_triggered": trigger is not None,
        "trigger_reason": (trigger or {}).get("reason"),
        "gate_control_rejected": control_verdict.get("passed") is False,
        "gate_control_reason": control_verdict.get("reason"),
        "blend_unchanged_on_reject": bool(blend_unchanged),
        "promoted": promoted,
        "gate": ({k: v for k, v in (verdict or {}).items()
                  if k not in ("promoted",)} if verdict else None),
        "promoted_blend": (verdict or {}).get("promoted"),
        "incumbent": incumbent,
        "drift_rate": cfg.drift_rate,
        "label_join": snap["label_join"],
        "buffer": snap["buffer"],
        "policy": {k: snap["policy"][k] for k in
                   ("triggers", "gate_pass", "gate_fail", "promotions")},
        "labeled_total": snap["prequential"]["labeled_total"],
        "drop_one_auc": snap["prequential"].get("drop_one_auc"),
        "virtual_duration_s": round(clock[0], 2),
        "events": len(plane.events),
    }
    if return_state:
        return summary, plane, job, scorer
    return summary


def compact_drill_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line digest (bench.py convention: full result
    on the preceding line, compact parseable verdict last)."""
    import json

    compact = {
        "metric": "feedback_drill",
        "passed": summary.get("passed"),
        "baseline_auc": summary.get("baseline_auc"),
        "dip_auc": summary.get("dip_auc"),
        "recovered_auc": summary.get("recovered_auc"),
        "auc_dipped": summary.get("auc_dipped"),
        "retrain_triggered": summary.get("retrain_triggered"),
        "trigger_reason": summary.get("trigger_reason"),
        "gate_control_rejected": summary.get("gate_control_rejected"),
        "blend_unchanged_on_reject":
            summary.get("blend_unchanged_on_reject"),
        "promoted": summary.get("promoted"),
        "promoted_blend": summary.get("promoted_blend"),
        "labels_matched": (summary.get("label_join") or {}).get("matched"),
        "labeled_total": summary.get("labeled_total"),
        "virtual_duration_s": summary.get("virtual_duration_s"),
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:     # hard contract: < 2 KB, one line
        for victim in ("promoted_blend", "trigger_reason", "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "feedback_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact

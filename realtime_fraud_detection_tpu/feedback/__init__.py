"""Closed-loop continuous-learning plane.

Turns the scoring pipeline into a closed loop: delayed ground-truth labels
(sim/simulator.py label events) join back to emitted predictions
(feedback/labels.LabelJoin), feed prequential test-then-train quality
metrics (feedback/prequential.py) and a bounded labeled-example buffer
(state/labeled.py); drift or prequential degradation triggers a background
retrain whose candidate blend must pass the promotion gate before it
reaches the serving models through the /reload-models recipe
(feedback/policy.py, feedback/plane.py). ``rtfd feedback-drill`` runs the
whole loop deterministically on a virtual clock (feedback/drill.py).
"""

from realtime_fraud_detection_tpu.feedback.labels import (  # noqa: F401
    LabelJoin,
    make_label_events,
)
from realtime_fraud_detection_tpu.feedback.prequential import (  # noqa: F401
    FadingAUC,
    PrequentialEvaluator,
    sliding_auc,
    weighted_auc,
)
from realtime_fraud_detection_tpu.feedback.policy import (  # noqa: F401
    PromotionGate,
    Retrainer,
    RetrainPolicy,
)
from realtime_fraud_detection_tpu.feedback.plane import (  # noqa: F401
    FeedbackPlane,
)
from realtime_fraud_detection_tpu.feedback.drill import (  # noqa: F401
    run_feedback_drill,
)

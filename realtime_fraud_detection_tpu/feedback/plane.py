"""FeedbackPlane: the closed loop, assembled.

Wires the label join (labels.py), the prequential evaluator
(prequential.py), the bounded labeled buffer (state/labeled.py), the drift
monitor (obs/drift.py), and the retrain/gate policy (policy.py) around a
live scorer:

    predictions ──▶ LabelJoin ◀── delayed labels
                        │ matched
                        ▼
        PrequentialEvaluator + LabeledExampleBuffer + FeatureDriftMonitor
                        │ degradation / drift
                        ▼
        RetrainPolicy ─▶ Retrainer ─▶ PromotionGate ─▶ promote
                                           │ fail
                                           ▼
                              nothing changes, verdict recorded

Promotion runs the /reload-models recipe — ``set_models`` + config blend
update + ``refresh_blend_from_config`` under the host's score lock — so a
promoted candidate deploys exactly the way an operator-driven reload does.
Every trigger, gate verdict, and promotion is appended to a bounded audit
trail (``events``) and mirrored to Prometheus by
``MetricsCollector.sync_feedback``.

Thread model: single-writer, like the stores it owns. The serving app and
the stream job both call ``on_predictions``/``on_labels`` from the one
thread that already owns the scorer's host state (under the score lock
where one exists); ``react`` — the expensive retrain — is safe to run from
a worker thread only because it touches the scorer exclusively through
``promote_fn``, which the host points at its own locked reload recipe.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

import numpy as np

from realtime_fraud_detection_tpu.feedback.labels import LabelJoin
from realtime_fraud_detection_tpu.feedback.policy import (
    PromotionGate,
    Retrainer,
    RetrainPolicy,
)
from realtime_fraud_detection_tpu.feedback.prequential import (
    PrequentialEvaluator,
)
from realtime_fraud_detection_tpu.state.labeled import LabeledExampleBuffer
from realtime_fraud_detection_tpu.utils.config import FeedbackSettings

__all__ = ["FeedbackPlane", "promote_candidate"]


def promote_candidate(scorer, config, candidate: Mapping[str, Any],
                      lock: Optional[threading.Lock] = None) -> Dict[str, Any]:
    """The /reload-models recipe, applied to a gate-passed candidate:
    swap the retrained branches into the model set, write the candidate's
    weights/strategy into the config's model table, and refresh the
    scorer's blend — all under the host's score lock, between batches.
    This is the ONE way the plane (or the serving endpoint) deploys a
    candidate; there is no side door that skips the gate."""
    import contextlib

    models = scorer.models.replace(
        trees=candidate["trees"], iforest=candidate["iforest"],
        **({"lstm": candidate["lstm"]}
           if candidate.get("lstm") is not None else {}))
    weights = candidate["weights"]
    with (lock if lock is not None else contextlib.nullcontext()):
        scorer.set_models(models)
        for name, mc in config.models.items():
            if name in weights:
                mc.enabled = True
                mc.weight = float(weights[name])
            else:
                mc.enabled = False
        config.ensemble.strategy = candidate.get("strategy",
                                                 "weighted_average")
        scorer.refresh_blend_from_config()
    return {"branches": sorted(weights),
            "strategy": config.ensemble.strategy}


class FeedbackPlane:
    """Continuous-learning plane around one scorer."""

    def __init__(self, settings: Optional[FeedbackSettings] = None,
                 scorer=None, config=None, metrics=None,
                 promote_fn: Optional[Callable[[Mapping[str, Any]],
                                               Dict[str, Any]]] = None,
                 drift_monitor=None,
                 clock: Callable[[], float] = time.time):
        self.settings = settings or FeedbackSettings()
        s = self.settings
        self.scorer = scorer
        self.config = config
        self.metrics = metrics
        self.clock = clock
        self.join = LabelJoin(horizon_s=s.label_horizon_s,
                              pred_ooo_s=s.pred_ooo_s,
                              label_ooo_s=s.label_ooo_s,
                              max_pending=s.join_max_pending)
        self.evaluator = PrequentialEvaluator(
            window=s.sliding_window, threshold=s.operating_threshold,
            fading_gamma=s.fading_gamma)
        self.buffer = LabeledExampleBuffer(
            capacity=s.buffer_size, store_history=s.buffer_store_history)
        self.drift = drift_monitor
        self.policy = RetrainPolicy(
            auc_drop=s.auc_drop, auc_floor=s.auc_floor,
            min_labels=s.min_labels, cooldown_s=s.cooldown_s,
            use_drift=s.use_drift_trigger)
        self.retrainer = Retrainer(
            n_trees=s.retrain_trees, depth=s.retrain_depth,
            iforest_trees=s.retrain_iforest_trees,
            select_frac=s.gate_select_frac,
            holdout_frac=s.gate_holdout_frac,
            train_neural=s.retrain_neural)
        self.gate = PromotionGate(
            auc_margin=s.gate_auc_margin,
            recall_tolerance=s.gate_recall_tolerance,
            min_positives=s.gate_min_positives,
            operating_threshold=s.operating_threshold)
        self._promote_fn = promote_fn
        self.events: deque = deque(maxlen=256)   # bounded audit trail
        self.counters: Dict[str, int] = {
            "triggers": 0, "gate_pass": 0, "gate_fail": 0, "promotions": 0,
        }
        self.pending_trigger: Optional[Dict[str, Any]] = None
        self._react_lock = threading.Lock()
        # evaluation stride: the full snapshot + PSI report only re-run
        # after this many NEW labels (the metrics can't move without new
        # labels, so denser evaluation is pure hot-path cost)
        self.eval_stride = max(1, s.sliding_window // 32)
        self._last_eval_labels = -self.eval_stride

    # ------------------------------------------------------------- audit
    def _record(self, event: Dict[str, Any]) -> Dict[str, Any]:
        self.events.append(event)
        return event

    # ------------------------------------------------------------- inputs
    def on_predictions(self, records: Sequence[Mapping[str, Any]],
                       results: Sequence[Mapping[str, Any]],
                       features: Optional[np.ndarray] = None,
                       now: Optional[float] = None) -> int:
        """Register a scored batch with the join (and the drift monitor).
        ``records``/``results`` are the job/serving pairs; ``features`` the
        assembled (B, F) rows — the retrain corpus. Returns newly matched
        labels processed (labels can beat predictions through the broker)."""
        from realtime_fraud_detection_tpu.state.stores import _event_time_ms

        matched = []
        for i, (rec, res) in enumerate(zip(records, results)):
            ts = (now if now is not None
                  else _event_time_ms(rec, None) / 1000.0)
            payload = {
                "score": float(res.get("fraud_score", 0.5)),
                "branch_preds": dict(res.get("model_predictions") or {}),
            }
            if features is not None and i < len(features):
                payload["features"] = np.asarray(features[i], np.float32)
            matched.extend(self.join.process_prediction(
                str(res.get("transaction_id", "")), float(ts), payload))
        if self.drift is not None and features is not None \
                and len(features):
            self.drift.update(np.asarray(features))
        for m in matched:
            self._ingest_match(m)
        return len(matched)

    def on_labels(self, events: Sequence[Mapping[str, Any]]) -> int:
        """Feed label events (the labels topic's payloads); returns newly
        matched pairs."""
        n = 0
        for ev in events:
            for m in self.join.process_label(ev):
                self._ingest_match(m)
                n += 1
        return n

    def _ingest_match(self, m: Mapping[str, Any]) -> None:
        self.evaluator.update(m["score"], m["is_fraud"],
                              branch_preds=m.get("branch_preds"),
                              label_lag_s=m.get("label_lag_s", 0.0))
        feats = m.get("features")
        if feats is not None:
            self.buffer.append(feats, m["is_fraud"], m["score"],
                               m.get("label_ts", m.get("pred_ts", 0.0)),
                               branch_preds=m.get("branch_preds"))

    # ------------------------------------------------------------- control
    def check_trigger(self, now: Optional[float] = None) -> Optional[Dict]:
        """Policy evaluation only (cheap; callable per batch). A fired
        trigger is recorded, counted, and parked in ``pending_trigger``
        for ``react`` to consume — callers decide where the expensive
        retrain runs (inline on the drill's virtual clock; a worker thread
        in serving)."""
        if self.pending_trigger is not None:
            return self.pending_trigger
        now = self.clock() if now is None else now
        if not self.policy.ready(self.evaluator.labeled_total, now):
            # O(1) exit on the scoring hot path: the full prequential
            # snapshot + PSI report only run once the policy is eligible
            return None
        if (self.evaluator.labeled_total - self._last_eval_labels
                < self.eval_stride):
            return None
        self._last_eval_labels = self.evaluator.labeled_total
        drift_report = self.drift.report() if self.drift is not None else None
        trigger = self.policy.observe(self.evaluator.snapshot(),
                                      drift_report, now)
        if trigger is not None:
            self.counters["triggers"] += 1
            self.pending_trigger = self._record(trigger)
        return trigger

    def react(self, now: Optional[float] = None,
              arrays: Optional[Mapping[str, np.ndarray]] = None
              ) -> Optional[Dict[str, Any]]:
        """Consume the pending trigger: retrain -> gate -> (maybe) promote.
        Returns the gate verdict event, or None when nothing was pending.
        Serialized — concurrent calls (serving worker threads) collapse to
        one retrain. ``arrays``: a buffer snapshot taken under the host's
        ingest lock — a caller whose ingest runs on another thread (the
        serving app) must pass one; reading the live buffer mid-append is
        only safe single-threaded (the job/drill default)."""
        with self._react_lock:
            trigger = self.pending_trigger
            if trigger is None:
                return None
            self.pending_trigger = None
            now = self.clock() if now is None else now
            try:
                candidate = self.retrainer.retrain(
                    arrays if arrays is not None else self.buffer.arrays(),
                    weights=(self.config.normalized_weights()
                             if self.config is not None else None))
            except ValueError as e:
                return self._record({"type": "retrain_skipped", "ts": now,
                                     "reason": str(e), "trigger": trigger})
            return self.submit_candidate(candidate, now=now,
                                         trigger=trigger)

    def submit_candidate(self, candidate: Mapping[str, Any],
                         now: Optional[float] = None,
                         trigger: Optional[Mapping[str, Any]] = None
                         ) -> Dict[str, Any]:
        """Gate a candidate; promote if and only if the gate passes. The
        drill also drives this directly (its negative control)."""
        now = self.clock() if now is None else now
        verdict = self.gate.evaluate(candidate)
        verdict.update(ts=now, trained_on=candidate.get("trained_on"),
                       select_auc=candidate.get("select_auc"),
                       trigger_reason=(trigger or {}).get("reason"))
        self._record(dict(verdict))
        if not verdict["passed"]:
            self.counters["gate_fail"] += 1
            return verdict
        self.counters["gate_pass"] += 1
        promoted = self._promote(candidate)
        self._record({"type": "promotion", "ts": now, **promoted})
        self.counters["promotions"] += 1
        verdict["promoted"] = promoted
        return verdict

    def _promote(self, candidate: Mapping[str, Any]) -> Dict[str, Any]:
        if self._promote_fn is not None:
            return self._promote_fn(candidate)
        if self.scorer is None or self.config is None:
            raise RuntimeError(
                "FeedbackPlane has no scorer/config and no promote_fn — "
                "nothing to promote into")
        # rtfd-lint: allow[lock-order] single-writer fallback path (job/drill); serving injects promote_fn bound to the score lock
        return promote_candidate(self.scorer, self.config, candidate)

    # ------------------------------------------------------------- snapshot
    @staticmethod
    def _json_safe(obj: Any) -> Any:
        """NaN/inf -> None, recursively: a cold window's AUC is NaN, and
        bare NaN in a JSON body breaks strict parsers downstream."""
        if isinstance(obj, float):
            return obj if math.isfinite(obj) else None
        if isinstance(obj, dict):
            return {k: FeedbackPlane._json_safe(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [FeedbackPlane._json_safe(v) for v in obj]
        return obj

    def snapshot(self) -> Dict[str, Any]:
        """The GET /quality/live payload (strict-JSON safe)."""
        weights = (self.config.normalized_weights()
                   if self.config is not None else None)
        return self._json_safe({
            "enabled": bool(self.settings.enabled),
            "prequential": self.evaluator.snapshot(weights=weights),
            "label_join": self.join.stats(),
            "buffer": self.buffer.stats(),
            "policy": {
                "pending_trigger": self.pending_trigger,
                "last_trigger_ts": (None if self.policy.last_trigger_ts
                                    == float("-inf")
                                    else self.policy.last_trigger_ts),
                **self.counters,
            },
            "events_tail": list(self.events)[-10:],
        })

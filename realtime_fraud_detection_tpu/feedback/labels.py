"""Delayed-label stream + the prediction x label join.

Ground truth in payments arrives late: a chargeback lands days after the
transaction, a confirmed-legit settles sooner. ``make_label_events``
synthesizes that delay distribution for simulator transactions (the
label-producer role); :class:`LabelJoin` matches label events back to the
predictions the pipeline actually emitted.

The join is patterned on stream/joins.py's watermark discipline but is a
*keyed interval join*, not a tumbling-window cross product: predictions and
labels pair 1:1 on ``transaction_id``, a match fires the moment both sides
are present, and a buffered prediction expires (counted, never silently
dropped) once the joint watermark passes its timestamp plus the label
horizon — the bound that keeps the pending table finite under label loss.
Single-writer discipline, same as stream/windows.py.
"""

from __future__ import annotations

import heapq
import math
from collections import deque
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

__all__ = ["LabelJoin", "make_label_events",
           "FRAUD_LABEL_DELAY_S", "LEGIT_LABEL_DELAY_S"]

# Chargeback-style delay parameters (seconds). Fraud labels ride the
# chargeback pipeline — lognormal around ~9 days with a heavy tail toward
# the 60-90 day network limits; legit confirmations settle in ~2 days.
# ``delay_scale`` compresses both (the drill runs the same shape on a
# virtual clock measured in seconds).
FRAUD_LABEL_DELAY_S = (math.log(9 * 86_400.0), 0.8)    # lognormal (mu, sigma)
LEGIT_LABEL_DELAY_S = (math.log(2 * 86_400.0), 0.5)


def make_label_events(txns: List[Mapping[str, Any]],
                      rng: np.random.Generator,
                      event_ts: Optional[List[float]] = None,
                      delay_scale: float = 1.0) -> List[Dict[str, Any]]:
    """Label events for a list of transactions, delayed chargeback-style.

    ``event_ts`` overrides each transaction's event time (virtual-clock
    runs); default parses ``timestamp_ms`` / ISO ``timestamp``. Returns
    events sorted by ``label_ts`` — the order a label topic would carry
    them in label time.
    """
    from realtime_fraud_detection_tpu.state.stores import _event_time_ms

    events = []
    for i, txn in enumerate(txns):
        is_fraud = bool(txn.get("is_fraud"))
        mu, sigma = FRAUD_LABEL_DELAY_S if is_fraud else LEGIT_LABEL_DELAY_S
        delay = float(rng.lognormal(mu, sigma)) * float(delay_scale)
        ts = (float(event_ts[i]) if event_ts is not None
              else _event_time_ms(txn, None) / 1000.0)
        events.append({
            "transaction_id": str(txn.get("transaction_id", "")),
            "is_fraud": is_fraud,
            "fraud_type": txn.get("fraud_type"),
            "event_ts": ts,
            "label_ts": ts + delay,
        })
    events.sort(key=lambda e: e["label_ts"])
    return events


class LabelJoin:
    """Keyed interval join of emitted predictions x delayed labels.

    ``process_prediction`` buffers a scored transaction (with whatever
    payload the caller wants back — served score, branch predictions,
    feature row); ``process_label`` matches by transaction_id. Both return
    the list of newly matched ``{prediction payload..., label fields...}``
    dicts. Out-of-order labels (label seen before its prediction — e.g. a
    replayed predictions partition) buffer on the label side and match when
    the prediction arrives.

    Watermark semantics (stream/joins.py discipline): the joint watermark
    is ``min(pred_max - pred_ooo, label_max - label_ooo)``; a prediction
    whose ``ts + horizon_s`` falls behind it will never get a label — it
    expires, counted in ``expired``. Early labels expire against the same
    horizon (``orphan_labels``: a label for a prediction this process never
    emitted, e.g. another consumer group's shard).
    """

    def __init__(self, horizon_s: float = 90 * 86_400.0,
                 pred_ooo_s: float = 5.0,
                 label_ooo_s: float = 60.0,
                 max_pending: int = 100_000,
                 matched_memory: int = 65_536):
        self.horizon_s = float(horizon_s)
        self.pred_ooo_s = float(pred_ooo_s)
        self.label_ooo_s = float(label_ooo_s)
        # hard memory bound: the watermark horizon only evicts while BOTH
        # streams advance (a silent labels topic freezes the joint
        # watermark at -inf), so a missing/wedged label producer must not
        # grow the pending table to OOM — beyond max_pending the oldest
        # prediction is expired outright (counted, like any expiry)
        self.max_pending = int(max_pending)
        self._pending: Dict[str, Dict[str, Any]] = {}   # txn_id -> payload
        self._early_labels: Dict[str, Dict[str, Any]] = {}
        self._pred_heap: List = []      # (ts, txn_id) lazy-deleted
        self._label_heap: List = []
        self._pred_max_ts = -math.inf
        self._label_max_ts = -math.inf
        # recently matched txn_ids: dedupes label/prediction REPLAYS that
        # arrive after their match already fired (both topics are
        # at-least-once) — bounded FIFO memory
        self._matched_ids: set = set()
        self._matched_fifo: deque = deque(maxlen=int(matched_memory))
        self.matched = 0
        self.expired = 0
        self.orphan_labels = 0
        self.duplicate_labels = 0

    @property
    def watermark(self) -> float:
        return min(self._pred_max_ts - self.pred_ooo_s,
                   self._label_max_ts - self.label_ooo_s)

    def __len__(self) -> int:
        return len(self._pending)

    # ---------------------------------------------------------------- inputs
    def _mark_matched(self, txn_id: str) -> None:
        self.matched += 1
        if self._matched_fifo.maxlen and \
                len(self._matched_fifo) == self._matched_fifo.maxlen:
            self._matched_ids.discard(self._matched_fifo[0])
        self._matched_fifo.append(txn_id)
        self._matched_ids.add(txn_id)

    def process_prediction(self, txn_id: str, ts: float,
                           payload: Mapping[str, Any]) -> List[Dict[str, Any]]:
        self._pred_max_ts = max(self._pred_max_ts, ts)
        txn_id = str(txn_id)
        if txn_id in self._matched_ids:
            # replayed prediction whose match already fired — buffering it
            # again would re-match a replayed label and double-count
            self._expire()
            return []
        early = self._early_labels.pop(txn_id, None)
        if early is not None:
            self._mark_matched(txn_id)
            self._expire()
            return [self._merge(payload, ts, early)]
        if txn_id in self._pending:
            # replayed prediction (at-least-once topic): first copy wins
            self._expire()
            return []
        self._pending[txn_id] = {"ts": float(ts), "payload": dict(payload)}
        heapq.heappush(self._pred_heap, (float(ts), txn_id))
        self._expire()
        return []

    def process_label(self, event: Mapping[str, Any]) -> List[Dict[str, Any]]:
        ts = float(event.get("label_ts", 0.0))
        self._label_max_ts = max(self._label_max_ts, ts)
        txn_id = str(event.get("transaction_id", ""))
        if txn_id in self._matched_ids:
            # replayed label whose match already fired
            self.duplicate_labels += 1
            self._expire()
            return []
        pend = self._pending.pop(txn_id, None)
        if pend is not None:
            self._mark_matched(txn_id)
            self._expire()
            return [self._merge(pend["payload"], pend["ts"], event)]
        if txn_id in self._early_labels:
            self.duplicate_labels += 1
            self._expire()
            return []
        self._early_labels[txn_id] = dict(event)
        heapq.heappush(self._label_heap, (ts, txn_id))
        self._expire()
        return []

    @staticmethod
    def _merge(payload: Mapping[str, Any], pred_ts: float,
               label: Mapping[str, Any]) -> Dict[str, Any]:
        out = dict(payload)
        out["pred_ts"] = float(pred_ts)
        out["is_fraud"] = bool(label.get("is_fraud"))
        out["fraud_type"] = label.get("fraud_type")
        out["label_ts"] = float(label.get("label_ts", pred_ts))
        out["label_lag_s"] = max(0.0, out["label_ts"] - float(pred_ts))
        return out

    # ---------------------------------------------------------------- expiry
    def _expire(self) -> None:
        wm = self.watermark
        cutoff = wm - self.horizon_s
        while self._pred_heap and self._pred_heap[0][0] <= cutoff:
            ts, txn_id = heapq.heappop(self._pred_heap)
            pend = self._pending.get(txn_id)
            if pend is not None and pend["ts"] == ts:
                del self._pending[txn_id]
                self.expired += 1
        # hard cap regardless of watermark progress: with a silent label
        # stream the joint watermark never advances, but memory must not
        # grow with stream length — expire the OLDEST pending predictions
        while len(self._pending) > self.max_pending and self._pred_heap:
            ts, txn_id = heapq.heappop(self._pred_heap)
            pend = self._pending.get(txn_id)
            if pend is not None and pend["ts"] == ts:
                del self._pending[txn_id]
                self.expired += 1
        while self._label_heap and self._label_heap[0][0] <= cutoff:
            ts, txn_id = heapq.heappop(self._label_heap)
            ev = self._early_labels.get(txn_id)
            if ev is not None and float(ev.get("label_ts", 0.0)) == ts:
                del self._early_labels[txn_id]
                self.orphan_labels += 1

    def stats(self) -> Dict[str, Any]:
        return {
            "pending_predictions": len(self._pending),
            "early_labels": len(self._early_labels),
            "matched": self.matched,
            "expired_unlabeled": self.expired,
            "orphan_labels": self.orphan_labels,
            "duplicate_labels": self.duplicate_labels,
        }

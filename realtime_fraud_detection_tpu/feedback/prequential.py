"""Prequential (test-then-train) quality metrics over the labeled stream.

Every prediction is scored BEFORE its label is known, so each matched
(prediction, label) pair is an unbiased test observation — the streaming
evaluation discipline of Gama et al. (prequential error) applied to the
fraud blend. Two memories run side by side:

- **Sliding window**: exact metrics over the last N labeled examples —
  AUC (tie-averaged Mann-Whitney, identical to sklearn.roc_auc_score),
  precision/recall at the pinned operating threshold, expected calibration
  error, and per-branch drop-one AUC attribution recomputed host-side from
  the stored per-branch predictions.
- **Exponentially-fading window**: the same statistics under geometric
  per-event decay (weight gamma^age). The fading AUC is EXACT for the
  retained horizon: events are kept until their weight falls below a
  floor, then dropped — at gamma=0.999 and floor 1e-9, ~20.7k events, so
  truncation error on the weighted AUC is below 1e-8.

The fading window reacts like a long EWMA — it IS the degradation
baseline the retrain policy compares the sliding window against (a fresh
drift dents the short window first).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Dict, Mapping, Optional

import numpy as np

__all__ = ["sliding_auc", "weighted_auc", "FadingAUC",
           "PrequentialEvaluator"]


def sliding_auc(y: np.ndarray, s: np.ndarray) -> float:
    """Mann-Whitney AUC with tie-averaged ranks (== sklearn.roc_auc_score).

    NaN when the window holds only one class.
    """
    y = np.asarray(y, np.float64)
    s = np.asarray(s, np.float64)
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    ends = np.cumsum(counts)
    rank = (ends - (counts - 1) / 2.0)[inv]
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((rank[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def weighted_auc(y: np.ndarray, s: np.ndarray, w: np.ndarray) -> float:
    """Weighted Mann-Whitney AUC with ties counted at half credit:

        AUC = sum_{i in pos, j in neg} w_i w_j ([s_i > s_j] + 0.5[s_i = s_j])
              / (W_pos * W_neg)

    Computed exactly in O(n log n) via per-unique-score mass cumsums (the
    test suite pins it against the O(n^2) double sum).
    """
    y = np.asarray(y, np.float64)
    s = np.asarray(s, np.float64)
    w = np.asarray(w, np.float64)
    pos = y > 0.5
    w_pos = float(w[pos].sum())
    w_neg = float(w[~pos].sum())
    if w_pos <= 0.0 or w_neg <= 0.0:
        return float("nan")
    uniq, inv = np.unique(s, return_inverse=True)
    pos_mass = np.zeros(len(uniq))
    neg_mass = np.zeros(len(uniq))
    np.add.at(pos_mass, inv[pos], w[pos])
    np.add.at(neg_mass, inv[~pos], w[~pos])
    neg_below = np.concatenate([[0.0], np.cumsum(neg_mass)[:-1]])
    num = float((pos_mass * (neg_below + 0.5 * neg_mass)).sum())
    return num / (w_pos * w_neg)


class FadingAUC:
    """Exponentially-fading AUC + operating-point metrics.

    Each update multiplies every prior observation's weight by ``gamma``
    (equivalently: the k-th most recent event weighs gamma^k). Events are
    dropped once gamma^age < ``weight_floor`` — the retained horizon is
    ceil(log(floor)/log(gamma)) events, beyond which the discarded mass is
    numerically invisible in the weighted AUC.
    """

    def __init__(self, gamma: float = 0.999, weight_floor: float = 1e-9,
                 threshold: float = 0.5):
        if not 0.0 < gamma < 1.0:
            raise ValueError(f"gamma must be in (0, 1), got {gamma}")
        self.gamma = float(gamma)
        self.threshold = float(threshold)
        horizon = int(math.ceil(math.log(weight_floor) / math.log(gamma)))
        self._events: deque = deque(maxlen=max(horizon, 8))  # (score, label)

    def update(self, score: float, label: bool) -> None:
        self._events.append((float(score), bool(label)))

    def __len__(self) -> int:
        return len(self._events)

    def _columns(self):
        n = len(self._events)
        s = np.fromiter((e[0] for e in self._events), np.float64, n)
        y = np.fromiter((e[1] for e in self._events), np.float64, n)
        # newest last in the deque; age = n-1-k for index k
        w = self.gamma ** np.arange(n - 1, -1, -1, dtype=np.float64)
        return y, s, w

    def auc(self) -> float:
        if not self._events:
            return float("nan")
        return weighted_auc(*self._columns())

    def precision_recall(self) -> Dict[str, float]:
        if not self._events:
            return {"precision": float("nan"), "recall": float("nan")}
        y, s, w = self._columns()
        flag = s >= self.threshold
        pos = y > 0.5
        tp = float(w[flag & pos].sum())
        fp = float(w[flag & ~pos].sum())
        fn = float(w[~flag & pos].sum())
        return {
            "precision": tp / (tp + fp) if tp + fp > 0 else float("nan"),
            "recall": tp / (tp + fn) if tp + fn > 0 else float("nan"),
        }


class PrequentialEvaluator:
    """The plane's quality ledger: feed every matched (prediction, label).

    ``update`` order is label-arrival order — the prequential contract:
    the score was produced before the label existed, so the metrics are an
    unbiased running estimate of live model quality.
    """

    def __init__(self, window: int = 2_000, threshold: float = 0.5,
                 fading_gamma: float = 0.999, calibration_bins: int = 10):
        self.window = int(window)
        self.threshold = float(threshold)
        self.calibration_bins = int(calibration_bins)
        # (score, label, branch_preds, label_lag_s)
        self._recent: deque = deque(maxlen=self.window)
        self.fading = FadingAUC(gamma=fading_gamma, threshold=threshold)
        self.labeled_total = 0
        self.fraud_total = 0
        self._lag_sum = 0.0

    # ---------------------------------------------------------------- update
    def update(self, score: float, label: bool,
               branch_preds: Optional[Mapping[str, float]] = None,
               label_lag_s: float = 0.0) -> None:
        self._recent.append((float(score), bool(label),
                             dict(branch_preds or {}), float(label_lag_s)))
        self.fading.update(score, label)
        self.labeled_total += 1
        self.fraud_total += int(bool(label))
        self._lag_sum += float(label_lag_s)

    def __len__(self) -> int:
        return len(self._recent)

    # --------------------------------------------------------------- metrics
    def _columns(self):
        n = len(self._recent)
        s = np.fromiter((e[0] for e in self._recent), np.float64, n)
        y = np.fromiter((e[1] for e in self._recent), np.float64, n)
        return y, s

    def auc(self) -> float:
        if not self._recent:
            return float("nan")
        return sliding_auc(*self._columns())

    def precision_recall(self) -> Dict[str, float]:
        if not self._recent:
            return {"precision": float("nan"), "recall": float("nan"),
                    "flag_rate": float("nan")}
        y, s = self._columns()
        flag = s >= self.threshold
        pos = y > 0.5
        tp = float((flag & pos).sum())
        return {
            "precision": (tp / float(flag.sum()) if flag.any()
                          else float("nan")),
            "recall": (tp / float(pos.sum()) if pos.any() else float("nan")),
            "flag_rate": float(flag.mean()),
        }

    def calibration_error(self) -> float:
        """Expected calibration error over equal-width score bins: the
        |mean score - fraud rate| gap, bin-mass weighted."""
        if not self._recent:
            return float("nan")
        y, s = self._columns()
        bins = np.clip((s * self.calibration_bins).astype(int), 0,
                       self.calibration_bins - 1)
        ece = 0.0
        n = len(s)
        for b in range(self.calibration_bins):
            m = bins == b
            if not m.any():
                continue
            ece += (m.sum() / n) * abs(float(s[m].mean())
                                       - float(y[m].mean()))
        return float(ece)

    def drop_one_attribution(
            self, weights: Mapping[str, float]) -> Dict[str, float]:
        """Per-branch contribution over the sliding window: served-blend
        AUC minus the AUC of the renormalized weighted average with that
        branch removed (recomputed host-side from the stored per-branch
        predictions — the same zero-device-work re-weighting the A/B plane
        uses). Positive = the branch is earning its slot on live traffic."""
        if not self._recent:
            return {}
        y, served = self._columns()
        base_auc = sliding_auc(y, served)
        if math.isnan(base_auc):
            return {}
        names = [n for n, w in weights.items() if w > 0.0]
        out: Dict[str, float] = {}
        n = len(self._recent)
        cols = {name: np.fromiter(
            (e[2].get(name, math.nan) for e in self._recent),
            np.float64, n) for name in names}
        for drop in names:
            rest = [nm for nm in names if nm != drop]
            if not rest:
                continue
            num = np.zeros(n)
            den = np.zeros(n)
            for nm in rest:
                col = cols[nm]
                ok = ~np.isnan(col)
                w = float(weights[nm])
                num[ok] += w * col[ok]
                den[ok] += w
            ok = den > 0
            if ok.sum() < 2:
                continue
            blend = num[ok] / den[ok]
            a = sliding_auc(y[ok], blend)
            if not math.isnan(a):
                out[drop] = round(base_auc - a, 6)
        return out

    # -------------------------------------------------------------- snapshot
    def snapshot(self, weights: Optional[Mapping[str, float]] = None
                 ) -> Dict[str, Any]:
        pr = self.precision_recall()
        fading_pr = self.fading.precision_recall()
        snap: Dict[str, Any] = {
            "labeled_total": self.labeled_total,
            "fraud_total": self.fraud_total,
            "window_size": len(self._recent),
            "operating_threshold": self.threshold,
            "mean_label_lag_s": (self._lag_sum / self.labeled_total
                                 if self.labeled_total else 0.0),
            "sliding": {
                "auc": self.auc(),
                "precision": pr["precision"],
                "recall": pr["recall"],
                "flag_rate": pr["flag_rate"],
                "calibration_error": self.calibration_error(),
            },
            "fading": {
                "auc": self.fading.auc(),
                "precision": fading_pr["precision"],
                "recall": fading_pr["recall"],
            },
        }
        if weights:
            snap["drop_one_auc"] = self.drop_one_attribution(weights)
        return snap
